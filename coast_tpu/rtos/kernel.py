"""Preemptive RTOS kernel model: tick-driven round-robin as a region.

The reference's production campaigns run FreeRTOS apps: every tick
interrupt preempts the running task -- the port saves its register context
onto the task's own stack, ``vTaskSwitchContext`` picks the next ready
task, the port restores that task's context, and the task runs until the
next tick (rtos/pynq).  The campaign flips bits in exactly that machinery:
per-task stacks (with the kernel's canary/watermark overflow check), TCB
fields, the ready list, the current-task pointer.

Here one region step IS one tick interrupt:

    save context   -> push the live register file onto the running task's
                      stack at its saved-SP (``push_frame``)
    pick next      -> round-robin over the ready list (``pick_next``,
                      the vTaskSwitchContext stand-in; the idle task is
                      the fallback when nothing is ready)
    restore        -> pop the next task's frame into the register file
                      (``pop_frame``)
    run slice      -> one slice of the scheduled task's work (the app's
                      task functions, coast_tpu.rtos.apps)

State is the kernel's own data model, each leaf independently injectable
per lane:

  * ``stacks``   [N_TASKS, STACK_WORDS] -- per-task stacks, ``KIND_STACK``
    with the canary word at index 0 (``LeafSpec.canary_word``), remaining
    words initialised to the watermark fill (tskSTACK_FILL_BYTE class).
  * ``tcb_sp``   [N_TASKS] -- saved stack pointer per task (the TCB's
    pxTopOfStack).
  * ``ready``    [N_TASKS] -- ready flags (the ready list).
  * ``slices``   [N_TASKS] -- per-task executed slice counts.
  * ``wmark``    [N_TASKS] -- stack high-water bookkeeping
    (uxTaskGetStackHighWaterMark class).
  * ``cur``      -- current-task pointer (pxCurrentTCB).
  * ``qbuf``/``qidx`` -- the message queue (xQueueSend).
  * ``uart``     -- unprotected UART mirror (the xil_printf class).
  * ``sched_trace`` [TICKS] -- which task ran at each tick: the scheduler
    interleaving as data (drives the determinism regression).

Failure detection is the kernel's own, declared as region guards and
evaluated per lane by the engine (pre-vote, like the replicated kernel's
checks in the reference build):

  * ``stack_guard``: canary blown or saved SP out of bounds ->
    ``DUE_STACK_OVERFLOW`` (taskCHECK_FOR_STACK_OVERFLOW / the
    vApplicationStackOverflowHook line, decoder.py:69).
  * ``assert_guard``: scheduler invariants (current-task pointer in
    range, ready flags boolean, slice counts sane) -> ``DUE_ASSERT``
    (the configASSERT class, decoder.py:67).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.ir.graph import BlockGraph
from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_REG, KIND_RO,
                                 KIND_STACK, LeafSpec, Region)
from coast_tpu.ops.indexing import row_select, row_update

N_TASKS = 3            # two workers + the idle task (task N_TASKS-1)
STACK_WORDS = 16       # words per task stack
FRAME_WORDS = 4        # saved context: r0..r3
CANARY = 0x5AC3A5C3    # stack-limit canary word (tskSTACK_FILL class)
FILL = 0x0A5A5A5A      # watermark fill pattern for unused stack words
QLEN = 32              # message-queue ring length
MASK = 0x7FFFFFFF

IDLE = N_TASKS - 1
# Saved-SP legal range: the canary occupies word 0; a frame must fit.
SP_MIN = 1
SP_MAX = STACK_WORDS - FRAME_WORDS


# ---------------------------------------------------------------------------
# Kernel module functions -- the unit the scope lists name.  App task
# functions come from coast_tpu.rtos.apps and join this namespace.
# ---------------------------------------------------------------------------

def clampi(i, n):
    """Index sanitiser (bounds bookkeeping kept outside the SoR)."""
    return jax.lax.rem(jnp.maximum(i, 0), jnp.int32(n))


def rng_next(seed):
    """LCG tick entropy (the rand() class: one stream, fanned out)."""
    return (jnp.int32(1103515245) * seed + jnp.int32(12345)) & jnp.int32(MASK)


def mix(x):
    """Shared hash round on every queued value."""
    x = (x ^ (x >> 3)) * jnp.int32(0x9E3779B1 - (1 << 32))
    return (x ^ (x >> 7)) & jnp.int32(MASK)


def fold(x):
    """Word fold companion to mix."""
    return ((x >> 16) ^ (x & jnp.int32(0xFFFF))) & jnp.int32(MASK)


def saturate(v):
    """Clamp into the logger's accepted range."""
    return jnp.clip(v, 0, jnp.int32(0x3FFFFFFF))


def uart_fmt(v):
    """UART formatter (the -ignoreFns xil_printf class)."""
    return v ^ jnp.int32(0x55AA55AA)


def push_frame(stacks, task, sp, regs):
    """Save context: write the FRAME_WORDS register file onto ``task``'s
    stack at ``sp`` (the port's context-save).  Indices clip like every
    dynamic store -- a corrupted SP lands the frame somewhere wrong and
    the stack_guard, not a trap, reports it (the fidelity envelope)."""
    row = row_select(stacks, task)
    row = jax.lax.dynamic_update_slice(row, regs, (jnp.int32(sp),))
    return row_update(stacks, row, task)


def pop_frame(stacks, task, sp):
    """Restore context: read ``task``'s saved frame at ``sp``."""
    row = row_select(stacks, task)
    return jax.lax.dynamic_slice(row, (jnp.int32(sp),), (FRAME_WORDS,))


def pick_next(cur, ready):
    """vTaskSwitchContext: next ready task after ``cur`` in round-robin
    order; the idle task when nothing is ready."""
    c1 = jax.lax.rem(cur + 1, jnp.int32(N_TASKS))
    c2 = jax.lax.rem(cur + 2, jnp.int32(N_TASKS))
    c3 = jax.lax.rem(cur + 3, jnp.int32(N_TASKS))
    rdy = lambda c: jnp.take(ready, c, mode="clip") > 0  # noqa: E731
    return jnp.where(rdy(c1), c1,
                     jnp.where(rdy(c2), c2,
                               jnp.where(rdy(c3), c3, jnp.int32(IDLE))))


def queue_send(qbuf, idx, v):
    """xQueueSend: write v at qbuf[idx] (the protectedLibFn class --
    replicated body, single-copy boundary)."""
    return row_update(qbuf, v, idx)


def stack_mark(mark, sp):
    """Stack high-water bookkeeping (uxTaskGetStackHighWaterMark class)."""
    return jnp.maximum(mark, jnp.int32(sp))


KERNEL_FUNCTIONS = {
    "clampi": clampi, "rng_next": rng_next, "mix": mix, "fold": fold,
    "saturate": saturate, "uart_fmt": uart_fmt,
    "push_frame": push_frame, "pop_frame": pop_frame,
    "pick_next": pick_next, "queue_send": queue_send,
    "stack_mark": stack_mark,
}


# ---------------------------------------------------------------------------
# Region factory
# ---------------------------------------------------------------------------

def make_kernel_region(
        name: str,
        tasks: Tuple[Callable, Callable, Callable],
        task_init: Tuple[int, int, int],
        task_names: Tuple[str, str, str],
        ticks: int = 48,
        quota: int = 10) -> Region:
    """Build a preemptive kernel region over three task-slice functions.

    ``tasks[k](regs, env, fns) -> regs`` runs one slice of task k on its
    restored FRAME_WORDS register file; ``env`` carries the per-tick
    inputs (``d`` data word, ``seed`` entropy, ``tick``, ``qbuf``).
    ``task_init[k]`` seeds regs[0] (the accumulator) of task k's initial
    frame.  Worker tasks (0 and 1) retire after ``quota`` slices; the
    idle task (2) never does.
    """
    data = jnp.asarray(
        ((np.arange(32, dtype=np.int64) * 2654435761) >> 11
         ).astype(np.int64) & 0xFFFF, jnp.int32)

    stacks0 = np.full((N_TASKS, STACK_WORDS), FILL, np.int64)
    stacks0[:, 0] = CANARY
    for k in range(N_TASKS):
        # Initial frame at SP_MIN: [acc, x, scratch, slice counter].
        stacks0[k, SP_MIN:SP_MIN + FRAME_WORDS] = [task_init[k], 0, 0, 0]
    stacks0 = jnp.asarray(stacks0, jnp.int32)

    def init():
        return {
            "data": data,
            "stacks": stacks0,
            "tcb_sp": jnp.full((N_TASKS,), SP_MIN, jnp.int32),
            "ready": jnp.ones((N_TASKS,), jnp.int32),
            "slices": jnp.zeros((N_TASKS,), jnp.int32),
            "wmark": jnp.full((N_TASKS,), SP_MIN, jnp.int32),
            "cur": jnp.int32(IDLE),
            "regs": jnp.asarray([task_init[IDLE], 0, 0, 0], jnp.int32),
            "qbuf": jnp.zeros(QLEN, jnp.int32),
            "uart": jnp.zeros(QLEN, jnp.int32),
            "sched_trace": jnp.zeros(ticks, jnp.int32),
            "seed": jnp.int32(2026),
            "tick": jnp.int32(0),
            "qidx": jnp.int32(0),
        }

    def step(s, t, fns):
        tick = s["tick"]
        cur = fns.clampi(s["cur"], N_TASKS)

        # --- tick interrupt: preempt the running task -------------------
        # Save context at a tick-varying frame depth (the running task's
        # call depth at interrupt time), always within [SP_MIN, SP_MAX].
        sp_new = jnp.int32(SP_MIN) + jax.lax.rem(tick, jnp.int32(8))
        stacks = fns.push_frame(s["stacks"], cur, sp_new, s["regs"])
        tcb_sp = row_update(s["tcb_sp"], sp_new, cur)
        wmark = row_update(
            s["wmark"],
            fns.stack_mark(row_select(s["wmark"], cur), sp_new), cur)

        # --- schedule + restore ----------------------------------------
        nxt = fns.pick_next(cur, s["ready"])
        sp_nxt = row_select(tcb_sp, nxt)
        regs = fns.pop_frame(stacks, nxt, sp_nxt)

        # --- run one slice of the scheduled task ------------------------
        # Every task's slice is computed and the scheduled one selected
        # (the batched-program idiom); each call routes through the
        # namespace so the scope lists rewrap user tasks independently of
        # the kernel functions.  ``qin`` is the queue-receive view the
        # consumer-style tasks read (xQueueReceive).
        d = row_select(s["data"], fns.clampi(tick, 32))
        seed = fns.rng_next(s["seed"])
        qin = row_select(s["qbuf"],
                         fns.clampi(row_select(s["slices"], jnp.int32(1)),
                                    QLEN))
        slice_outs = [fns[nm](regs, d, seed, tick, qin)
                      for nm in task_names]
        regs = jnp.select([nxt == 0, nxt == 1],
                          slice_outs[:2], slice_outs[2])
        regs = (regs & jnp.int32(MASK)).astype(jnp.int32)

        # --- queue send + UART mirror (worker slices only) --------------
        is_worker = nxt < jnp.int32(IDLE)
        val = fns.saturate(fns.fold(fns.mix(regs[0])))
        slot = fns.clampi(s["qidx"], QLEN)
        qbuf = jnp.where(is_worker,
                         fns.queue_send(s["qbuf"], slot, val), s["qbuf"])
        uart = jnp.where(is_worker,
                         row_update(s["uart"], fns.uart_fmt(val), slot),
                         s["uart"])
        qidx = s["qidx"] + is_worker.astype(jnp.int32)

        # --- retire workers at quota ------------------------------------
        slices = row_update(s["slices"], row_select(s["slices"], nxt) + 1,
                            nxt)
        retired = jnp.logical_and(is_worker,
                                  row_select(slices, nxt) >= quota)
        ready = jnp.where(retired,
                          row_update(s["ready"], jnp.int32(0), nxt),
                          s["ready"])

        return {
            "data": s["data"],
            "stacks": stacks,
            "tcb_sp": tcb_sp,
            "ready": ready,
            "slices": slices,
            "wmark": wmark,
            "cur": nxt,
            "regs": regs,
            "qbuf": qbuf,
            "uart": uart,
            "sched_trace": row_update(s["sched_trace"], nxt,
                                      fns.clampi(tick, ticks)),
            "seed": seed,
            "tick": tick + 1,
            "qidx": qidx,
        }

    def done(s):
        return s["tick"] >= ticks

    def output(s):
        return jnp.concatenate(
            [s["qbuf"], s["uart"], s["sched_trace"], s["regs"],
             s["slices"], s["wmark"], s["tcb_sp"],
             jnp.stack([s["qidx"], s["cur"]])]).astype(jnp.uint32)

    # --- the kernel's own failure detectors (per-lane, engine-evaluated) --
    def stack_guard(s):
        """taskCHECK_FOR_STACK_OVERFLOW: canary intact, saved SPs legal."""
        canary_blown = jnp.any(s["stacks"][:, 0] != jnp.int32(CANARY))
        sp_bad = jnp.any(jnp.logical_or(s["tcb_sp"] < SP_MIN,
                                        s["tcb_sp"] > SP_MAX))
        return jnp.logical_or(canary_blown, sp_bad)

    def assert_guard(s):
        """configASSERT: scheduler invariants."""
        cur_bad = jnp.logical_or(s["cur"] < 0, s["cur"] >= N_TASKS)
        ready_bad = jnp.any(jnp.logical_or(s["ready"] < 0, s["ready"] > 1))
        slices_bad = jnp.any(jnp.logical_or(s["slices"] < 0,
                                            s["slices"] > ticks))
        return jnp.logical_or(cur_bad,
                              jnp.logical_or(ready_bad, slices_bad))

    graph = BlockGraph(
        names=["entry", "tick", "exit"],
        edges=[(0, 1), (1, 1), (1, 2)],
        block_of=lambda s: jnp.where(s["tick"] >= ticks, jnp.int32(2),
                                     jnp.int32(1)).astype(jnp.int32),
    )

    functions: Dict[str, Callable] = dict(KERNEL_FUNCTIONS)
    for tname, task in zip(task_names, tasks):
        # Task slices enter the namespace with their app names so the
        # scope lists can put user tasks in/out of the protected scope
        # independently of the kernel functions.  The step dispatches
        # through the namespace so each task call is rewrapped per its
        # scope class.
        functions[tname] = task

    region = Region(
        name=name,
        init=init,
        step=step,
        done=done,
        check=lambda s: jnp.int32(0),     # replaced with golden compare
        output=output,
        nominal_steps=ticks,
        max_steps=3 * ticks,
        spec={
            "data": LeafSpec(KIND_RO),
            "stacks": LeafSpec(KIND_STACK, xmr=True, canary_word=0),
            "tcb_sp": LeafSpec(KIND_MEM),
            "ready": LeafSpec(KIND_MEM),
            "slices": LeafSpec(KIND_MEM),
            "wmark": LeafSpec(KIND_MEM),
            "cur": LeafSpec(KIND_CTRL),
            "regs": LeafSpec(KIND_REG),
            "qbuf": LeafSpec(KIND_MEM, xmr=True),
            # The UART mirror lives outside the SoR (xil_printf class,
            # boundary-voted stores).
            "uart": LeafSpec(KIND_MEM, xmr=False, no_verify=True),
            "sched_trace": LeafSpec(KIND_MEM),
            "seed": LeafSpec(KIND_REG),
            "tick": LeafSpec(KIND_CTRL),
            "qidx": LeafSpec(KIND_CTRL),
        },
        default_xmr=True,
        graph=graph,
        functions=functions,
        meta={
            "oracle": "Number of errors: 0",
            # Per-section attribution categories for campaign artifacts:
            # which leaves are stack memory, kernel/TCB structures, or
            # task data (the stack/TCB/task-data split of the issue's
            # acceptance bar).
            "rtos_sections": {
                "stack": ("stacks",),
                "tcb": ("tcb_sp", "ready", "slices", "wmark", "cur",
                        "tick"),
                "task_data": ("qbuf", "uart", "sched_trace", "regs",
                              "seed", "qidx", "data"),
            },
        },
        stack_guard=stack_guard,
        assert_guard=assert_guard,
    )

    golden = jax.device_get(output(region.run_unprotected()))
    golden = jnp.asarray(golden)
    region.check = lambda s: jnp.sum(output(s) != golden).astype(jnp.int32)
    return region
