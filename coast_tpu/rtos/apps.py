"""RTOS application task sets: the rtos_mm / rtos_kUser targets.

The reference builds two FreeRTOS app flavours under the production COAST
config (rtos/pynq/Makefile): ``rtos_mm`` runs the matrix-multiply workload
as preemptive tasks, ``rtos_kUser`` protects kernel AND user code of a
queue-passing user app.  Each task function here is one *slice* of its
task -- the work between two tick interrupts -- over the task's restored
register file ``regs`` ([acc, x, scratch, count], FRAME_WORDS words):

    task(regs, d, seed, tick, qin) -> regs'

``d`` is the tick's input word, ``seed`` the tick entropy stream, ``qin``
the queue-receive view (consumer tasks).  Task state lives ONLY in regs:
between slices it sits as a saved frame on the task's stack, which is
what makes stack corruption consequential.
"""

from __future__ import annotations

import jax.numpy as jnp

from coast_tpu.rtos.kernel import MASK, make_kernel_region


def _pack(acc, x, scratch, count):
    return (jnp.stack([acc, x, scratch, count])
            & jnp.int32(MASK)).astype(jnp.int32)


# -- rtos_mm: the matrix-multiply workload as tasks -------------------------

def task_mm(regs, d, seed, tick, qin):
    """Multiply-accumulate worker (the rtos_mm payload)."""
    acc = regs[0] + d * d
    return _pack(acc, d, regs[2] ^ acc, regs[3] + 1)


def task_crc(regs, d, seed, tick, qin):
    """CRC-ish fold worker."""
    x = (regs[0] ^ d) & jnp.int32(0xFFFF)
    acc = ((regs[0] << 5) ^ (x * jnp.int32(0x5BD1)) ^ (x >> 3))
    return _pack(acc, x, regs[2] + d, regs[3] + 1)


def task_idle(regs, d, seed, tick, qin):
    """Idle/heartbeat task: checksum over the tick entropy."""
    acc = regs[0] + tick * jnp.int32(31) + (seed & jnp.int32(0xFFFF))
    return _pack(acc, seed, regs[2], regs[3] + 1)


def make_rtos_mm():
    return make_kernel_region(
        name="rtos_mm",
        tasks=(task_mm, task_crc, task_idle),
        task_init=(0, 0x1D0F, 0),
        task_names=("task_mm", "task_crc", "task_idle"),
        ticks=48, quota=10)


# -- rtos_kUser: queue-passing user app (kernel+user protection scope) ------

def task_prod(regs, d, seed, tick, qin):
    """Producer: derives a message from the tick entropy and its own
    running state; the kernel queue_send publishes it."""
    acc = (regs[0] * jnp.int32(0x9E3B) + (seed & jnp.int32(0xFFFFF)) + d)
    return _pack(acc, seed, regs[2] ^ d, regs[3] + 1)


def task_cons(regs, d, seed, tick, qin):
    """Consumer: folds the queue-receive view into its accumulator."""
    acc = ((regs[0] << 3) ^ qin ^ (regs[0] >> 11)) + jnp.int32(0x101)
    return _pack(acc, qin, regs[2] + qin, regs[3] + 1)


def task_wdg(regs, d, seed, tick, qin):
    """Watchdog/idle: liveness counter over ticks."""
    acc = regs[0] + (tick ^ jnp.int32(0x5A5)) + 1
    return _pack(acc, tick, regs[2], regs[3] + 1)


def make_rtos_kuser():
    return make_kernel_region(
        name="rtos_kUser",
        tasks=(task_prod, task_cons, task_wdg),
        task_init=(1, 0, 0),
        task_names=("task_prod", "task_cons", "task_wdg"),
        ticks=60, quota=12)
