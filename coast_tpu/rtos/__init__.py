"""coast_tpu.rtos: the preemptive RTOS kernel model subsystem.

The reference's canonical *production* configuration is a FreeRTOS port:
rtos/pynq builds the kernel + app sources under ``-TMR -countErrors`` with
dozens-long scope lists, and its campaigns corrupt preemptive-task state --
per-task stacks, TCBs, the ready list, the current-task pointer -- with
stack overflows and assertion failures decoded as their own DUE classes
(supportClasses.py:278-389; decoder.py:67-69).

This package is that capability re-expressed on the stepped region model:

  * :mod:`coast_tpu.rtos.kernel` -- a tick-driven preemptive round-robin
    scheduler as a protected region.  Every step is one tick interrupt:
    save the running task's context onto its stack, pick the next ready
    task, restore its context, run one slice of it.  Per-task stacks are
    ``KIND_STACK`` leaves with a canary/watermark word; TCB saved-SP
    words, the ready list and the current-task pointer are ordinary
    injectable leaves, each independently corruptible per lane.
  * :mod:`coast_tpu.rtos.apps` -- the task sets: ``rtos_mm`` (the
    matrix-multiply workload of the reference's rtos_mm target) and
    ``rtos_kUser`` (a producer/consumer queue app, the kernel+user
    protection-scope split of rtos_kUser).

The kernel regions declare ``stack_guard`` / ``assert_guard`` hooks: the
engine evaluates them per lane on pre-vote state (the replicated kernel's
own checks), latching ``DUE_STACK_OVERFLOW`` / ``DUE_ASSERT`` -- the DUE
sub-bucket taxonomy that flows through inject/classify -> inject/logs ->
analysis/json_parser -> scripts/mwtf_report.

Canonical build config: ``rtos/Makefile`` (targets ``rtos_mm`` /
``rtos_kUser``) + ``rtos/kernel.config`` (the file half of the scope
lists), mirroring the reference's Makefile/functions.config split.
"""

from coast_tpu.rtos.kernel import (CANARY, FRAME_WORDS, N_TASKS,
                                   STACK_WORDS, make_kernel_region)

__all__ = ["make_kernel_region", "CANARY", "N_TASKS", "STACK_WORDS",
           "FRAME_WORDS"]
