"""Cache and register injection models: the MemHierarchy / A9Register
analogues.

The reference injects into three target families (injector.py:125-200):
named CPU registers (``A9Register`` enum, resources/registers.py), ELF
memory sections (resources/mem.py:56-85), and cache words addressed as
(row, block, word) through the QEMU plugin's geometry model
(``CacheData``/``MemHierarchy``, resources/mem.py:86-161; geometry table
resources/benchmarks.py:186-207).  A TPU program has no architectural
registers or SRAM caches, so each family is mapped onto the region's state
with a documented fidelity envelope (SURVEY.md §7):

  * **registers** -> words of ``reg``/``ctrl`` leaves (loop-carried state),
    named like a register file (:class:`RegisterFile`);
  * **dcache / l2cache** -> a geometry-faithful overlay on the ``mem``
    leaves: a random (row, block, word) maps to a backing memory word when
    the line falls inside the program's footprint, and is *discarded as an
    invalid line* otherwise -- mirroring the plugin's valid-line queries
    (injector.pluginCommunicate, injector.py:74-123): an injection into an
    invalid/clean line never lands in the guest's dataflow;
  * **icache** -> control state (``ctrl`` + CFCSS signature leaves):
    an instruction-fetch corruption manifests as a control-flow error,
    which is precisely the fault class CFCSS exists to catch.

Geometry defaults are the pynq (Cortex-A9) table so campaign shapes stay
comparable with the reference's.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from coast_tpu.inject.mem import MemoryMap
from coast_tpu.inject.schedule import FaultSchedule

# Cache geometry (resources/benchmarks.py:186-207, board "pynq").
CACHE_INFO: Dict[str, Dict[str, Dict[str, int]]] = {
    "pynq": {
        "icache": {"size": 32768, "assoc": 4, "bSize": 32, "policy": 0},
        "dcache": {"size": 32768, "assoc": 4, "bSize": 32, "policy": 0},
        "l2cache": {"size": 524288, "assoc": 8, "bSize": 32, "policy": 1},
    },
}
# The TPU "board" keeps the A9 geometry so campaign section weights match
# the reference's; alias rather than copy.
CACHE_INFO["tpu"] = CACHE_INFO["pynq"]


@dataclasses.dataclass(frozen=True)
class CacheData:
    """One cache's geometry (resources/mem.py:86-117)."""

    name: str
    size: int
    assoc: int
    block_size: int
    policy: int
    word_size: int = 4

    @property
    def rows(self) -> int:
        return self.size // (self.block_size * self.assoc)

    @property
    def words_per_block(self) -> int:
        return self.block_size // self.word_size

    @property
    def total_words(self) -> int:
        return self.size // self.word_size

    def random_word_cache_addr(self, rng: np.random.RandomState
                               ) -> Tuple[int, int, int]:
        """(row, block, word), uniform (randomWordCacheAddr mem.py:113-117)."""
        return (int(rng.randint(self.rows)),
                int(rng.randint(self.assoc)),
                int(rng.randint(self.words_per_block)))


class MemHierarchy:
    """All of a board's caches + size-weighted random choice
    (resources/mem.py:120-161)."""

    def __init__(self, board: str = "tpu"):
        if board not in CACHE_INFO:
            raise ValueError(f"Invalid board for cache setup: {board!r}")
        self.board = board
        self.caches: Dict[str, CacheData] = {
            name: CacheData(name, g["size"], g["assoc"], g["bSize"],
                            g["policy"])
            for name, g in CACHE_INFO[board].items()
        }
        self._names = list(self.caches)
        self._weights = np.array(
            [c.size for c in self.caches.values()], dtype=np.float64)
        self._weights /= self._weights.sum()

    def random_word_cache_addr(self, rng: np.random.RandomState,
                               cache_name: Optional[str] = None
                               ) -> Tuple[str, int, int, int]:
        if cache_name is None:
            cache_name = self._names[
                int(rng.choice(len(self._names), p=self._weights))]
        cache = self.caches[cache_name]
        return (cache_name, *cache.random_word_cache_addr(rng))


class RegisterFile:
    """Named pseudo-registers over the loop-carried state: the A9Register
    enum analogue (resources/registers.py:1-184).

    Every 32-bit word of a ``reg``/``ctrl`` leaf is one register; scalars
    keep the leaf name ('sp'), vector words are indexed ('moves[3]') --
    like r0..r15 / s0..s31 naming a physical register file.
    """

    def __init__(self, prog):
        self.prog = prog
        # (name, leaf_id, lane, word): replicated leaves contribute one
        # register file per lane (N independently corruptible copies, like
        # cloned globals at distinct addresses).
        self._rows: List[Tuple[str, int, int, int]] = []
        for leaf_id, (name, kind, lanes, words) in enumerate(
                prog.injectable_sections()):
            if kind not in ("reg", "ctrl"):
                continue
            for lane in range(lanes):
                suffix = f"@{lane}" if lanes > 1 else ""
                if words == 1:
                    self._rows.append((f"{name}{suffix}", leaf_id, lane, 0))
                else:
                    self._rows.extend(
                        (f"{name}[{w}]{suffix}", leaf_id, lane, w)
                        for w in range(words))
        if not self._rows:
            raise ValueError("program has no register-class leaves")

    @property
    def names(self) -> List[str]:
        return [r[0] for r in self._rows]

    def name_lookup(self, reg_str: str) -> Optional[Tuple[int, int, int]]:
        """(leaf_id, lane, word) for a register name, None if absent
        (nameLookup, registers.py:193-198)."""
        for name, leaf_id, lane, word in self._rows:
            if name == reg_str:
                return leaf_id, lane, word
        return None

    def random(self, rng: np.random.RandomState
               ) -> Tuple[str, int, int, int]:
        return self._rows[int(rng.randint(len(self._rows)))]


# What each cache overlays: instruction fetch corruption manifests in
# control/CFCSS state; data caches back the memory image.  Shared by the
# scalar mapping, the vectorised scheduler, and the supervisor's 'text'
# section alias.
ICACHE_KINDS = ("ctrl", "cfcss")
# Training regions' parameters and optimizer state (coast_tpu.train) are
# data in HBM like any KIND_MEM image: the dcache overlays them, and the
# supervisor's 'memory' section reaches them.  Regions without train
# leaves match nothing extra, so pre-train footprints are unchanged.
DCACHE_KINDS = ("mem", "ro", "param", "opt_state")


def _overlay_rows(mmap: MemoryMap, cache_name: str):
    """The (section_idx, section) rows a cache overlays, in map order --
    the single source of truth for the footprint mapping."""
    kinds = ICACHE_KINDS if cache_name == "icache" else DCACHE_KINDS
    return [(idx, s) for idx, s in enumerate(mmap.sections)
            if s.kind in kinds]


def cache_addr_to_fault(mmap: MemoryMap, cache: CacheData, row: int,
                        block: int, word: int
                        ) -> Optional[Tuple[int, int, int, int]]:
    """Map a (row, block, word) cache address onto an injectable word.

    Returns (leaf_id, lane, word, section_idx) of the backing word, or
    ``None`` when the line is outside the program footprint (an
    invalid-line injection, discarded exactly as the plugin's validity
    query discards it).

      * data caches overlay the ``mem``/``ro`` sections in memory-map
        order (physically-indexed cache over the address space);
      * the icache overlays control state (``ctrl`` and CFCSS leaves).
    """
    rows = _overlay_rows(mmap, cache.name)
    if not rows:
        return None
    linear = ((row * cache.assoc) + block) * cache.words_per_block + word
    total = sum(s.lanes * s.words for _, s in rows)
    # Footprint model: the cache is direct-mapped onto the program image;
    # lines past the image hold no program data (invalid).
    if linear >= total:
        return None
    for sec_idx, s in rows:
        sec_words = s.lanes * s.words
        if linear < sec_words:
            return (s.leaf_id, linear // s.words, linear % s.words, sec_idx)
        linear -= sec_words
    raise AssertionError("unreachable")


def generate_cache_schedule(mmap: MemoryMap, hierarchy: MemHierarchy,
                            n: int, seed: int, nominal_steps: int,
                            cache_name: Optional[str] = None
                            ) -> FaultSchedule:
    """A cache-section campaign schedule: n draws over the hierarchy,
    fully vectorised (one numpy pass per cache, no per-draw python loop --
    the schedule must not become the bottleneck of a 10^6-injection
    campaign).

    Non-resident draws keep their row in the schedule with ``t = -1`` --
    the flip never fires (the enable predicate requires t == step), and the
    run classifies as success, mirroring an injection the plugin discarded
    (logs mark them '<invalid-line>').
    """
    rng = np.random.RandomState(seed)
    bit = rng.randint(0, 32, n).astype(np.int32)
    t = rng.randint(0, max(nominal_steps, 1), n).astype(np.int32)
    if cache_name is None:
        cache_idx = rng.choice(len(hierarchy._names), size=n,
                               p=hierarchy._weights)
    else:
        cache_idx = np.full(n, hierarchy._names.index(cache_name))
    leaf_id = np.zeros(n, np.int32)
    lane = np.zeros(n, np.int32)
    word = np.zeros(n, np.int32)
    sec = np.zeros(n, np.int32)
    for ci, cname in enumerate(hierarchy._names):
        mask = cache_idx == ci
        k = int(mask.sum())
        if k == 0:
            continue
        c = hierarchy.caches[cname]
        row = rng.randint(0, c.rows, k)
        blk = rng.randint(0, c.assoc, k)
        w = rng.randint(0, c.words_per_block, k)
        linear = ((row * c.assoc) + blk) * c.words_per_block + w
        rows = _overlay_rows(mmap, cname)
        if not rows:
            t[mask] = -1
            continue
        sizes = np.array([s.lanes * s.words for _, s in rows])
        edges = np.cumsum(sizes)
        resident = linear < int(edges[-1])
        sidx = np.clip(np.searchsorted(edges, linear, side="right"),
                       0, len(rows) - 1)
        offs = linear - (edges[sidx] - sizes[sidx])
        words_per = np.array([s.words for _, s in rows])[sidx]
        leaf_id[mask] = np.where(
            resident, np.array([s.leaf_id for _, s in rows])[sidx], 0)
        lane[mask] = np.where(resident, offs // words_per, 0)
        word[mask] = np.where(resident, offs % words_per, 0)
        sec[mask] = np.where(resident,
                             np.array([i for i, _ in rows])[sidx], 0)
        t_m = t[mask]
        t_m[~resident] = -1
        t[mask] = t_m
    return FaultSchedule(leaf_id, lane, word, bit, t, sec, seed)
