"""Campaign supervisor CLI: the simulation/platform/supervisor.py surface.

The reference supervisor spawns QEMU + GDB per campaign and drives the
state machine over sockets (supervisor.py:400-509); here the whole campaign
is the batched XLA program of :mod:`coast_tpu.inject.campaign`, and this
module keeps the *interface*: the same section vocabulary, campaign sizing,
forced-injection debug hook, and JSON logs.

    python -m coast_tpu.inject.supervisor -f matrixMultiply -s memory -t 1000
    python -m coast_tpu.inject.supervisor -f crc16 -O "-DWC" -s registers -t 500
    python -m coast_tpu.inject.supervisor -f aes -s dcache -e 10 -l logs/

Section choices (supervisor.py:340) map onto leaf kinds:
``data/bss/heap/init`` -> written memory leaves, ``rodata`` -> read-only
leaves, ``memory`` -> both, ``registers`` -> loop-carried reg/ctrl leaves,
``stack`` -> LeafSpec.stack leaves, ``text``/``icache`` -> control +
CFCSS-signature state (instruction-fetch corruption manifests as control
flow), ``dcache``/``l2cache``/``cache`` -> the geometry overlay of
:mod:`coast_tpu.inject.hierarchy`.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

#: Geometry-overlay sections: these draw their own cache schedules
#: (coast_tpu.inject.hierarchy), outside the seeded generate() paths --
#: several CLI gates below refuse flags that only make sense there.
CACHE_SECTIONS = ("cache", "icache", "dcache", "l2cache")

SECTION_CHOICES = ["stack", "text", "rodata", "data", "bss", "heap", "init",
                   "registers", "memory", "params", "opt_state",
                   "interconnect", *CACHE_SECTIONS]

from coast_tpu.inject.hierarchy import DCACHE_KINDS, ICACHE_KINDS

_KIND_SECTIONS = {
    # "memory" includes the link-kind in-flight buffers so the 'link'
    # fault model works under the default section choice; non-link
    # models never draw into them (schedule._nonlink_sites), so the
    # addition changes nothing on benchmarks without a link surface.
    "memory": (*DCACHE_KINDS, "link"),
    # The sharded halo-exchange surface alone (ir/region.KIND_LINK):
    # the natural section for --fault-model link campaigns.
    "interconnect": ("link",),
    "data": ("mem",),
    "bss": ("mem",),
    "heap": ("mem",),
    "init": ("mem",),
    "rodata": ("ro",),
    "registers": ("reg", "ctrl"),
    "text": ICACHE_KINDS,
    # Training targets (coast_tpu.train): the persistent state classes
    # by name, for campaigns over just the weights or just the
    # optimizer moments (docs/training.md).
    "params": ("param",),
    "opt_state": ("opt_state",),
}


def parse_command_line(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        description="Supervisor for batched TPU fault injection")
    parser.add_argument("--filename", "-f", type=str, required=True,
                        help="program to run: a benchmark registry name "
                        "or a path to a restricted-C source (.c)")
    # DEPRECATED (QEMU era): the reference supervisor parceled GDB/QEMU
    # socket ports per worker (supervisor.py:335); the batched campaign
    # has no sockets to parcel (scale-out is the mesh batch axis and the
    # fleet queue, python -m coast_tpu.fleet).  Accept-and-warn so old
    # scripts keep running; hidden from --help's primary group.
    parser.add_argument("--port-range", "-p", type=int, default=None,
                        help=argparse.SUPPRESS)
    parser.add_argument("-t", metavar="N", type=int, default=1,
                        help="number of injections")
    parser.add_argument("-e", "--errorCount", metavar="N", type=int,
                        help="run until N errors seen, then complete the "
                        "next 1000 injections")
    parser.add_argument("--section", "-s", type=str, default="memory",
                        choices=SECTION_CHOICES,
                        help="memory section to inject faults into")
    parser.add_argument("--board", "-d", type=str, default="tpu",
                        choices=["tpu", "cpu", "pynq", "hifive1"],
                        help="execution backend (cpu = the x86 regression "
                        "board)")
    parser.add_argument("--opt-passes", "-O", type=str, default="-TMR",
                        help="protection to apply (opt CLI flag string); "
                        "the reference bakes this into the ELF instead. "
                        "All of `-O -TMR`, `-O '-TMR -countErrors'` and "
                        "`--opt-passes=-TMR` work; pass flags that "
                        "collide with supervisor flags (e.g. the `-s` "
                        "segmenting flag) need the quoted or `=` form")
    parser.add_argument("--log-dir", "-l", type=str, default=None,
                        help="directory in which to create the log files")
    parser.add_argument("--no-logging", "-q", action="store_true",
                        help="do not produce log files")
    parser.add_argument("--verbosity", "-v", default="n",
                        choices=["n", "c", "e", "s", "i", "a"])
    parser.add_argument("--forceBreak", "-b", metavar="EXPRESSION", type=str,
                        help="forced injection leaf:lane:word:bit:t "
                        "(injector.py setBreaking analogue)")
    parser.add_argument("--breakCount", "-c", metavar="ITERATION", type=int,
                        default=1, help="how many forced injections to run")
    parser.add_argument("--seed", type=int, default=0,
                        help="campaign schedule seed (replayable)")
    parser.add_argument("--start-num", type=int, default=0,
                        help="resume the seeded campaign at injection "
                        "#N (gdbClient.py:401 --start-num analogue)")
    parser.add_argument("--batch-size", type=int, default=4096)
    parser.add_argument("--unroll", type=int, default=1,
                        help="early-exit loop steps per iteration in the "
                        "batched runner; classification-identical at any "
                        "value, trades loop dispatch overhead against "
                        "masked overshoot work (sweep: scripts/"
                        "mfu_sweep.py)")
    parser.add_argument("--fault-model", type=str, default="single",
                        metavar="SPEC",
                        help="what one injection IS: 'single' (default; "
                        "the historical one-bit flip), 'multibit(k=K)' "
                        "(K distinct bits of one word), 'cluster(span=S,"
                        "k=K)' (K flips in adjacent words, lane-crossing), "
                        "'burst(window=W,rate=R)' (round(W*R) upsets "
                        "inside a W-step window), or 'link' / "
                        "'link(offset=O,period=P)' (one bit in the "
                        "in-flight interconnect buffers of a sharded "
                        "region, fired inside the send->receive window; "
                        "bare 'link' takes the region's own window).  "
                        "Colon form works too (multibit:k=3).  Recorded "
                        "in the log summary and the journal header; "
                        "resume under a different model is refused with "
                        "a typed error")
    parser.add_argument("--placement", type=str, default="compute",
                        choices=["compute", "link"],
                        help="voter placement of a sharded halo-exchange "
                        "benchmark (e.g. stencil): 'compute' votes "
                        "BEFORE the exchange (a compute flip's blast "
                        "radius is bounded to its own shard; corruption "
                        "on the link itself is the blind spot), 'link' "
                        "votes AFTER it (link corruption is repaired by "
                        "the receiver's majority; the pre-exchange pack "
                        "is a single point of failure).  Placement is "
                        "campaign identity: it joins the journal header "
                        "and resume under the other placement is "
                        "refused with a typed error")
    parser.add_argument("--equiv", action="store_true",
                        help="fault-site equivalence reduction "
                        "(analysis/equiv): statically partition the "
                        "site space into propagation classes, inject "
                        "ONE representative per class, and multiply "
                        "counts by the class weights -- the reported "
                        "distribution is over effective injections and "
                        "exactly matches the exhaustive campaign at a "
                        "fraction of the dispatches.  Seeded -t "
                        "campaigns only; single-bit fault model only")
    parser.add_argument("--delta-from", type=str, default=None,
                        metavar="JOURNAL",
                        help="delta campaign: re-inject only the "
                        "sections whose propagation fingerprint changed "
                        "since JOURNAL (a completed --equiv --journal "
                        "run of the same campaign) was written, and "
                        "splice the recorded outcomes for the rest.  A "
                        "no-op rebuild re-injects zero rows.  Implies "
                        "--equiv; incompatible journals are refused "
                        "with a typed error.  Combine with --stop-when "
                        "to convergence-bound each re-injected section "
                        "on its own (spliced sections keep their exact "
                        "recorded counts)")
    parser.add_argument("--static-budget", action="store_true",
                        help="delta campaigns: allocate the per-section "
                        "convergence budget by the static vulnerability "
                        "map (analysis/propagation) -- sdc-possible "
                        "sections re-inject first, and sections the map "
                        "proves masked/detected-bounded run under a "
                        "quartered --stop-when min floor (same per-class "
                        "thresholds, fewer physical injections).  Needs "
                        "--delta-from")
    parser.add_argument("--stratified", action="store_true",
                        help="equal-allocation sampling per section: -t "
                        "is divided across sections (floored at 1 each, "
                        "so the actual count is reported in the summary); "
                        "small sections are measured at the same "
                        "resolution as large ones")
    parser.add_argument("--log-format", type=str, default="json",
                        choices=["json", "ndjson", "columnar", "reference"],
                        help="log writer: json = reference InjectionLog "
                        "schema, ndjson/columnar = bulk formats for "
                        "10^6-run campaigns, reference = the reference "
                        "tool's own container (exec-path line + bare "
                        "array; readable by its jsonParser.py unmodified)")
    parser.add_argument("--collect", type=str, default="dense",
                        choices=["dense", "sparse"],
                        help="result-collection mode: 'dense' (default) "
                        "uploads per-batch fault arrays and fetches "
                        "every row's outcome columns; 'sparse' keeps "
                        "the loop device-resident -- flip sites "
                        "regenerate on device from the schedule seed, "
                        "per-batch accounting is a 10-int histogram, "
                        "and only the compacted interesting rows "
                        "(class outside success/corrected) cross the "
                        "host boundary.  Counts are identical at the "
                        "same seed; logs/journals record histograms + "
                        "interesting rows.  Collection mode is campaign "
                        "identity (journaled; resume under the other "
                        "mode is refused)")
    parser.add_argument("--stream-logs", action="store_true",
                        help="serialize the campaign log incrementally in "
                        "a background thread as each batch is collected "
                        "(byte-identical file to the one-shot writer), so "
                        "host serialization overlaps device dispatch "
                        "instead of following it; supports ndjson/"
                        "columnar/reference formats on the seeded -t, "
                        "--stratified, and cache-section paths")
    parser.add_argument("--mesh", type=int, default=None, metavar="N",
                        help="shard the campaign batch over the first N "
                        "devices (jax mesh + shard_map): the multi-chip "
                        "replacement for the reference's side-by-side "
                        "supervisors on disjoint port ranges; "
                        "classification counts identical to single-"
                        "device at the same seed/schedule")
    parser.add_argument("--journal", type=str, default=None,
                        help="append-only campaign journal: every "
                        "collected batch (or chunk, with -e) is fsync'd "
                        "here so a crash/SIGKILL loses nothing; relaunch "
                        "with --resume to continue at the first missing "
                        "batch with bit-identical results")
    parser.add_argument("--resume", action="store_true",
                        help="resume the campaign recorded in --journal "
                        "(header must match this invocation's program/"
                        "seed/flags; refused loudly otherwise).  Without "
                        "--resume an existing journal is an error, never "
                        "silently overwritten")
    parser.add_argument("--stop-when", type=str, default=None,
                        metavar="SPEC",
                        help="statistical early stop: comma-separated "
                        "class:half_width targets with optional ;z=Q "
                        "and ;min=N knobs (e.g. 'sdc:0.002;min=4096'). "
                        "The campaign stops dispatching once every "
                        "target class's Wilson CI half-width is at or "
                        "below its threshold; with --journal the stop "
                        "is a first-class terminal record and the "
                        "condition joins the header identity (resume "
                        "under a different condition is refused)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="serve live campaign metrics over HTTP on "
                        "127.0.0.1:PORT while the campaign runs: "
                        "/metrics is Prometheus text exposition, "
                        "/status the full JSON document (rates with "
                        "Wilson CIs, time-series rings, stage totals). "
                        "0 picks an ephemeral port (printed)")
    parser.add_argument("--status-json", type=str, default=None,
                        metavar="PATH",
                        help="mirror the live JSON status document to "
                        "PATH, atomically replaced after every "
                        "collected batch -- the headless-fleet "
                        "observation surface (a scraper never sees a "
                        "torn file)")
    parser.add_argument("--heartbeat", type=float, default=0.0,
                        metavar="SECONDS",
                        help="rate-limited one-line progress heartbeat "
                        "on stderr every SECONDS (0 disables); the "
                        "final state is always flushed, even on a "
                        "wedged campaign")
    parser.add_argument("--console", action="store_true",
                        help="live TTY dashboard on stderr (progress "
                        "bar, per-class rates with Wilson CI bars, "
                        "stage breakdown) repainted in place; replaces "
                        "the bare --heartbeat line")
    parser.add_argument("--trace-out", type=str, default=None,
                        metavar="PATH",
                        help="write the campaign's Chrome/Perfetto "
                        "trace_event JSON here at the end (per-batch "
                        "spans; on a resumed --journal campaign the "
                        "crashed run's recorded batches are included, "
                        "marked as replayed)")
    parser.add_argument("--profile", action="store_true",
                        help="per-dispatch device-time attribution: "
                        "measure each compiled batch's device-busy "
                        "duration and host-side gap (blocking-marker "
                        "timing), record the summary profile/mfu "
                        "blocks (roofline accounting), feed the "
                        "dispatch-latency histograms to --metrics-port, "
                        "and put device spans on their own --trace-out "
                        "track.  Outputs are byte-identical either way")
    parser.add_argument("--slo", type=str, default=None, metavar="SPEC",
                        help="declarative reliability SLO set evaluated "
                        "live over the campaign's own evidence, e.g. "
                        "'sdc_rate<=0.002,availability>=0.99;min=4096' "
                        "(docs/observability.md 'Reliability SLOs'): "
                        "Wilson-backed attainment, error budgets, and "
                        "burn verdicts ride /status, /metrics, the "
                        "heartbeat/console line, and summary()['slo']")
    parser.add_argument("--max-retries", type=int, default=0,
                        help="retry transient XLA/device dispatch "
                        "failures up to N times per batch (exponential "
                        "backoff + jitter); OOM degrades batch size "
                        "instead of retrying.  0 keeps failures fatal")
    parser.add_argument("--collect-timeout", type=float, default=None,
                        help="watchdog seconds on the blocking batch "
                        "fetch (device_get): a wedged batch raises "
                        "CampaignWedgedError and is re-dispatched (the "
                        "supervisor's QEMU-wedge restart analogue); "
                        "implies retries even if --max-retries is 0")
    # `-O -TMR` ergonomics: argparse eats a bare `-TMR` as an (unknown)
    # option, so the space-separated form the reference CLI uses routinely
    # would fail with "expected one argument".  Pre-join the pass flags
    # following -O/--opt-passes into `-O=<flags>` before argparse sees
    # them.  Tokens that ARE supervisor options (e.g. `-s`, which is both
    # the supervisor's section flag and the engine's segmenting flag) stop
    # the join -- those need the quoted or `=` form, as --help documents.
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    known = {s for a in parser._actions for s in a.option_strings}
    joined, i = [], 0
    while i < len(argv):
        tok = argv[i]
        if tok in ("-O", "--opt-passes") and i + 1 < len(argv):
            passes, j = [], i + 1
            while (j < len(argv) and argv[j].startswith("-")
                   and argv[j] not in known):
                passes.append(argv[j])
                j += 1
            if passes:
                joined.append(tok + "=" + " ".join(passes))
                i = j
                continue
        joined.append(tok)
        i += 1
    args = parser.parse_args(joined)

    if args.port_range is not None:
        print("Warning, --port-range/-p is deprecated and ignored: the "
              "GDB/QEMU port fabric it parceled out no longer exists "
              "(scale-out is CampaignRunner(mesh=) and the campaign "
              "fleet, python -m coast_tpu.fleet)", file=sys.stderr)
    if args.board in ("pynq", "hifive1"):
        print("This board not yet supported in this version", file=sys.stderr)
        sys.exit(-1)
    if args.stratified and (args.errorCount or args.start_num
                            or args.section in CACHE_SECTIONS):
        print("Error, --stratified cannot be combined with -e/--errorCount, "
              "--start-num, or cache sections (those draw their own "
              "schedules; strata are separately seeded streams)",
              file=sys.stderr)
        sys.exit(-1)
    if args.errorCount and args.start_num:
        # Hard error beats a silently ignored resume point: the
        # error-bounded sizing loop draws fresh per-chunk seeds, so there
        # is no single schedule stream a --start-num could index into.
        print("Error, --start-num cannot be combined with -e/--errorCount",
              file=sys.stderr)
        sys.exit(-1)
    if args.log_dir and not os.path.isdir(args.log_dir):
        print(f"Error, directory {args.log_dir} does not exist!",
              file=sys.stderr)
        sys.exit(-1)
    if args.resume and not args.journal:
        print("Error, --resume requires --journal (there is nothing to "
              "resume from)", file=sys.stderr)
        sys.exit(-1)
    if args.fault_model != "single":
        from coast_tpu.inject.schedule import FaultModel
        try:
            args.fault_model_parsed = FaultModel.parse(args.fault_model)
        except ValueError as e:
            print(f"Error, bad --fault-model: {e}", file=sys.stderr)
            sys.exit(-1)
        if args.forceBreak or args.section in CACHE_SECTIONS:
            # Forced injections name ONE site by hand; cache schedules
            # draw geometry-overlay sites outside the seeded generate()
            # paths the expansion is defined over.
            print("Error, --fault-model applies to the seeded campaign "
                  "paths (-t/-e/--stratified), not --forceBreak or cache "
                  "sections", file=sys.stderr)
            sys.exit(-1)
        if args.fault_model_parsed.kind == "link" and args.stratified:
            # Mirror schedule.generate_stratified's refusal at the CLI
            # boundary: link draws target ONLY the link-kind sections.
            print("Error, --stratified contradicts --fault-model link "
                  "(link draws target only the interconnect sections; "
                  "use the seeded -t path)", file=sys.stderr)
            sys.exit(-1)
    else:
        args.fault_model_parsed = None
    if args.stream_logs and (args.no_logging or args.errorCount
                             or args.forceBreak
                             or args.log_format == "json"):
        # -e's sizing loop runs per-chunk campaigns whose row numbering
        # restarts at 0 (the merged log is written once at the end);
        # write_json's summary-wrapped container has no streaming form.
        print("Error, --stream-logs needs a single-schedule campaign "
              "with --log-format ndjson/columnar/reference (not -e/"
              "--errorCount, --forceBreak, -q/--no-logging, or the "
              "default json format)", file=sys.stderr)
        sys.exit(-1)
    if args.delta_from:
        args.equiv = True      # fingerprints come from the partition
    if args.equiv and (args.forceBreak or args.stratified or args.errorCount
                       or args.section in CACHE_SECTIONS):
        # The partition reasons over the seeded generate() stream; the
        # sizing loop, strata, cache overlays, and forced one-offs draw
        # schedules it is not defined over.
        print("Error, --equiv/--delta-from apply to the seeded -t "
              "campaign path, not -e/--errorCount, --stratified, "
              "--forceBreak, or cache sections", file=sys.stderr)
        sys.exit(-1)
    if args.equiv and args.fault_model != "single":
        print("Error, --equiv needs the single-bit fault model (a flip "
              "group has no per-site propagation class)", file=sys.stderr)
        sys.exit(-1)
    if args.delta_from and (args.journal or args.resume
                            or args.stream_logs):
        print("Error, --delta-from reads its journal as the splice base; "
              "it cannot be combined with --journal/--resume/"
              "--stream-logs", file=sys.stderr)
        sys.exit(-1)
    if args.static_budget and not (args.delta_from and args.stop_when):
        # Without a stop condition there is no per-section budget to
        # allocate -- accepting the flag would record a static_budget
        # block for a run the allocator never shaped.
        print("Error, --static-budget allocates a delta campaign's "
              "per-section convergence budget; it needs --delta-from "
              "AND --stop-when", file=sys.stderr)
        sys.exit(-1)
    if args.collect == "sparse":
        if args.errorCount or args.forceBreak or args.delta_from:
            # -e's sizing loop journals full per-chunk columns; forced
            # injections are one-offs; delta splices exact per-row
            # records -- all inherently dense.
            print("Error, --collect sparse applies to the seeded -t/"
                  "--stratified/cache campaign paths, not -e/"
                  "--errorCount, --forceBreak, or --delta-from",
                  file=sys.stderr)
            sys.exit(-1)
        if args.stream_logs and args.log_format != "ndjson":
            print("Error, --collect sparse with --stream-logs supports "
                  "--log-format ndjson only (sparse rows have no "
                  "streaming columnar/reference form)", file=sys.stderr)
            sys.exit(-1)
        if args.log_format == "reference" and not args.no_logging:
            # The reference container is a bare InjectionLog array with
            # no summary block: a sparse log's counts live ONLY in the
            # summary, so both this repo's parser and the unmodified
            # reference jsonParser would silently summarize just the
            # interesting rows as if they were the whole campaign.
            print("Error, --collect sparse needs a summary-carrying "
                  "--log-format (json/ndjson/columnar): the reference "
                  "container has no summary block to hold the sparse "
                  "histogram counts", file=sys.stderr)
            sys.exit(-1)
    if args.stop_when:
        from coast_tpu.obs.convergence import StopWhen, StopWhenError
        if args.errorCount or args.forceBreak:
            # -e has its own stopping rule (error-bounded sizing);
            # forced injections are debug one-offs.  --delta-from IS
            # compatible: the early stop applies per re-injected
            # section (the spliced sections keep their exact recorded
            # counts and never enter a tracker).
            print("Error, --stop-when applies to the seeded/stratified/"
                  "cache/delta campaign paths, not -e/--errorCount or "
                  "--forceBreak", file=sys.stderr)
            sys.exit(-1)
        try:
            args.stop_when_parsed = StopWhen.parse(args.stop_when)
        except StopWhenError as e:
            print(f"Error, bad --stop-when: {e}", file=sys.stderr)
            sys.exit(-1)
    else:
        args.stop_when_parsed = None
    if args.journal and (args.forceBreak or args.stratified
                         or args.section in CACHE_SECTIONS):
        # Forced injections are debug one-offs; cache/stratified schedules
        # are journalable in principle but the header vocabulary (seed, n,
        # start_num) does not describe them yet -- refuse loudly rather
        # than journal something resume could misinterpret.
        print("Error, --journal supports the seeded campaign paths (-t/"
              "-e), not --forceBreak, --stratified, or cache sections",
              file=sys.stderr)
        sys.exit(-1)
    return args


def build_program(bench: str, opt_passes: str, placement: str = "compute"):
    """Build the protected program from an opt-CLI flag string, using the
    opt parser itself so flag semantics (and error behavior on typos)
    cannot drift from `python -m coast_tpu.opt`."""
    from coast_tpu import DWC, TMR, unprotected
    from coast_tpu.interface.config import ConfigError
    from coast_tpu.models import REGISTRY
    from coast_tpu.opt import UsageError, build_overrides, parse_argv
    # The reference supervisor takes the guest program by path; registry
    # names and .c source paths resolve through the shared resolver (same
    # path as `python -m coast_tpu.opt ... file.c`).
    from coast_tpu.frontend import LiftError
    from coast_tpu.models import resolve_region
    try:
        # Only sharded halo-exchange benchmarks take the voter-placement
        # knob; threading the default through every other factory would
        # turn "no such knob" into a silent no-op instead of an error.
        if placement != "compute":
            region = resolve_region(bench, placement=placement)
        else:
            region = resolve_region(bench)
    except (FileNotFoundError, KeyError):
        print(f"Error, file {bench} does not exist!", file=sys.stderr)
        sys.exit(-1)
    except TypeError:
        print(f"Error, benchmark {bench} has no --placement knob (voter "
              "placement applies to sharded halo-exchange regions, e.g. "
              "stencil)", file=sys.stderr)
        sys.exit(-1)
    except LiftError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        sys.exit(-1)
    try:
        flags, positional = parse_argv(opt_passes.split())
        if positional:
            raise UsageError(
                f"unexpected positional argument(s) in -O: {positional}")
        if flags.get("i") and flags.get("s"):
            raise UsageError("-i and -s are mutually exclusive")
        overrides = build_overrides(flags)
    except (UsageError, ConfigError) as e:
        print(f"ERROR: {e}", file=sys.stderr)
        sys.exit(-1)
    # The supervisor always wants the correction counter (it feeds the
    # 'faults' column of the logs).
    overrides["count_errors"] = True
    if flags.get("TMR"):
        return TMR(region, **overrides), "TMR"
    if flags.get("DWC"):
        return DWC(region, **overrides), "DWC"
    return unprotected(region, **overrides), "unprotected"


def section_filter(prog, section: str):
    """CLI section choice -> MemoryMap ``sections`` argument (kind names or
    leaf names), or None for the full map (cache overlays)."""
    if section in _KIND_SECTIONS:
        return _KIND_SECTIONS[section]
    if section == "stack":
        # Both stack notions qualify: -protectStack return-address copies
        # (LeafSpec.stack) and the RTOS kernel's per-task KIND_STACK
        # stacks (coast_tpu.rtos).
        from coast_tpu.ir.region import KIND_STACK
        names = [n for n, s in prog.region.spec.items()
                 if s.stack or s.kind == KIND_STACK]
        if not names:
            print(f"Error, {prog.region.name} has no stack-class leaves!",
                  file=sys.stderr)
            sys.exit(-1)
        return names
    # cache sections overlay the full map.
    return None


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_command_line(argv)

    if args.board == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from coast_tpu.inject import logs
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.hierarchy import (MemHierarchy,
                                            generate_cache_schedule)

    prog, strategy = build_program(args.filename, args.opt_passes,
                                   placement=args.placement)
    retry = None
    if args.max_retries > 0 or args.collect_timeout:
        from coast_tpu.inject.resilience import RetryPolicy
        retry = RetryPolicy(max_attempts=max(1, args.max_retries) + 1,
                            collect_timeout=args.collect_timeout)
    mesh = None
    if args.mesh:
        import jax
        from coast_tpu.parallel.mesh import make_mesh
        if args.mesh > len(jax.devices()):
            print(f"Error, --mesh {args.mesh} wants more devices than the "
                  f"backend exposes ({len(jax.devices())})", file=sys.stderr)
            return 1
        mesh = make_mesh(args.mesh)
    # Live observability surfaces: one metrics hub fed by the runner per
    # collected batch; the HTTP endpoint and the status file both read
    # from it.
    metrics = None
    server = None
    # Multi-chunk paths (-e's sizing loop, --delta-from's splice+rerun)
    # run SEVERAL run_schedule campaigns: the runner-level metrics hook
    # would reset the live surfaces to zero (and flash "finished") at
    # every chunk boundary, so those paths feed the hub through the
    # cross-chunk progress callback instead (same pattern as
    # scripts/campaign_1m.py).
    chunked = bool(args.errorCount or args.delta_from)
    slo_set = None
    if args.slo:
        from coast_tpu.obs.slo import SLOError, SLOSet
        try:
            slo_set = SLOSet.parse(args.slo)
        except SLOError as e:
            print(f"Error, bad --slo spec: {e}", file=sys.stderr)
            return 1
    if args.metrics_port is not None or args.status_json:
        from coast_tpu.obs.metrics import CampaignMetrics
        metrics = CampaignMetrics(status_path=args.status_json,
                                  slo=slo_set)
    if args.metrics_port is not None:
        from coast_tpu.obs.serve import MetricsServer
        server = MetricsServer(metrics, port=args.metrics_port)
        port = server.start()
        print(f"# metrics: http://127.0.0.1:{port}/metrics  "
              f"status: http://127.0.0.1:{port}/status",
              file=sys.stderr, flush=True)
    try:
        runner = CampaignRunner(prog,
                                sections=section_filter(prog, args.section),
                                strategy_name=strategy,
                                unroll=args.unroll,
                                retry=retry,
                                mesh=mesh,
                                fault_model=args.fault_model_parsed,
                                equiv=args.equiv,
                                metrics=None if chunked else metrics,
                                collect=args.collect,
                                profile=args.profile,
                                slo=slo_set)
    except ValueError as e:
        if args.equiv:
            print(f"Error, {e}", file=sys.stderr)
            return 1
        print(f"Error, {prog.region.name} has no injectable leaves in "
              f"section '{args.section}'!", file=sys.stderr)
        return 1
    mmap = runner.mmap

    # Pre-flight CLI copy of CampaignJournal.open(resume=False)'s
    # JournalExistsError: the library check only fires after schedule
    # generation (the header embeds the schedule fingerprint), and the
    # runner's path-argument journals auto-resume -- refuse up front so
    # a forgotten --resume cannot touch an existing journal at all.
    if args.journal and not args.resume and os.path.exists(args.journal) \
            and os.path.getsize(args.journal) > 0:
        print(f"Error, journal {args.journal} already exists; pass "
              "--resume to continue it or delete the file to start "
              "fresh", file=sys.stderr)
        return 1

    if args.forceBreak:
        # Forced injection replay (--forceBreak, supervisor.py:357-359;
        # injector.setBreaking injector.py:59-68): run the named flip
        # breakCount times.
        import jax
        from coast_tpu.opt import UsageError, _parse_inject
        try:
            fault = _parse_inject(args.forceBreak, prog)
        except (UsageError, ValueError) as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 2
        for i in range(args.breakCount):
            rec = jax.jit(prog.run)(fault)
            print(f"forced injection {i}: E: {int(rec['errors'])} "
                  f"F: {int(rec['corrected'])} T: {int(rec['steps'])} "
                  f"dwc={bool(rec['dwc_fault'])} cfc={bool(rec['cfc_fault'])}")
        return 0

    log_dir = args.log_dir or "."
    log_path = os.path.join(
        log_dir, f"{prog.region.name}_{strategy}_{args.section}.json")
    src_paths = prog.region.meta.get("source_paths")
    stream = None
    if args.stream_logs:
        # Overlapped serialization: the writer thread encodes each batch
        # as it is collected, so the log is (nearly) on disk when the
        # last batch lands -- byte-identical to the one-shot writer.
        stream = logs.StreamLogWriter(
            log_path, mmap, fmt=args.log_format,
            exec_path=(src_paths[0] if args.log_format == "reference"
                       and src_paths else None))

    # Live progress surface: the TTY dashboard (--console) or the
    # one-line heartbeat (--heartbeat).  The last beat is re-emitted
    # unconditionally in the ``finally`` below -- the terminal-flush
    # guarantee: a campaign's final state (completion, or the counts
    # standing when a CampaignWedgedError killed it) always reaches the
    # terminal, even when the rate limiter just suppressed a beat.
    beat = None
    progress = None
    last_beat = {}
    if args.console or args.heartbeat > 0:
        # Unknown-size campaigns get no percent bar: -e sizes itself as
        # it goes, and --equiv's progress counts PHYSICAL representative
        # rows (unknown until the partition reduces the schedule) while
        # -t names effective injections.
        total = 0 if (args.errorCount or args.equiv) else args.t
        if args.console:
            from coast_tpu.obs.console import Console
            beat = Console(total, interval_s=(args.heartbeat or 1.0),
                           label=f"{prog.region.name}/{strategy}",
                           metrics=metrics,
                           stop_when=args.stop_when_parsed)
        else:
            from coast_tpu.obs.heartbeat import Heartbeat
            # The hub (when armed) gives the beat the live
            # transfer-bytes counters, so the link rate is visible
            # DURING the campaign, not just in the summary.
            beat = Heartbeat(total, interval_s=args.heartbeat,
                             metrics=metrics)

        def progress(done, counts):
            last_beat["state"] = (done, counts)
            # Ambient activation so the beat's instant/gauge marks land
            # in the runner's recorder (and thus --trace-out).
            with runner.telemetry.activate():
                beat.update(done, counts)

    if metrics is not None and chunked:
        metrics.campaign_started(prog.region.name, strategy, 0, 0)
        _mrows = {"done": 0}
        _beat_progress = progress

        def progress(done, counts, _inner=_beat_progress):
            metrics.record_batch(done, max(0, done - _mrows["done"]),
                                 counts, {}, {})
            _mrows["done"] = done
            if _inner is not None:
                _inner(done, counts)

    try:
        if args.section in CACHE_SECTIONS:
            hierarchy = MemHierarchy("tpu")
            cache_name = None if args.section == "cache" else args.section
            sched = generate_cache_schedule(
                mmap, hierarchy, args.t, args.seed,
                prog.region.nominal_steps, cache_name)
            res = runner.run_schedule(
                sched, batch_size=min(args.batch_size, len(sched)),
                progress=progress, stream=stream,
                stop_when=args.stop_when_parsed)
        elif args.errorCount:
            res = runner.run_until_errors(args.errorCount, seed=args.seed,
                                          batch_size=args.batch_size,
                                          progress=progress,
                                          journal=args.journal)
        elif args.stratified:
            from coast_tpu.inject.schedule import generate_stratified_total
            sched = generate_stratified_total(mmap, args.t, args.seed,
                                              prog.region.nominal_steps,
                                              model=runner.fault_model)
            res = runner.run_schedule(
                sched, batch_size=min(args.batch_size, len(sched)),
                progress=progress, stream=stream,
                stop_when=args.stop_when_parsed)
        elif args.delta_from:
            from coast_tpu.analysis.equiv import DeltaMismatchError
            try:
                res = runner.run_delta(args.t, args.delta_from,
                                       seed=args.seed,
                                       batch_size=args.batch_size,
                                       start_num=args.start_num,
                                       progress=progress,
                                       stop_when=args.stop_when_parsed,
                                       static_budget=args.static_budget)
            except DeltaMismatchError as e:
                print(f"Error, {e}", file=sys.stderr)
                return 1
        else:
            res = runner.run(args.t, seed=args.seed,
                             batch_size=args.batch_size,
                             start_num=args.start_num, journal=args.journal,
                             stream=stream, progress=progress,
                             stop_when=args.stop_when_parsed)
    except BaseException as e:
        if stream is not None:
            stream.abort()       # never leave a half-written final log
        if metrics is not None and chunked:
            # Single-schedule paths report failure from inside
            # run_schedule; the progress-fed chunked paths do it here.
            metrics.campaign_finished(error=f"{type(e).__name__}: {e}")
        raise
    finally:
        if beat is not None and "state" in last_beat:
            with runner.telemetry.activate():
                beat.final(*last_beat["state"])
        if server is not None:
            server.stop()

    if metrics is not None and chunked:
        metrics.campaign_finished(res.summary())

    if args.trace_out:
        from coast_tpu import obs as obs_mod
        obs_mod.write_trace(
            runner.telemetry, args.trace_out,
            metadata={"benchmark": prog.region.name, "strategy": strategy,
                      "section": args.section},
            process_name=f"supervisor {prog.region.name}/{strategy}")
        print(f"# trace -> {args.trace_out} (open at ui.perfetto.dev)",
              file=sys.stderr, flush=True)

    print(res.summary())
    if not args.no_logging:
        if stream is not None:
            stream.finish(res)
        else:
            writer = {"json": logs.write_json, "ndjson": logs.write_ndjson,
                      "columnar": logs.write_columnar,
                      "reference": logs.write_reference_json
                      }[args.log_format]
            if args.log_format == "reference" and src_paths:
                # A lifted program's guest-executable line is its SOURCE
                # file (the registry fallback would name the package).
                writer(res, mmap, log_path, exec_path=src_paths[0])
            else:
                writer(res, mmap, log_path)
        print(f"wrote {log_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
