"""Campaign logs in the reference's InjectionLog JSON schema.

Each injection serialises to the dict layout of
supportClasses.InjectionLog.getDict (supportClasses.py:338-353) with a
result sub-dict whose discriminating keys match the FromDict dispatch
(supportClasses.py:355-389): "core" -> RunResult, "timeout" ->
TimeoutResult, "message" -> AbortResult, "invalid" -> InvalidResult.
jsonParser.py-style analysis therefore carries over directly
(coast_tpu.analysis.json_parser consumes the same files).
"""

from __future__ import annotations

import datetime
import json
from typing import Dict, List

from coast_tpu.inject import classify as cls
from coast_tpu.inject.campaign import CampaignResult
from coast_tpu.inject.mem import MemoryMap


def _timestamp() -> str:
    return datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S.%f")


def _result_dict(code: int, errors: int, corrected: int, steps: int,
                 ts: str) -> Dict[str, object]:
    if code in (cls.SUCCESS, cls.CORRECTED, cls.SDC):
        return {"timestamp": ts, "core": 0, "runtime": int(steps),
                "errors": int(errors), "faults": int(corrected)}
    if code == cls.DUE_ABORT:
        return {"type": "DWC/CFCSS", "message": "FAULT_DETECTED abort",
                "timestamp": ts, "errors": 1}
    if code == cls.DUE_TIMEOUT:
        return {"trap": False, "timeout": f"hit step bound at {int(steps)}",
                "timestamp": ts}
    return {"invalid": f"self-check out of domain (E={int(errors)})",
            "timestamp": ts}


def to_injection_logs(res: CampaignResult,
                      mmap: MemoryMap) -> List[Dict[str, object]]:
    ts = _timestamp()
    secs = {s.leaf_id: s for s in mmap.sections}
    logs = []
    sched = res.schedule
    for i in range(res.n):
        sec = secs[int(sched.leaf_id[i])]
        discarded = int(sched.t[i]) < 0
        if discarded:
            # Cache draw outside the program footprint: never fired (the
            # plugin's invalid-line discard); must not be attributed to a
            # real section.
            section, symbol = "cache-invalid", "<invalid-line>"
            name = f"<invalid-line>^bit{int(sched.bit[i])}"
        else:
            section, symbol = sec.kind, sec.name
            name = (f"{sec.name}[lane {int(sched.lane[i])}]"
                    f"^bit{int(sched.bit[i])}")
        logs.append({
            "timestamp": ts,
            "number": i,
            "section": section,
            "address": int(sched.word[i]),
            "oldValue": None,              # values live on-device; the flip
            "newValue": None,              # is XOR(1<<bit), recorded below
            "sleepTime": 0,
            "cycles": int(sched.t[i]),     # step index = cycle analogue
            "PC": int(sched.t[i]),
            "name": name,
            "symbol": symbol,              # clean key for per-symbol
                                           # attribution (elfUtils.py:105-176)
            "result": _result_dict(int(res.codes[i]), int(res.errors[i]),
                                   int(res.corrected[i]), int(res.steps[i]), ts),
            "cacheInfo": None,
        })
    return logs


def write_json(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Append-mode-equivalent structured log (threadFunctions.py:195-198
    flushes per injection; we flush per campaign)."""
    with open(path, "w") as f:
        json.dump({
            "summary": res.summary(),
            "runs": to_injection_logs(res, mmap),
        }, f, indent=1)
