"""Campaign logs in the reference's InjectionLog JSON schema.

Each injection serialises to the dict layout of
supportClasses.InjectionLog.getDict (supportClasses.py:338-353) with a
result sub-dict whose discriminating keys match the FromDict dispatch
(supportClasses.py:355-389): "core" -> RunResult, "timeout" ->
TimeoutResult, "message" -> AbortResult, "invalid" -> InvalidResult.

Container formats: ``write_reference_json`` emits the reference's own
file container (exec path line + bare InjectionLog array,
jsonParser.py:121-133) and is consumed by the UNMODIFIED reference
``simulation/platform/jsonParser.py`` (executed against it in
tests/test_reference_parser.py).  ``write_json`` / ``write_ndjson`` /
``write_columnar`` use repo-native containers (summary header + runs)
that only ``coast_tpu.analysis.json_parser`` reads; their per-run dicts
are FromDict-compatible, the file wrapper is not.

Throughput note: the reference logs one injection per several seconds, so
per-run Python dicts are free.  A batched campaign produces 10^6 runs in a
few seconds, so serialisation must not be the bottleneck: all per-run
columns are converted with a single C-speed ``ndarray.tolist()`` each, and
two bulk writers exist alongside the schema-compatible one --
``write_ndjson`` (one template-formatted JSON line per run) and
``write_columnar`` (one JSON doc of parallel arrays; O(1) Python objects),
both consumed by coast_tpu.analysis.json_parser.
"""

from __future__ import annotations

import contextlib
import datetime
import json
import os
import queue
import shutil
import threading
import time
from typing import Dict, List, Optional

from coast_tpu import obs
from coast_tpu.inject import classify as cls
from coast_tpu.inject.campaign import CampaignResult
from coast_tpu.inject.mem import MemoryMap


def _timestamp() -> str:
    return datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S.%f")


class _AbortWrite(Exception):
    """Internal: discard the temp file without surfacing an error (the
    native ndjson fast path bowing out mid-file)."""


def _gz_writer(raw, mode: str):
    """Deterministic gzip layer over an open binary file: no filename, no
    mtime in the member header, so the same campaign bytes compress to
    the same .gz bytes (the streamed-vs-one-shot parity tests compare
    compressed files directly).  Text modes get a TextIOWrapper whose
    close() finalises the gzip trailer but leaves ``raw`` open -- the
    caller still owns the fsync + rename."""
    import gzip
    import io
    gz = gzip.GzipFile(filename="", mode="wb", fileobj=raw, mtime=0)
    return gz if "b" in mode else io.TextIOWrapper(gz)


@contextlib.contextmanager
def _atomic_write(path: str, mode: str = "w"):
    """Crash-safe log writing: serialize into a same-directory temp file
    and ``os.replace`` it into place only when complete, so a crash (or
    SIGKILL) mid-serialize never leaves a truncated log that json_parser
    chokes on -- readers see either the old file or the whole new one.
    Any exception from the body discards the temp file and propagates
    (:class:`_AbortWrite` included -- callers catch it).

    A ``.gz`` path transparently gzip-compresses the body (deterministic
    header; analysis/json_parser decompresses just as transparently) --
    one extension flip turns a 347 MB campaign ndjson into its
    compressed form with no call-site changes."""
    tmp = f"{path}.tmp.{os.getpid()}"
    gzipped = path.endswith(".gz")
    raw = open(tmp, "wb" if gzipped else mode)
    f = _gz_writer(raw, mode) if gzipped else raw
    try:
        yield f
        f.flush()
        if f is not raw:
            f.close()          # gzip trailer; GzipFile leaves raw open
        raw.flush()
        os.fsync(raw.fileno())
        raw.close()
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError, ValueError):
            if f is not raw:
                f.close()
        with contextlib.suppress(OSError):
            raw.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


@contextlib.contextmanager
def _serialize_stage(res: CampaignResult, writer: str, path: str):
    """Bill a writer's wall-clock to the campaign's 'serialize' stage
    (and to the ambient telemetry's timeline, for trace export).  The
    campaign object exists before any log is written, so serialization
    lands in ``res.stages`` after the fact via record_stage.

    Recording follows the telemetry on/off knob: bill only when the
    campaign recorded stages (its runner's telemetry was on) or an
    enabled ambient recorder is active -- otherwise a disabled-telemetry
    campaign would end up with a stages block containing *only*
    serialize, reading as ~100% of a pipeline that was never timed."""
    with obs.span("serialize", writer=writer, path=path):
        t0 = time.perf_counter()
        yield
        if res.stages or obs.current().enabled:
            res.record_stage("serialize", time.perf_counter() - t0)


def _result_dict(code: int, errors: int, corrected: int, steps: int,
                 ts: str) -> Dict[str, object]:
    if code in (cls.SUCCESS, cls.CORRECTED, cls.SDC):
        return {"timestamp": ts, "core": 0, "runtime": int(steps),
                "errors": int(errors), "faults": int(corrected)}
    if code == cls.DUE_ABORT:
        return {"type": "DWC/CFCSS", "message": "FAULT_DETECTED abort",
                "timestamp": ts, "errors": 1}
    if code == cls.DUE_TIMEOUT:
        return {"trap": False, "timeout": f"hit step bound at {int(steps)}",
                "timestamp": ts}
    if code == cls.DUE_STACK_OVERFLOW:
        # StackOverflowResult class: the guest's FreeRTOS hook line names
        # the overflowing task (decoder.py:69); the batched campaign
        # records which step the kernel's check tripped at instead.
        return {"stackOverflow": f"stack check tripped at step {int(steps)}",
                "taskName": "<kernel>", "timestamp": ts, "errors": 1}
    if code == cls.DUE_ASSERT:
        # AssertionFailResult class (decoder.py:67 configASSERT line).
        return {"assertion": f"kernel assert tripped at step {int(steps)}",
                "timestamp": ts, "errors": 1}
    if code == cls.TRAIN_SELF_HEAL:
        # Completed training run whose weights differ bit-for-bit from
        # the golden trajectory (errors > 0) but whose loss re-converged
        # within the heal window: the discriminating "selfHeal" key
        # rides alongside the ordinary RunResult fields so runtime/
        # error accounting works unchanged.
        return {"selfHeal": f"transient loss perturbation healed "
                            f"(E={int(errors)})",
                "timestamp": ts, "core": 0, "runtime": int(steps),
                "errors": int(errors), "faults": int(corrected)}
    if code == cls.TRAIN_SDC:
        # Persistent silent training corruption: final weights AND loss
        # diverged from the fault-free trajectory.
        return {"trainSdc": f"persistent weight corruption "
                            f"(E={int(errors)})",
                "timestamp": ts, "core": 0, "runtime": int(steps),
                "errors": int(errors), "faults": int(corrected)}
    return {"invalid": f"self-check out of domain (E={int(errors)})",
            "timestamp": ts}


def _columns(res: CampaignResult, mmap: MemoryMap):
    """Per-run columns as plain Python lists (one C-speed conversion each).

    Sparse-collect campaigns (``res.collect == "sparse"``) have per-run
    columns only for their INTERESTING rows: the site columns come from
    the host schedule at ``res.interesting_rows`` and an explicit
    ``number`` column carries each row's absolute injection number --
    the class totals live in the summary's histogram-derived counts, not
    in the rows."""
    secs = {s.leaf_id: s for s in mmap.sections}
    sched = res.schedule
    if res.collect != "dense":
        from coast_tpu.inject.campaign import _rows_subset
        sched = _rows_subset(sched, res.interesting_rows)
    col = {
        "leaf_id": sched.leaf_id.tolist(),
        "lane": sched.lane.tolist(),
        "word": sched.word.tolist(),
        "bit": sched.bit.tolist(),
        "t": sched.t.tolist(),
        "code": res.codes.tolist(),
        "errors": res.errors.tolist(),
        "corrected": res.corrected.tolist(),
        "steps": res.steps.tolist(),
    }
    # Equivalence-reduced campaigns (analysis/equiv): each row is a
    # class representative; the weight column lets json_parser multiply
    # counts back out to effective injections.  Exhaustive campaigns
    # omit the key, keeping their logs byte-identical to before the
    # pass existed (the fault-model rule).
    if getattr(sched, "class_weight", None) is not None:
        col["weight"] = sched.class_weight.tolist()
    if res.collect != "dense":
        col["number"] = [int(r) for r in res.interesting_rows]
    return col, secs


def _batch_columns(part, out: Dict[str, "np.ndarray"]):
    """Per-run columns of ONE collected batch as plain Python lists: the
    schedule slice supplies where/when, the collected ``out`` dict the
    outcome columns.  The streaming writer's unit of work."""
    col = {
        "leaf_id": part.leaf_id.tolist(),
        "lane": part.lane.tolist(),
        "word": part.word.tolist(),
        "bit": part.bit.tolist(),
        "t": part.t.tolist(),
        "code": out["code"].tolist(),
        "errors": out["errors"].tolist(),
        "corrected": out["corrected"].tolist(),
        "steps": out["steps"].tolist(),
    }
    if getattr(part, "class_weight", None) is not None:
        col["weight"] = part.class_weight.tolist()
    return col


def _injection_log_rows(col, sec_kind: Dict[int, str],
                        sec_name: Dict[int, str], ts: str,
                        num0: int = 0) -> List[Dict[str, object]]:
    """InjectionLog dicts for the rows of ``col`` (plain-list columns),
    numbered ``num0``...: the one formatting loop behind the one-shot
    ``to_injection_logs`` AND the streaming reference writer, so the two
    cannot drift."""
    logs = []
    weights = col.get("weight")
    numbers = col.get("number")
    for i in range(len(col["code"])):
        lid = col["leaf_id"][i]
        t_i = col["t"][i]
        if t_i < 0:
            # Cache draw outside the program footprint: never fired (the
            # plugin's invalid-line discard); must not be attributed to a
            # real section.
            section, symbol = "cache-invalid", "<invalid-line>"
            name = f"<invalid-line>^bit{col['bit'][i]}"
        else:
            section, symbol = sec_kind[lid], sec_name[lid]
            name = f"{sec_name[lid]}[lane {col['lane'][i]}]^bit{col['bit'][i]}"
        row = {
            "timestamp": ts,
            "number": numbers[i] if numbers is not None else num0 + i,
            "section": section,
            "address": col["word"][i],
            "oldValue": None,              # values live on-device; the flip
            "newValue": None,              # is XOR(1<<bit), recorded below
            "sleepTime": 0,
            "cycles": t_i,                 # step index = cycle analogue
            "PC": t_i,
            "name": name,
            "symbol": symbol,              # clean key for per-symbol
                                           # attribution (elfUtils.py:105-176)
            "result": _result_dict(col["code"][i], col["errors"][i],
                                   col["corrected"][i], col["steps"][i], ts),
            "cacheInfo": None,
        }
        if weights is not None:
            # Class-representative row of an equivalence-reduced
            # campaign: stands for this many physical draws.
            row["weight"] = weights[i]
        logs.append(row)
    return logs


def to_injection_logs(res: CampaignResult,
                      mmap: MemoryMap) -> List[Dict[str, object]]:
    ts = _timestamp()
    col, secs = _columns(res, mmap)
    sec_kind = {lid: s.kind for lid, s in secs.items()}
    sec_name = {lid: s.name for lid, s in secs.items()}
    return _injection_log_rows(col, sec_kind, sec_name, ts)


def _escaped_leaf_tables(mmap: MemoryMap):
    """Per-leaf (kind, name) string tables, JSON-escaped once per campaign
    for the native encoder (which only formats numbers).  None when the
    map has no sections -- callers fall back to the Python formatter."""
    secs = {s.leaf_id: s for s in mmap.sections}
    if not secs:
        return None
    n_leaves = max(secs) + 1
    kind_by_leaf = ["" for _ in range(n_leaves)]
    name_by_leaf = ["" for _ in range(n_leaves)]
    for lid, s in secs.items():
        kind_by_leaf[lid] = json.dumps(s.kind)[1:-1]
        name_by_leaf[lid] = json.dumps(s.name)[1:-1]
    return kind_by_leaf, name_by_leaf


def _ndjson_try_native(res: CampaignResult, mmap: MemoryMap, ts: str,
                       path: str) -> bool:
    """Write the whole ndjson log (summary line + streamed rows) via the
    native encoder; False means the native core is unavailable and the
    caller should run the Python formatter.  Strings are JSON-escaped
    here, once per section -- the native pass only formats numbers."""
    from coast_tpu import native
    if not native.native_available():
        return False
    sched = res.schedule
    if getattr(sched, "class_weight", None) is not None:
        # Equivalence-reduced rows carry a weight key the native encoder
        # does not know; the Python formatter owns them.
        return False
    if res.collect != "dense":
        # Sparse rows carry non-consecutive injection numbers the native
        # encoder cannot produce; the (small) interesting-row set is the
        # Python formatter's.
        return False
    tables = _escaped_leaf_tables(mmap)
    if tables is None:
        return False
    kind_by_leaf, name_by_leaf = tables
    col = {"leaf_id": sched.leaf_id, "lane": sched.lane, "word": sched.word,
           "bit": sched.bit, "t": sched.t, "code": res.codes,
           "errors": res.errors, "corrected": res.corrected,
           "steps": res.steps}
    try:
        with _atomic_write(path, "wb") as f:
            f.write((json.dumps({"summary": {**res.summary(),
                                             "format": "ndjson"}})
                     + "\n").encode())
            if not native.ndjson_stream_rows(0, res.n, col, kind_by_leaf,
                                             name_by_leaf, ts, f.write):
                # Native core bowed out mid-file: discard the temp file
                # (never a half-written log) and fall back to Python.
                raise _AbortWrite
    except _AbortWrite:
        return False
    return True


def write_reference_json(res: CampaignResult, mmap: MemoryMap, path: str,
                         exec_path: str = None) -> None:
    """Campaign log in the reference tool's OWN container: line 1 names
    the protected program (the guest-executable line; readJsonFile
    refuses the file when that path does not exist on disk,
    jsonParser.py:121-133), followed by one JSON array of InjectionLog
    dicts.  The reference's simulation/platform/jsonParser.py -- not a
    reimplementation -- parses these files directly, so its summary,
    compare-files/-dirs, and MWTF reports run unmodified on campaigns
    from this engine.  ``exec_path`` defaults to the benchmark's model
    module (models.model_source).

    Known reference-tool limitation (theirs, not this writer's): its
    otherStats takes statistics.mean over fully-clean runs and raises
    StatisticsError on a campaign with zero successes (e.g. a small TMR
    campaign where every injection was corrected); its own QEMU
    campaigns always contain clean runs, so the path was never guarded."""
    if res.collect != "dense":
        raise ValueError(
            "write_reference_json needs a dense result: the reference "
            "container is a bare InjectionLog array with no summary "
            "block, so a sparse campaign's histogram counts would be "
            "silently lost (readers would summarize only the "
            "interesting rows)")
    if exec_path is None:
        from coast_tpu.models import model_source
        exec_path = model_source(res.benchmark)
    exec_path = os.path.realpath(exec_path)
    if not os.path.exists(exec_path):
        raise FileNotFoundError(
            f"exec_path {exec_path!r} does not exist; the reference's "
            "readJsonFile exits on logs whose line-1 path is missing")
    with _serialize_stage(res, "reference_json", path):
        with _atomic_write(path) as f:
            f.write(exec_path + "\n")
            json.dump(to_injection_logs(res, mmap), f, indent=1)


def write_json(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Reference-schema structured log (threadFunctions.py:195-198 flushes
    per injection; we flush per campaign)."""
    with _serialize_stage(res, "json", path):
        with _atomic_write(path) as f:
            json.dump({
                "summary": res.summary(),
                "runs": to_injection_logs(res, mmap),
            }, f, indent=1)


def write_ndjson(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Newline-delimited bulk log: line 1 is the campaign summary (with a
    ``"format": "ndjson"`` marker), each following line one run in the
    InjectionLog schema.  The row formatting is delegated to the native
    C++ encoder (coast_ndjson_encode) when available -- one C pass over
    the columns -- with this function's template loop as the bit-identical
    Python fallback, so a 10^6-run campaign serialises in well under a
    second natively and in seconds otherwise.

    The stage accounting (res.stages['serialize']) is recorded *after*
    the write, so the summary line inside the file reflects the stages
    known before this serialization -- the serialize stage of a log file
    describes earlier writers, not itself."""
    ts = _timestamp()
    with _serialize_stage(res, "ndjson", path):
        if _ndjson_try_native(res, mmap, ts, path):
            return
        _write_ndjson_py(res, mmap, ts, path)


def _ndjson_templates(ts: str):
    """(result templates by class code, line template) for the Python
    ndjson formatter -- one compile per campaign, shared by the one-shot
    writer and the streaming writer's fallback path."""
    # One result template per class, mirroring _result_dict (timestamps
    # identical across the campaign, as with write_json).
    run_tpl = ('{"timestamp": "%s", "core": 0, "runtime": %%(steps)d, '
               '"errors": %%(errors)d, "faults": %%(faults)d}' % ts)
    res_tpl = {
        cls.SUCCESS: run_tpl,
        cls.CORRECTED: run_tpl,
        cls.SDC: run_tpl,
        cls.DUE_ABORT: ('{"type": "DWC/CFCSS", "message": "FAULT_DETECTED '
                        'abort", "timestamp": "%s", "errors": 1}' % ts),
        cls.DUE_TIMEOUT: ('{"trap": false, "timeout": "hit step bound at '
                          '%%(steps)d", "timestamp": "%s"}' % ts),
        cls.INVALID: ('{"invalid": "self-check out of domain '
                      '(E=%%(errors)d)", "timestamp": "%s"}' % ts),
        cls.DUE_STACK_OVERFLOW: (
            '{"stackOverflow": "stack check tripped at step %%(steps)d", '
            '"taskName": "<kernel>", "timestamp": "%s", "errors": 1}' % ts),
        cls.DUE_ASSERT: (
            '{"assertion": "kernel assert tripped at step %%(steps)d", '
            '"timestamp": "%s", "errors": 1}' % ts),
        cls.TRAIN_SELF_HEAL: (
            '{"selfHeal": "transient loss perturbation healed '
            '(E=%%(errors)d)", "timestamp": "%s", "core": 0, '
            '"runtime": %%(steps)d, "errors": %%(errors)d, '
            '"faults": %%(faults)d}' % ts),
        cls.TRAIN_SDC: (
            '{"trainSdc": "persistent weight corruption (E=%%(errors)d)", '
            '"timestamp": "%s", "core": 0, "runtime": %%(steps)d, '
            '"errors": %%(errors)d, "faults": %%(faults)d}' % ts),
    }
    line_tpl = (
        '{"timestamp": "%s", "number": %%(i)d, "section": "%%(section)s", '
        '"address": %%(word)d, "oldValue": null, "newValue": null, '
        '"sleepTime": 0, "cycles": %%(t)d, "PC": %%(t)d, '
        '"name": "%%(name)s", "symbol": "%%(symbol)s", '
        '"result": %%(result)s, "cacheInfo": null}' % ts)
    return res_tpl, line_tpl


def _ndjson_rows_py(col, sec_kind: Dict[int, str], sec_name: Dict[int, str],
                    ts: str, num0: int, write) -> None:
    """Python template formatter for ndjson rows of ``col`` (plain-list
    columns), numbered ``num0``...; one ``write(str)`` per line.  Shared
    by the one-shot writer (num0=0, full columns) and the streaming
    writer (per-batch columns), byte-identical by construction."""
    res_tpl, line_tpl = _ndjson_templates(ts)
    weights = col.get("weight")
    numbers = col.get("number")
    for i in range(len(col["code"])):
        lid = col["leaf_id"][i]
        t_i = col["t"][i]
        if t_i < 0:
            section, symbol = "cache-invalid", "<invalid-line>"
            name = f"<invalid-line>^bit{col['bit'][i]}"
        else:
            section, symbol = sec_kind[lid], sec_name[lid]
            name = (f"{sec_name[lid]}[lane {col['lane'][i]}]"
                    f"^bit{col['bit'][i]}")
        result = res_tpl[col["code"][i]] % {
            "errors": col["errors"][i], "faults": col["corrected"][i],
            "steps": col["steps"][i]}
        # json.dumps on the string fields: leaf names are arbitrary
        # author-chosen strings and must be JSON-escaped.
        line = line_tpl % {
            "i": numbers[i] if numbers is not None else num0 + i,
            "section": json.dumps(section)[1:-1],
            "word": col["word"][i], "t": t_i,
            "name": json.dumps(name)[1:-1],
            "symbol": json.dumps(symbol)[1:-1],
            "result": result}
        if weights is not None:
            # Reduced-campaign representative: splice the weight before
            # the closing brace (exhaustive lines stay byte-identical).
            line = f'{line[:-1]}, "weight": {weights[i]}}}'
        write(line + "\n")


def _write_ndjson_py(res: CampaignResult, mmap: MemoryMap, ts: str,
                     path: str) -> None:
    col, secs = _columns(res, mmap)
    sec_kind = {lid: s.kind for lid, s in secs.items()}
    sec_name = {lid: s.name for lid, s in secs.items()}
    with _atomic_write(path) as f:
        f.write(json.dumps({"summary": {**res.summary(),
                                        "format": "ndjson"}}) + "\n")
        _ndjson_rows_py(col, sec_kind, sec_name, ts, 0, f.write)


#: Column order of _columns / write_columnar; the streaming columnar
#: assembly must emit keys in exactly this order for byte-identity.
_COLUMN_KEYS = ("leaf_id", "lane", "word", "bit", "t",
                "code", "errors", "corrected", "steps")


class StreamLogWriter:
    """Overlapped campaign-log serialization: the one-shot writers' output,
    produced incrementally while the campaign is still dispatching.

    The 10^6-injection TPU rerun spent 6.9 s serializing 347 MB of ndjson
    *after* 3.6 s of run time (docs/perf.md): host serialization was the
    pipeline's standing bottleneck because it strictly followed the
    device work.  This writer restructures the hot path to
    ``max(device, host)``: ``CampaignRunner.run_schedule(stream=...)``
    hands every collected batch to a background thread that serializes
    it immediately -- rows via the native per-batch encoder
    (``coast_ndjson_encode_rows``) when available -- so by the time the
    last batch is collected, nearly the whole log is already on disk.

    Guarantees:

    * **Byte-identical output** to the one-shot writer of the same
      format (``write_ndjson`` / ``write_columnar`` /
      ``write_reference_json``) for the same campaign result -- pinned
      by tests/test_stream_logs.py for the native and Python paths.
    * **Journal composition**: a journal-resumed campaign feeds its
      replayed batches from disk through the same path, so the resumed
      stream file equals the uninterrupted run's (the batch columns come
      from the journal; no re-dispatch).
    * **Atomicity**: rows accumulate in a same-directory temp file; the
      final file appears only via ``os.replace`` at :meth:`finish`
      (``.gz`` paths compress at finish, trading that overlap for size).

    Accounting: ``finish`` bills the campaign's ``stages`` block with
    ``serialize`` = the *non-overlapped* wall clock (feed stalls + the
    finish-side drain/assemble) and ``overlap`` = the fraction of total
    serialization work that ran concurrently with dispatch.
    """

    FORMATS = ("ndjson", "columnar", "reference")

    def __init__(self, path: str, mmap: MemoryMap, fmt: str = "ndjson",
                 exec_path: Optional[str] = None, queue_batches: int = 8):
        if fmt not in self.FORMATS:
            raise ValueError(f"unknown stream log format {fmt!r}; "
                             f"one of {self.FORMATS}")
        self.path = path
        self.fmt = fmt
        self._secs = {s.leaf_id: s for s in mmap.sections}
        self._sec_kind = {lid: s.kind for lid, s in self._secs.items()}
        self._sec_name = {lid: s.name for lid, s in self._secs.items()}
        self._tables = _escaped_leaf_tables(mmap)
        self._exec_path = exec_path
        if exec_path is not None and not os.path.exists(exec_path):
            raise FileNotFoundError(
                f"exec_path {exec_path!r} does not exist; the reference's "
                "readJsonFile exits on logs whose line-1 path is missing")
        # Bounded queue: feed() blocks when the writer falls this many
        # batches behind -- that stall is the honest non-overlapped
        # serialize cost, and it caps resident batch memory.
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, queue_batches))
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._use_native: Optional[bool] = None
        self._ts: Optional[str] = None
        self._rows_tmp = f"{path}.rows.{os.getpid()}"
        self._rows_f = None
        self._frags: Dict[str, List[str]] = {k: [] for k in _COLUMN_KEYS}
        self._expected = 0          # next row number feed() must supply
        self._bg_busy = 0.0         # background serialization seconds
        self._blocked = 0.0         # main-thread seconds stalled on feed
        self._finished = False
        self._sparse = False        # armed by the first feed_sparse()

    # -- lifecycle -----------------------------------------------------------
    def begin(self) -> None:
        """Open the rows temp file and start the writer thread.  Idempotent;
        ``feed`` calls it lazily on the first batch."""
        if self._thread is not None:
            return
        if self._finished:
            raise RuntimeError("StreamLogWriter already finished/aborted")
        self._ts = _timestamp()
        if self.fmt in ("ndjson", "reference"):
            self._rows_f = open(self._rows_tmp, "wb")
        self._thread = threading.Thread(target=self._worker,
                                        name="coast-stream-log",
                                        daemon=True)
        self._thread.start()

    def feed(self, num0: int, part, out: Dict[str, object]) -> None:
        """Hand one collected batch to the writer: ``part`` is the batch's
        FaultSchedule slice (where/when), ``out`` the trimmed outcome
        columns, ``num0`` the batch's first global row number.  Batches
        must arrive in order with no gaps -- exactly how
        ``run_schedule`` collects them."""
        if self._finished:
            # Without this guard a feed after finish()/abort() would
            # enqueue into the exited worker's queue -- the first
            # queue_batches feeds silently vanish, the next blocks
            # forever on the bounded put.
            raise RuntimeError("StreamLogWriter already finished/aborted")
        if self._exc is not None:
            raise RuntimeError(
                f"stream log writer for {self.path!r} failed"
            ) from self._exc
        self.begin()
        n = len(out["code"])
        if len(part) != n:
            raise ValueError(f"schedule slice ({len(part)} rows) does not "
                             f"match batch columns ({n} rows)")
        if num0 != self._expected:
            raise ValueError(
                f"stream feed out of order: got rows [{num0}, {num0 + n}) "
                f"but expected the stream to continue at {self._expected}")
        self._expected += n
        if n == 0:
            return
        t0 = time.perf_counter()
        self._q.put((num0, part, out))
        self._blocked += time.perf_counter() - t0

    def feed_sparse(self, numbers, part, out: Dict[str, object]) -> None:
        """Hand one sparse-collect batch's INTERESTING rows to the
        writer: ``numbers`` are the rows' absolute injection numbers
        (non-contiguous by construction), ``part`` the schedule subset
        at those rows, ``out`` their outcome columns.  ndjson only --
        the columnar/reference containers have no sparse row form."""
        if self.fmt != "ndjson":
            raise ValueError(
                "sparse streams support the ndjson format only (got "
                f"{self.fmt!r}); columnar/reference sparse logs are "
                "one-shot writers")
        if self._finished:
            raise RuntimeError("StreamLogWriter already finished/aborted")
        if self._exc is not None:
            raise RuntimeError(
                f"stream log writer for {self.path!r} failed"
            ) from self._exc
        self._sparse = True
        self.begin()
        n = len(out["code"])
        if len(part) != n or len(numbers) != n:
            raise ValueError(
                f"sparse feed shape mismatch: {len(numbers)} numbers, "
                f"{len(part)} schedule rows, {n} outcome rows")
        self._expected += n
        if n == 0:
            return
        t0 = time.perf_counter()
        self._q.put(([int(r) for r in numbers], part, out))
        self._blocked += time.perf_counter() - t0

    def finish(self, res: CampaignResult) -> None:
        """Drain the writer, assemble the final file atomically, and bill
        the campaign's stage block (``serialize`` non-overlapped seconds
        + ``overlap`` fraction).  ``res`` is the completed campaign the
        stream's batches came from -- its summary becomes the file
        header, exactly as the one-shot writer would emit it."""
        if self._finished:
            raise RuntimeError("StreamLogWriter already finished/aborted")
        self.begin()                # an empty campaign still gets a file
        t_fin0 = time.perf_counter()
        self._q.put(None)
        self._thread.join()
        self._finished = True
        if self._exc is not None:
            self._cleanup()
            raise RuntimeError(
                f"stream log writer for {self.path!r} failed"
            ) from self._exc
        if res.collect != "dense":
            # Sparse streams carry exactly the interesting rows.
            rows = len(res.codes)
        else:
            rows = res.physical_n if res.physical_n is not None else res.n
        if rows != self._expected:
            self._cleanup()
            raise ValueError(
                f"stream received {self._expected} rows but the campaign "
                f"result records {rows}; refusing to write a log that "
                "does not match its summary")
        try:
            with obs.span("serialize", writer=f"stream_{self.fmt}",
                          path=self.path):
                t_asm0 = time.perf_counter()
                self._assemble(res)
                asm = time.perf_counter() - t_asm0
        finally:
            self._cleanup()
        fin = time.perf_counter() - t_fin0
        blocking = self._blocked + fin
        work = self._bg_busy + asm
        if res.stages or obs.current().enabled:
            res.record_stage("serialize", blocking)
            res.stages["overlap"] = (
                round(max(0.0, 1.0 - blocking / work), 4) if work > 0
                else 0.0)

    def abort(self) -> None:
        """Discard the stream (campaign failed / interrupted): stop the
        thread and remove the temp files.  The final path is never
        touched.  Safe to call at any point, including twice."""
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join()
        self._finished = True
        self._cleanup()

    def __enter__(self) -> "StreamLogWriter":
        self.begin()
        return self

    def __exit__(self, exc_type, *exc) -> None:
        # Context-manager convenience for error paths only: a normal exit
        # still requires an explicit finish(res) (the writer cannot know
        # the campaign result); an exceptional exit aborts.
        if exc_type is not None and not self._finished:
            self.abort()

    def _cleanup(self) -> None:
        if self._rows_f is not None:
            with contextlib.suppress(OSError):
                self._rows_f.close()
            self._rows_f = None
        with contextlib.suppress(OSError):
            os.unlink(self._rows_tmp)
        self._frags = {k: [] for k in _COLUMN_KEYS}

    # -- background serialization --------------------------------------------
    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if self._exc is not None:
                continue            # drain so feeders never deadlock
            t0 = time.perf_counter()
            try:
                self._serialize_batch(*item)
            except BaseException as e:  # noqa: BLE001 - surfaced at feed
                self._exc = e
            finally:
                self._bg_busy += time.perf_counter() - t0

    def _serialize_batch(self, num0: int, part, out) -> None:
        if self.fmt == "ndjson":
            if self._sparse:
                # Non-contiguous injection numbers: the Python formatter
                # with an explicit number column (interesting rows are
                # few by construction).
                col = _batch_columns(part, out)
                col["number"] = num0     # the feed's numbers list
                _ndjson_rows_py(col, self._sec_kind, self._sec_name,
                                self._ts, 0,
                                lambda s: self._rows_f.write(s.encode()))
                return
            if (self._use_native is not False and self._tables is not None
                    and getattr(part, "class_weight", None) is None):
                from coast_tpu import native
                col = {"leaf_id": part.leaf_id, "lane": part.lane,
                       "word": part.word, "bit": part.bit, "t": part.t,
                       "code": out["code"], "errors": out["errors"],
                       "corrected": out["corrected"],
                       "steps": out["steps"]}
                if native.ndjson_stream_batch(num0, col, self._tables[0],
                                              self._tables[1], self._ts,
                                              self._rows_f.write):
                    self._use_native = True
                    return
            # Decided once: a campaign's rows all come from one formatter.
            self._use_native = False
            col = _batch_columns(part, out)
            _ndjson_rows_py(col, self._sec_kind, self._sec_name, self._ts,
                            num0, lambda s: self._rows_f.write(s.encode()))
        elif self.fmt == "columnar":
            col = _batch_columns(part, out)
            for k in col:           # _COLUMN_KEYS (+ weight when reduced)
                self._frags.setdefault(k, []).append(
                    ", ".join(map(str, col[k])))
        else:                                   # reference
            col = _batch_columns(part, out)
            rows = _injection_log_rows(col, self._sec_kind, self._sec_name,
                                       self._ts, num0)
            text = json.dumps(rows, indent=1)
            # json.dumps(rows, indent=1) == "[\n" + elements + "\n]";
            # strip the brackets and join batches with ",\n" so the
            # concatenation equals json.dump over the whole list.
            inner = text[2:-2]
            if num0 > 0:
                self._rows_f.write(b",\n")
            self._rows_f.write(inner.encode())

    def _splice_rows(self, f) -> None:
        """Copy the accumulated rows file into the final file at the
        current position -- kernel-side (``os.sendfile``) for plain
        binary targets, userspace for ``.gz`` (the bytes must pass
        through the compressor)."""
        self._rows_f.flush()
        with open(self._rows_tmp, "rb") as rf:
            if not self.path.endswith(".gz"):
                f.flush()
                size = os.fstat(rf.fileno()).st_size
                off = 0
                try:
                    while off < size:
                        sent = os.sendfile(f.fileno(), rf.fileno(), off,
                                           size - off)
                        if sent == 0:
                            break
                        off += sent
                except OSError:
                    pass              # cross-device/FS refusal: userspace
                if off >= size:
                    return
                rf.seek(off)
            shutil.copyfileobj(rf, f, 1 << 20)

    # -- final assembly ------------------------------------------------------
    def _assemble(self, res: CampaignResult) -> None:
        if self.fmt == "ndjson":
            with _atomic_write(self.path, "wb") as f:
                f.write((json.dumps({"summary": {**res.summary(),
                                                 "format": "ndjson"}})
                         + "\n").encode())
                self._splice_rows(f)
        elif self.fmt == "columnar":
            # Byte-for-byte the json.dump(...) of write_columnar: same
            # top-level key order, default separators, list items joined
            # ", " -- with the column bodies spliced from the per-batch
            # fragments instead of materialised lists.
            sections = [{"leaf_id": s.leaf_id, "name": s.name,
                         "kind": s.kind, "lanes": s.lanes,
                         "words": s.words} for s in self._secs.values()]
            keys = list(_COLUMN_KEYS)
            if "weight" in self._frags:
                keys.append("weight")   # matches _columns' insertion order
            with _atomic_write(self.path) as f:
                f.write('{"summary": ')
                json.dump({**res.summary(), "format": "columnar"}, f)
                f.write(', "sections": ')
                json.dump(sections, f)
                f.write(', "columns": {')
                for j, k in enumerate(keys):
                    f.write(('' if j == 0 else ', ') + f'"{k}": [')
                    f.write(", ".join(frag for frag in self._frags[k]))
                    f.write(']')
                f.write('}}')
        else:                                   # reference
            exec_path = self._exec_path
            if exec_path is None:
                from coast_tpu.models import model_source
                exec_path = model_source(res.benchmark)
            exec_path = os.path.realpath(exec_path)
            if not os.path.exists(exec_path):
                raise FileNotFoundError(
                    f"exec_path {exec_path!r} does not exist; the "
                    "reference's readJsonFile exits on logs whose line-1 "
                    "path is missing")
            with _atomic_write(self.path, "wb") as f:
                f.write((exec_path + "\n").encode())
                if self._expected == 0:
                    f.write(b"[]")
                else:
                    f.write(b"[\n")
                    self._splice_rows(f)
                    f.write(b"\n]")


def write_columnar(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Columnar bulk log: the whole campaign as parallel arrays plus the
    section table -- O(1) Python objects regardless of campaign size, and
    the natural format for numpy-side analysis.  json_parser summarises it
    directly without materialising per-run dicts."""
    with _serialize_stage(res, "columnar", path):
        col, secs = _columns(res, mmap)
        with _atomic_write(path) as f:
            json.dump({
                "summary": {**res.summary(), "format": "columnar"},
                "sections": [{"leaf_id": s.leaf_id, "name": s.name,
                              "kind": s.kind, "lanes": s.lanes,
                              "words": s.words}
                             for s in secs.values()],
                "columns": col,
            }, f)
