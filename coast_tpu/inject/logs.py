"""Campaign logs in the reference's InjectionLog JSON schema.

Each injection serialises to the dict layout of
supportClasses.InjectionLog.getDict (supportClasses.py:338-353) with a
result sub-dict whose discriminating keys match the FromDict dispatch
(supportClasses.py:355-389): "core" -> RunResult, "timeout" ->
TimeoutResult, "message" -> AbortResult, "invalid" -> InvalidResult.

Container formats: ``write_reference_json`` emits the reference's own
file container (exec path line + bare InjectionLog array,
jsonParser.py:121-133) and is consumed by the UNMODIFIED reference
``simulation/platform/jsonParser.py`` (executed against it in
tests/test_reference_parser.py).  ``write_json`` / ``write_ndjson`` /
``write_columnar`` use repo-native containers (summary header + runs)
that only ``coast_tpu.analysis.json_parser`` reads; their per-run dicts
are FromDict-compatible, the file wrapper is not.

Throughput note: the reference logs one injection per several seconds, so
per-run Python dicts are free.  A batched campaign produces 10^6 runs in a
few seconds, so serialisation must not be the bottleneck: all per-run
columns are converted with a single C-speed ``ndarray.tolist()`` each, and
two bulk writers exist alongside the schema-compatible one --
``write_ndjson`` (one template-formatted JSON line per run) and
``write_columnar`` (one JSON doc of parallel arrays; O(1) Python objects),
both consumed by coast_tpu.analysis.json_parser.
"""

from __future__ import annotations

import contextlib
import datetime
import json
import os
import time
from typing import Dict, List

from coast_tpu import obs
from coast_tpu.inject import classify as cls
from coast_tpu.inject.campaign import CampaignResult
from coast_tpu.inject.mem import MemoryMap


def _timestamp() -> str:
    return datetime.datetime.now().strftime("%Y-%m-%d %H:%M:%S.%f")


class _AbortWrite(Exception):
    """Internal: discard the temp file without surfacing an error (the
    native ndjson fast path bowing out mid-file)."""


@contextlib.contextmanager
def _atomic_write(path: str, mode: str = "w"):
    """Crash-safe log writing: serialize into a same-directory temp file
    and ``os.replace`` it into place only when complete, so a crash (or
    SIGKILL) mid-serialize never leaves a truncated log that json_parser
    chokes on -- readers see either the old file or the whole new one.
    Any exception from the body discards the temp file and propagates
    (:class:`_AbortWrite` included -- callers catch it)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
    except BaseException:
        f.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


@contextlib.contextmanager
def _serialize_stage(res: CampaignResult, writer: str, path: str):
    """Bill a writer's wall-clock to the campaign's 'serialize' stage
    (and to the ambient telemetry's timeline, for trace export).  The
    campaign object exists before any log is written, so serialization
    lands in ``res.stages`` after the fact via record_stage.

    Recording follows the telemetry on/off knob: bill only when the
    campaign recorded stages (its runner's telemetry was on) or an
    enabled ambient recorder is active -- otherwise a disabled-telemetry
    campaign would end up with a stages block containing *only*
    serialize, reading as ~100% of a pipeline that was never timed."""
    with obs.span("serialize", writer=writer, path=path):
        t0 = time.perf_counter()
        yield
        if res.stages or obs.current().enabled:
            res.record_stage("serialize", time.perf_counter() - t0)


def _result_dict(code: int, errors: int, corrected: int, steps: int,
                 ts: str) -> Dict[str, object]:
    if code in (cls.SUCCESS, cls.CORRECTED, cls.SDC):
        return {"timestamp": ts, "core": 0, "runtime": int(steps),
                "errors": int(errors), "faults": int(corrected)}
    if code == cls.DUE_ABORT:
        return {"type": "DWC/CFCSS", "message": "FAULT_DETECTED abort",
                "timestamp": ts, "errors": 1}
    if code == cls.DUE_TIMEOUT:
        return {"trap": False, "timeout": f"hit step bound at {int(steps)}",
                "timestamp": ts}
    if code == cls.DUE_STACK_OVERFLOW:
        # StackOverflowResult class: the guest's FreeRTOS hook line names
        # the overflowing task (decoder.py:69); the batched campaign
        # records which step the kernel's check tripped at instead.
        return {"stackOverflow": f"stack check tripped at step {int(steps)}",
                "taskName": "<kernel>", "timestamp": ts, "errors": 1}
    if code == cls.DUE_ASSERT:
        # AssertionFailResult class (decoder.py:67 configASSERT line).
        return {"assertion": f"kernel assert tripped at step {int(steps)}",
                "timestamp": ts, "errors": 1}
    return {"invalid": f"self-check out of domain (E={int(errors)})",
            "timestamp": ts}


def _columns(res: CampaignResult, mmap: MemoryMap):
    """Per-run columns as plain Python lists (one C-speed conversion each)."""
    secs = {s.leaf_id: s for s in mmap.sections}
    sched = res.schedule
    return {
        "leaf_id": sched.leaf_id.tolist(),
        "lane": sched.lane.tolist(),
        "word": sched.word.tolist(),
        "bit": sched.bit.tolist(),
        "t": sched.t.tolist(),
        "code": res.codes.tolist(),
        "errors": res.errors.tolist(),
        "corrected": res.corrected.tolist(),
        "steps": res.steps.tolist(),
    }, secs


def to_injection_logs(res: CampaignResult,
                      mmap: MemoryMap) -> List[Dict[str, object]]:
    ts = _timestamp()
    col, secs = _columns(res, mmap)
    sec_kind = {lid: s.kind for lid, s in secs.items()}
    sec_name = {lid: s.name for lid, s in secs.items()}
    logs = []
    for i in range(res.n):
        lid = col["leaf_id"][i]
        t_i = col["t"][i]
        if t_i < 0:
            # Cache draw outside the program footprint: never fired (the
            # plugin's invalid-line discard); must not be attributed to a
            # real section.
            section, symbol = "cache-invalid", "<invalid-line>"
            name = f"<invalid-line>^bit{col['bit'][i]}"
        else:
            section, symbol = sec_kind[lid], sec_name[lid]
            name = f"{sec_name[lid]}[lane {col['lane'][i]}]^bit{col['bit'][i]}"
        logs.append({
            "timestamp": ts,
            "number": i,
            "section": section,
            "address": col["word"][i],
            "oldValue": None,              # values live on-device; the flip
            "newValue": None,              # is XOR(1<<bit), recorded below
            "sleepTime": 0,
            "cycles": t_i,                 # step index = cycle analogue
            "PC": t_i,
            "name": name,
            "symbol": symbol,              # clean key for per-symbol
                                           # attribution (elfUtils.py:105-176)
            "result": _result_dict(col["code"][i], col["errors"][i],
                                   col["corrected"][i], col["steps"][i], ts),
            "cacheInfo": None,
        })
    return logs


def _ndjson_try_native(res: CampaignResult, mmap: MemoryMap, ts: str,
                       path: str) -> bool:
    """Write the whole ndjson log (summary line + streamed rows) via the
    native encoder; False means the native core is unavailable and the
    caller should run the Python formatter.  Strings are JSON-escaped
    here, once per section -- the native pass only formats numbers."""
    from coast_tpu import native
    if not native.native_available():
        return False
    sched = res.schedule
    secs = {s.leaf_id: s for s in mmap.sections}
    if not secs:
        return False
    n_leaves = max(secs) + 1
    kind_by_leaf = ["" for _ in range(n_leaves)]
    name_by_leaf = ["" for _ in range(n_leaves)]
    for lid, s in secs.items():
        kind_by_leaf[lid] = json.dumps(s.kind)[1:-1]
        name_by_leaf[lid] = json.dumps(s.name)[1:-1]
    col = {"leaf_id": sched.leaf_id, "lane": sched.lane, "word": sched.word,
           "bit": sched.bit, "t": sched.t, "code": res.codes,
           "errors": res.errors, "corrected": res.corrected,
           "steps": res.steps}
    try:
        with _atomic_write(path, "wb") as f:
            f.write((json.dumps({"summary": {**res.summary(),
                                             "format": "ndjson"}})
                     + "\n").encode())
            if not native.ndjson_stream_rows(0, res.n, col, kind_by_leaf,
                                             name_by_leaf, ts, f.write):
                # Native core bowed out mid-file: discard the temp file
                # (never a half-written log) and fall back to Python.
                raise _AbortWrite
    except _AbortWrite:
        return False
    return True


def write_reference_json(res: CampaignResult, mmap: MemoryMap, path: str,
                         exec_path: str = None) -> None:
    """Campaign log in the reference tool's OWN container: line 1 names
    the protected program (the guest-executable line; readJsonFile
    refuses the file when that path does not exist on disk,
    jsonParser.py:121-133), followed by one JSON array of InjectionLog
    dicts.  The reference's simulation/platform/jsonParser.py -- not a
    reimplementation -- parses these files directly, so its summary,
    compare-files/-dirs, and MWTF reports run unmodified on campaigns
    from this engine.  ``exec_path`` defaults to the benchmark's model
    module (models.model_source).

    Known reference-tool limitation (theirs, not this writer's): its
    otherStats takes statistics.mean over fully-clean runs and raises
    StatisticsError on a campaign with zero successes (e.g. a small TMR
    campaign where every injection was corrected); its own QEMU
    campaigns always contain clean runs, so the path was never guarded."""
    if exec_path is None:
        from coast_tpu.models import model_source
        exec_path = model_source(res.benchmark)
    exec_path = os.path.realpath(exec_path)
    if not os.path.exists(exec_path):
        raise FileNotFoundError(
            f"exec_path {exec_path!r} does not exist; the reference's "
            "readJsonFile exits on logs whose line-1 path is missing")
    with _serialize_stage(res, "reference_json", path):
        with _atomic_write(path) as f:
            f.write(exec_path + "\n")
            json.dump(to_injection_logs(res, mmap), f, indent=1)


def write_json(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Reference-schema structured log (threadFunctions.py:195-198 flushes
    per injection; we flush per campaign)."""
    with _serialize_stage(res, "json", path):
        with _atomic_write(path) as f:
            json.dump({
                "summary": res.summary(),
                "runs": to_injection_logs(res, mmap),
            }, f, indent=1)


def write_ndjson(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Newline-delimited bulk log: line 1 is the campaign summary (with a
    ``"format": "ndjson"`` marker), each following line one run in the
    InjectionLog schema.  The row formatting is delegated to the native
    C++ encoder (coast_ndjson_encode) when available -- one C pass over
    the columns -- with this function's template loop as the bit-identical
    Python fallback, so a 10^6-run campaign serialises in well under a
    second natively and in seconds otherwise.

    The stage accounting (res.stages['serialize']) is recorded *after*
    the write, so the summary line inside the file reflects the stages
    known before this serialization -- the serialize stage of a log file
    describes earlier writers, not itself."""
    ts = _timestamp()
    with _serialize_stage(res, "ndjson", path):
        if _ndjson_try_native(res, mmap, ts, path):
            return
        _write_ndjson_py(res, mmap, ts, path)


def _write_ndjson_py(res: CampaignResult, mmap: MemoryMap, ts: str,
                     path: str) -> None:
    col, secs = _columns(res, mmap)
    # One result template per class, mirroring _result_dict (timestamps
    # identical across the campaign, as with write_json).
    run_tpl = ('{"timestamp": "%s", "core": 0, "runtime": %%(steps)d, '
               '"errors": %%(errors)d, "faults": %%(faults)d}' % ts)
    res_tpl = {
        cls.SUCCESS: run_tpl,
        cls.CORRECTED: run_tpl,
        cls.SDC: run_tpl,
        cls.DUE_ABORT: ('{"type": "DWC/CFCSS", "message": "FAULT_DETECTED '
                        'abort", "timestamp": "%s", "errors": 1}' % ts),
        cls.DUE_TIMEOUT: ('{"trap": false, "timeout": "hit step bound at '
                          '%%(steps)d", "timestamp": "%s"}' % ts),
        cls.INVALID: ('{"invalid": "self-check out of domain '
                      '(E=%%(errors)d)", "timestamp": "%s"}' % ts),
        cls.DUE_STACK_OVERFLOW: (
            '{"stackOverflow": "stack check tripped at step %%(steps)d", '
            '"taskName": "<kernel>", "timestamp": "%s", "errors": 1}' % ts),
        cls.DUE_ASSERT: (
            '{"assertion": "kernel assert tripped at step %%(steps)d", '
            '"timestamp": "%s", "errors": 1}' % ts),
    }
    line_tpl = (
        '{"timestamp": "%s", "number": %%(i)d, "section": "%%(section)s", '
        '"address": %%(word)d, "oldValue": null, "newValue": null, '
        '"sleepTime": 0, "cycles": %%(t)d, "PC": %%(t)d, '
        '"name": "%%(name)s", "symbol": "%%(symbol)s", '
        '"result": %%(result)s, "cacheInfo": null}' % ts)
    sec_kind = {lid: s.kind for lid, s in secs.items()}
    sec_name = {lid: s.name for lid, s in secs.items()}
    with _atomic_write(path) as f:
        f.write(json.dumps({"summary": {**res.summary(),
                                        "format": "ndjson"}}) + "\n")
        write = f.write
        for i in range(res.n):
            lid = col["leaf_id"][i]
            t_i = col["t"][i]
            if t_i < 0:
                section, symbol = "cache-invalid", "<invalid-line>"
                name = f"<invalid-line>^bit{col['bit'][i]}"
            else:
                section, symbol = sec_kind[lid], sec_name[lid]
                name = (f"{sec_name[lid]}[lane {col['lane'][i]}]"
                        f"^bit{col['bit'][i]}")
            result = res_tpl[col["code"][i]] % {
                "errors": col["errors"][i], "faults": col["corrected"][i],
                "steps": col["steps"][i]}
            # json.dumps on the string fields: leaf names are arbitrary
            # author-chosen strings and must be JSON-escaped.
            write(line_tpl % {
                "i": i, "section": json.dumps(section)[1:-1],
                "word": col["word"][i], "t": t_i,
                "name": json.dumps(name)[1:-1],
                "symbol": json.dumps(symbol)[1:-1],
                "result": result} + "\n")


def write_columnar(res: CampaignResult, mmap: MemoryMap, path: str) -> None:
    """Columnar bulk log: the whole campaign as parallel arrays plus the
    section table -- O(1) Python objects regardless of campaign size, and
    the natural format for numpy-side analysis.  json_parser summarises it
    directly without materialising per-run dicts."""
    with _serialize_stage(res, "columnar", path):
        col, secs = _columns(res, mmap)
        with _atomic_write(path) as f:
            json.dump({
                "summary": {**res.summary(), "format": "columnar"},
                "sections": [{"leaf_id": s.leaf_id, "name": s.name,
                              "kind": s.kind, "lanes": s.lanes,
                              "words": s.words}
                             for s in secs.values()],
                "columns": col,
            }, f)
