"""Run classification: the reference's SDC/DUE taxonomy as device-side codes.

Mirrors the result-class lattice of supportClasses.py (RunResult /
TimeoutResult / AbortResult / StackOverflowResult / AssertionFailResult /
InvalidResult) and the counting rules of jsonParser.summarizeRuns
(jsonParser.py:148-201):

  * abort, stack-overflow, and assert-fail *also* count as timeouts (DUE)
    there (the decoder classes of decoder.py:67-69); here they are
    distinct codes that all aggregate into the DUE bucket
    (``CampaignResult.due`` / ``Summary.due``).
  * a RunResult with errors>0 is SDC regardless of faults; faults>0 with
    errors==0 is a corrected run; otherwise success.

DUE sub-buckets (the FreeRTOS production config's failure modes):
``DUE_STACK_OVERFLOW`` is a tripped kernel stack check -- blown
canary/watermark word or out-of-bounds saved stack pointer, the
vApplicationStackOverflowHook class (decoder.py:69).  ``DUE_ASSERT`` is a
tripped kernel/task assertion (the configASSERT class, decoder.py:67).
Both are latched by a region's declared guards
(Region.stack_guard/assert_guard), checked per lane like the replicated
kernel's own checks in the reference rtos build.

Precedence (a DWC abort freezes an incomplete results matrix, so E>0 there
must not be read as SDC; a guard that tripped names the failure more
precisely than the generic abort): INVALID > DUE_STACK_OVERFLOW >
DUE_ASSERT > DUE_ABORT > DUE_TIMEOUT > SDC > CORRECTED > SUCCESS.

Timeout on TPU: "hang" is defined by the watchdog step bound
(Region.max_steps; the reference arms a threading.Timer watchdog on every
continue, gdbHandlers.py:22-47).  INVALID (unparseable UART in the
reference, decoder.py:62-116) maps to a self-check result outside its
representable domain -- reachable when a flip corrupts the check machinery.

New codes append after the pre-existing six so that every recorded
campaign log (codes are serialised as integers) keeps its meaning.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

SUCCESS = 0
CORRECTED = 1   # "faults" column: TMR voted away a miscompare, output clean
SDC = 2         # "errors" column: silent data corruption
DUE_ABORT = 3   # DWC / CFCSS detected -> abort()
DUE_TIMEOUT = 4  # watchdog bound hit (hang)
INVALID = 5
DUE_STACK_OVERFLOW = 6  # kernel stack check: blown canary / sp out of range
DUE_ASSERT = 7          # kernel/task assertion tripped (configASSERT class)
# Silent-training-corruption refinement of the SDC bucket (training
# regions only, coast_tpu.train): a completed run whose final weights
# differ bit-for-bit from the fault-free weights is still an SDC, but
# training dynamics give it a second axis -- did the LOSS trajectory
# re-converge to the golden trajectory within the heal window
# (transient perturbation the optimizer absorbed) or stay diverged
# (persistent weight corruption)?  Region.train_probe supplies the
# verdict; non-training records never carry the probe, so these codes
# are unreachable there and the pre-training taxonomy stays pinned.
TRAIN_SELF_HEAL = 8     # weights differ, loss re-converged (transient)
TRAIN_SDC = 9           # weights differ, loss diverged (persistent SDC)

NUM_CLASSES = 10
CLASS_NAMES = ("success", "corrected", "sdc", "due_abort", "due_timeout",
               "invalid", "due_stack_overflow", "due_assert",
               "train_self_heal", "train_sdc")
# The taxonomy every pre-training campaign speaks: counts dicts for
# regions without a train probe are built over exactly these keys, so
# their logs/journals stay byte-identical to before the train classes
# existed (the fault-model absent-means-single rule, applied to classes).
BASE_CLASS_NAMES = CLASS_NAMES[:TRAIN_SELF_HEAL]

# The DUE bucket's members (abort/timeout/stack-overflow/assert all count
# as DUE, jsonParser.py:165-172 "aborts also count as timeouts"); single
# source of truth for CampaignResult.due / Summary.due.
DUE_CLASSES = ("due_abort", "due_timeout", "due_stack_overflow",
               "due_assert")
# Uncorrected silent corruption: the classes an error rate / MWTF
# comparison must count as "errors" (train_self_heal is deliberately
# NOT here -- the output the workload cares about, the converged loss,
# was not corrupted).
SDC_CLASSES = ("sdc", "train_sdc")
# Classes whose runs completed (reached the region's own result line)
# and therefore contribute to the mean-runtime statistic.
COMPLETED_CLASSES = ("success", "corrected", "sdc", "train_self_heal",
                     "train_sdc")


def classify(rec: Dict[str, jax.Array], output_words: int) -> jax.Array:
    """record (from ProtectedProgram.run) -> int32 class code."""
    errors = rec["errors"]
    invalid = jnp.logical_or(errors < 0, errors > output_words)
    code = jnp.where(rec["corrected"] > 0, CORRECTED, SUCCESS)
    code = jnp.where(errors > 0, SDC, code)
    if "train_probe" in rec:
        # Training regions only (Region.train_probe): split the SDC
        # bucket by whether the loss trajectory re-converged.  Applied
        # BEFORE the DUE/INVALID overrides so precedence is unchanged:
        # a hung or aborted training step is a DUE, not a train SDC.
        code = jnp.where(code == SDC,
                         jnp.where(rec["train_probe"] >= 2,
                                   TRAIN_SDC, TRAIN_SELF_HEAL),
                         code)
    code = jnp.where(jnp.logical_not(rec["done"]), DUE_TIMEOUT, code)
    code = jnp.where(jnp.logical_or(rec["dwc_fault"], rec["cfc_fault"]),
                     DUE_ABORT, code)
    code = jnp.where(rec["assert_fault"], DUE_ASSERT, code)
    code = jnp.where(rec["stack_fault"], DUE_STACK_OVERFLOW, code)
    code = jnp.where(invalid, INVALID, code)
    return code.astype(jnp.int32)


def histogram(codes: jax.Array) -> jax.Array:
    """Per-class counts (int32 [NUM_CLASSES]); psum-able across shards."""
    return jnp.sum(
        jax.nn.one_hot(codes, NUM_CLASSES, dtype=jnp.int32), axis=0)


def counts_dict(binc, train: bool = False):
    """Class-histogram array -> the counts dict campaigns report.

    ``train=False`` (any region without a train probe) emits exactly the
    pre-training key set (BASE_CLASS_NAMES) -- the absent-means-zero
    rule that keeps non-train log summaries and journal records
    byte-identical to before the train classes existed; a nonzero tail
    count is still emitted (it should be impossible there, and silently
    dropping it would hide a classifier bug).  ``train=True`` always
    carries the train keys, zero or not, so a train campaign's report
    shape is stable."""
    out = {}
    for i, name in enumerate(CLASS_NAMES):
        if train or i < len(BASE_CLASS_NAMES) or int(binc[i]):
            out[name] = int(binc[i])
    return out


def completed_mask(codes):
    """Boolean mask of runs that completed (reached the result line):
    success/corrected/sdc plus the train refinements of sdc.  The single
    membership rule behind every mean-runtime statistic."""
    codes = np.asarray(codes)
    return (codes <= SDC) | (codes >= TRAIN_SELF_HEAL)


def weighted_histogram(codes, weights=None):
    """Host-side per-class counts (int64 [NUM_CLASSES]) with optional
    per-run weights -- the single counting point for equivalence-reduced
    campaigns (analysis/equiv): each representative's outcome is
    multiplied by its ``class_weight``, so the reported distribution is
    over *effective* injections while only the representatives ran."""
    codes = np.asarray(codes)
    if weights is None:
        return np.bincount(codes, minlength=NUM_CLASSES).astype(np.int64)
    return np.round(np.bincount(
        codes, weights=np.asarray(weights, np.float64),
        minlength=NUM_CLASSES)).astype(np.int64)


def counts_histogram(counts) -> np.ndarray:
    """The histogram-only inverse of :func:`counts_dict`: a counts
    mapping (class name -> count; extra keys like ``cache_invalid``
    ignored) back to the int64 [NUM_CLASSES] histogram array.  Sparse
    consumers live on this shape -- sparse journal records, sparse log
    summaries, and resume replay all carry histograms rather than
    per-row code columns."""
    out = np.zeros(NUM_CLASSES, np.int64)
    for i, name in enumerate(CLASS_NAMES):
        out[i] = int(counts.get(name, 0))
    return out
