"""Run classification: the reference's SDC/DUE taxonomy as device-side codes.

Mirrors the result-class lattice of supportClasses.py (RunResult /
TimeoutResult / AbortResult / StackOverflowResult / AssertionFailResult /
InvalidResult) and the counting rules of jsonParser.summarizeRuns
(jsonParser.py:148-201):

  * abort, stack-overflow, and assert-fail *also* count as timeouts (DUE)
    there (the decoder classes of decoder.py:67-69); here they are
    distinct codes that all aggregate into the DUE bucket
    (``CampaignResult.due`` / ``Summary.due``).
  * a RunResult with errors>0 is SDC regardless of faults; faults>0 with
    errors==0 is a corrected run; otherwise success.

DUE sub-buckets (the FreeRTOS production config's failure modes):
``DUE_STACK_OVERFLOW`` is a tripped kernel stack check -- blown
canary/watermark word or out-of-bounds saved stack pointer, the
vApplicationStackOverflowHook class (decoder.py:69).  ``DUE_ASSERT`` is a
tripped kernel/task assertion (the configASSERT class, decoder.py:67).
Both are latched by a region's declared guards
(Region.stack_guard/assert_guard), checked per lane like the replicated
kernel's own checks in the reference rtos build.

Precedence (a DWC abort freezes an incomplete results matrix, so E>0 there
must not be read as SDC; a guard that tripped names the failure more
precisely than the generic abort): INVALID > DUE_STACK_OVERFLOW >
DUE_ASSERT > DUE_ABORT > DUE_TIMEOUT > SDC > CORRECTED > SUCCESS.

Timeout on TPU: "hang" is defined by the watchdog step bound
(Region.max_steps; the reference arms a threading.Timer watchdog on every
continue, gdbHandlers.py:22-47).  INVALID (unparseable UART in the
reference, decoder.py:62-116) maps to a self-check result outside its
representable domain -- reachable when a flip corrupts the check machinery.

New codes append after the pre-existing six so that every recorded
campaign log (codes are serialised as integers) keeps its meaning.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

SUCCESS = 0
CORRECTED = 1   # "faults" column: TMR voted away a miscompare, output clean
SDC = 2         # "errors" column: silent data corruption
DUE_ABORT = 3   # DWC / CFCSS detected -> abort()
DUE_TIMEOUT = 4  # watchdog bound hit (hang)
INVALID = 5
DUE_STACK_OVERFLOW = 6  # kernel stack check: blown canary / sp out of range
DUE_ASSERT = 7          # kernel/task assertion tripped (configASSERT class)

NUM_CLASSES = 8
CLASS_NAMES = ("success", "corrected", "sdc", "due_abort", "due_timeout",
               "invalid", "due_stack_overflow", "due_assert")

# The DUE bucket's members (abort/timeout/stack-overflow/assert all count
# as DUE, jsonParser.py:165-172 "aborts also count as timeouts"); single
# source of truth for CampaignResult.due / Summary.due.
DUE_CLASSES = ("due_abort", "due_timeout", "due_stack_overflow",
               "due_assert")


def classify(rec: Dict[str, jax.Array], output_words: int) -> jax.Array:
    """record (from ProtectedProgram.run) -> int32 class code."""
    errors = rec["errors"]
    invalid = jnp.logical_or(errors < 0, errors > output_words)
    code = jnp.where(rec["corrected"] > 0, CORRECTED, SUCCESS)
    code = jnp.where(errors > 0, SDC, code)
    code = jnp.where(jnp.logical_not(rec["done"]), DUE_TIMEOUT, code)
    code = jnp.where(jnp.logical_or(rec["dwc_fault"], rec["cfc_fault"]),
                     DUE_ABORT, code)
    code = jnp.where(rec["assert_fault"], DUE_ASSERT, code)
    code = jnp.where(rec["stack_fault"], DUE_STACK_OVERFLOW, code)
    code = jnp.where(invalid, INVALID, code)
    return code.astype(jnp.int32)


def histogram(codes: jax.Array) -> jax.Array:
    """Per-class counts (int32 [NUM_CLASSES]); psum-able across shards."""
    return jnp.sum(
        jax.nn.one_hot(codes, NUM_CLASSES, dtype=jnp.int32), axis=0)


def weighted_histogram(codes, weights=None):
    """Host-side per-class counts (int64 [NUM_CLASSES]) with optional
    per-run weights -- the single counting point for equivalence-reduced
    campaigns (analysis/equiv): each representative's outcome is
    multiplied by its ``class_weight``, so the reported distribution is
    over *effective* injections while only the representatives ran."""
    import numpy as np
    codes = np.asarray(codes)
    if weights is None:
        return np.bincount(codes, minlength=NUM_CLASSES).astype(np.int64)
    return np.round(np.bincount(
        codes, weights=np.asarray(weights, np.float64),
        minlength=NUM_CLASSES)).astype(np.int64)
