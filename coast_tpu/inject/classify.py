"""Run classification: the reference's SDC/DUE taxonomy as device-side codes.

Mirrors the result-class lattice of supportClasses.py (RunResult /
TimeoutResult / AbortResult / StackOverflowResult / InvalidResult) and the
counting rules of jsonParser.summarizeRuns (jsonParser.py:148-201):

  * abort and stack-overflow *also* count as timeouts (DUE) there; here
    DUE_ABORT and DUE_TIMEOUT are distinct codes that both aggregate into
    the DUE bucket.
  * a RunResult with errors>0 is SDC regardless of faults; faults>0 with
    errors==0 is a corrected run; otherwise success.

Precedence (a DWC abort freezes an incomplete results matrix, so E>0 there
must not be read as SDC): INVALID > DUE_ABORT > DUE_TIMEOUT > SDC >
CORRECTED > SUCCESS.

Timeout on TPU: "hang" is defined by the watchdog step bound
(Region.max_steps; the reference arms a threading.Timer watchdog on every
continue, gdbHandlers.py:22-47).  INVALID (unparseable UART in the
reference, decoder.py:62-116) maps to a self-check result outside its
representable domain -- reachable when a flip corrupts the check machinery.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

SUCCESS = 0
CORRECTED = 1   # "faults" column: TMR voted away a miscompare, output clean
SDC = 2         # "errors" column: silent data corruption
DUE_ABORT = 3   # DWC / CFCSS detected -> abort()
DUE_TIMEOUT = 4  # watchdog bound hit (hang)
INVALID = 5

NUM_CLASSES = 6
CLASS_NAMES = ("success", "corrected", "sdc", "due_abort", "due_timeout",
               "invalid")


def classify(rec: Dict[str, jax.Array], output_words: int) -> jax.Array:
    """record (from ProtectedProgram.run) -> int32 class code."""
    errors = rec["errors"]
    invalid = jnp.logical_or(errors < 0, errors > output_words)
    code = jnp.where(rec["corrected"] > 0, CORRECTED, SUCCESS)
    code = jnp.where(errors > 0, SDC, code)
    code = jnp.where(jnp.logical_not(rec["done"]), DUE_TIMEOUT, code)
    code = jnp.where(jnp.logical_or(rec["dwc_fault"], rec["cfc_fault"]),
                     DUE_ABORT, code)
    code = jnp.where(invalid, INVALID, code)
    return code.astype(jnp.int32)


def histogram(codes: jax.Array) -> jax.Array:
    """Per-class counts (int32 [NUM_CLASSES]); psum-able across shards."""
    return jnp.sum(
        jax.nn.one_hot(codes, NUM_CLASSES, dtype=jnp.int32), axis=0)
