"""Memory map: the injectable address space of a protected program.

The reference targets ELF sections parsed from ``objdump -h``
(resources/mem.py:56-85 ``MemoryMap``; resources/utils.py:18-57 ``readElf``)
and samples a uniformly random address within a size-weighted section
(``MemorySection.getRandomAddress`` mem.py:48-53).  The TPU analogue's
"sections" are the state-pytree leaves of a protected program; replicated
leaves contribute ``num_clones`` independently corruptible copies, exactly as
the reference's cloned globals occupy distinct addresses.

Sections are word-addressed (32-bit), matching the word-granular injections
of injector.py:125-200.  Register-section injections map to ``reg``/``ctrl``
leaves (loop-carried state), cache-section to the HBM-resident ``mem``
leaves -- the fidelity envelope documented in SURVEY.md §7.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from coast_tpu import obs
from coast_tpu.passes.dataflow_protection import ProtectedProgram


@dataclasses.dataclass(frozen=True)
class MemorySection:
    """One injectable leaf: ``bits = lanes * words * 32``."""

    name: str
    leaf_id: int
    kind: str
    lanes: int          # num_clones if replicated else 1
    words: int          # flat 32-bit words per lane
    @property
    def bits(self) -> int:
        return self.lanes * self.words * 32


class MemoryMap:
    """Section table + uniform sampling over all injectable bits."""

    def __init__(self, prog: ProtectedProgram,
                 sections: Optional[Sequence[str]] = None):
        # Span via the ambient telemetry (CampaignRunner activates its
        # recorder around construction): map building walks the whole
        # state pytree, part of the schedule-build stage.
        with obs.span("memory_map"):
            self.sections: List[MemorySection] = []
            for leaf_id, (name, kind, lanes, words) in enumerate(
                    prog.injectable_sections()):
                if sections is not None and kind not in sections \
                        and name not in sections:
                    continue
                self.sections.append(MemorySection(
                    name=name,
                    leaf_id=leaf_id,
                    kind=kind,
                    lanes=lanes,
                    words=max(words, 1),
                ))
            if not self.sections:
                raise ValueError("no injectable sections selected")
            self.total_bits = sum(s.bits for s in self.sections)

    def by_name(self, name: str) -> MemorySection:
        for s in self.sections:
            if s.name == name:
                return s
        raise KeyError(name)

    def section_tables(self):
        """The flat section layout as the native core consumes it
        (coast_fault_expand): ``(bits_end, leaf_id, lanes, words)`` --
        cumulative bit edges (int64) plus the per-section int32 columns.
        Single marshalling point shared by schedule expansion and its
        parity tests, so they cannot drift from what production passes."""
        return (np.cumsum([s.bits for s in self.sections]).astype(np.int64),
                np.array([s.leaf_id for s in self.sections], np.int32),
                np.array([s.lanes for s in self.sections], np.int32),
                np.array([s.words for s in self.sections], np.int32))

    def decode(self, flat_bits: np.ndarray):
        """Map uniform draws over [0, total_bits) to (leaf_id, lane, word, bit).

        Vectorised over a schedule; the size-weighted section choice mirrors
        MemHierarchy's weighted pick (mem.py:120-161).
        """
        flat_bits = np.asarray(flat_bits, dtype=np.int64)
        edges = np.cumsum([s.bits for s in self.sections])
        sec_idx = np.searchsorted(edges, flat_bits, side="right")
        leaf_ids = np.array([s.leaf_id for s in self.sections])[sec_idx]
        offs = flat_bits - (edges[sec_idx] - np.array(
            [s.bits for s in self.sections])[sec_idx])
        words_per = np.array([s.words for s in self.sections])[sec_idx]
        lane = offs // (words_per * 32)
        rem = offs % (words_per * 32)
        word = rem // 32
        bit = rem % 32
        return (leaf_ids.astype(np.int32), lane.astype(np.int32),
                word.astype(np.int32), bit.astype(np.int32), sec_idx)
