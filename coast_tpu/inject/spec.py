"""CampaignSpec: the ONE campaign-identity vocabulary.

Before this module, three subsystems each spelled the same identity
tuple -- benchmark / opt flags / section / seed / n / start_num / batch
geometry / fault model / equiv / stop-when -- in their own dialect:

  * the **journal header** (:mod:`coast_tpu.inject.journal`): the resume
    contract, written as loose kwargs by ``CampaignRunner.run``;
  * the **fleet queue item spec** (:mod:`coast_tpu.fleet.queue`
    ``item_spec``): the work-ledger contract, a hand-rolled dict with
    its own defaulting and validation;
  * the **delta/equiv identity** (:mod:`coast_tpu.analysis.equiv.delta`
    ``_IDENTITY_KEYS``): the splice-soundness contract, a tuple of
    header keys compared by hand.

Three spellings of one fact is how vocabularies drift: a key added to
the journal but not the queue makes a worker regenerate a campaign the
journal refuses; a default that differs between the item spec and the
delta identity silently re-injects (or worse, splices) the wrong rows.
:class:`CampaignSpec` is the single type all three serialize through.

**Evolution rules are part of the type.**  Two asymmetric encodings
exist on disk and both must stay bit-for-bit stable:

  * ``to_item()`` emits exactly the historical queue-item dict
    (``fault_model`` always present, ``stop_when`` an explicit null,
    ``delta_from`` only when set) so enqueue ids -- the sha over the
    sorted item JSON -- and every pre-PR queue directory keep their
    meaning.
  * ``run_header_fields()`` emits the journal's absent-means-default
    subset (``fault_model``/``stop_when`` omitted at their defaults) so
    journals written before those keys existed still open, resume, and
    delta exactly as :data:`~coast_tpu.inject.journal._VOLATILE_KEYS`
    and the PR 6 absent-means-``single`` rule promise.

``from_item`` / ``from_header`` invert the two encodings; round-trip
bit parity against pre-PR journals and queue items is pinned in
``tests/test_ci.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

__all__ = ["CampaignSpec", "SpecError", "FAULT_MODEL_DEFAULT",
           "COLLECT_DEFAULT", "PLACEMENT_DEFAULT", "FUSE_DEFAULT",
           "header_collect", "header_placement", "header_fuse"]

#: The journal-evolution default: an absent ``fault_model`` key means
#: the historical single-bit flip (journals and queue items written
#: before PR 6 carry no key at all).
FAULT_MODEL_DEFAULT = "single"

#: Same evolution rule for the collection mode: an absent ``collect``
#: key means the historical dense per-row fetch.
COLLECT_DEFAULT = "dense"

#: Same evolution rule for the voter placement (sharded benchmarks'
#: factory knob): an absent ``placement`` key means the registry build
#: -- vote-then-exchange (``"compute"``).  Journals and queue items
#: written before the knob existed stay byte-identical and still
#: open/resume.
PLACEMENT_DEFAULT = "compute"

#: Same evolution rule for the fused protected-step engine: an absent
#: ``fuse`` key means the historical unfused interpreter loop.  The
#: fused path is pinned bit-identical, but the *program* the campaign
#: measured (op counts, kernel schedule, MFU attribution) differs, so
#: fuse mode is campaign identity -- resuming a journal under the other
#: engine is refused typed rather than silently blending measurements.
FUSE_DEFAULT = False


class SpecError(ValueError):
    """A malformed campaign spec (bad n, unknown fault model, equiv over
    a flip-group model, unparseable stop condition, delta misuse)."""


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """One campaign's identity, in canonical normalized form.

    Field semantics (shared verbatim by the journal header, the queue
    item, and the delta identity):

    ``benchmark``
        Registry name or restricted-C source path.
    ``n``
        Requested injections.  For equivalence-reduced campaigns this is
        the EFFECTIVE count; the physical representative count is a
        property of the partition, not of the identity.
    ``opt_passes``
        Protection flags (opt CLI string) -- the protection-config
        source.  The journal header pins the derived ``config_sha``
        instead; the queue item carries the flags so a worker can
        rebuild the program.
    ``section`` / ``seed`` / ``start_num`` / ``batch_size`` / ``unroll``
        As everywhere else.  ``batch_size`` is volatile for resume
        (journal ``_VOLATILE_KEYS``) but part of the queue item.
    ``fault_model``
        ``FaultModel.spec()`` string; ``"single"`` is the default and is
        OMITTED from journal headers (absent-means-single rule).
    ``equiv``
        Equivalence reduction on/off.  The journal header carries the
        derived partition fingerprint block instead of the flag; the
        flag is what a worker needs to rebuild the runner.
    ``stop_when``
        Canonical ``StopWhen.spec()`` string or None.  Part of resume
        identity (an early-stopped journal's rows are a prefix chosen BY
        the condition).
    ``throttle_s``
        Operator rate limit; fleet-item-only, never identity.
    ``delta_from``
        Path to a completed equiv run journal to splice unchanged
        sections from.  Fleet-item-only (the CI's delta items); never
        part of the journal header (a delta campaign's output is a
        plain run result).
    ``static_budget``
        Delta campaigns only: allocate the per-section convergence
        budget by the static vulnerability map
        (:mod:`coast_tpu.analysis.propagation`) -- ``sdc-possible``
        sections re-inject first and statically-proven sections run
        under a relaxed ``min_done`` floor.  Fleet-item-only like
        ``delta_from`` (it shapes HOW the delta spends budget, not what
        the result means); joins the item dict only when set, so every
        pre-existing item stays byte-identical.
    ``placement``
        Voter placement of a sharded benchmark (the stencil's
        ``make_region(placement=...)`` knob): ``"compute"`` (default;
        vote-then-exchange -- the registry build) or ``"link"``
        (exchange-then-vote).  Campaign identity: the two placements are
        DIFFERENT programs (different halo leaf shape, different blast
        radius), so resuming one under the other must refuse with a
        typed error naming the knob.  Absent-means-compute everywhere,
        so every pre-placement journal and queue item stays
        byte-identical.
    ``collect``
        Result-collection mode: ``"dense"`` (default; every row's
        outcome columns cross the host boundary, the historical
        behavior) or ``"sparse"`` (device-resident loop: flip sites
        regenerate on device, only per-batch histograms plus the
        compacted interesting rows come back).  Campaign identity: a
        sparse journal's batch records are histogram + interesting-row
        records, so resuming one under dense (or vice versa) must
        refuse.  Absent-means-dense everywhere (journals, queue items,
        and logs written before the mode existed stay byte-identical).
    """

    benchmark: str
    n: int
    seed: int = 0
    opt_passes: str = "-TMR"
    section: str = "memory"
    batch_size: int = 4096
    start_num: int = 0
    fault_model: str = FAULT_MODEL_DEFAULT
    equiv: bool = False
    stop_when: Optional[str] = None
    unroll: int = 1
    throttle_s: float = 0.0
    delta_from: Optional[str] = None
    collect: str = COLLECT_DEFAULT
    static_budget: bool = False
    placement: str = PLACEMENT_DEFAULT

    # -- validation ----------------------------------------------------------
    def validate(self) -> "CampaignSpec":
        """Raise :class:`SpecError` (or the parser's own typed error) on
        a spec no campaign could run.  Returns self so call sites can
        chain.  Validation happens at the BOUNDARY (enqueue, CLI parse,
        baseline load) so a bad spec fails its author, not a worker an
        hour later."""
        if self.n <= 0:
            raise SpecError(f"campaign wants n={self.n} injections; "
                            "need > 0")
        if self.fault_model != FAULT_MODEL_DEFAULT:
            from coast_tpu.inject.schedule import FaultModel
            FaultModel.parse(self.fault_model)   # ValueError on typos
            if self.equiv:
                raise SpecError(
                    "equiv=True needs the single-bit fault model")
        if self.stop_when:
            from coast_tpu.obs.convergence import StopWhen
            StopWhen.parse(self.stop_when)       # StopWhenError on typos
        if self.delta_from and not self.equiv:
            raise SpecError(
                "delta_from needs equiv=True: the equivalence partition "
                "supplies the per-section fingerprints a delta diffs")
        if self.collect not in ("dense", "sparse"):
            raise SpecError(
                f"unknown collect mode {self.collect!r}; one of "
                "'dense', 'sparse'")
        if self.placement not in ("compute", "link"):
            raise SpecError(
                f"unknown voter placement {self.placement!r}; one of "
                "'compute' (vote-then-exchange), 'link' "
                "(exchange-then-vote)")
        if self.delta_from and self.collect != COLLECT_DEFAULT:
            raise SpecError(
                "delta_from campaigns are dense by construction (the "
                "spliced rows are exact per-row journal records); drop "
                "collect='sparse'")
        if self.static_budget and not (self.delta_from and self.stop_when):
            raise SpecError(
                "static_budget allocates a DELTA campaign's per-section "
                "convergence budget; it needs delta_from AND stop_when")
        return self

    # -- parsed accessors ----------------------------------------------------
    def fault_model_parsed(self):
        """FaultModel instance, or None for the single-bit default (the
        shape CampaignRunner(fault_model=) takes)."""
        if self.fault_model == FAULT_MODEL_DEFAULT:
            return None
        from coast_tpu.inject.schedule import FaultModel
        return FaultModel.parse(self.fault_model)

    def stop_when_parsed(self):
        """StopWhen instance, or None."""
        if not self.stop_when:
            return None
        from coast_tpu.obs.convergence import StopWhen
        return StopWhen.parse(self.stop_when)

    # -- queue-item encoding (fleet/queue.py) --------------------------------
    def to_item(self) -> Dict[str, object]:
        """The fleet queue item dict, bit-compatible with the historical
        ``item_spec`` output: same keys, same order, same explicit-null
        conventions -- enqueue ids sha the sorted JSON of this dict, so
        its shape IS on-disk compatibility.  ``delta_from`` joins only
        when set, keeping every pre-delta item byte-identical."""
        doc: Dict[str, object] = {
            "benchmark": str(self.benchmark),
            "opt_passes": str(self.opt_passes),
            "section": str(self.section), "n": int(self.n),
            "seed": int(self.seed), "start_num": int(self.start_num),
            "batch_size": int(self.batch_size),
            "fault_model": str(self.fault_model),
            "equiv": bool(self.equiv),
            "stop_when": self.stop_when if self.stop_when else None,
            "unroll": int(self.unroll),
            "throttle_s": float(self.throttle_s),
        }
        if self.delta_from:
            doc["delta_from"] = str(self.delta_from)
        if self.static_budget:
            # Joins only when set (like delta_from): pre-existing item
            # dicts -- and their sha'd enqueue ids -- stay byte-identical.
            doc["static_budget"] = True
        if self.collect != COLLECT_DEFAULT:
            # Joins only when sparse (like delta_from): enqueue ids sha
            # the item dict, so every pre-sparse item stays byte-
            # identical.
            doc["collect"] = str(self.collect)
        if self.placement != PLACEMENT_DEFAULT:
            # Joins only when non-default (same byte-identity argument).
            doc["placement"] = str(self.placement)
        return doc

    @classmethod
    def from_item(cls, spec: Dict[str, object]) -> "CampaignSpec":
        """Inverse of :meth:`to_item`, tolerant of absent keys (items
        enqueued by older code lack the newer ones)."""
        return cls(
            benchmark=str(spec["benchmark"]),
            n=int(spec["n"]),
            seed=int(spec.get("seed", 0)),
            opt_passes=str(spec.get("opt_passes", "-TMR")),
            section=str(spec.get("section", "memory")),
            batch_size=int(spec.get("batch_size", 4096)),
            start_num=int(spec.get("start_num", 0)),
            fault_model=str(spec.get("fault_model",
                                     FAULT_MODEL_DEFAULT)),
            equiv=bool(spec.get("equiv", False)),
            stop_when=spec.get("stop_when") or None,
            unroll=int(spec.get("unroll", 1)),
            throttle_s=float(spec.get("throttle_s", 0.0) or 0.0),
            delta_from=spec.get("delta_from") or None,
            collect=str(spec.get("collect", COLLECT_DEFAULT)
                        or COLLECT_DEFAULT),
            static_budget=bool(spec.get("static_budget", False)),
            placement=header_placement(spec),
        )

    # -- journal-header encoding (inject/journal.py) -------------------------
    def run_header_fields(self) -> Dict[str, object]:
        """The spec-owned fields of a ``mode: "run"`` journal header, in
        the header's historical key order (headers are serialized
        without sort_keys, so order is byte parity): seed, n, start_num,
        batch_size.  ``fault_model`` and ``stop_when`` are deliberately
        NOT here -- the runner places them at their historical header
        positions, and both follow absent-means-default evolution rules
        (:func:`header_fault_model`)."""
        return {"seed": int(self.seed), "n": int(self.n),
                "start_num": int(self.start_num),
                "batch_size": int(self.batch_size)}

    @classmethod
    def from_header(cls, header: Dict[str, object],
                    opt_passes: str = "-TMR",
                    section: str = "memory") -> "CampaignSpec":
        """Extract the identity vocabulary from a ``mode: "run"``
        journal header.  The header pins ``config_sha`` rather than the
        opt flag string (and carries no section), so those two are
        caller-supplied when known; everything else -- including the
        absent-means-default rules for ``fault_model``/``stop_when`` and
        equiv-block presence -- decodes here, the one place the rules
        are spelled."""
        return cls(
            benchmark=str(header.get("benchmark")),
            n=int(header.get("n", 0)),
            seed=int(header.get("seed", 0)),
            opt_passes=opt_passes,
            section=section,
            batch_size=int(header.get("batch_size", 4096)),
            start_num=int(header.get("start_num", 0)),
            fault_model=header_fault_model(header),
            equiv=bool(header.get("equiv")),
            stop_when=header.get("stop_when") or None,
            collect=header_collect(header),
            placement=header_placement(header),
        )

    # -- delta identity (analysis/equiv/delta.py) ----------------------------
    def delta_identity(self) -> Dict[str, object]:
        """The spec-owned half of delta-splice identity: the keys that
        must match between a delta base journal and the current campaign
        for the recorded outcomes to be reusable at all.  (``mode`` and
        ``strategy`` are header-level facts outside the spec; the
        protection config is deliberately absent -- the config changing
        is the whole point of a delta.)"""
        doc = {"benchmark": str(self.benchmark), "seed": int(self.seed),
               "n": int(self.n), "start_num": int(self.start_num),
               "fault_model": str(self.fault_model)}
        if self.placement != PLACEMENT_DEFAULT:
            # A placement change is a different REGION (different leaf
            # shapes, different blast radius), not just a different
            # protection config: spliced outcomes would be meaningless.
            # Only-when-set keeps every pre-placement identity dict --
            # and its comparisons -- byte-identical.
            doc["placement"] = str(self.placement)
        return doc


def header_fault_model(header: Dict[str, object]) -> str:
    """The PR 6 journal-evolution rule, spelled once: an absent
    ``fault_model`` header key means the historical single-bit model."""
    return str(header.get("fault_model", FAULT_MODEL_DEFAULT)
               or FAULT_MODEL_DEFAULT)


def header_collect(header: Dict[str, object]) -> str:
    """The collection-mode evolution rule, spelled once: an absent
    ``collect`` header key means the historical dense per-row fetch."""
    return str(header.get("collect", COLLECT_DEFAULT) or COLLECT_DEFAULT)


def header_placement(header: Dict[str, object]) -> str:
    """The voter-placement evolution rule, spelled once: an absent
    ``placement`` key means the registry build -- vote-then-exchange
    (``"compute"``).  Pre-placement journals and queue items decode (and
    resume) unchanged."""
    return str(header.get("placement", PLACEMENT_DEFAULT)
               or PLACEMENT_DEFAULT)


def header_fuse(header: Dict[str, object]) -> bool:
    """The fused-engine evolution rule, spelled once: an absent ``fuse``
    key means the historical unfused interpreter loop.  Pre-fusion
    journals and queue items decode (and resume) unchanged."""
    return bool(header.get("fuse", FUSE_DEFAULT))
