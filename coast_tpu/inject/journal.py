"""Durable campaign journal: crash-safe record of every collected batch.

The reference platform's single biggest engineering investment after
injection itself is surviving its own failures: the supervisor detects
wedged QEMU runs, restarts them, and resumes the seeded campaign at
``--start-num`` (supervisor.py:400-509, gdbClient.py:401).  The batched
engine kept the seeded-resume *math* (``start_num``, ``chunks``) but
until this module not the *machinery*: a flagship campaign that hit a
TPU preemption or a plain SIGKILL lost every completed batch.

A journal is an append-only ndjson file.  Line 1 is the **header** --
the campaign's identity (benchmark, strategy, protection-config
fingerprint, seed, n, start_num, batch geometry, schedule fingerprint).
The spec-owned subset of that vocabulary is one shared type,
:class:`coast_tpu.inject.spec.CampaignSpec`, which also serializes the
fleet queue-item and delta-identity encodings of the same facts.
Every subsequent line is one **record**, fsync'd as it is appended so a
kill at any instant leaves at worst one truncated trailing line (which
:meth:`CampaignJournal._load` tolerates and drops):

  * ``batch``    -- one collected dispatch batch: its row range
    (``lo``, ``n``) plus the per-run ``codes``/``errors``/``corrected``/
    ``steps`` columns, the cumulative class counts, and the stage
    seconds so far.  Sparse-collect campaigns (``collect: "sparse"`` in
    the header -- identity, so dense and sparse journals refuse each
    other) write the same record kind with ``"sparse": true``: the
    batch's 10-int class histogram + weighted invalid-draw count stand
    in for the full columns, and the per-row columns cover only the
    batch's *interesting* rows (class outside success/corrected), keyed
    by their absolute ``rows``.  ``batch_prefix`` treats both shapes
    identically (``lo``/``n`` carry the physical row range either way).
  * ``chunk``    -- one completed chunk of a multi-chunk campaign
    (``run_until_errors`` / ``replay_chunks``): its (seed, n,
    start_num) identity plus the same per-run columns.
  * ``geometry`` -- the runner degraded ``batch_size`` (OOM halving,
    :mod:`coast_tpu.inject.resilience`); recorded so the artifact trail
    explains the shape change.
  * ``retry``    -- a transient dispatch/collect failure was retried
    (forensics only; resume ignores it).
  * ``early_stop`` -- the campaign's statistical stop condition
    (``stop_when``, coast_tpu.obs.convergence) tripped after ``rows``
    rows: the journal is COMPLETE at that prefix, and resume replays
    to exactly there instead of extending it.  The condition itself
    rides in the header (identity: resuming under a different one
    refuses).

Batch records additionally carry their own span timing (``spans``:
``[name, unix_start_s, duration_s]`` triples), so a resumed campaign
re-materialises the crashed run's batches into its telemetry and the
exported Perfetto trace is ONE coherent timeline with replayed batches
marked as such.

Resume (``CampaignJournal.open`` on an existing file) validates the
header against the current program/schedule and **refuses mismatches
loudly** (:class:`JournalMismatchError`): a journal written for a
different seed, program, or protection config must never silently seed
another campaign's results.  ``batch_prefix`` then returns the
contiguous completed-batch prefix so ``run_schedule`` restarts at the
first missing batch -- the resumed campaign's ``codes`` is bit-for-bit
identical to the uninterrupted run (tests/test_resilience.py pins it).

FastFlip (arxiv 2403.13989) frames the same requirement
compositionally: error-injection results should be durable,
incrementally accumulated units that survive and compose across
interrupted analyses.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import os
from typing import Dict, List, Optional

import numpy as np

from coast_tpu.inject.spec import (header_fault_model, header_fuse,
                                   header_placement)
from coast_tpu.obs import flightrec

try:
    import fcntl
except ImportError:                     # pragma: no cover - non-POSIX
    fcntl = None

__all__ = [
    "JournalError", "JournalExistsError", "JournalMismatchError",
    "FaultModelMismatchError", "PlacementMismatchError",
    "FuseStepMismatchError", "JournalLockedError", "CampaignJournal",
    "schedule_fingerprint", "config_fingerprint",
]


class JournalError(RuntimeError):
    """Base class for journal failures (corrupt file, misuse)."""


class JournalExistsError(JournalError):
    """A non-empty journal exists and the caller did not ask to resume."""


class JournalMismatchError(JournalError):
    """The journal's header does not describe the current campaign."""


class JournalLockedError(JournalError):
    """Another process holds the journal's exclusive append lock.

    Every journal takes a non-blocking ``flock`` on its append handle
    for as long as it is open, so two fleet workers (or a worker and a
    wrongly-requeued duplicate of itself) can never interleave appends
    into one journal -- the loser fails fast with this typed error
    instead of silently corrupting the batch stream.  The lock dies
    with the process, so a SIGKILL'd worker's journal is immediately
    claimable by its replacement."""


class FaultModelMismatchError(JournalMismatchError):
    """The journal records a different FAULT MODEL than the resuming
    campaign.  Raised before (and instead of) the generic header diff: a
    model change also changes the schedule fingerprint, and "schedule-sha
    mismatch" would bury the actual cause -- the operator changed what an
    injection *is*, not the seed."""


class PlacementMismatchError(JournalMismatchError):
    """The journal records a different VOTER PLACEMENT than the resuming
    campaign.  Same burying argument as the fault model: the placement
    changes the region itself (halo leaf shape, memory map, schedule and
    config fingerprints), and the generic diff would report those
    derived symptoms instead of the knob the operator flipped.  Absent
    header key == ``"compute"`` (the registry build; pre-placement
    journals resume unchanged -- the rule lives in
    :func:`coast_tpu.inject.spec.header_placement`)."""


class FuseStepMismatchError(JournalMismatchError):
    """The journal records a different STEP ENGINE (fused vs. unfused)
    than the resuming campaign.  The fused path is pinned bit-identical,
    but the program the rows measured (op counts, kernel schedule, MFU
    attribution) is not the same program -- blending rows from both
    engines into one journal would corrupt any perf claim made from it.
    Absent header key == unfused (pre-fusion journals resume unchanged
    -- the rule lives in :func:`coast_tpu.inject.spec.header_fuse`)."""


def schedule_fingerprint(sched) -> str:
    """sha256 over a FaultSchedule's columns + seed: the journal's proof
    that a resumed campaign will inject exactly the recorded faults.
    Multi-site schedules also hash the fault model and every extra
    flip-group row; single-site schedules hash exactly the historical
    columns, so pre-model journals still validate."""
    h = hashlib.sha256()
    h.update(str(int(sched.seed)).encode())
    for field in ("leaf_id", "lane", "word", "bit", "t"):
        col = np.ascontiguousarray(getattr(sched, field), dtype=np.int32)
        h.update(col.tobytes())
    extra = getattr(sched, "extra", None)
    if extra is not None:
        h.update(sched.model.spec().encode())
        for key in sorted(extra):
            h.update(np.ascontiguousarray(extra[key],
                                          dtype=np.int32).tobytes())
    weights = getattr(sched, "class_weight", None)
    if weights is not None:
        # Equivalence-reduced schedule: the weights are part of what a
        # resumed campaign must replay exactly (they multiply counts).
        h.update(b"equiv")
        h.update(np.ascontiguousarray(weights, dtype=np.int64).tobytes())
    return h.hexdigest()


def config_fingerprint(cfg) -> str:
    """Stable fingerprint of a ProtectionConfig: resuming under different
    protection flags would measure a different program."""
    fields = dataclasses.asdict(cfg)
    # Evolution rule: knobs added after journals existed must vanish
    # from the fingerprint at their default value, or every pre-knob
    # journal's config_sha would spuriously change and refuse to resume.
    if not fields.get("fuse_step", False):
        fields.pop("fuse_step", None)
    doc = json.dumps(fields, sort_keys=True, default=str)
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


#: Header keys that may legitimately differ between the original run and
#: a resume (batch geometry is re-negotiable: OOM degradation changes it
#: mid-campaign, and the resumed process may choose another size -- the
#: per-row records make resume independent of batching).
#: ``section_fingerprints`` is the DELTA-campaign vocabulary, not resume
#: identity: journals written before the equivalence pass have no block
#: at all and must still open/resume cleanly (the absent-means-legacy
#: rule of the fault-model key), and any program change the fingerprints
#: could flag is already refused by config_sha/schedule_sha.
_VOLATILE_KEYS = frozenset({"batch_size", "created", "argv",
                            "section_fingerprints"})


class CampaignJournal:
    """Append-only fsync'd ndjson journal for one campaign."""

    VERSION = 1

    def __init__(self, path: str, header: Dict[str, object],
                 records: Optional[List[Dict[str, object]]] = None,
                 fsync: bool = True):
        self.path = path
        self.header = header
        self.fsync = fsync
        self._records: List[Dict[str, object]] = records or []
        self.resumed = records is not None
        self._fh = None

    # -- construction --------------------------------------------------------
    @classmethod
    def open(cls, path: str, header: Dict[str, object],
             resume: bool = True, fsync: bool = True) -> "CampaignJournal":
        """Create a fresh journal at ``path``, or resume the one already
        there.

        A fresh journal writes (and fsyncs) the header line immediately.
        An existing non-empty journal is validated: every header key
        except the volatile geometry ones must match ``header`` exactly,
        else :class:`JournalMismatchError` names the differing keys.
        ``resume=False`` refuses an existing non-empty journal outright
        (:class:`JournalExistsError`) -- the CLI's no-``--resume``
        safety."""
        header = {"format": "coast-journal", "version": cls.VERSION,
                  **header}
        if os.path.exists(path) and os.path.getsize(path) > 0:
            if not resume:
                raise JournalExistsError(
                    f"journal {path!r} already exists; pass --resume to "
                    "continue it or delete the file to start fresh")
            # Lock BEFORE loading: two resuming processes must not both
            # truncate a torn tail / replay the prefix and then race
            # their appends.
            fh = cls._locked_append_handle(path)
            try:
                found_header, records, valid_bytes = cls._load(path)
                cls._validate(found_header, header, path)
            except BaseException:
                fh.close()
                raise
            if valid_bytes < os.path.getsize(path):
                # Torn trailing line (kill mid-append): cut it off NOW,
                # before any new append would fuse onto the fragment and
                # corrupt the journal for the *next* resume.  (The
                # append handle is O_APPEND: it seeks to the new end on
                # every write, so truncating under it is safe.)
                with open(path, "rb+") as tfh:
                    tfh.truncate(valid_bytes)
            j = cls(path, found_header, records, fsync=fsync)
            j._fh = fh
            flightrec.record("journal_open", path=path, resumed=True,
                             records=len(records))
            return j
        j = cls(path, header, fsync=fsync)
        j.append({"kind": "header", **header})
        flightrec.record("journal_open", path=path, resumed=False)
        return j

    @staticmethod
    def _locked_append_handle(path: str):
        """Open ``path`` for append and take the exclusive non-blocking
        ``flock`` every open journal holds until close: the single-writer
        guarantee of the fleet (two workers can never interleave appends
        into one journal).  Raises :class:`JournalLockedError` if another
        process -- or another open handle in this one -- holds it."""
        fh = open(path, "a")
        if fcntl is not None:
            try:
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK,
                               errno.EACCES):
                    fh.close()
                    raise JournalLockedError(
                        f"journal {path!r} is locked by another process "
                        "(its campaign is still appending); a second "
                        "writer would interleave batch records.  Wait "
                        "for the holder to finish or requeue the work "
                        "item") from e
                if e.errno in (errno.ENOLCK, errno.ENOTSUP,
                               errno.EOPNOTSUPP, errno.EINVAL):
                    # Filesystem without flock support (some NFS
                    # mounts): degrade to unlocked, same as the
                    # no-fcntl platform path -- a bogus "locked" error
                    # here would make every fleet item ping-pong
                    # between workers forever.
                    return fh
                fh.close()
                raise
        return fh

    @staticmethod
    def _load(path: str):
        """Parse an existing journal, tolerating one truncated trailing
        line (the crash-mid-append case); corruption anywhere else is a
        hard error.  Returns (header, records, valid_bytes) where
        valid_bytes is the file length up to the last complete record --
        the caller truncates the torn tail before appending."""
        records: List[Dict[str, object]] = []
        valid_bytes = 0
        with open(path, "rb") as fh:
            lines = fh.readlines()
        for i, raw in enumerate(lines):
            line = raw.strip()
            if not line:
                valid_bytes += len(raw)
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                if i == len(lines) - 1:
                    break           # torn tail: the record never landed
                raise JournalError(
                    f"journal {path!r} is corrupt at line {i + 1}: "
                    f"{e}") from e
            records.append(rec)
            valid_bytes += len(raw)
        if not records or records[0].get("kind") != "header":
            raise JournalError(
                f"journal {path!r} has no header record; not a campaign "
                "journal (or its header line was torn)")
        header = {k: v for k, v in records[0].items() if k != "kind"}
        return header, records[1:], valid_bytes

    @staticmethod
    def _validate(found: Dict[str, object], expect: Dict[str, object],
                  path: str) -> None:
        # Fault-model mismatch first, as its own typed error: the model
        # also perturbs the schedule fingerprint, and the generic diff
        # below would report that derived symptom instead of the cause.
        # Absent key == "single" (journals written before the model;
        # the rule lives in coast_tpu.inject.spec with the rest of the
        # identity vocabulary).
        found_model = header_fault_model(found)
        expect_model = header_fault_model(expect)
        if found_model != expect_model:
            raise FaultModelMismatchError(
                f"journal {path!r} records fault model {found_model!r} but "
                f"this campaign runs {expect_model!r}; a resumed campaign "
                "must replay the recorded flip groups exactly.  Rerun with "
                "the original --fault-model, or start a fresh journal.")
        found_place = header_placement(found)
        expect_place = header_placement(expect)
        if found_place != expect_place:
            raise PlacementMismatchError(
                f"journal {path!r} records voter placement "
                f"{found_place!r} but this campaign runs "
                f"{expect_place!r}; the two placements are different "
                "programs (different halo leaf, different blast radius). "
                "Rerun with the original --placement, or start a fresh "
                "journal.")
        found_fuse = header_fuse(found)
        expect_fuse = header_fuse(expect)
        if found_fuse != expect_fuse:
            raise FuseStepMismatchError(
                f"journal {path!r} records "
                f"{'the fused' if found_fuse else 'the unfused'} step "
                f"engine but this campaign runs "
                f"{'the fused' if expect_fuse else 'the unfused'} one; "
                "the rows measured a different compiled program.  Rerun "
                "with the original fuse mode (-fuseStep/-noFuseStep), or "
                "start a fresh journal.")
        keys = (set(found) | set(expect)) - _VOLATILE_KEYS
        diffs = [k for k in sorted(keys) if found.get(k) != expect.get(k)]
        if diffs:
            detail = ", ".join(
                f"{k}: journal={found.get(k)!r} vs current="
                f"{expect.get(k)!r}" for k in diffs)
            raise JournalMismatchError(
                f"journal {path!r} records a different campaign; "
                f"refusing to resume ({detail}).  Delete the journal or "
                "rerun with the original program/seed/flags.")

    # -- appending -----------------------------------------------------------
    def append(self, record: Dict[str, object]) -> None:
        """Append one record and make it durable (flush + fsync) before
        returning, so a kill immediately after a batch is collected can
        never lose that batch.

        Appends are write-only: ``self._records`` holds what ``open``
        loaded from disk (the resume queries' input), never live
        appends -- a journaled 10^6-row campaign must not keep every
        batch's columns resident for its whole lifetime."""
        if self._fh is None:
            self._fh = self._locked_append_handle(self.path)
        self._fh.write(json.dumps(record, separators=(",", ":")) + "\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def append_batch(self, lo: int, out: Dict[str, np.ndarray],
                     counts: Dict[str, int],
                     stage_seconds: Dict[str, float],
                     spans: "Optional[list]" = None) -> None:
        """One fsync'd record per collected batch: row range, per-run
        columns, cumulative counts, stage seconds so far.  ``spans`` is
        the batch's own span timing -- ``(name, unix_start_s,
        duration_s)`` triples for its pad/dispatch/collect spans -- so a
        resumed campaign can re-materialise the crashed run's batches
        into one coherent exported trace (marked as replayed).  Optional
        and absent-tolerant: journals written before the key (or with
        telemetry disabled) replay without trace continuity, nothing
        else changes."""
        rec = {
            "kind": "batch", "lo": int(lo), "n": int(len(out["code"])),
            "codes": out["code"].tolist(),
            "errors": out["errors"].tolist(),
            "corrected": out["corrected"].tolist(),
            "steps": out["steps"].tolist(),
            "counts": counts,
            "stage_seconds": {k: round(v, 6)
                              for k, v in stage_seconds.items()},
        }
        if spans:
            rec["spans"] = [[str(name), float(t), float(dur)]
                            for name, t, dur in spans]
        self.append(rec)

    def append_batch_sparse(self, lo: int, n: int,
                            hist, invalid: int, rows,
                            out: Dict[str, np.ndarray],
                            counts: Dict[str, int],
                            stage_seconds: Dict[str, float],
                            spans: "Optional[list]" = None) -> None:
        """Sparse-collect batch record: the batch's class histogram +
        weighted invalid-draw count, and the per-row columns for only
        its interesting rows (absolute row numbers in ``rows``).  Same
        ``lo``/``n`` contract as the dense record, so ``batch_prefix``
        and the fleet merge's contiguity check read both shapes; the
        concatenated ``codes`` of a sparse journal are exactly the
        campaign's interesting-row codes (the fleet parity pin's
        subject in sparse mode)."""
        rec = {
            "kind": "batch", "sparse": True,
            "lo": int(lo), "n": int(n),
            "hist": [int(v) for v in hist],
            "invalid": int(invalid),
            "rows": [int(r) for r in rows],
            "codes": out["code"].tolist(),
            "errors": out["errors"].tolist(),
            "corrected": out["corrected"].tolist(),
            "steps": out["steps"].tolist(),
            "counts": counts,
            "stage_seconds": {k: round(v, 6)
                              for k, v in stage_seconds.items()},
        }
        if spans:
            rec["spans"] = [[str(name), float(t), float(dur)]
                            for name, t, dur in spans]
        self.append(rec)

    def append_chunk(self, res) -> None:
        """One completed chunk of a multi-chunk campaign (the CampaignResult
        of one ``run`` call inside ``run_until_errors``/``replay_chunks``)."""
        self.append({
            "kind": "chunk", "seed": int(res.seed), "n": int(res.n),
            "start_num": int(res.start_num),
            "codes": res.codes.tolist(),
            "errors": res.errors.tolist(),
            "corrected": res.corrected.tolist(),
            "steps": res.steps.tolist(),
            "counts": {k: int(v) for k, v in res.counts.items()},
            "seconds": round(float(res.seconds), 6),
            "stage_seconds": {k: round(v, 6)
                              for k, v in res.stages.items()},
        })

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "CampaignJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- resume queries (over the records loaded at open, not live
    # appends -- see append) -------------------------------------------------
    def records(self) -> List[Dict[str, object]]:
        return list(self._records)

    def batch_prefix(self, base: int, n_rows: int) -> List[Dict[str, object]]:
        """The contiguous completed-batch prefix of rows
        [``base``, ``base + n_rows``): batch records starting exactly at
        ``base`` with no gap.  ``run_schedule`` restarts at the first
        missing batch (``base + sum(n for rec in prefix)``).  Records
        below ``base`` belong to earlier chunks sharing this journal;
        a gap or out-of-range record ends the prefix (those rows were
        dispatched but never collected)."""
        out: List[Dict[str, object]] = []
        expected = int(base)
        for rec in self._records:
            if rec.get("kind") != "batch":
                continue
            lo = int(rec["lo"])
            if lo < base:
                continue
            if lo != expected or expected + int(rec["n"]) > base + n_rows:
                break
            out.append(rec)
            expected += int(rec["n"])
        return out

    def chunk_records(self) -> List[Dict[str, object]]:
        """Completed multi-chunk records, in append order."""
        return [r for r in self._records if r.get("kind") == "chunk"]
