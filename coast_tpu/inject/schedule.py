"""Seeded fault schedules: where/when each campaign run flips its bit.

The reference draws a uniformly random sleep inside the benchmark's runtime
window (threadFunctions.py:451-520) and a uniformly random address in a
size-weighted memory section (injector.py:125-200); with the QEMU plugin the
"when" is a uniformly random *cycle count* so injections are uniform in
cycles rather than wall clock (SURVEY.md #9).  Here a schedule is a struct of
arrays -- one row per injection: (leaf_id, lane, word, bit, t) -- generated
up front from a seed, so a whole campaign is deterministic and replayable
(the determinism-parity test of SURVEY.md §4 depends on this).

Generation is delegated to the native C++ core (coast_tpu.native:
counter-mode splitmix64 bulk generator) with a numpy fallback producing
bit-identical streams.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

from coast_tpu import obs
from coast_tpu.inject.mem import MemoryMap
from coast_tpu.native import splitmix_fill


@dataclasses.dataclass
class FaultSchedule:
    """One campaign's worth of injection targets (host-side numpy)."""

    leaf_id: np.ndarray   # int32 [n]
    lane: np.ndarray      # int32 [n]
    word: np.ndarray      # int32 [n]
    bit: np.ndarray       # int32 [n]
    t: np.ndarray         # int32 [n] step index of the flip
    section_idx: np.ndarray  # int32 [n] index into MemoryMap.sections
    seed: int

    def __len__(self) -> int:
        return len(self.leaf_id)

    def device_arrays(self) -> Dict[str, np.ndarray]:
        return {"leaf_id": self.leaf_id, "lane": self.lane,
                "word": self.word, "bit": self.bit, "t": self.t}

    def slice(self, lo: int, hi: int) -> "FaultSchedule":
        return FaultSchedule(
            self.leaf_id[lo:hi], self.lane[lo:hi], self.word[lo:hi],
            self.bit[lo:hi], self.t[lo:hi], self.section_idx[lo:hi], self.seed)


def generate(mmap: MemoryMap, n: int, seed: int,
             nominal_steps: int) -> FaultSchedule:
    """n seeded draws: uniform over all injectable bits x uniform over the
    nominal runtime window (the injection window of threadFunctions.py:451)."""
    with obs.span("schedule", n=n, seed=seed):
        raw = splitmix_fill(seed, 2 * n)      # uint64 stream, native or numpy
        flat_bits = (raw[:n] % np.uint64(mmap.total_bits)).astype(np.int64)
        t = (raw[n:] % np.uint64(max(nominal_steps, 1))).astype(np.int32)
        leaf_id, lane, word, bit, sec_idx = mmap.decode(flat_bits)
        return FaultSchedule(leaf_id, lane, word, bit, t,
                             sec_idx.astype(np.int32), seed)


def generate_stratified(mmap: MemoryMap, n_per_section: int, seed: int,
                        nominal_steps: int) -> FaultSchedule:
    """n_per_section seeded draws into EACH section (equal-allocation
    stratified sampling).

    Size-weighted sampling (``generate``) starves small sections: a 1-word
    loop counter next to a KiB-scale buffer draws a handful of injections
    per campaign, so its estimated harm rate is noise -- yet control words
    are exactly the high-leverage targets.  Equal allocation measures every
    section at the same resolution; population-level rates are recovered by
    size-reweighting (post-stratification), which is how the advisor uses
    it.  Rows are ordered section-major and deterministic per seed; each
    section's sub-stream is keyed by a splitmix draw from the master seed
    (not seed+idx, which would make adjacent master seeds share stream
    bits shifted one section over), so campaigns replay per stratum and
    different master seeds are decorrelated."""
    with obs.span("schedule", n_per_section=n_per_section, seed=seed,
                  stratified=True):
        return _generate_stratified(mmap, n_per_section, seed, nominal_steps)


def _generate_stratified(mmap: MemoryMap, n_per_section: int, seed: int,
                         nominal_steps: int) -> FaultSchedule:
    keys = splitmix_fill(seed, len(mmap.sections))
    section_start = np.cumsum([0] + [s.bits for s in mmap.sections])
    flat_parts = []
    t_parts = []
    for idx, sec in enumerate(mmap.sections):
        raw = splitmix_fill(int(keys[idx]), 2 * n_per_section)
        offs = (raw[:n_per_section] % np.uint64(sec.bits)).astype(np.int64)
        t_parts.append((raw[n_per_section:]
                        % np.uint64(max(nominal_steps, 1))).astype(np.int32))
        flat_parts.append(section_start[idx] + offs)
    # One source of truth for the bit layout: per-section offsets become
    # global flat indices and go through the same decode as generate().
    leaf_id, lane, word, bit, sec_idx = mmap.decode(
        np.concatenate(flat_parts))
    return FaultSchedule(leaf_id, lane, word, bit, np.concatenate(t_parts),
                         sec_idx.astype(np.int32), seed)


def generate_stratified_total(mmap: MemoryMap, total: int, seed: int,
                              nominal_steps: int) -> FaultSchedule:
    """Stratified schedule sized by a total budget: ``total`` is divided
    equally across sections, floored at one draw per section, so the
    actual campaign size is ``max(1, total // n_sections) * n_sections``
    (callers report len(schedule), which may round away from ``total``).
    Single allocation policy shared by the advisor and the supervisor."""
    n_per = max(1, total // len(mmap.sections))
    return generate_stratified(mmap, n_per, seed, nominal_steps)
