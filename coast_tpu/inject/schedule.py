"""Seeded fault schedules: where/when each campaign run flips its bit(s).

The reference draws a uniformly random sleep inside the benchmark's runtime
window (threadFunctions.py:451-520) and a uniformly random address in a
size-weighted memory section (injector.py:125-200); with the QEMU plugin the
"when" is a uniformly random *cycle count* so injections are uniform in
cycles rather than wall clock (SURVEY.md #9).  Here a schedule is a struct of
arrays -- one row per injection: (leaf_id, lane, word, bit, t) -- generated
up front from a seed, so a whole campaign is deterministic and replayable
(the determinism-parity test of SURVEY.md §4 depends on this).

COAST's original fault model is exactly one bit, one word, one step per
run.  Real upsets are not: multi-bit upsets flip several bits of one word,
spatially-correlated events span adjacent words (and, for replicated
state, adjacent replicas -- cloned globals sit at consecutive addresses),
and bursts deposit several upsets inside a short time window.  A
:class:`FaultModel` generalizes the schedule to per-injection flip
GROUPS: the base row keeps today's single-site layout (``FaultModel
.single`` schedules are bit-identical to the historical ``generate``
stream), and the extra sites live in a struct-of-arrays with a group
index (``FaultSchedule.extra``), expanded from the campaign seed by the
native core (coast_fault_expand) with a bit-identical numpy fallback.
FastFlip (arXiv:2403.13989) is why the model is explicit schedule
metadata rather than an injector knob: outcome-equivalence reasoning
needs the fault model in the campaign's identity (journal header,
config fingerprints), not just in its RNG.

Generation is delegated to the native C++ core (coast_tpu.native:
counter-mode splitmix64 bulk generator) with a numpy fallback producing
bit-identical streams.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional

import numpy as np

from coast_tpu import obs
from coast_tpu.inject.mem import MemoryMap
from coast_tpu.native import fault_expand, splitmix_fill


@dataclasses.dataclass(frozen=True)
class FaultModel:
    """How many bits one injection flips, and how they correlate.

    ``kind``:

    * ``single``           -- one bit, one word, one step (the historical
      COAST model; the default, and the bit-identical legacy stream).
    * ``multibit(k)``      -- k distinct bits of the SAME word at the same
      step (an intra-word MBU).  k <= 32.
    * ``cluster(span,k)``  -- k flips in adjacent words of the same leaf:
      each extra site lands 1..span words past the base site in the
      lane-major word space, so clusters can cross replica (lane)
      boundaries exactly as physically-adjacent cloned globals do.
    * ``burst(window,rate)`` -- temporally-bursty independent upsets:
      ``round(window * rate)`` sites (min 1), each at a fresh uniform
      location, fired at ``t0 + U[0, window)`` (clamped to the nominal
      window).
    * ``link(offset,period)`` -- an interconnect upset: one bit, but the
      draw is restricted to the program's ``link``-kind sections (the
      in-flight halo/exchange buffers of a sharded region,
      ir/region.KIND_LINK) and, when ``period > 0``, the flip step is
      restricted to the receive window ``offset + i*period`` -- the
      steps where the buffer's words are "on the wire" between a
      permute send and its receive (a flip outside the window would
      land on a buffer the next pack overwrites, i.e. a compute-side
      upset, not a link upset).  Defaults to the region's own
      ``meta['link_window']`` when the caller passes none.

    The link-kind sections are the ``link`` model's EXCLUSIVE surface:
    when a benchmark exposes them, every other model's base-site draw
    maps onto the complement (the compute/memory sections), so the
    per-model outcome tables partition the fault surface instead of
    double-counting in-flight words as memory upsets.  Benchmarks
    without link sections are bit-identical to the historical stream.
    (One asymmetry, by construction: ``burst`` EXTRA sites come from
    ``native.fault_expand``'s full-map uniform draw, whose native/numpy
    parity is pinned -- only base sites are restricted.)

    The classifier taxonomy is deliberately untouched by the model: a
    multi-site injection is still one run with one outcome code.

    Site coincidence: ``multibit`` engineers k *distinct* bits (odd bit
    stride over Z/32); ``cluster``/``burst`` draw their extra sites
    independently, so two sites of one group may land on the same
    (word, bit) and fire at the same step -- the XOR flips then cancel,
    exactly as a physical double-upset of one cell restores it.  The
    effective flip multiplicity is therefore <= sites (noticeably so
    only for tiny spans/leaves, e.g. cluster(span=1): each extra site
    has a 1/32 chance of restoring the previous one's bit).
    """

    kind: str = "single"
    k: int = 1            # sites for multibit/cluster
    span: int = 1         # max word offset of a cluster site
    window: int = 1       # burst time window (steps)
    rate: float = 1.0     # burst flips per step within the window
    # link only: receive-window arithmetic (t = offset + i*period).
    # (0, 0) means "no window": uniform over the nominal runtime, or the
    # region's declared meta['link_window'] when generate() is handed one.
    t_offset: int = 0
    t_period: int = 0

    def __post_init__(self):
        if self.kind not in ("single", "multibit", "cluster", "burst",
                             "link"):
            raise ValueError(f"unknown fault-model kind {self.kind!r}")
        if self.kind == "multibit" and not (2 <= self.k <= 32):
            raise ValueError("multibit needs 2 <= k <= 32 (distinct bits "
                             "of one 32-bit word)")
        if self.kind == "cluster" and (self.k < 2 or self.span < 1):
            raise ValueError("cluster needs k >= 2 sites and span >= 1")
        if self.kind == "burst" and (self.window < 1 or self.rate <= 0):
            raise ValueError("burst needs window >= 1 and rate > 0")
        if self.kind == "link":
            if self.t_offset < 0 or self.t_period < 0:
                raise ValueError("link needs offset >= 0 and period >= 0")
            if self.t_period == 0 and self.t_offset != 0:
                raise ValueError(
                    "link offset without a period is meaningless (the "
                    "window is offset + i*period); pass period too")
        elif self.t_offset or self.t_period:
            raise ValueError(
                f"offset/period are link-model arguments, not {self.kind!r}")

    # -- constructors --------------------------------------------------------
    @classmethod
    def single(cls) -> "FaultModel":
        return cls()

    @classmethod
    def multibit(cls, k: int = 2) -> "FaultModel":
        return cls(kind="multibit", k=int(k))

    @classmethod
    def cluster(cls, span: int = 4, k: int = 2) -> "FaultModel":
        return cls(kind="cluster", span=int(span), k=int(k))

    @classmethod
    def burst(cls, window: int = 8, rate: float = 0.25) -> "FaultModel":
        return cls(kind="burst", window=int(window), rate=float(rate))

    @classmethod
    def link(cls, offset: int = 0, period: int = 0) -> "FaultModel":
        return cls(kind="link", t_offset=int(offset), t_period=int(period))

    # -- identity ------------------------------------------------------------
    @property
    def sites(self) -> int:
        """Flip sites per injection (the group size; 1 == legacy path)."""
        if self.kind in ("multibit", "cluster"):
            return self.k
        if self.kind == "burst":
            return max(1, int(round(self.window * self.rate)))
        return 1

    def spec(self) -> str:
        """Canonical string form -- the journal-header / CLI vocabulary."""
        if self.kind == "multibit":
            return f"multibit(k={self.k})"
        if self.kind == "cluster":
            return f"cluster(span={self.span},k={self.k})"
        if self.kind == "burst":
            return f"burst(window={self.window},rate={self.rate:g})"
        if self.kind == "link":
            if self.t_period:
                return f"link(offset={self.t_offset},period={self.t_period})"
            return "link"
        return "single"

    @classmethod
    def parse(cls, text: str) -> "FaultModel":
        """Parse a CLI spec: ``single``, ``multibit(k=3)`` / ``multibit:k=3``
        / bare ``multibit`` (defaults), and likewise for cluster/burst."""
        text = text.strip()
        m = re.fullmatch(r"(\w+)(?:[:(]([^()]*)\)?)?", text)
        if not m:
            raise ValueError(f"unparseable fault model {text!r}")
        kind, argstr = m.group(1), (m.group(2) or "").strip()
        args: Dict[str, float] = {}
        if argstr:
            for part in argstr.split(","):
                key, _, val = part.partition("=")
                if not _:
                    raise ValueError(
                        f"fault-model argument {part!r} is not key=value")
                args[key.strip()] = float(val)
        try:
            if kind == "single":
                if args:
                    raise ValueError("single takes no arguments")
                return cls.single()
            if kind == "multibit":
                return cls.multibit(k=int(args.pop("k", 2)), **args)
            if kind == "cluster":
                return cls.cluster(span=int(args.pop("span", 4)),
                                   k=int(args.pop("k", 2)), **args)
            if kind == "burst":
                return cls.burst(window=int(args.pop("window", 8)),
                                 rate=args.pop("rate", 0.25), **args)
            if kind == "link":
                return cls.link(offset=int(args.pop("offset", 0)),
                                period=int(args.pop("period", 0)), **args)
        except TypeError as e:
            raise ValueError(f"bad fault-model arguments in {text!r}: {e}")
        raise ValueError(f"unknown fault-model kind {kind!r} in {text!r}")


#: Site-column names shared by the base rows and the extra-site arrays.
SITE_KEYS = ("leaf_id", "lane", "word", "bit", "t")


@dataclasses.dataclass
class FaultSchedule:
    """One campaign's worth of injection targets (host-side numpy).

    The five site columns hold each injection's BASE site (site 0) -- for
    ``FaultModel.single`` schedules that is the whole story and the
    layout is bit-identical to the historical single-bit schedule.
    Multi-site models add ``extra``: a struct-of-arrays of the remaining
    ``sites - 1`` flips per injection, site-major, with a ``group``
    column mapping each extra row back to its injection index within
    this schedule."""

    leaf_id: np.ndarray   # int32 [n]
    lane: np.ndarray      # int32 [n]
    word: np.ndarray      # int32 [n]
    bit: np.ndarray       # int32 [n]
    t: np.ndarray         # int32 [n] step index of the flip
    section_idx: np.ndarray  # int32 [n] index into MemoryMap.sections
    seed: int
    # Extra flip-group sites (None for single-site schedules): int32
    # arrays keyed "group" + SITE_KEYS, length n * (sites - 1), where
    # extra row i*(sites-1)+(j-1) is injection i's site j.
    extra: Optional[Dict[str, np.ndarray]] = None
    model: FaultModel = FaultModel()
    # Equivalence-reduced schedules (analysis/equiv): each row is one
    # propagation-class representative standing for ``class_weight[i]``
    # physically-drawn sites; ``equiv_sha`` is the partition fingerprint
    # (part of the campaign identity -- journaled and resume-validated).
    # None for ordinary exhaustive schedules.
    class_weight: Optional[np.ndarray] = None   # int64 [n]
    equiv_sha: Optional[str] = None
    # Device-regeneration metadata (inject/device_gen): a schedule whose
    # rows are a contiguous window of one ``generate()`` stream records
    # the stream's full length, this window's offset into it, and the
    # step-window modulus the t column was drawn with, so a
    # sparse-collect campaign can regenerate every row's flip sites
    # inside the compiled step from (seed, stream_n, row index) alone --
    # no per-batch fault upload.  ``gen_steps`` is part of the identity:
    # regenerating with any other modulus would inject at different
    # timesteps than the host schedule records.  None for schedules the
    # stream cannot reproduce row-by-row (stratified strata,
    # equivalence-reduced subsets, cache overlays, merged chunks).
    gen_stream_n: Optional[int] = None
    gen_lo: int = 0
    gen_steps: Optional[int] = None

    def __len__(self) -> int:
        return len(self.leaf_id)

    @property
    def effective_n(self) -> int:
        """Injections this schedule REPRESENTS: the physical row count,
        or the summed class weights of a reduced schedule."""
        if self.class_weight is None:
            return len(self)
        return int(self.class_weight.sum())

    @property
    def sites(self) -> int:
        """Flip sites per injection (1 unless a multi-site model)."""
        return 1 if self.extra is None else self.model.sites

    def device_arrays(self) -> Dict[str, np.ndarray]:
        """Per-injection fault columns for the device: 1-D [n] for the
        single-site path (bit-identical to the legacy layout, so the
        compiled program is unchanged), [n, sites] for flip groups
        (column 0 is the base site)."""
        if self.extra is None:
            return {"leaf_id": self.leaf_id, "lane": self.lane,
                    "word": self.word, "bit": self.bit, "t": self.t}
        n, e = len(self), self.sites - 1
        return {k: np.concatenate(
                    [getattr(self, k)[:, None],
                     self.extra[k].reshape(n, e)], axis=1)
                for k in SITE_KEYS}

    def slice(self, lo: int, hi: int) -> "FaultSchedule":
        extra = None
        if self.extra is not None:
            e = self.sites - 1
            extra = {k: v[lo * e:hi * e] for k, v in self.extra.items()}
            extra["group"] = (extra["group"] - np.int32(lo)).astype(np.int32)
        return FaultSchedule(
            self.leaf_id[lo:hi], self.lane[lo:hi], self.word[lo:hi],
            self.bit[lo:hi], self.t[lo:hi], self.section_idx[lo:hi],
            self.seed, extra=extra, model=self.model,
            class_weight=(None if self.class_weight is None
                          else self.class_weight[lo:hi]),
            equiv_sha=self.equiv_sha,
            gen_stream_n=self.gen_stream_n,
            gen_lo=self.gen_lo + lo,
            gen_steps=self.gen_steps)


def _expand(mmap: MemoryMap, sched: FaultSchedule, model: FaultModel,
            seed: int, nominal_steps: int) -> FaultSchedule:
    """Attach a multi-site model's extra flip-group rows to a base
    schedule (native splitmix expansion; numpy fallback bit-identical)."""
    if model.kind == "single":
        return sched
    sched.model = model
    if model.sites == 1:          # e.g. burst(window*rate <= 1): base only
        return sched
    tables = mmap.section_tables()
    base = {"leaf_id": sched.leaf_id, "lane": sched.lane,
            "word": sched.word, "bit": sched.bit, "t": sched.t,
            "section_idx": sched.section_idx}
    group, leaf_id, lane, word, bit, t = fault_expand(
        seed, model.kind, model.sites, model.span, model.window,
        max(nominal_steps, 1), base, tables)
    sched.extra = {"group": group, "leaf_id": leaf_id, "lane": lane,
                   "word": word, "bit": bit, "t": t}
    return sched


def _draw_tables(mmap: MemoryMap, link: bool):
    """Site-draw remapping tables for the sections with (link=True) or
    without (link=False) ``kind == 'link'``: per-section bit sizes, local
    cumulative edges, and each section's global flat-bit start."""
    idx = [i for i, s in enumerate(mmap.sections)
           if (s.kind == "link") == link]
    sizes = np.array([mmap.sections[i].bits for i in idx], np.int64)
    local_edges = np.cumsum(sizes)
    all_edges = np.cumsum([s.bits for s in mmap.sections]).astype(np.int64)
    global_starts = np.array(
        [all_edges[i] - mmap.sections[i].bits for i in idx], np.int64)
    return sizes, local_edges, global_starts


def _nonlink_sites(mmap: MemoryMap, raws: np.ndarray) -> np.ndarray:
    """Base-site draws for every non-link fault model: uniform over the
    non-link sections' bits, relocated into the global flat space.  With
    no link sections in the map this is exactly ``raws % total_bits``
    (the pinned historical stream, byte for byte)."""
    if not any(s.kind == "link" for s in mmap.sections):
        return (raws % np.uint64(mmap.total_bits)).astype(np.int64)
    sizes, local_edges, global_starts = _draw_tables(mmap, link=False)
    if not len(sizes):
        raise ValueError(
            "every injectable section is link-kind: non-link fault "
            "models have no compute/memory surface to draw from")
    local = (raws % np.uint64(int(local_edges[-1]))).astype(np.int64)
    li = np.searchsorted(local_edges, local, side="right")
    return global_starts[li] + (local - (local_edges[li] - sizes[li]))


def generate(mmap: MemoryMap, n: int, seed: int, nominal_steps: int,
             model: Optional[FaultModel] = None,
             equiv: "Optional[object]" = None) -> FaultSchedule:
    """n seeded draws: uniform over all injectable bits x uniform over the
    nominal runtime window (the injection window of threadFunctions.py:451).

    ``model`` generalizes each draw to a flip group (FaultModel); the
    default single-bit stream is bit-identical to the historical one,
    and a multi-site model's BASE sites are that same stream -- the
    extra sites come from a derived expansion stream, so the single-bit
    component of any model replays the legacy campaign exactly.

    ``equiv`` (a :class:`coast_tpu.analysis.equiv.EquivPartition`)
    reduces the n-draw stream to one seeded representative per realized
    propagation-equivalence class: the returned schedule's rows are a
    subset of the exhaustive stream (first draw of each class, stream
    order) and carry ``class_weight`` so classification counts multiply
    back out to the full n.  Only defined for the single-bit model --
    flip-group outcomes are not site-equivalence-reasoned."""
    with obs.span("schedule", n=n, seed=seed):
        raw = splitmix_fill(seed, 2 * n)      # uint64 stream, native or numpy
        if model is not None and model.kind == "link":
            if equiv is not None:
                raise ValueError(
                    "equiv= reduction is defined for the single-bit "
                    f"fault model, not {model.spec()!r}: link draws are "
                    "restricted to the interconnect sections and their "
                    "receive window, which the site-equivalence partition "
                    "does not reason about")
            with obs.span("schedule_link", model=model.spec()):
                return _generate_link(mmap, raw, n, seed, nominal_steps,
                                      model)
        flat_bits = _nonlink_sites(mmap, raw[:n])
        t = (raw[n:] % np.uint64(max(nominal_steps, 1))).astype(np.int32)
        leaf_id, lane, word, bit, sec_idx = mmap.decode(flat_bits)
        sched = FaultSchedule(leaf_id, lane, word, bit, t,
                              sec_idx.astype(np.int32), seed,
                              gen_stream_n=n,
                              gen_steps=max(nominal_steps, 1))
        if model is not None and model.kind != "single":
            if equiv is not None:
                raise ValueError(
                    "equiv= reduction is defined for the single-bit "
                    f"fault model, not {model.spec()!r}: a flip GROUP's "
                    "outcome is not a function of one site's "
                    "propagation class")
            with obs.span("schedule_expand", model=model.spec()):
                return _expand(mmap, sched, model, seed, nominal_steps)
        if equiv is not None:
            with obs.span("schedule_equiv"):
                reduced = equiv.reduce(sched)
                obs.count("equiv_reduced_rows", len(sched) - len(reduced),
                          physical=len(reduced), effective=len(sched))
                return reduced
        return sched


def link_steps(model: FaultModel, nominal_steps: int) -> int:
    """Receive-window size of a link model: how many distinct steps its t
    column can take inside the nominal runtime.  Shared by the host
    generator and the device regeneration path so the two cannot drift."""
    steps = max(nominal_steps, 1)
    if model.t_period <= 0:
        return steps
    k = len(range(model.t_offset, steps, model.t_period))
    if k < 1:
        raise ValueError(
            f"link window offset={model.t_offset} starts past the nominal "
            f"runtime ({steps} steps): no receive step to flip at")
    return k


def _generate_link(mmap: MemoryMap, raw: np.ndarray, n: int, seed: int,
                   nominal_steps: int, model: FaultModel) -> FaultSchedule:
    """Link-model draws: the same raw splitmix stream as ``generate``,
    but site draws map onto the union of link-kind sections' bits (the
    in-flight halo words) and the t draw maps into the receive window."""
    sizes, local_edges, global_starts = _draw_tables(mmap, link=True)
    if not len(sizes):
        raise ValueError(
            "fault model 'link' needs at least one link-kind section "
            "(ir/region.KIND_LINK leaf) in the injectable map; this "
            "benchmark exposes none -- it has no interconnect surface")
    local = (raw[:n] % np.uint64(int(local_edges[-1]))).astype(np.int64)
    li = np.searchsorted(local_edges, local, side="right")
    flat_bits = global_starts[li] + (local - (local_edges[li] - sizes[li]))

    k = link_steps(model, nominal_steps)
    draws = (raw[n:] % np.uint64(k)).astype(np.int64)
    if model.t_period > 0:
        t = (model.t_offset + draws * model.t_period).astype(np.int32)
    else:
        t = draws.astype(np.int32)

    leaf_id, lane, word, bit, sec_idx = mmap.decode(flat_bits)
    return FaultSchedule(leaf_id, lane, word, bit, t,
                         sec_idx.astype(np.int32), seed, model=model,
                         gen_stream_n=n, gen_steps=max(nominal_steps, 1))


def generate_stratified(mmap: MemoryMap, n_per_section: int, seed: int,
                        nominal_steps: int,
                        model: Optional[FaultModel] = None) -> FaultSchedule:
    """n_per_section seeded draws into EACH section (equal-allocation
    stratified sampling).

    Size-weighted sampling (``generate``) starves small sections: a 1-word
    loop counter next to a KiB-scale buffer draws a handful of injections
    per campaign, so its estimated harm rate is noise -- yet control words
    are exactly the high-leverage targets.  Equal allocation measures every
    section at the same resolution; population-level rates are recovered by
    size-reweighting (post-stratification), which is how the advisor uses
    it.  Rows are ordered section-major and deterministic per seed; each
    section's sub-stream is keyed by a splitmix draw from the master seed
    (not seed+idx, which would make adjacent master seeds share stream
    bits shifted one section over), so campaigns replay per stratum and
    different master seeds are decorrelated.

    ``model`` expands the concatenated base rows into flip groups exactly
    as in ``generate`` (the expansion is keyed by the master seed)."""
    with obs.span("schedule", n_per_section=n_per_section, seed=seed,
                  stratified=True):
        if model is not None and model.kind == "link":
            raise ValueError(
                "stratified allocation contradicts the 'link' fault model: "
                "link draws target ONLY the link-kind sections (use "
                "generate() with the link model instead)")
        sched = _generate_stratified(mmap, n_per_section, seed,
                                     nominal_steps)
        if model is None or model.kind == "single":
            return sched
        with obs.span("schedule_expand", model=model.spec()):
            return _expand(mmap, sched, model, seed, nominal_steps)


def _generate_stratified(mmap: MemoryMap, n_per_section: int, seed: int,
                         nominal_steps: int) -> FaultSchedule:
    keys = splitmix_fill(seed, len(mmap.sections))
    section_start = np.cumsum([0] + [s.bits for s in mmap.sections])
    flat_parts = []
    t_parts = []
    for idx, sec in enumerate(mmap.sections):
        if sec.kind == "link":
            # The link-kind sections belong to the 'link' model (which
            # stratified refuses above); drawing memory-model strata into
            # them would double-count the interconnect surface.  Keys stay
            # indexed by global section position so the other strata's
            # sub-streams are unchanged by the skip.
            continue
        raw = splitmix_fill(int(keys[idx]), 2 * n_per_section)
        offs = (raw[:n_per_section] % np.uint64(sec.bits)).astype(np.int64)
        t_parts.append((raw[n_per_section:]
                        % np.uint64(max(nominal_steps, 1))).astype(np.int32))
        flat_parts.append(section_start[idx] + offs)
    # One source of truth for the bit layout: per-section offsets become
    # global flat indices and go through the same decode as generate().
    leaf_id, lane, word, bit, sec_idx = mmap.decode(
        np.concatenate(flat_parts))
    return FaultSchedule(leaf_id, lane, word, bit, np.concatenate(t_parts),
                         sec_idx.astype(np.int32), seed)


def generate_stratified_total(mmap: MemoryMap, total: int, seed: int,
                              nominal_steps: int,
                              model: Optional[FaultModel] = None
                              ) -> FaultSchedule:
    """Stratified schedule sized by a total budget: ``total`` is divided
    equally across sections, floored at one draw per section, so the
    actual campaign size is ``max(1, total // n_sections) * n_sections``
    (callers report len(schedule), which may round away from ``total``).
    Single allocation policy shared by the advisor and the supervisor.

    The flooring is usually a few rows of rounding, but a budget smaller
    than (or barely above) the section count realizes a very different
    campaign than requested -- that deviation is surfaced, not silent:
    >10% drift from ``total`` emits a one-line warning and an obs
    counter (``stratified_budget_drift_rows``)."""
    n_sections = sum(1 for s in mmap.sections if s.kind != "link")
    n_per = max(1, total // max(n_sections, 1))
    realized = n_per * n_sections
    if total > 0 and abs(realized - total) > 0.10 * total:
        import sys
        obs.count("stratified_budget_drift_rows", abs(realized - total),
                  requested=int(total), realized=int(realized),
                  sections=n_sections)
        print(f"warning: stratified budget {total} realized as {realized} "
              f"rows ({n_sections} sections x {n_per}/section, "
              f"{100.0 * abs(realized - total) / total:.0f}% off the "
              "requested budget)", file=sys.stderr)
    return generate_stratified(mmap, n_per, seed, nominal_steps, model=model)
