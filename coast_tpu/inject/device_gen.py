"""On-device fault-schedule generation: the host-upload eliminator.

Every seeded schedule this repo runs is counter-mode splitmix64
(``native.splitmix_fill``: value i = finalizer(seed + (i+1)*golden)), so
a schedule row is a pure function of (seed, row index) -- there is no
reason to expand it on the host and ship O(n * sites) int32 fault
arrays down the PCIe link per batch.  This module re-implements the
exact splitmix64 stream -- and the fault-model expansion streams of
``native.fault_expand`` -- as jax-traceable 32-bit arithmetic (XLA on
TPU has no 64-bit integer path without the global x64 flag, so u64 is
emulated as (hi, lo) uint32 pairs), letting the compiled campaign step
regenerate its own flip sites from a scalar row offset.

Bit parity with the host path is a hard contract, pinned per fault-model
kind in tests/test_sparse.py the same way native-vs-numpy expansion
parity is pinned: the host-side ``FaultSchedule`` remains the campaign's
source of truth (journal fingerprints, log site columns), and the device
must provably inject exactly those sites.

The HBM-resident-state discipline follows the TPU CFD framework
(arXiv:2108.11076); the scale motivation (10^7-10^8 injection campaigns
cheap enough to gate merges) is FastFlip's (arXiv:2403.13989).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.inject.mem import MemoryMap
from coast_tpu.inject.schedule import FaultModel, link_steps
from coast_tpu.native import FAULT_EXPAND_SALT

__all__ = ["DeviceGenError", "DeviceScheduleGen"]

_MASK32 = 0xFFFFFFFF

# splitmix64 constants, split into (hi, lo) uint32 halves.
_GOLDEN = (0x9E3779B9, 0x7F4A7C15)
_MIX1 = (0xBF58476D, 0x1CE4E5B9)
_MIX2 = (0x94D049BB, 0x133111EB)


class DeviceGenError(ValueError):
    """The schedule cannot be regenerated on device (address space too
    large for the 32-bit emulation, unsupported model)."""


# -- u64 as (hi, lo) uint32 pairs -------------------------------------------

def _u32(x) -> jax.Array:
    return jnp.asarray(x, jnp.uint32)


def _const64(value: int) -> Tuple[jax.Array, jax.Array]:
    value &= 0xFFFFFFFFFFFFFFFF
    return _u32(value >> 32), _u32(value & _MASK32)


def _add64(x, y):
    lo = x[1] + y[1]
    carry = (lo < x[1]).astype(jnp.uint32)
    return x[0] + y[0] + carry, lo


def _mul32(a, b):
    """Full 32x32 -> 64 product as (hi, lo) uint32."""
    a0 = a & _u32(0xFFFF)
    a1 = a >> 16
    b0 = b & _u32(0xFFFF)
    b1 = b >> 16
    ll = a0 * b0
    m1 = a1 * b0
    m2 = a0 * b1
    hh = a1 * b1
    carry = ((ll >> 16) + (m1 & _u32(0xFFFF)) + (m2 & _u32(0xFFFF))) >> 16
    lo = ll + (m1 << 16) + (m2 << 16)
    hi = hh + (m1 >> 16) + (m2 >> 16) + carry
    return hi, lo


def _mul64(x, y):
    """Low 64 bits of the u64 product (exactly numpy's wrapping *)."""
    hi, lo = _mul32(x[1], y[1])
    return hi + x[1] * y[0] + x[0] * y[1], lo


def _xor64(x, y):
    return x[0] ^ y[0], x[1] ^ y[1]


def _shr64(z, k: int):
    """z >> k for constant 1 <= k <= 31."""
    hi, lo = z
    return hi >> k, (lo >> k) | (hi << (32 - k))


def _splitmix64(seed, counter):
    """finalizer(seed + counter * golden): counter-mode splitmix64, the
    exact stream of native.splitmix_fill (value i uses counter i+1)."""
    z = _add64(seed, _mul64(counter, _const64(0x9E3779B97F4A7C15)))
    z = _mul64(_xor64(z, _shr64(z, 30)), _const64(0xBF58476D1CE4E5B9))
    z = _mul64(_xor64(z, _shr64(z, 27)), _const64(0x94D049BB133111EB))
    return _xor64(z, _shr64(z, 31))


def _mod64(z, m: int) -> jax.Array:
    """(hi, lo) u64 modulo a host-constant m (1 <= m < 2^32) -> uint32.

    lo reduces natively; each set bit k of hi contributes the host
    constant 2^(32+k) mod m, folded in with an overflow-safe conditional
    subtract (both operands stay < m < 2^32 at every step)."""
    if not 1 <= m < (1 << 32):
        raise DeviceGenError(f"modulus {m} outside the u32 emulation range")
    hi, lo = z
    if m & (m - 1) == 0:
        # Power of two: the low bits are the remainder.
        return lo & _u32(m - 1)
    m32 = _u32(m)
    r = lo % m32
    for k in range(32):
        c = (1 << (32 + k)) % m
        if c == 0:
            continue
        term = ((hi >> k) & _u32(1)) * _u32(c)
        r = jnp.where(r >= m32 - term, r - (m32 - term), r + term)
    return r


# -- the generator -----------------------------------------------------------

class DeviceScheduleGen:
    """Regenerates a seeded ``generate()`` stream (any FaultModel kind)
    inside a compiled program, from (seed, stream length, row index).

    Seed and stream length arrive as *traced* scalars, so one compiled
    campaign step serves every seed -- the per-batch host upload is the
    scalar row offset, nothing else.  The section layout, nominal step
    window, and fault-model geometry are trace-time constants (they are
    campaign identity anyway)."""

    def __init__(self, mmap: MemoryMap, nominal_steps: int,
                 model: Optional[FaultModel] = None):
        self.model = model if model is not None else FaultModel()
        bits_end, sec_leaf, sec_lanes, sec_words = mmap.section_tables()
        self.total_bits = int(bits_end[-1])
        if self.total_bits >= (1 << 32):
            raise DeviceGenError(
                f"injectable address space is {self.total_bits} bits; "
                "the on-device generator's 32-bit address emulation "
                "covers < 2^32 bits -- run this campaign with "
                "collect='dense'")
        self.steps = max(int(nominal_steps), 1)
        starts = bits_end - np.asarray([s.bits for s in mmap.sections],
                                       np.int64)
        # Trace-time constant tables (uint32 is safe: total_bits < 2^32).
        self._edges = jnp.asarray(bits_end.astype(np.uint32))
        self._starts = jnp.asarray(starts.astype(np.uint32))
        self._leaf = jnp.asarray(sec_leaf.astype(np.int32))
        self._lanes = jnp.asarray(sec_lanes.astype(np.uint32))
        self._words = jnp.asarray(sec_words.astype(np.uint32))
        if self.model.kind == "link":
            # Restricted draw tables: site draws map onto the union of the
            # link-kind sections' bits (the in-flight halo words), exactly
            # mirroring schedule._generate_link's host mapping.
            link_idx = [i for i, s in enumerate(mmap.sections)
                        if s.kind == "link"]
            if not link_idx:
                raise DeviceGenError(
                    "fault model 'link' has no link-kind sections to "
                    "regenerate draws for on this benchmark")
            sizes = np.array([mmap.sections[i].bits for i in link_idx],
                             np.int64)
            ledges = np.cumsum(sizes)
            self.link_total = int(ledges[-1])
            self._link_edges = jnp.asarray(ledges.astype(np.uint32))
            self._link_local_starts = jnp.asarray(
                (ledges - sizes).astype(np.uint32))
            self._link_global_starts = jnp.asarray(
                starts[link_idx].astype(np.uint32))
            self._link_k = link_steps(self.model, self.steps)
        else:
            # The complement restriction: when the map exposes link-kind
            # sections they are the link model's EXCLUSIVE surface, so
            # every other model's base-site draw maps onto the non-link
            # sections' bits (schedule._nonlink_sites).  draw_total is
            # None on maps without link sections: the base draw is then
            # plain `site % total_bits` (the pinned historical stream).
            nl_idx = [i for i, s in enumerate(mmap.sections)
                      if s.kind != "link"]
            self.draw_total = None
            if len(nl_idx) != len(mmap.sections):
                if not nl_idx:
                    raise DeviceGenError(
                        "every injectable section is link-kind: non-link "
                        "fault models have no surface to regenerate")
                sizes = np.array([mmap.sections[i].bits for i in nl_idx],
                                 np.int64)
                dedges = np.cumsum(sizes)
                self.draw_total = int(dedges[-1])
                self._draw_edges = jnp.asarray(dedges.astype(np.uint32))
                self._draw_local_starts = jnp.asarray(
                    (dedges - sizes).astype(np.uint32))
                self._draw_global_starts = jnp.asarray(
                    starts[nl_idx].astype(np.uint32))

    # -- decode (MemoryMap.decode, on device) --------------------------------
    def _decode(self, flat: jax.Array):
        sec = jnp.searchsorted(self._edges, flat, side="right")
        off = flat - self._starts[sec]
        wpl = self._words[sec] * _u32(32)
        lane = off // wpl
        rem = off % wpl
        return (self._leaf[sec], lane.astype(jnp.int32),
                (rem >> 5).astype(jnp.int32),
                (off & _u32(31)).astype(jnp.int32), sec)

    # -- the stream ----------------------------------------------------------
    def columns(self, seed: Tuple[jax.Array, jax.Array],
                stream_n: jax.Array,
                rows: jax.Array) -> Dict[str, jax.Array]:
        """Fault columns for global stream rows ``rows`` (uint32 [B]):
        int32 [B] per key for the single model, [B, sites] (column 0 the
        base site) for flip groups -- exactly
        ``generate(mmap, stream_n, seed, steps, model).device_arrays()``
        at those rows, bit for bit.

        ``seed`` is a (hi, lo) uint32 scalar pair; ``stream_n`` the full
        stream length (generate()'s n: the t column's draws start at
        stream index n, so the layout depends on it)."""
        rows = rows.astype(jnp.uint32)
        zero = jnp.zeros_like(rows)
        c_site = (zero, rows + _u32(1))
        c_t = _add64(c_site, (jnp.uint32(0), stream_n.astype(jnp.uint32)))
        model = self.model
        if model.kind == "link":
            # Same raw stream positions as the generic path, restricted
            # draw mapping: site modulo the link sections' bit total then
            # relocated into the global flat space; t modulo the receive
            # window then mapped to offset + draw*period.
            local = _mod64(_splitmix64(seed, c_site), self.link_total)
            lsec = jnp.searchsorted(self._link_edges, local, side="right")
            flat = (self._link_global_starts[lsec]
                    + (local - self._link_local_starts[lsec]))
            leaf, lane, word, bit, _sec = self._decode(flat)
            draw = _mod64(_splitmix64(seed, c_t), self._link_k)
            if model.t_period > 0:
                t = (_u32(model.t_offset)
                     + draw * _u32(model.t_period)).astype(jnp.int32)
            else:
                t = draw.astype(jnp.int32)
            return {"leaf_id": leaf, "lane": lane, "word": word,
                    "bit": bit, "t": t}
        if self.draw_total is not None:
            # Non-link base draw on a map WITH link sections: modulo the
            # non-link bit total, relocated into the global flat space.
            local = _mod64(_splitmix64(seed, c_site), self.draw_total)
            dsec = jnp.searchsorted(self._draw_edges, local, side="right")
            flat = (self._draw_global_starts[dsec]
                    + (local - self._draw_local_starts[dsec]))
        else:
            flat = _mod64(_splitmix64(seed, c_site), self.total_bits)
        leaf, lane, word, bit, sec = self._decode(flat)
        t = _mod64(_splitmix64(seed, c_t), self.steps).astype(jnp.int32)
        if model.kind == "single" or model.sites == 1:
            return {"leaf_id": leaf, "lane": lane, "word": word,
                    "bit": bit, "t": t}
        # Derived expansion stream: exp_seed = splitmix_at(seed, SALT),
        # computed in-trace so the seed stays a runtime scalar.
        exp_seed = _splitmix64(seed, _const64(FAULT_EXPAND_SALT + 1))
        base = {"leaf_id": leaf, "lane": lane, "word": word,
                "bit": bit, "t": t}
        cols = {k: [v] for k, v in base.items()}
        extras = model.sites - 1
        for j in range(1, model.sites):
            site = self._extra_site(model, exp_seed, rows, extras, j,
                                    base, sec)
            for k in cols:
                cols[k].append(site[k])
        return {k: jnp.stack(v, axis=1) for k, v in cols.items()}

    def _extra_site(self, model: FaultModel, exp_seed, rows, extras: int,
                    j: int, base: Dict[str, jax.Array], sec: jax.Array
                    ) -> Dict[str, jax.Array]:
        """Site ``j`` (1-based) of each row's flip group: the numpy
        fallback of ``native.fault_expand``, re-spelled in u32 pairs."""
        zero = jnp.zeros_like(rows)
        if model.kind == "multibit":
            u = _splitmix64(exp_seed, (zero, rows + _u32(1)))
            stride = _u32(1) + _u32(2) * (u[1] & _u32(15))
            bit = ((base["bit"].astype(jnp.uint32) + _u32(j) * stride)
                   & _u32(31)).astype(jnp.int32)
            return {**base, "bit": bit}
        # cluster/burst: extra row r = i*extras + (j-1) consumes the
        # derived stream's draws 2r and 2r+1 (counters 2r+1, 2r+2).
        r = _add64(_mul64((zero, rows), _const64(extras)),
                   _const64(j - 1))
        c0 = _add64(_mul64(r, _const64(2)), _const64(1))
        u0 = _splitmix64(exp_seed, c0)
        u1 = _splitmix64(exp_seed, _add64(c0, _const64(1)))
        if model.kind == "cluster":
            words = self._words[sec]
            lw = self._lanes[sec] * words
            phys = (base["lane"].astype(jnp.uint32) * words
                    + base["word"].astype(jnp.uint32) + _u32(1)
                    + _mod64(u0, model.span)) % lw
            return {"leaf_id": base["leaf_id"],
                    "lane": (phys // words).astype(jnp.int32),
                    "word": (phys % words).astype(jnp.int32),
                    "bit": (u1[1] & _u32(31)).astype(jnp.int32),
                    "t": base["t"]}
        # burst: fresh uniform location + clustered time.
        flat = _mod64(u0, self.total_bits)
        leaf, lane, word, bit, _sec = self._decode(flat)
        tj = jnp.minimum(
            base["t"] + _mod64(u1, model.window).astype(jnp.int32),
            self.steps - 1)
        return {"leaf_id": leaf, "lane": lane, "word": word, "bit": bit,
                "t": jnp.where(base["t"] < 0, base["t"], tj)}

    # -- host-side convenience (tests, debugging) ----------------------------
    def rows_np(self, seed: int, stream_n: int,
                rows: np.ndarray) -> Dict[str, np.ndarray]:
        """Host entry point: run the traced generator over ``rows`` and
        fetch the columns -- the parity tests' subject."""
        seed &= 0xFFFFFFFFFFFFFFFF
        fn = jax.jit(lambda sh, sl, n, r: self.columns((sh, sl), n, r))
        out = fn(np.uint32(seed >> 32), np.uint32(seed & _MASK32),
                 np.uint32(stream_n), np.asarray(rows, np.uint32))
        return {k: np.asarray(v) for k, v in out.items()}
