"""Batched fault-injection campaigns: the supervisor.py replacement.

The reference campaign loop costs seconds per injection: spawn QEMU + GDB,
sleep to a random point, interrupt, GDB round-trips to flip one bit, run to
a breakpoint, parse UART, restart everything when a run wedges
(threadFunctions.py:315-953; supervisor.py:400-509).  Here an entire batch
of injections is ONE jitted XLA program:

    vmap over campaigns ( scan over steps ( flip-at-t  +  N-lane step ) )

so the per-injection cost is amortised to a few microseconds, and the only
host<->device traffic is one classification tensor per batch (the north-star
>=1000x injections/sec of BASELINE.json).  Campaign scale-out across chips
-- the reference runs multiple supervisors side-by-side on disjoint port
ranges (supervisor.py:335,386-391) -- is the batch axis sharded over a
device mesh (coast_tpu.parallel.mesh).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.inject import classify as cls
from coast_tpu.inject.mem import MemoryMap
from coast_tpu.inject.schedule import FaultSchedule, generate
from coast_tpu.passes.dataflow_protection import ProtectedProgram


@dataclasses.dataclass
class CampaignResult:
    """Aggregate + per-run results of one campaign (host-side)."""

    benchmark: str
    strategy: str
    n: int
    counts: Dict[str, int]            # class name -> count
    seconds: float
    codes: np.ndarray                 # int32 [n] class code per run
    errors: np.ndarray                # int32 [n] E per run
    corrected: np.ndarray             # int32 [n] F per run
    steps: np.ndarray                 # int32 [n] T per run
    schedule: FaultSchedule
    seed: int
    # For merged multi-chunk campaigns (run_until_errors): the exact
    # (seed, n) of every chunk, in order.  The merged ``schedule``
    # concatenates different-seed streams, so ``seed`` alone cannot
    # regenerate it; replaying these chunks (CampaignRunner.replay_chunks)
    # reproduces ``codes`` bit-for-bit.  None for single-seed campaigns,
    # where ``seed`` + ``n`` suffice.
    chunks: Optional[List[Dict[str, int]]] = None

    @property
    def injections_per_sec(self) -> float:
        return self.n / self.seconds if self.seconds > 0 else float("inf")

    @property
    def due(self) -> int:
        """DUE bucket: aborts also count as timeouts in the reference's
        summary (jsonParser.py:165-172)."""
        return self.counts["due_abort"] + self.counts["due_timeout"]

    def summary(self) -> Dict[str, object]:
        out = {
            "benchmark": self.benchmark,
            "strategy": self.strategy,
            "injections": self.n,
            **self.counts,
            "due": self.due,
            "seconds": round(self.seconds, 6),
            "injections_per_sec": round(self.injections_per_sec, 2),
            "seed": self.seed,
        }
        if self.chunks is not None:
            out["chunks"] = self.chunks
        return out


class CampaignRunner:
    """Runs seeded bit-flip campaigns against one protected program."""

    def __init__(self, prog: ProtectedProgram,
                 sections: Optional[Sequence[str]] = None,
                 strategy_name: Optional[str] = None,
                 unroll: int = 1):
        """``unroll`` forwards to ``ProtectedProgram.run``: how many
        early-exit steps each loop iteration executes.  Classification is
        identical at any value (overshoot sub-steps are masked no-ops);
        it trades per-iteration loop overhead against masked work.
        MEASURED on-chip (artifacts/unroll_sweep.json, 2026-08-01): with
        one-hot indexing the knob is noise (48.4-57.7k inj/s across
        {1,2,4,8}) and under the slice lowering it HURTS (5.8k -> 3.7k),
        so the default stays 1; the win the hypothesis predicted belonged
        to the indexing mode, not the unroll."""
        self.prog = prog
        self.mmap = MemoryMap(prog, sections)
        self.strategy_name = strategy_name or f"N={prog.cfg.num_clones}"
        self.unroll = max(1, int(unroll))
        out_words = int(np.prod(jax.eval_shape(
            prog.region.output, jax.eval_shape(prog.region.init)).shape))

        def run_one(fault: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            rec = prog.run(fault, unroll=self.unroll)
            return {
                "code": cls.classify(rec, out_words),
                "errors": rec["errors"],
                "corrected": rec["corrected"],
                "steps": rec["steps"],
            }

        self._run_one = run_one
        self._run_batch = jax.jit(jax.vmap(run_one))

    # -- overridable batching hooks (ShardedCampaignRunner replaces these) --
    def _round_batch(self, batch_size: int) -> int:
        # Floor at one row: call sites clamp to len(schedule) to avoid
        # padding waste, and an empty schedule (cache draws all invalid,
        # zero budget) must step range() by 1, not 0.
        return max(1, batch_size)

    @staticmethod
    def _padded_fault(part: FaultSchedule, batch_size: int):
        """Device fault arrays for one batch, edge-padded to batch_size so
        every batch hits the same compiled program.  Returns (fault, n_valid);
        callers drop or mask the padded tail."""
        n_part = len(part)
        pad = batch_size - n_part if n_part < batch_size else 0
        fault = {k: jnp.asarray(np.pad(v, (0, pad), mode="edge"))
                 for k, v in part.device_arrays().items()}
        return fault, n_part

    def _dispatch(self, fault: Dict[str, jax.Array]):
        """Launch one batch; returns the (async) device result."""
        return self._run_batch(fault)

    @staticmethod
    def _collect(pending) -> Dict[str, np.ndarray]:
        """Block on a dispatched batch and fetch it to the host."""
        return jax.device_get(pending)

    # -- execution ----------------------------------------------------------
    def run_schedule(self, sched: FaultSchedule,
                     batch_size: int = 4096) -> CampaignResult:
        # Deliberately no clamp to len(sched) here: every batch is
        # edge-padded to batch_size so all chunks (including a caller's
        # externally-sliced tail, e.g. scripts/campaign_1m.py) share one
        # compiled program.  One-shot small campaigns clamp at the call
        # site (advisor, supervisor) where a single smaller compile beats
        # padding waste.
        batch_size = self._round_batch(batch_size)
        t0 = time.perf_counter()
        outs: List[Dict[str, np.ndarray]] = []
        # Double-buffered: dispatch batch i+1 before collecting batch i, so
        # the host-side fetch (one tunnel round-trip per batch) overlaps the
        # device work -- jax dispatch is async, the device_get is the only
        # blocking point.
        in_flight: List[Tuple[object, int]] = []
        for lo in range(0, len(sched), batch_size):
            part = sched.slice(lo, min(lo + batch_size, len(sched)))
            fault, n_part = self._padded_fault(part, batch_size)
            in_flight.append((self._dispatch(fault), n_part))
            if len(in_flight) > 1:
                pending, n_prev = in_flight.pop(0)
                got = self._collect(pending)
                outs.append({k: v[:n_prev] for k, v in got.items()})
        for pending, n_prev in in_flight:
            got = self._collect(pending)
            outs.append({k: v[:n_prev] for k, v in got.items()})
        if outs:
            merged = {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}
        else:
            merged = {k: np.zeros(0, np.int32)
                      for k in ("code", "errors", "corrected", "steps")}
        seconds = time.perf_counter() - t0
        # Cache draws outside the program footprint (t < 0) never fire a
        # flip: a clean run that injected nothing is not a "survived
        # injection", so they get their own bucket instead of inflating
        # success -- the analogue of the reference summary's cacheValids
        # column (jsonParser.py summarizeRuns counts lines whose cacheInfo
        # says the chosen line was not dirty).
        invalid_draw = np.asarray(sched.t) < 0
        binc = np.bincount(merged["code"][~invalid_draw],
                           minlength=cls.NUM_CLASSES)
        counts = {name: int(binc[i]) for i, name in enumerate(cls.CLASS_NAMES)}
        counts["cache_invalid"] = int(invalid_draw.sum())
        return CampaignResult(
            benchmark=self.prog.region.name,
            strategy=self.strategy_name,
            n=len(sched),
            counts=counts,
            seconds=seconds,
            codes=merged["code"],
            errors=merged["errors"],
            corrected=merged["corrected"],
            steps=merged["steps"],
            schedule=sched,
            seed=sched.seed,
        )

    def run(self, n: int, seed: int = 0,
            batch_size: int = 4096, start_num: int = 0) -> CampaignResult:
        """``start_num`` resumes a seeded campaign at injection #start_num:
        the schedule stream for (seed, start_num+n) is generated and the
        first start_num rows skipped, so a resumed campaign injects exactly
        the faults the interrupted one would have (the --start-num counter
        of gdbClient.py:401)."""
        sched = generate(self.mmap, start_num + n, seed,
                         self.prog.region.nominal_steps)
        return self.run_schedule(sched.slice(start_num, start_num + n),
                                 batch_size)

    def run_until_errors(self, min_errors: int, seed: int = 0,
                         batch_size: int = 4096,
                         round_to: int = 1000,
                         max_n: int = 1_000_000) -> CampaignResult:
        """The reference's campaign-sizing convention: inject until N SDC
        errors are seen, then round the campaign up to the next ``round_to``
        (supervisor.py:339; threadFunctions.py:534-558).

        The result's ``chunks`` records every chunk's exact (seed, n), and
        ``replay_chunks(result.chunks)`` reproduces the campaign
        bit-for-bit -- the merged schedule spans several seed streams, so
        the master seed alone cannot."""
        results: List[CampaignResult] = []
        total = 0
        errors_seen = 0
        chunk_seed = seed
        while total < max_n:
            res = self.run(batch_size, seed=chunk_seed, batch_size=batch_size)
            results.append(res)
            total += res.n
            errors_seen += res.counts["sdc"]
            chunk_seed += 1
            if errors_seen >= min_errors:
                break
        target = ((total + round_to - 1) // round_to) * round_to
        while total < target and total < max_n:
            res = self.run(min(batch_size, target - total), seed=chunk_seed,
                           batch_size=batch_size)
            results.append(res)
            total += res.n
            chunk_seed += 1
        return _merge_results(results, seed)

    def replay_chunks(self, chunks: Sequence[Dict[str, int]],
                      batch_size: int = 4096) -> CampaignResult:
        """Re-run a recorded multi-chunk campaign exactly.

        ``chunks`` is ``CampaignResult.chunks`` (each entry ``{"seed", "n"}``);
        the replay regenerates each chunk's seeded schedule and merges in
        the same order, so ``codes`` matches the original bit-for-bit --
        the campaign-resume guarantee of gdbClient.py:401 extended to the
        error-bounded sizing loop."""
        results = [self.run(int(c["n"]), seed=int(c["seed"]),
                            batch_size=batch_size) for c in chunks]
        return _merge_results(results, int(chunks[0]["seed"]) if chunks
                              else 0)


def _merge_results(parts: List[CampaignResult], seed: int) -> CampaignResult:
    first = parts[0]
    counts = {k: sum(p.counts[k] for p in parts) for k in first.counts}
    sched = FaultSchedule(
        *(np.concatenate([getattr(p.schedule, f) for p in parts])
          for f in ("leaf_id", "lane", "word", "bit", "t", "section_idx")),
        seed=seed)
    return CampaignResult(
        benchmark=first.benchmark,
        strategy=first.strategy,
        n=sum(p.n for p in parts),
        counts=counts,
        seconds=sum(p.seconds for p in parts),
        codes=np.concatenate([p.codes for p in parts]),
        errors=np.concatenate([p.errors for p in parts]),
        corrected=np.concatenate([p.corrected for p in parts]),
        steps=np.concatenate([p.steps for p in parts]),
        schedule=sched,
        seed=seed,
        chunks=[{"seed": p.seed, "n": p.n} for p in parts],
    )
