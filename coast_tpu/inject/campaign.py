"""Batched fault-injection campaigns: the supervisor.py replacement.

The reference campaign loop costs seconds per injection: spawn QEMU + GDB,
sleep to a random point, interrupt, GDB round-trips to flip one bit, run to
a breakpoint, parse UART, restart everything when a run wedges
(threadFunctions.py:315-953; supervisor.py:400-509).  Here an entire batch
of injections is ONE jitted XLA program:

    vmap over campaigns ( scan over steps ( flip-at-t  +  N-lane step ) )

so the per-injection cost is amortised to a few microseconds, and the only
host<->device traffic is one classification tensor per batch (the north-star
>=1000x injections/sec of BASELINE.json).  Campaign scale-out across chips
-- the reference runs multiple supervisors side-by-side on disjoint port
ranges (supervisor.py:335,386-391) -- is the batch axis sharded over a
device mesh (coast_tpu.parallel.mesh).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu import obs
from coast_tpu.obs import flightrec
from coast_tpu.inject import classify as cls
from coast_tpu.inject import resilience as resilience_mod
from coast_tpu.inject.journal import (CampaignJournal, JournalMismatchError,
                                      config_fingerprint,
                                      schedule_fingerprint)
from coast_tpu.inject.mem import MemoryMap
from coast_tpu.inject.schedule import FaultModel, FaultSchedule, generate
from coast_tpu.inject.spec import CampaignSpec
from coast_tpu.passes.dataflow_protection import ProtectedProgram


@dataclasses.dataclass
class CampaignResult:
    """Aggregate + per-run results of one campaign (host-side)."""

    benchmark: str
    strategy: str
    n: int
    counts: Dict[str, int]            # class name -> count
    seconds: float
    codes: np.ndarray                 # int32 [n] class code per run
    errors: np.ndarray                # int32 [n] E per run
    corrected: np.ndarray             # int32 [n] F per run
    steps: np.ndarray                 # int32 [n] T per run
    schedule: FaultSchedule
    seed: int
    # For merged multi-chunk campaigns (run_until_errors, resumable
    # flagship loops): the exact (seed, n, start_num) of every chunk, in
    # order.  The merged ``schedule`` concatenates several seeded
    # streams, so ``seed`` alone cannot regenerate it; replaying these
    # chunks (CampaignRunner.replay_chunks) reproduces ``codes``
    # bit-for-bit.  None for single-seed campaigns, where ``seed`` +
    # ``n`` suffice -- including externally-sliced ones
    # (scripts/campaign_1m.py): slices of one seed stream are NOT
    # replayable as independent chunk records, because generate(n)'s t
    # column depends on the stream length n.
    chunks: Optional[List[Dict[str, int]]] = None
    # Per-stage wall-clock attribution (schedule/pad/dispatch/collect/
    # classify seconds, plus serialize once a logs writer ran), recorded
    # by the runner's Telemetry; {} when telemetry is disabled.
    stages: Dict[str, float] = dataclasses.field(default_factory=dict)
    # First injection number of this campaign within its seed stream
    # (CampaignRunner.run's resume offset); chunk records carry it so
    # replay_chunks can regenerate resumed chunks exactly.
    start_num: int = 0
    # Fault-tolerant-dispatch accounting (retry_transient / retry_wedged /
    # oom_degrade counts, coast_tpu.inject.resilience); populated -- with
    # zeros -- whenever the runner had a RetryPolicy, {} otherwise.
    resilience: Dict[str, int] = dataclasses.field(default_factory=dict)
    # Equivalence-reduced campaigns (analysis/equiv): ``n`` and
    # ``counts`` are over EFFECTIVE injections (each representative
    # multiplied by its class weight); ``physical_n`` is how many
    # representatives actually ran.  None for exhaustive campaigns.
    physical_n: Optional[int] = None
    # Delta-campaign accounting (run_delta): changed sections, reused vs
    # re-injected row counts.  None for ordinary campaigns.
    delta: Optional[Dict[str, object]] = None
    # Statistical-convergence block (obs/convergence): per-class Wilson
    # intervals at campaign end, the stop condition, and whether it
    # tripped (``stopped`` True means the schedule was cut short at
    # ``done_n`` of ``planned_n`` effective injections).  None unless
    # the campaign ran with ``stop_when=``.
    convergence: Optional[Dict[str, object]] = None
    # Collection mode (CampaignRunner(collect=)): "dense" fetches every
    # row's outcome columns (the historical behavior; codes/errors/
    # corrected/steps cover all n rows), "sparse" keeps the loop
    # device-resident -- counts come from per-batch histograms and the
    # per-run columns cover only the INTERESTING rows (class outside
    # success/corrected), indexed by ``interesting_rows``.
    collect: str = "dense"
    # Sparse campaigns: schedule-local row index (int64) of each entry
    # of codes/errors/corrected/steps.  None in dense mode.
    interesting_rows: Optional[np.ndarray] = None
    # Measured host<->device traffic in bytes ({"up", "down"}), recorded
    # on every campaign the runner executes -- the quantity the sparse
    # mode exists to shrink.  Empty for results rebuilt from journals.
    transfer: Dict[str, int] = dataclasses.field(default_factory=dict)
    # Device-time attribution (CampaignRunner(profile=True)): the
    # per-dispatch blocking-marker timeline -- device-busy / host-gap /
    # host-other seconds summing exactly to the campaign wall clock,
    # per-phase device seconds, dispatch-latency histograms, and the
    # roofline "mfu" sub-block (coast_tpu.obs.profiler / roofline).
    # None for unprofiled campaigns (the default), so every existing
    # summary stays byte-identical.
    profile: Optional[Dict[str, object]] = None
    # Reliability-SLO verdicts (obs/slo.summary_block) when the runner
    # (or its metrics hub) carried an SLO set: per-objective attainment,
    # error-budget remaining, burn rate, worst verdict.  None otherwise,
    # so unconfigured summaries stay byte-identical.
    slo: Optional[Dict[str, object]] = None
    # Sharded-backend accounting (ShardedCampaignRunner): the mesh
    # geometry (device count, axis names/sizes) plus the per-shard
    # interesting-row counts this process collected -- which physical
    # shard's runs produced the non-success outcomes.  None on the
    # single-device runner, so every existing summary stays
    # byte-identical.
    mesh: Optional[Dict[str, object]] = None

    @property
    def injections_per_sec(self) -> float:
        """Device-honest rate: physically dispatched runs per second."""
        phys = self.physical_n if self.physical_n is not None else self.n
        return phys / self.seconds if self.seconds > 0 else float("inf")

    def record_stage(self, name: str, seconds: float) -> None:
        """Accumulate ``seconds`` into one stage bucket (log writers add
        'serialize' here after the campaign object already exists)."""
        self.stages[name] = self.stages.get(name, 0.0) + float(seconds)

    @property
    def due(self) -> int:
        """DUE bucket: aborts (and the stack-overflow / assert-fail
        sub-buckets) also count as timeouts in the reference's summary
        (jsonParser.py:165-172)."""
        return sum(self.counts[k] for k in cls.DUE_CLASSES)

    @property
    def sdc_total(self) -> int:
        """Uncorrected silent corruption: ``sdc`` plus the persistent
        train refinement (classify.SDC_CLASSES; the self-heal bucket is
        deliberately excluded -- the converged loss was not corrupted)."""
        return sum(self.counts.get(k, 0) for k in cls.SDC_CLASSES)

    @property
    def fault_model(self) -> FaultModel:
        """The schedule's fault model (FaultModel.single legacy default)."""
        return getattr(self.schedule, "model", None) or FaultModel()

    def summary(self) -> Dict[str, object]:
        stages = {k: round(v, 6) for k, v in self.stages.items()}
        # ``overlap`` is part of the stage vocabulary, not an optional
        # extra: 0.0 simply means no serialization was hidden under
        # dispatch (streaming off).  Always present, so every consumer
        # (json_parser, mwtf_report, fleet scrapers) can read it without
        # branching on absence.
        stages.setdefault("overlap", 0.0)
        out = {
            "benchmark": self.benchmark,
            "strategy": self.strategy,
            "injections": self.n,
            **self.counts,
            "due": self.due,
            "seconds": round(self.seconds, 6),
            "injections_per_sec": round(self.injections_per_sec, 2),
            "seed": self.seed,
            "stages": stages,
        }
        if self.transfer:
            # Host<->device traffic, alongside the stage seconds it
            # explains.  Volatile-classed like ``stages`` (a telemetry
            # block, not campaign identity).
            out["transfer_bytes"] = {
                "up": int(self.transfer.get("up", 0)),
                "down": int(self.transfer.get("down", 0))}
        if self.collect != "dense":
            # Absent-means-dense: dense log summaries stay byte-stable.
            out["collect"] = self.collect
            out["interesting_rows"] = int(len(self.codes))
        # The fault-model axis of the logs: only non-single models add the
        # key, so single-bit campaign logs stay byte-identical to every
        # log written before the model existed.
        if self.fault_model.kind != "single":
            out["fault_model"] = self.fault_model.spec()
            out["fault_sites"] = self.fault_model.sites
        # The equivalence axis follows the same absent-means-exhaustive
        # rule: only reduced campaigns add the keys.
        if self.physical_n is not None:
            out["physical_injections"] = int(self.physical_n)
            out["equiv_reduction"] = round(
                self.n / self.physical_n, 2) if self.physical_n else 0.0
        if self.delta is not None:
            out["delta"] = dict(self.delta)
        if self.profile is not None:
            # Telemetry-classed blocks like ``stages``/``transfer_bytes``
            # (volatile, never campaign identity): the device-time
            # attribution, with the roofline accounting split out as its
            # own ``mfu`` key for json_parser / mwtf_report consumers.
            prof = dict(self.profile)
            mfu = prof.pop("mfu", None)
            out["profile"] = prof
            if mfu is not None:
                out["mfu"] = mfu
        if self.convergence is not None:
            out["convergence"] = dict(self.convergence)
        if self.slo is not None:
            out["slo"] = dict(self.slo)
        if self.mesh is not None:
            # Sharded campaigns only (absent-means-single-device, so
            # every single-device summary stays byte-identical): the
            # mesh geometry and which shard's runs produced the
            # interesting outcomes.
            out["mesh"] = dict(self.mesh)
        if self.chunks is not None:
            out["chunks"] = self.chunks
        if self.resilience:
            out["resilience"] = dict(self.resilience)
        return out


def _pack_layout(out_words: int, max_steps: int) -> tuple:
    """Bit layout of the sparse interesting-row packed word: code(4) |
    errors(e) | corrected(f) | steps(t), summing to exactly 32.

    ``steps`` is hard-bounded by the watchdog (<= max_steps) and
    ``errors`` by the output size for non-invalid runs, so both fields
    are sized to always fit; ``corrected`` takes the remainder with its
    all-ones value reserved as the NOT-PACKABLE sentinel (garbage E on
    an invalid run, an overflowing correction count) -- sentinel rows
    ride the exact int32 side buffer instead.  Returns (e_bits, f_bits,
    t_bits)."""
    t_bits = min(max(int(max_steps).bit_length(), 1), 20)
    e_bits = min(max(int(out_words + 1).bit_length(), 1), 27 - t_bits)
    f_bits = 28 - e_bits - t_bits
    return e_bits, f_bits, t_bits


def _sparse_device_outputs(out: Dict[str, jax.Array], count_w: jax.Array,
                           valid: jax.Array, cap: int, pack: tuple
                           ) -> Dict[str, jax.Array]:
    """Device-side sparse accounting over one (shard of a) batch: the
    weighted class histogram, the interesting-row bitmask, and the
    fixed-capacity compaction buffers.  Shared by the single-device
    runner and the shard_map body of the sharded backend (the histogram
    is psum-able; everything else is shard-local).

    Returns hist[NUM_CLASSES] i32, n_int/n_exact i32 scalars,
    mask u32[ceil(B/32)], packed u32[cap+1], exact i32[cap+1, 3].
    Buffer slot ``cap`` is the shared overflow sink (dropped on fetch);
    correctness under overflow comes from the caller's dense fallback.
    """
    e_bits, f_bits, t_bits = pack
    sentinel = (1 << f_bits) - 1
    code = out["code"]
    err, cor, steps = out["errors"], out["corrected"], out["steps"]
    hist = jnp.sum(jax.nn.one_hot(code, cls.NUM_CLASSES, dtype=jnp.int32)
                   * count_w[:, None], axis=0)
    interesting = jnp.logical_and(valid, code > cls.CORRECTED)
    n_int = jnp.sum(interesting.astype(jnp.int32))
    packable = ((err >= 0) & (err < (1 << e_bits))
                & (cor >= 0) & (cor < sentinel)
                & (steps >= 0) & (steps < (1 << t_bits)))
    cu = code.astype(jnp.uint32) & jnp.uint32(15)
    word = (cu
            | ((err.astype(jnp.uint32) & jnp.uint32((1 << e_bits) - 1))
               << 4)
            | ((cor.astype(jnp.uint32) & jnp.uint32(sentinel))
               << (4 + e_bits))
            | ((steps.astype(jnp.uint32) & jnp.uint32((1 << t_bits) - 1))
               << (4 + e_bits + f_bits)))
    packed_word = jnp.where(
        packable, word, cu | jnp.uint32(sentinel << (4 + e_bits)))
    exact_sel = jnp.logical_and(interesting, jnp.logical_not(packable))
    n_exact = jnp.sum(exact_sel.astype(jnp.int32))
    # Bitmask: bit k of word w marks row w*32+k interesting -- the
    # host derives row numbers from it, so no index column crosses the
    # link.
    n = code.shape[0]
    n_words = (n + 31) // 32
    bits = jnp.pad(interesting, (0, n_words * 32 - n)).reshape(n_words, 32)
    mask = jnp.sum(bits.astype(jnp.uint32)
                   << jnp.arange(32, dtype=jnp.uint32)[None, :], axis=1)
    # Stream compaction into the fixed buffers: position = running
    # count of interesting rows so far, clamped to the overflow sink.
    idx = jnp.cumsum(interesting.astype(jnp.int32)) - 1
    pos = jnp.where(jnp.logical_and(interesting, idx < cap), idx, cap)
    packed = jnp.zeros(cap + 1, jnp.uint32).at[pos].set(packed_word)
    eidx = jnp.cumsum(exact_sel.astype(jnp.int32)) - 1
    epos = jnp.where(jnp.logical_and(exact_sel, eidx < cap), eidx, cap)
    exact = jnp.zeros((cap + 1, 3), jnp.int32).at[epos].set(
        jnp.stack([err, cor, steps], axis=1))
    return {"hist": hist, "n_int": n_int, "n_exact": n_exact,
            "mask": mask, "packed": packed, "exact": exact}


def _mask_rows(mask: np.ndarray, limit: int) -> np.ndarray:
    """Interesting-row positions encoded in a device bitmask (host
    side): bit k of word w -> row w*32+k, clipped to ``limit``."""
    bits = ((mask[:, None] >> np.arange(32, dtype=np.uint32)) & 1
            ).astype(bool).ravel()
    rows = np.flatnonzero(bits[:limit])
    return rows


def _unpack_rows(packed: np.ndarray, exact: np.ndarray, pack: tuple):
    """Packed interesting-row words -> exact (code, E, F, T) int32
    columns; sentinel rows (corrected-field all ones) take their E/F/T
    from the exact side buffer, in order."""
    e_bits, f_bits, t_bits = pack
    sentinel = (1 << f_bits) - 1
    code = (packed & 15).astype(np.int32)
    err = ((packed >> 4) & ((1 << e_bits) - 1)).astype(np.int32)
    cor = ((packed >> (4 + e_bits)) & sentinel).astype(np.int32)
    steps = (packed >> (4 + e_bits + f_bits)).astype(np.int32)
    is_sent = cor == sentinel
    n_sent = int(is_sent.sum())
    if n_sent:
        if len(exact) < n_sent:
            raise RuntimeError(
                "sparse collect: sentinel rows exceed the exact "
                "buffer prefix (device/host accounting diverged)")
        err[is_sent] = exact[:n_sent, 0]
        cor[is_sent] = exact[:n_sent, 1]
        steps[is_sent] = exact[:n_sent, 2]
    return code, err, cor, steps


def _rows_subset(sched: FaultSchedule, rows: np.ndarray) -> FaultSchedule:
    """Arbitrary-row BASE-SITE subset of ``sched`` (model preserved,
    extra flip-group rows dropped -- per-row serialization only ever
    records the base site, exactly as in dense logs).  The one subset
    builder behind the delta paths' working shape
    (:meth:`CampaignRunner._take_rows`) and the sparse log writers'
    interesting-row slices."""
    idx = np.asarray(rows, np.int64)
    return FaultSchedule(
        *(np.ascontiguousarray(np.asarray(getattr(sched, f))[idx])
          for f in ("leaf_id", "lane", "word", "bit", "t",
                    "section_idx")),
        seed=sched.seed, model=sched.model,
        class_weight=(sched.class_weight[idx]
                      if sched.class_weight is not None else None),
        equiv_sha=sched.equiv_sha)


class CampaignRunner:
    """Runs seeded bit-flip campaigns against one protected program."""

    def __new__(cls, prog: ProtectedProgram, *args, **kw):
        # ``mesh=`` promotes the runner to the sharded backend
        # (coast_tpu.parallel.mesh.ShardedCampaignRunner): campaign
        # scale-out is a constructor argument, not a separate import --
        # the batch axis shard_map'd over the mesh, classification
        # seed-stable and identical to single-device at the same
        # schedule.  Instantiating the subclass routes its __init__
        # automatically (type(obj).__init__ is what Python calls).
        if cls is CampaignRunner and kw.get("mesh") is not None:
            from coast_tpu.parallel.mesh import ShardedCampaignRunner
            return object.__new__(ShardedCampaignRunner)
        return object.__new__(cls)

    def __init__(self, prog: ProtectedProgram,
                 sections: Optional[Sequence[str]] = None,
                 strategy_name: Optional[str] = None,
                 unroll: int = 1,
                 telemetry: Optional[obs.Telemetry] = None,
                 preflight: "bool | str" = False,
                 retry: "Optional[object]" = None,
                 mesh: "Optional[object]" = None,
                 fault_model: "Optional[FaultModel]" = None,
                 equiv: "bool | object" = False,
                 metrics: "Optional[object]" = None,
                 collect: str = "dense",
                 sparse_capacity: "Optional[int]" = None,
                 profile: "bool | object" = False,
                 slo: "Optional[object]" = None,
                 slo_baseline: "Optional[Dict[str, float]]" = None):
        """``unroll`` forwards to ``ProtectedProgram.run``: how many
        early-exit steps each loop iteration executes.  Classification is
        identical at any value (overshoot sub-steps are masked no-ops);
        it trades per-iteration loop overhead against masked work.
        MEASURED on-chip (artifacts/unroll_sweep.json, 2026-08-01): with
        one-hot indexing the knob is noise (48.4-57.7k inj/s across
        {1,2,4,8}) and under the slice lowering it HURTS (5.8k -> 3.7k),
        so the default stays 1; the win the hypothesis predicted belonged
        to the indexing mode, not the unroll.

        ``telemetry`` is the runner's stage recorder (coast_tpu.obs);
        default a fresh enabled one (COAST_TELEMETRY=0 disables).  Every
        campaign records per-stage wall-clock into it and exposes the
        totals as ``CampaignResult.stages``; export the full timeline
        with ``obs.write_trace(runner.telemetry, path)``.

        ``preflight`` runs the replication-integrity linter before any
        schedule is built and raises ``ReplicationLintError`` on an error
        finding -- a multi-hour campaign must refuse to start on a
        program whose redundancy was compiled away (every injection
        would measure a protection that no longer exists).  ``True`` or
        ``"full"`` runs the static lane-provenance rules, the
        lane-isolation noninterference prover
        (:mod:`coast_tpu.analysis.propagation`), and the post-XLA
        survival checks; ``"static"`` runs the provenance rules only
        (quick iteration); ``"propagation"`` runs provenance plus the
        isolation prover without the survival compile.

        ``retry`` is a :class:`coast_tpu.inject.resilience.RetryPolicy`:
        transient XLA/device errors re-dispatch the batch with backoff,
        OOM halves the batch geometry instead of aborting, and a
        collect watchdog converts a hung ``device_get`` into a
        re-dispatch.  None (the default) keeps dispatch failures fatal,
        exactly as before.

        ``mesh`` (a ``jax.sharding.Mesh``) selects the sharded backend:
        ``CampaignRunner(prog, mesh=make_mesh(8))`` builds a
        :class:`coast_tpu.parallel.mesh.ShardedCampaignRunner` whose
        batch axis is shard_map'd over every mesh axis -- pass keyword
        arguments alongside it (the subclass takes ``mesh`` as its
        second parameter).

        ``fault_model`` (:class:`coast_tpu.inject.schedule.FaultModel`)
        selects what one injection IS for every seeded campaign this
        runner draws: the default single-bit flip, or a multi-site model
        (multibit / cluster / burst) whose schedules carry per-injection
        flip groups.  It is part of the campaign's identity -- journaled
        in the header (resume under a different model is refused with a
        typed error) and recorded in the log summary's fault-model
        axis.

        ``equiv`` turns on fault-site equivalence reduction
        (:mod:`coast_tpu.analysis.equiv`): ``True`` derives the
        propagation partition from the protected step's jaxpr at
        construction (one extra clean-run compile), or pass an
        already-built :class:`EquivPartition`.  Every seeded ``run``
        then injects ONE representative per realized class and
        multiplies counts by the class weights, so the reported
        distribution is over effective injections at a fraction of the
        physical dispatches -- exactly matching the exhaustive
        distribution (the FastFlip contract, pinned differentially in
        tests).  Journals record the partition fingerprint and the
        per-section fingerprints that power ``run_delta``.  Requires
        the single-bit fault model.

        ``metrics`` is a :class:`coast_tpu.obs.metrics.CampaignMetrics`
        hub: every campaign this runner executes feeds it one sample
        per collected batch (progress, inj/s, weighted class rates,
        stage totals, resilience counters, device-memory watermark), so
        a metrics server (:mod:`coast_tpu.obs.serve`), a status-file
        export, or a live console can observe the campaign while it
        runs.  None (the default) records nothing.

        ``collect`` selects the result-collection mode.  ``"dense"``
        (default, byte-identical to the historical behavior) uploads
        per-batch fault arrays and fetches every row's outcome columns.
        ``"sparse"`` keeps the inner loop device-resident: seeded
        schedules regenerate their flip sites inside the compiled step
        (:mod:`coast_tpu.inject.device_gen`; bit-parity with the host
        schedule pinned per fault-model kind), per-batch accounting is
        a 10-int class histogram computed on device, and only the
        compacted INTERESTING rows (class outside success/corrected)
        cross the host boundary -- host traffic becomes O(interesting
        outcomes) in both directions.  Classification counts and the
        interesting-row set are identical to dense at the same
        schedule; ``CampaignResult.codes`` then covers only those rows
        (``interesting_rows`` carries their schedule-local indices).
        Collection mode is campaign identity: it joins the journal
        header (absent-means-dense) and resuming a sparse journal under
        dense -- or vice versa -- refuses.

        ``sparse_capacity`` bounds the on-device interesting-row buffer
        per batch (default ``max(256, batch_size // 4)``).  Correctness
        never depends on it: a batch whose interesting rows overflow
        the buffer falls back to a dense fetch for that batch.

        ``profile`` arms per-dispatch device-time attribution
        (:class:`coast_tpu.obs.profiler.CampaignProfiler`, or ``True``
        to build one from this program): every compiled invocation gets
        a measured device-busy duration and host-side gap (blocking-
        marker timing, backend-independent), split per protected-region
        phase, summed so ``device_busy + host_gap + host_other`` equals
        the campaign wall clock exactly, and combined with the analytic
        roofline model into ``summary()["profile"]``/``["mfu"]``.
        Campaign OUTPUTS (codes/counts/logs/journals) are byte-identical
        with the profiler on or off -- it only observes timing; the
        disabled default adds one attribute test per batch.

        ``slo`` attaches a reliability SLO set (:mod:`coast_tpu.obs
        .slo`): a spec string (``"sdc_rate<=0.002;min=4096"``) or an
        :class:`~coast_tpu.obs.slo.SLOSet`.  The runner's metrics hub
        (created on demand when ``metrics`` is None) re-evaluates the
        error budgets every collected batch, and every finished
        campaign lands the verdicts in ``CampaignResult.slo`` /
        ``summary()["slo"]``.  ``slo_baseline`` feeds the ``mwtf``
        objective (``{"sdc_rate", "inj_per_sec"}`` from an unprotected
        run's recorded evidence)."""
        if mesh is not None:
            raise TypeError(
                "mesh= reached the base CampaignRunner constructor; pass "
                "it as a keyword to CampaignRunner(prog, mesh=...) or use "
                "coast_tpu.parallel.mesh.ShardedCampaignRunner directly")
        if preflight:
            from coast_tpu.analysis import lint as lint_mod
            lint_mod.check(
                prog,
                survival=preflight not in ("static", "propagation"),
                propagation=preflight in (True, "full", "propagation"))
        self.prog = prog
        self.retry = retry
        if slo is not None:
            from coast_tpu.obs.metrics import CampaignMetrics
            from coast_tpu.obs.slo import SLOSet
            slo_set = SLOSet.parse(slo) if isinstance(slo, str) else slo
            if metrics is None:
                metrics = CampaignMetrics(slo=slo_set,
                                          slo_baseline=slo_baseline)
            elif getattr(metrics, "slo_set", None) is None:
                metrics.slo_set = slo_set
                metrics.slo_baseline = (dict(slo_baseline)
                                        if slo_baseline else None)
        self.metrics = metrics
        self.fault_model = fault_model if fault_model is not None \
            else FaultModel()
        region_meta = getattr(prog.region, "meta", None) or {}
        # Voter placement of a sharded region (the stencil's factory
        # knob): campaign identity, journaled absent-means-compute.
        self.placement = str(region_meta.get("placement", "compute"))
        if (self.fault_model.kind == "link"
                and self.fault_model.t_period == 0
                and self.fault_model.t_offset == 0
                and region_meta.get("link_window")):
            # A bare "link" model against a region that declares its
            # in-flight window (meta["link_window"] = (offset, period))
            # upgrades to the windowed model: flips land only at steps
            # where the halo words are actually on the wire.  Explicit
            # offsets/periods are respected; regions without the meta
            # key keep the all-steps bare model.
            off, per = region_meta["link_window"]
            self.fault_model = FaultModel.link(offset=int(off),
                                               period=int(per))
        if collect not in ("dense", "sparse"):
            raise ValueError(
                f"unknown collect mode {collect!r}; one of 'dense', "
                "'sparse'")
        self.collect = collect
        self._sparse_capacity = (int(sparse_capacity)
                                 if sparse_capacity else None)
        self._sparse_jits: Dict[object, object] = {}
        self._device_gen = None
        self._pack_bits: Optional[tuple] = None
        # Training regions (Region.train_probe) report the train outcome
        # classes; every other region keeps the pre-training counts key
        # set (classify.counts_dict absent-means-zero rule).
        self._train = prog.region.train_probe is not None
        if equiv and self.fault_model.kind != "single":
            raise ValueError(
                "equiv=True needs the single-bit fault model: a flip "
                f"group ({self.fault_model.spec()}) has no per-site "
                "propagation class to reduce over")
        self.telemetry = telemetry if telemetry is not None \
            else obs.Telemetry()
        self.profiler = None
        if profile:
            from coast_tpu.obs.profiler import CampaignProfiler
            self.profiler = (profile
                             if isinstance(profile, CampaignProfiler)
                             else CampaignProfiler(prog))
            if self.profiler.telemetry is None:
                self.profiler.telemetry = self.telemetry
        self.equiv_partition = None
        if equiv:
            from coast_tpu.analysis.equiv import (EquivPartition,
                                                  analyze_equivalence)
            with self.telemetry.activate(), \
                    self.telemetry.span("equiv_analysis"):
                self.equiv_partition = (
                    equiv if isinstance(equiv, EquivPartition)
                    else analyze_equivalence(prog))
        with self.telemetry.activate():
            self.mmap = MemoryMap(prog, sections)
        self.strategy_name = strategy_name or f"N={prog.cfg.num_clones}"
        self.unroll = max(1, int(unroll))
        out_words = int(np.prod(jax.eval_shape(
            prog.region.output, jax.eval_shape(prog.region.init)).shape))
        self._out_words = out_words

        def run_one(fault: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            rec = prog.run(fault, unroll=self.unroll)
            return {
                "code": cls.classify(rec, out_words),
                "errors": rec["errors"],
                "corrected": rec["corrected"],
                "steps": rec["steps"],
            }

        self._run_one = run_one
        self._run_batch = jax.jit(jax.vmap(run_one))

    # -- overridable per-shard accounting hooks (no-ops here; the
    # sharded backend attributes each collected batch's interesting rows
    # to the physical shard that ran them) ----------------------------------
    def _ledger_reset(self) -> None:
        """Start-of-run_schedule reset of the per-shard ledger."""

    def _ledger_rows(self, rows: np.ndarray, per: int) -> None:
        """Attribute one sparse batch's BATCH-LOCAL interesting rows
        (shard of row r = r // per under the sharded batch split)."""

    def _ledger_dense(self, out: Dict[str, np.ndarray],
                      batch_size: int) -> None:
        """Attribute one dense batch's interesting rows by position."""

    def _mesh_block(self) -> Optional[Dict[str, object]]:
        """The result's ``mesh`` accounting block; None on the
        single-device runner (absent-means-single-device keeps every
        existing summary byte-identical)."""
        return None

    # -- overridable batching hooks (ShardedCampaignRunner replaces these) --
    def _round_batch(self, batch_size: int) -> int:
        # Floor at one row: call sites clamp to len(schedule) to avoid
        # padding waste, and an empty schedule (cache draws all invalid,
        # zero budget) must step range() by 1, not 0.
        return max(1, batch_size)

    @staticmethod
    def _padded_fault(part: FaultSchedule, batch_size: int):
        """Device fault arrays for one batch, edge-padded to batch_size so
        every batch hits the same compiled program.  Returns (fault, n_valid);
        callers drop or mask the padded tail.  Multi-site schedules pad the
        batch axis only -- the trailing sites axis is part of the compiled
        shape, never padded."""
        n_part = len(part)
        pad = batch_size - n_part if n_part < batch_size else 0
        fault = {k: jnp.asarray(np.pad(
                     v, [(0, pad)] + [(0, 0)] * (v.ndim - 1), mode="edge"))
                 for k, v in part.device_arrays().items()}
        return fault, n_part

    def _dispatch(self, fault: Dict[str, jax.Array]):
        """Launch one batch; returns the (async) device result."""
        return self._run_batch(fault)

    @staticmethod
    def _collect(pending) -> Dict[str, np.ndarray]:
        """Block on a dispatched batch and fetch it to the host."""
        return jax.device_get(pending)

    # -- sparse (device-resident) collection ---------------------------------
    def _sparse_shards(self) -> int:
        """Leading buffer axis of the sparse outputs: 1 here; the
        sharded backend returns its device count (per-shard buffers)."""
        return 1

    def _sparse_pack(self) -> tuple:
        if self._pack_bits is None:
            self._pack_bits = _pack_layout(self._out_words,
                                           self.prog.region.max_steps)
        return self._pack_bits

    def _sparse_cap(self, batch_size: int) -> int:
        """Per-shard interesting-row buffer capacity (ceil-divided over
        shards, clamped to the per-shard row count)."""
        shards = self._sparse_shards()
        per = max(1, batch_size // shards)
        cap = int(self._sparse_capacity or max(256, batch_size // 4))
        return max(1, min(-(-cap // shards), per))

    def _make_sparse_fn(self, batch_size: int, mode: str, cap: int,
                        gen) -> "Callable":
        """Compile the sparse batch program.  ``mode`` is ``"gen"``
        (flip sites regenerated on device from scalar inputs) or
        ``"resident"`` (fault columns arrive as device arrays -- the
        already-uploaded resident schedule's slices).  Outputs carry a
        leading per-shard axis (length 1 here) so the host extraction
        is shared with the sharded backend."""
        pack = self._sparse_pack()
        run_one = self._run_one

        def _wrap(o, out):
            o = {k: (v if k == "hist" else v[None])
                 for k, v in o.items()}
            o["full"] = out
            return o

        if mode == "gen":
            def fn(seed_hi, seed_lo, stream_n, offset, n_valid):
                rows = offset + jnp.arange(batch_size, dtype=jnp.uint32)
                fault = gen.columns((seed_hi, seed_lo), stream_n, rows)
                out = jax.vmap(run_one)(fault)
                valid = jnp.arange(batch_size, dtype=jnp.int32) < n_valid
                o = _sparse_device_outputs(out, valid.astype(jnp.int32),
                                           valid, cap, pack)
                return _wrap(o, out)
        else:
            def fn(fault, count_w, n_valid):
                out = jax.vmap(run_one)(fault)
                valid = jnp.arange(batch_size, dtype=jnp.int32) < n_valid
                o = _sparse_device_outputs(out, count_w, valid, cap, pack)
                return _wrap(o, out)
        return jax.jit(fn)

    def _sparse_setup(self, sched: FaultSchedule, batch_size: int,
                      transfer: Dict[str, int]) -> Dict[str, object]:
        """Per-run_schedule sparse state: the compiled batch program and
        its per-batch inputs.  Seeded single-stream schedules take the
        GENERATED path (zero per-batch upload; the device regenerates
        the host schedule bit for bit); everything else -- equivalence
        reductions, strata, cache overlays, merged chunks -- uploads the
        schedule to the device ONCE and slices it there (the
        device-RESIDENT path)."""
        from coast_tpu.inject.device_gen import (DeviceGenError,
                                                 DeviceScheduleGen)
        shards = self._sparse_shards()
        cap = self._sparse_cap(batch_size)
        state: Dict[str, object] = {
            "cap": cap, "shards": shards,
            "per_shard": max(1, batch_size // shards),
            "batch_size": batch_size,
        }
        # The gen path regenerates (site, t) from (seed, stream length,
        # step modulus): all three must come from the SCHEDULE's own
        # recorded generation metadata -- a schedule generated with a
        # different step window than the region's nominal one must not
        # be silently regenerated mod the wrong value.
        gen_ok = (sched.gen_stream_n is not None
                  and sched.gen_steps is not None
                  and sched.class_weight is None)
        if gen_ok:
            try:
                key = ("gen", batch_size, cap, sched.model.spec(),
                       int(sched.gen_steps))
                if key not in self._sparse_jits:
                    gen = DeviceScheduleGen(
                        self.mmap, sched.gen_steps, sched.model)
                    self._sparse_jits[key] = self._make_sparse_fn(
                        batch_size, "gen", cap, gen)
                seed = int(sched.seed) & 0xFFFFFFFFFFFFFFFF
                state.update({
                    "mode": "gen", "fn": self._sparse_jits[key],
                    "seed_hi": np.uint32(seed >> 32),
                    "seed_lo": np.uint32(seed & 0xFFFFFFFF),
                    "stream_n": np.uint32(sched.gen_stream_n),
                    "gen_lo": int(sched.gen_lo),
                })
                return state
            except DeviceGenError:
                pass            # address space too large: resident path
        key = ("resident", batch_size, cap,
               sched.sites if sched.extra is not None else 1)
        if key not in self._sparse_jits:
            self._sparse_jits[key] = self._make_sparse_fn(
                batch_size, "resident", cap, None)
        n = len(sched)
        # Headroom for ANY batch start < n, not just 0-aligned ones: an
        # OOM degrade mid-campaign restarts at the first uncollected
        # row, which need not be a multiple of the new batch size, and
        # the compiled program's shapes are fixed at batch_size.
        padded = n + batch_size
        pad = padded - n
        arrays = {}
        for k, v in sched.device_arrays().items():
            v = np.pad(v, [(0, pad)] + [(0, 0)] * (v.ndim - 1),
                       mode="edge") if pad else np.asarray(v)
            arrays[k] = jnp.asarray(v)
            transfer["up"] += int(arrays[k].nbytes)
        if sched.class_weight is not None:
            w = sched.class_weight.astype(np.int64)
            # The device histogram accumulates these weights in int32
            # (and psums int32 across shards): bound the worst-case
            # PER-BATCH weight sum -- alignment-independently (an OOM
            # degrade can restart batches at any offset), so bound the
            # sum of the batch_size LARGEST weights.  Two 2^30 weights
            # of one class in one batch would wrap negative where the
            # dense path's float64 bincount stays exact.
            top = (np.partition(w, n - batch_size)[n - batch_size:]
                   if n > batch_size else w)
            if n and int(top.sum()) >= 2 ** 31:
                raise ValueError(
                    "sparse collect: a batch's summed class weights "
                    f"(worst case {int(top.sum())}) could exceed the "
                    "device histogram's int32 range; run this campaign "
                    "dense (or with a smaller batch_size)")
        else:
            w = np.ones(n, np.int64)
        w = np.where(np.asarray(sched.t) < 0, 0, w).astype(np.int32)
        count_w = jnp.asarray(np.pad(w, (0, pad)))
        transfer["up"] += int(count_w.nbytes)
        state.update({"mode": "resident", "fn": self._sparse_jits[key],
                      "arrays": arrays, "count_w": count_w})
        return state

    @staticmethod
    def _sparse_args(state: Dict[str, object], lo: int, n_part: int,
                     transfer: Dict[str, int]) -> tuple:
        """Per-batch inputs for the sparse program -- the whole up-link
        payload (scalars on the generated path; the resident path's
        slices are device-side views, no transfer)."""
        if state["mode"] == "gen":
            transfer["up"] += 20        # 4 u32/i32 scalars + offset
            return (state["seed_hi"], state["seed_lo"],
                    state["stream_n"],
                    np.uint32(int(state["gen_lo"]) + lo),
                    np.int32(n_part))
        transfer["up"] += 4             # n_valid scalar
        b = int(state["batch_size"])
        fault = {k: v[lo:lo + b] for k, v in state["arrays"].items()}
        return (fault, state["count_w"][lo:lo + b], np.int32(n_part))

    def _sparse_extract(self, state: Dict[str, object], pending,
                        head: Dict[str, np.ndarray], n_part: int,
                        transfer: Dict[str, int]) -> Dict[str, object]:
        """Host-side merge of one sparse batch: rows from the bitmask,
        columns from the packed words (+ exact sentinel side-buffer);
        a shard whose interesting rows overflowed its buffer falls the
        whole batch back to a dense fetch.  Returns the sparse batch
        out dict (hist int64[10], batch-local rows, int32 columns)."""
        pack = self._sparse_pack()
        cap, per = int(state["cap"]), int(state["per_shard"])
        hist = np.asarray(head["hist"], np.int64)
        n_int = np.atleast_1d(np.asarray(head["n_int"]))
        n_exact = np.atleast_1d(np.asarray(head["n_exact"]))
        # Device-side sizes: the histogram is int32 on the wire.
        transfer["down"] += cls.NUM_CLASSES * 4 + int(len(n_int) * 8)
        if (n_int > cap).any() or (n_exact > cap).any():
            # Capacity overflow: correctness never depends on the cap.
            self.telemetry.count("sparse_overflow_fallback",
                                 rows=int(n_int.sum()))
            full = jax.device_get(pending["full"])
            transfer["down"] += sum(int(v.nbytes) for v in full.values())
            code = np.asarray(full["code"])
            valid = np.arange(len(code)) < n_part
            rows = np.flatnonzero(valid & (code > cls.CORRECTED))
            self._ledger_rows(rows.astype(np.int64), per)
            return {"hist": hist, "rows": rows.astype(np.int64),
                    "code": code[rows].astype(np.int32),
                    "errors": np.asarray(full["errors"])[rows],
                    "corrected": np.asarray(full["corrected"])[rows],
                    "steps": np.asarray(full["steps"])[rows]}
        rows_parts, col_parts = [], {"code": [], "errors": [],
                                     "corrected": [], "steps": []}
        for s in range(len(n_int)):
            mask_np = np.asarray(pending["mask"][s])
            transfer["down"] += int(mask_np.nbytes)
            k, ke = int(n_int[s]), int(n_exact[s])
            if not k:
                continue
            packed = np.asarray(pending["packed"][s, :k])
            exact = (np.asarray(pending["exact"][s, :ke])
                     if ke else np.zeros((0, 3), np.int32))
            transfer["down"] += 4 * k + 12 * ke
            code, err, cor, steps = _unpack_rows(packed, exact, pack)
            rows_s = _mask_rows(mask_np, per)
            if len(rows_s) != k:
                raise RuntimeError(
                    f"sparse collect: bitmask names {len(rows_s)} "
                    f"interesting rows but the device counted {k}")
            rows_parts.append(rows_s.astype(np.int64) + s * per)
            col_parts["code"].append(code)
            col_parts["errors"].append(err)
            col_parts["corrected"].append(cor)
            col_parts["steps"].append(steps)
        if rows_parts:
            out = {"rows": np.concatenate(rows_parts),
                   **{k: np.concatenate(v)
                      for k, v in col_parts.items()}}
        else:
            out = {"rows": np.zeros(0, np.int64),
                   **{k: np.zeros(0, np.int32) for k in col_parts}}
        self._ledger_rows(out["rows"], per)
        out["hist"] = hist
        return out

    # -- execution ----------------------------------------------------------
    def run_schedule(self, sched: FaultSchedule,
                     batch_size: int = 4096,
                     progress: Optional[
                         Callable[[int, Dict[str, int]], None]] = None,
                     _telemetry_mark: Optional[int] = None,
                     journal: "Optional[object]" = None,
                     journal_base: int = 0,
                     stream: "Optional[object]" = None,
                     stop_when: "Optional[object]" = None
                     ) -> CampaignResult:
        """Run every row of ``sched`` in edge-padded batches.

        ``progress(done, counts_so_far)`` is called after each collected
        batch (for heartbeats; ``counts_so_far`` is the cumulative class
        histogram of the rows fetched so far).  Stage wall-clock (pad /
        dispatch / collect / classify, plus per-batch pad-waste) is
        recorded into ``self.telemetry`` and summed onto the result's
        ``stages``; ``_telemetry_mark`` lets ``run`` extend the stage
        window back over its schedule-generation span.

        ``journal`` is an open :class:`coast_tpu.inject.journal
        .CampaignJournal` (header already written/validated by the
        caller): every collected batch is appended as one fsync'd record
        before the loop moves on, and on entry the journal's contiguous
        completed-batch prefix is replayed from disk so the loop
        restarts at the first missing batch -- a resumed campaign's
        ``codes`` is bit-for-bit the uninterrupted run's.
        ``journal_base`` offsets this schedule's rows within a larger
        journaled stream (scripts/campaign_1m.py's sliced chunks).

        When ``self.retry`` is set, dispatch/collect failures are
        classified (:mod:`coast_tpu.inject.resilience`): transient
        errors and watchdog-wedged collects re-dispatch the batch with
        exponential backoff; OOM halves ``batch_size``, recompiles,
        re-pads, and journals the new geometry.  Everything else is
        fatal and re-raised.

        ``stream`` is a :class:`coast_tpu.inject.logs.StreamLogWriter`:
        every collected batch (journal-replayed ones included, so a
        resumed campaign's stream file equals the uninterrupted run's)
        is handed to its background serializer as it lands, row-numbered
        ``journal_base + lo``.  The caller owns ``finish(res)`` /
        ``abort()`` -- the stream may span several run_schedule calls
        (scripts/campaign_1m.py's sliced chunks).

        ``stop_when`` (:class:`coast_tpu.obs.convergence.StopWhen`)
        arms statistical early stop: after every collected batch the
        weighted class histogram's Wilson intervals are checked, and
        once every target class's CI half-width is at or below its
        threshold the campaign stops dispatching -- the remaining
        schedule rows are dropped, the result covers exactly the rows
        that ran, and ``CampaignResult.convergence`` records the
        intervals.  With a journal the stop is a first-class terminal
        record (``kind: "early_stop"``), the stop condition is part of
        the header identity (resume under a different -- or no --
        condition refuses), and a resumed campaign replays the prefix
        and stops at the same batch, bit-for-bit.
        """
        # Deliberately no clamp to len(sched) here: every batch is
        # edge-padded to batch_size so all chunks (including a caller's
        # externally-sliced tail, e.g. scripts/campaign_1m.py) share one
        # compiled program.  One-shot small campaigns clamp at the call
        # site (advisor, supervisor) where a single smaller compile beats
        # padding waste.
        batch_size = self._round_batch(batch_size)
        self._ledger_reset()
        if journal is not None:
            # Model = campaign identity, wherever the schedule came from:
            # an externally-generated multi-site schedule journaled under
            # a header that says "single" (or vice versa) would poison
            # every later resume, so the open journal's header must name
            # the schedule's own model.
            from coast_tpu.inject.journal import FaultModelMismatchError
            sched_model = getattr(sched, "model", None)
            sched_spec = sched_model.spec() if sched_model else "single"
            header_spec = journal.header.get("fault_model", "single")
            if header_spec != sched_spec:
                raise FaultModelMismatchError(
                    f"journal {journal.path!r} header records fault model "
                    f"{header_spec!r} but the schedule being run carries "
                    f"{sched_spec!r}; open the journal with the "
                    "schedule's model (CampaignRunner(fault_model=...))")
            # Same identity rule for the equivalence partition: batch
            # records are per-representative, so replaying them under a
            # different (or no) partition would weight them wrongly.
            header_part = (journal.header.get("equiv") or {}).get(
                "partition")
            sched_part = getattr(sched, "equiv_sha", None)
            if header_part != sched_part:
                raise JournalMismatchError(
                    f"journal {journal.path!r} records equivalence "
                    f"partition {header_part!r} but the schedule being "
                    f"run carries {sched_part!r}; refusing to mix "
                    "reduced and exhaustive row records")
            # Stop condition = campaign identity too: an early-stopped
            # journal's rows are a prefix chosen BY the condition, so
            # resuming under a different (or no) condition would either
            # silently extend a complete campaign or stop a full one
            # short.
            header_stop = journal.header.get("stop_when")
            current_stop = stop_when.spec() if stop_when is not None \
                else None
            if header_stop != current_stop:
                raise JournalMismatchError(
                    f"journal {journal.path!r} records stop_when="
                    f"{header_stop!r} but this campaign runs "
                    f"stop_when={current_stop!r}; an early-stop "
                    "condition is part of the campaign's identity -- "
                    "rerun with the original --stop-when (or a fresh "
                    "journal)")
            # Collection mode = campaign identity too (absent-means-
            # dense): a sparse journal's batch records carry histograms
            # + interesting rows, which a dense replay cannot expand,
            # and vice versa.
            from coast_tpu.inject.spec import header_collect
            header_mode = header_collect(journal.header)
            if header_mode != self.collect:
                raise JournalMismatchError(
                    f"journal {journal.path!r} records collect="
                    f"{header_mode!r} but this runner collects "
                    f"{self.collect!r}; rerun with the original "
                    "--collect (or a fresh journal)")
            # Voter placement = campaign identity too (absent-means-
            # compute): the two placements are different programs, so a
            # journal written under one must never seed the other.
            from coast_tpu.inject.journal import PlacementMismatchError
            from coast_tpu.inject.spec import header_placement
            header_place = header_placement(journal.header)
            if header_place != self.placement:
                raise PlacementMismatchError(
                    f"journal {journal.path!r} records voter placement "
                    f"{header_place!r} but this runner's region is built "
                    f"{self.placement!r}; rerun with the original "
                    "--placement (or a fresh journal)")
            # Step engine = campaign identity too (absent-means-unfused):
            # the fused path is pinned bit-identical, but the rows
            # measured a different compiled program (op counts, MFU
            # attribution), so a journal written under one engine must
            # never blend batches from the other.
            from coast_tpu.inject.journal import FuseStepMismatchError
            from coast_tpu.inject.spec import header_fuse
            header_fused = header_fuse(journal.header)
            runner_fused = bool(getattr(self.prog.cfg, "fuse_step", False))
            if header_fused != runner_fused:
                raise FuseStepMismatchError(
                    f"journal {journal.path!r} records "
                    f"fuse={header_fused} but this runner's program is "
                    f"built fuse={runner_fused}; rerun with the original "
                    "fuse mode (-fuseStep/-noFuseStep, or a fresh "
                    "journal)")
        retry = self.retry
        metrics = self.metrics
        tracker = None
        if stop_when is not None:
            from coast_tpu.obs.convergence import ConvergenceTracker
            tracker = ConvergenceTracker(stop_when)
        planned_effective = sched.effective_n
        if metrics is not None:
            metrics.campaign_started(self.prog.region.name,
                                     self.strategy_name,
                                     len(sched), planned_effective)
        tel = self.telemetry
        mark = tel.mark() if _telemetry_mark is None else _telemetry_mark
        t0 = time.perf_counter()
        prof = self.profiler
        if prof is not None:
            prof.begin(t0)
        outs: List[Dict[str, np.ndarray]] = []
        done = 0
        live_counts = np.zeros(cls.NUM_CLASSES, np.int64)
        live_invalid = 0
        resilience: Dict[str, int] = (
            {"retry_transient": 0, "retry_wedged": 0, "oom_degrade": 0}
            if retry is not None else {})
        sched_t = np.asarray(sched.t)
        sched_w = getattr(sched, "class_weight", None)
        # Host<->device traffic ledger ({"up","down"} bytes), recorded on
        # every campaign -- the quantity sparse collection shrinks.
        transfer: Dict[str, int] = {"up": 0, "down": 0}
        sparse_state: Optional[Dict[str, object]] = None
        if self.collect == "sparse":
            with tel.span("sparse_setup"):
                sparse_state = self._sparse_setup(sched, batch_size,
                                                  transfer)

        def _batch_invalid(lo: int, n: int) -> int:
            """Weighted never-fired (t < 0) draws of batch rows
            [lo, lo+n): host-side, from the schedule -- the sparse
            path's cache_invalid source (on device those rows classify
            success and carry zero count weight)."""
            inv = sched_t[lo:lo + n] < 0
            if sched_w is None:
                return int(inv.sum())
            return int(sched_w[lo:lo + n][inv].sum())

        def _account(out: Dict[str, np.ndarray], lo: int) -> Dict[str, int]:
            """Cumulative class histogram over the rows fetched so far
            (progress heartbeats and journal batch records).  Reduced
            schedules multiply each representative by its class weight,
            so the live counts are over effective injections."""
            nonlocal live_invalid
            n_out = len(out["code"])
            fired = sched_t[lo:lo + n_out] >= 0
            if sched_w is None:
                live_counts[:] += np.bincount(
                    out["code"][fired], minlength=cls.NUM_CLASSES)
                live_invalid += int(n_out - fired.sum())
            else:
                w = sched_w[lo:lo + n_out]
                live_counts[:] += cls.weighted_histogram(
                    out["code"][fired], w[fired])
                live_invalid += int(w[~fired].sum())
            counts_so_far = cls.counts_dict(live_counts, self._train)
            counts_so_far["cache_invalid"] = live_invalid
            return counts_so_far

        def _account_sparse(out: Dict[str, object]) -> Dict[str, int]:
            """Sparse counterpart of _account: the device already
            histogrammed the batch (weighted, never-fired rows at zero
            weight); the host just accumulates 10 ints."""
            nonlocal live_invalid
            live_counts[:] += np.asarray(out["hist"], np.int64)
            live_invalid += int(out["invalid"])
            counts_so_far = cls.counts_dict(live_counts, self._train)
            counts_so_far["cache_invalid"] = live_invalid
            return counts_so_far

        def _journal_early_stop(rows: int) -> None:
            """The ONE builder of the terminal early_stop record (live
            trip and crash-window backfill must write identical
            shapes)."""
            tel.instant("early_stop", rows=rows)
            if journal is not None:
                journal.append({
                    "kind": "early_stop",
                    "base": int(journal_base),
                    "rows": int(rows),
                    "lo": int(journal_base + rows),
                    "stop_when": stop_when.spec(),
                    "half_widths": {
                        k: round(v["half_width"], 8)
                        for k, v in tracker.intervals().items()},
                })

        # Resume: replay the journal's contiguous completed-batch prefix
        # (rows [journal_base, ...) in stream coordinates) from disk, so
        # the dispatch loop below starts at the first missing batch.
        stopped = False
        if journal is not None:
            for rec in journal.batch_prefix(journal_base, len(sched)):
                if rec.get("sparse"):
                    # Sparse batch record: histogram + interesting rows
                    # (absolute numbers -> schedule-local).
                    out = {
                        "hist": np.asarray(rec["hist"], np.int64),
                        "invalid": int(rec.get("invalid", 0)),
                        "rows": (np.asarray(rec["rows"], np.int64)
                                 - journal_base),
                        **{k: np.asarray(rec[src], np.int32)
                           for k, src in (("code", "codes"),
                                          ("errors", "errors"),
                                          ("corrected", "corrected"),
                                          ("steps", "steps"))}}
                    outs.append(out)
                    counts_so_far = _account_sparse(out)
                    n_batch = int(rec["n"])
                    if stream is not None:
                        stream.feed_sparse(
                            journal_base + out["rows"],
                            _rows_subset(sched, out["rows"]),
                            out)
                else:
                    out = {k: np.asarray(rec[src], dtype=np.int32)
                           for k, src in (("code", "codes"),
                                          ("errors", "errors"),
                                          ("corrected", "corrected"),
                                          ("steps", "steps"))}
                    outs.append(out)
                    counts_so_far = _account(out, done)
                    n_batch = len(out["code"])
                    if stream is not None:
                        # A journaled batch is also a serialized batch:
                        # the replayed columns flow through the stream
                        # writer from disk, so the resumed stream file
                        # is the uninterrupted run's -- no re-dispatch,
                        # and the device loop below only serializes
                        # what it runs.
                        stream.feed(journal_base + done,
                                    sched.slice(done, done + n_batch),
                                    out)
                done += n_batch
                # Re-materialise the batch's recorded span timing
                # (marked as replayed) at its original wall-clock
                # offsets, so the resumed recorder exports ONE coherent
                # Perfetto timeline covering the crashed run's batches
                # too -- the export shifts time zero to the earliest
                # event.
                for name, t_abs, dur in rec.get("spans") or []:
                    t0_local = tel.origin + (float(t_abs) - tel.epoch)
                    tel.span_at(str(name), t0_local,
                                t0_local + float(dur), replayed=True)
                if tracker is not None:
                    tracker.update(counts_so_far)
                if metrics is not None:
                    metrics.record_batch(done, n_batch, counts_so_far,
                                         tel.stage_totals(since=mark),
                                         resilience, replayed=True,
                                         transfer=transfer)
                if progress is not None:
                    progress(done, counts_so_far)
            if done:
                tel.instant("journal_resume", rows=done)
                flightrec.record("journal_resume", rows=int(done))
            # An early_stop record is the campaign's terminal state: the
            # replayed prefix IS the whole campaign, so the dispatch
            # loop below must not extend it.  (The live tracker would
            # reach the same verdict from the identical counts; honoring
            # the record makes that termination first-class.)
            early = next(
                (r for r in journal.records()
                 if r.get("kind") == "early_stop"
                 and int(r.get("base", 0)) == int(journal_base)), None)
            if early is not None and done >= int(early["rows"]):
                stopped = True
            elif tracker is not None and tracker.converged:
                # Crash window: the final batch record fsync'd but the
                # kill landed before the early_stop record did.  The
                # replayed counts are the same data the crashed run
                # stopped on, so the tracker reaches the same verdict
                # here -- stop at the same batch (and backfill the
                # terminal record the crash swallowed) instead of
                # dispatching past the recorded stop point.
                stopped = True
                _journal_early_stop(done)

        def _last_span(store: List) -> None:
            """Capture the just-exited span's (name, t0, t1) for the
            journal's per-batch span-timing record.  Call immediately
            after a ``with tel.span(...)`` block (events are appended at
            exit); a disabled recorder captures nothing."""
            if tel.enabled and tel.events \
                    and tel.events[-1]["kind"] == "span":
                e = tel.events[-1]
                store.append((str(e["name"]), float(e["t0"]),
                              float(e["t1"])))

        def _grab(flight: Dict[str, object], got) -> Dict[str, int]:
            """Post-collect accounting: journal the batch durably, update
            progress.  NOT retried -- appending the same rows twice would
            corrupt the campaign, so failures here are fatal.  Returns
            the cumulative counts (the convergence tracker's input)."""
            nonlocal done
            n_part = flight["n"]
            spans = [(name, round(tel.epoch + (t0 - tel.origin), 6),
                      round(t1 - t0, 6))
                     for name, t0, t1 in flight.get("spans") or []]
            if sparse_state is not None:
                out = got
                out["invalid"] = _batch_invalid(flight["lo"], n_part)
                # Batch-local -> schedule-local row numbers.
                out["rows"] = out["rows"] + int(flight["lo"])
                counts_so_far = _account_sparse(out)
                done += n_part
                if journal is not None:
                    journal.append_batch_sparse(
                        journal_base + flight["lo"], n_part,
                        out["hist"], out["invalid"],
                        journal_base + out["rows"],
                        {"code": out["code"], "errors": out["errors"],
                         "corrected": out["corrected"],
                         "steps": out["steps"]},
                        counts_so_far, tel.stage_totals(since=mark),
                        spans=spans)
                if stream is not None:
                    stream.feed_sparse(journal_base + out["rows"],
                                       _rows_subset(sched, out["rows"]),
                                       out)
            else:
                out = {k: v[:n_part] for k, v in got.items()}
                self._ledger_dense(out, batch_size)
                counts_so_far = _account(out, done)
                done += n_part
                if journal is not None:
                    # Batch records carry this batch's span timing as
                    # (name, unix_start, duration) triples, so a resumed
                    # campaign can re-materialise the crashed run's
                    # timeline into one coherent trace.
                    journal.append_batch(
                        journal_base + flight["lo"], out, counts_so_far,
                        tel.stage_totals(since=mark), spans=spans)
                if stream is not None:
                    # Hand the batch to the background serializer right
                    # after it is durable: the encode overlaps the next
                    # dispatch, and a feed stall (writer behind) is
                    # billed as the stream's non-overlapped serialize
                    # cost, not dispatch.
                    stream.feed(journal_base + flight["lo"],
                                sched.slice(flight["lo"],
                                            flight["lo"] + n_part),
                                out)
            outs.append(out)
            if metrics is not None:
                metrics.record_batch(done, n_part, counts_so_far,
                                     tel.stage_totals(since=mark),
                                     resilience, transfer=transfer,
                                     profile=(prof.batch_sample()
                                              if prof is not None
                                              else None))
            if progress is not None:
                progress(done, counts_so_far)
            return counts_so_far

        def _collect_flight(flight: Dict[str, object]):
            """Block on one batch, watchdog-guarded when armed.  This is
            the only collect-side work inside the retry loop -- it is
            idempotent (a re-dispatch replays the same seeded rows).

            Sparse mode blocks on the batch's accounting head (the
            10-int histogram + buffer fill counts) and then fetches
            only the interesting-row buffers -- or, on capacity
            overflow, that batch's dense columns."""
            if sparse_state is not None:
                pending = flight["pending"]

                def fetch():
                    # The WHOLE sparse fetch -- head, buffers, and the
                    # overflow fallback's dense columns -- runs under
                    # the watchdog: a link that wedges after the head
                    # must still trip the re-dispatch path, exactly as
                    # a dense fetch would.  (A retried fetch re-counts
                    # its transfer bytes: the traffic really was
                    # re-attempted.)
                    head = jax.device_get(
                        {k: pending[k]
                         for k in ("hist", "n_int", "n_exact")})
                    return self._sparse_extract(
                        sparse_state, pending, head, flight["n"],
                        transfer)
            else:
                def fetch():
                    return self._collect(flight["pending"])
            if prof is not None:
                # Blocking-marker device timing: wait for the batch to
                # finish ON DEVICE (no transfer) under timing, then run
                # the ordinary fetch.  Inside the fetch closure so the
                # watchdog (below) guards the marker exactly like the
                # fetch it precedes.  ``_p`` pins the dispatched result
                # THIS attempt blocks on: an abandoned watchdog thread
                # that wakes after the flight was re-dispatched sees a
                # different pending object and must not report a ready
                # for work the live attempt re-timed (the profiler's
                # lock guards the remaining tiny window).
                def fetch(_inner=fetch, _fl=flight,
                          _p=flight["pending"]):
                    jax.block_until_ready(_p)
                    if _fl["pending"] is _p:
                        prof.ready(_fl["lo"], _fl["n"],
                                   time.perf_counter())
                    return _inner()
            with tel.span("collect", n=flight["n"]):
                if retry is not None and retry.collect_timeout:
                    # Ambient activation so the watchdog's own obs
                    # counter (resilience.watchdog_collect fires
                    # ``watchdog_fired`` on timeout) records into THIS
                    # campaign's recorder, not the no-op default.
                    with tel.activate():
                        got = resilience_mod.watchdog_collect(
                            fetch, retry.collect_timeout)
                else:
                    got = fetch()
                if sparse_state is None:
                    transfer["down"] += sum(int(v.nbytes)
                                            for v in got.values())
            _last_span(flight.setdefault("spans", []))
            return got

        def _redispatch(flight: Dict[str, object]):
            """Launch (or re-launch) a flight's device work from its
            recorded inputs -- the one dispatch point shared by the
            first attempt and the retry path."""
            if sparse_state is not None:
                return sparse_state["fn"](*flight["fault"])
            return self._dispatch(flight["fault"])

        def _dispatch_batch(lo: int) -> Dict[str, object]:
            spans_rec: List = []
            n_part = min(lo + batch_size, len(sched)) - lo
            with tel.span("pad", lo=lo):
                if sparse_state is not None:
                    # The whole up-link payload: scalars (generated
                    # path) or device-side slices of the resident
                    # schedule -- never per-batch fault arrays.
                    fault = self._sparse_args(sparse_state, lo, n_part,
                                              transfer)
                else:
                    part = sched.slice(lo, lo + n_part)
                    fault, n_part = self._padded_fault(part, batch_size)
                    transfer["up"] += sum(int(v.nbytes)
                                          for v in fault.values())
            _last_span(spans_rec)
            if batch_size - n_part:
                tel.count("pad_waste_rows", batch_size - n_part)
            flight = {"pending": None, "n": n_part, "fault": fault,
                      "lo": lo, "attempts": 1, "spans": spans_rec}
            flightrec.record("dispatch", lo=int(lo), n=int(n_part),
                             batch_size=int(batch_size))
            _td0 = time.perf_counter() if prof is not None else 0.0
            with tel.span("dispatch", n=n_part):
                flight["pending"] = _redispatch(flight)
            _last_span(spans_rec)
            if prof is not None:
                prof.dispatched(lo, n_part, _td0, time.perf_counter())
            return flight

        def _note_retry(flight_lo: int, attempt: int,
                        exc: BaseException, kind: str) -> None:
            key = "retry_wedged" if kind == "wedged" else "retry_transient"
            resilience[key] += 1
            tel.count(f"resilience_{key}", lo=flight_lo,
                      error=type(exc).__name__)
            flightrec.record("retry", lo=int(flight_lo),
                             attempt=int(attempt), kind=kind,
                             error=type(exc).__name__)
            if journal is not None:
                journal.append({"kind": "retry", "lo": journal_base
                                + flight_lo, "attempt": attempt,
                                "class": kind,
                                "error": type(exc).__name__})

        class _Degrade(Exception):
            """Internal signal: OOM observed; unwind to the outer loop."""

        def _handle(flight: Dict[str, object], exc: BaseException) -> None:
            """Common failure path for dispatch and collect: classify,
            then retry / degrade / re-raise.  Mutates ``flight`` so the
            caller's loop re-dispatches."""
            kind = retry.classify(exc) if retry is not None else "fatal"
            if kind == "fatal":
                raise exc
            if kind == "oom":
                raise _Degrade() from exc
            attempts = int(flight["attempts"])
            if attempts >= retry.max_attempts:
                raise exc
            _note_retry(int(flight["lo"]), attempts, exc, kind)
            time.sleep(retry.backoff(attempts))
            flight["attempts"] = attempts + 1
            flight["pending"] = None           # re-dispatch before collect

        # Double-buffered: dispatch batch i+1 before collecting batch i, so
        # the host-side fetch (one tunnel round-trip per batch) overlaps the
        # device work -- jax dispatch is async, the device_get is the only
        # blocking point.  The dispatch span therefore times the host-side
        # enqueue; device execution time lands in the matching collect span.
        in_flight: List[Dict[str, object]] = []
        next_lo = done
        disp_attempts = 1
        try:
            while done < len(sched) and not stopped:
                try:
                    while next_lo < len(sched) and len(in_flight) < 2:
                        try:
                            in_flight.append(_dispatch_batch(next_lo))
                        except Exception as e:  # noqa: BLE001 - classified
                            probe = {"lo": next_lo,
                                     "attempts": disp_attempts}
                            _handle(probe, e)
                            disp_attempts = int(probe["attempts"])
                            continue           # retry the same dispatch
                        next_lo += batch_size
                        disp_attempts = 1
                    flight = in_flight.pop(0)
                    while True:
                        try:
                            if flight["pending"] is None:
                                _tr0 = (time.perf_counter()
                                        if prof is not None else 0.0)
                                with tel.span("dispatch", n=flight["n"],
                                              retry=flight["attempts"]):
                                    flight["pending"] = _redispatch(
                                        flight)
                                _last_span(flight["spans"])
                                if prof is not None:
                                    prof.dispatched(
                                        int(flight["lo"]),
                                        int(flight["n"]), _tr0,
                                        time.perf_counter())
                            got = _collect_flight(flight)
                            break
                        except _Degrade:
                            raise
                        except Exception as e:  # noqa: BLE001 - classified
                            _handle(flight, e)
                    counts_now = _grab(flight, got)
                    if tracker is not None:
                        tracker.update(counts_now)
                        if tracker.converged:
                            # Statistical early stop: every target
                            # class's CI half-width is at (or below) its
                            # threshold.  Drop the in-flight batches --
                            # their rows were never collected, so the
                            # campaign IS the prefix that ran -- and
                            # journal the stop as a first-class terminal
                            # record so resume replays to exactly here.
                            stopped = True
                            in_flight.clear()
                            _journal_early_stop(done)
                except _Degrade as sig:
                    # OOM: the geometry was too ambitious for the live
                    # HBM headroom.  Halve the batch, drop the
                    # (uncollectable) in-flight work, and restart at the
                    # first uncollected row -- the compiled program
                    # re-specialises on the new shape at the next
                    # dispatch.
                    new_bs = retry.degraded_batch(batch_size)
                    if new_bs is None:
                        raise sig.__cause__
                    new_bs = self._round_batch(new_bs)
                    if new_bs >= batch_size:
                        raise sig.__cause__    # rounding floor reached
                    resilience["oom_degrade"] += 1
                    tel.count("resilience_oom_degrade", batch_size=new_bs)
                    flightrec.record("oom_degrade",
                                     batch_size=int(new_bs),
                                     lo=int(done))
                    batch_size = new_bs
                    in_flight.clear()
                    next_lo = done
                    if sparse_state is not None:
                        # The sparse program (and the resident padded
                        # arrays) are shaped by the batch geometry:
                        # rebuild for the degraded size.
                        sparse_state = self._sparse_setup(
                            sched, batch_size, transfer)
                    if journal is not None:
                        journal.append({"kind": "geometry",
                                        "batch_size": batch_size,
                                        "lo": journal_base + done})
        except BaseException as e:
            # The campaign died (fatal dispatch error, retries
            # exhausted, the caller's progress hook aborting): the live
            # metrics surfaces must say so rather than show "running"
            # forever, and the blackbox dumps its forensic bundle while
            # the failing state still exists.
            flightrec.record("campaign_crash", lo=int(done),
                             error=type(e).__name__)
            flightrec.current().dump(
                f"campaign_crash:{type(e).__name__}",
                extra={"error": f"{type(e).__name__}: {e}",
                       "done_rows": int(done)})
            if metrics is not None:
                metrics.campaign_finished(
                    error=f"{type(e).__name__}: {e}")
            raise
        if stopped and done < len(sched):
            # Early stop cut the schedule short: the result describes
            # exactly the rows that ran -- codes/weights/invalid-draw
            # masks all line up with the truncated schedule, and
            # ``convergence`` (below) records the planned size.
            sched = sched.slice(0, done)
            sched_w = getattr(sched, "class_weight", None)
        interesting_rows = None
        with tel.span("classify"):
            if sparse_state is not None:
                # The device histogrammed every batch already; the
                # campaign totals are their sum (identical to dense's
                # end-of-run bincount over all rows), and the per-run
                # columns cover exactly the interesting rows.
                cols = ("code", "errors", "corrected", "steps")
                if outs:
                    merged = {k: np.concatenate([o[k] for o in outs])
                              for k in cols}
                    interesting_rows = np.concatenate(
                        [o["rows"] for o in outs])
                    binc = np.sum([o["hist"] for o in outs], axis=0)
                    invalid_total = int(sum(o["invalid"] for o in outs))
                else:
                    merged = {k: np.zeros(0, np.int32) for k in cols}
                    interesting_rows = np.zeros(0, np.int64)
                    binc = np.zeros(cls.NUM_CLASSES, np.int64)
                    invalid_total = 0
                counts = cls.counts_dict(binc, self._train)
                counts["cache_invalid"] = invalid_total
            else:
                if outs:
                    merged = {k: np.concatenate([o[k] for o in outs])
                              for k in outs[0]}
                else:
                    merged = {k: np.zeros(0, np.int32)
                              for k in ("code", "errors", "corrected",
                                        "steps")}
                # Cache draws outside the program footprint (t < 0)
                # never fire a flip: a clean run that injected nothing
                # is not a "survived injection", so they get their own
                # bucket instead of inflating success -- the analogue of
                # the reference summary's cacheValids column
                # (jsonParser.py summarizeRuns counts lines whose
                # cacheInfo says the chosen line was not dirty).
                invalid_draw = np.asarray(sched.t) < 0
                if sched_w is None:
                    binc = np.bincount(merged["code"][~invalid_draw],
                                       minlength=cls.NUM_CLASSES)
                    invalid_total = int(invalid_draw.sum())
                else:
                    binc = cls.weighted_histogram(
                        merged["code"][~invalid_draw],
                        sched_w[~invalid_draw])
                    invalid_total = int(sched_w[invalid_draw].sum())
                counts = cls.counts_dict(binc, self._train)
                counts["cache_invalid"] = invalid_total
        seconds = time.perf_counter() - t0
        profile = None
        if prof is not None:
            # The attribution identity: device_busy + host_gap +
            # host_other == seconds (this campaign's wall clock), exact
            # by construction -- the profile_mm.json acceptance check.
            profile = prof.finish(time.perf_counter(), wall_s=seconds)
        res = CampaignResult(
            benchmark=self.prog.region.name,
            strategy=self.strategy_name,
            n=sched.effective_n,
            physical_n=(len(sched) if sched_w is not None else None),
            counts=counts,
            seconds=seconds,
            codes=merged["code"],
            errors=merged["errors"],
            corrected=merged["corrected"],
            steps=merged["steps"],
            schedule=sched,
            seed=sched.seed,
            stages=tel.stage_totals(since=mark),
            resilience=resilience,
            collect=self.collect,
            interesting_rows=interesting_rows,
            transfer={"up": int(transfer["up"]),
                      "down": int(transfer["down"])},
            profile=profile,
            mesh=self._mesh_block(),
        )
        if tracker is not None:
            res.convergence = tracker.report(
                stopped, planned_n=planned_effective,
                done_n=sched.effective_n)
        if metrics is not None and \
                getattr(metrics, "slo_set", None) is not None:
            report = metrics.slo_status()
            if report is not None:
                from coast_tpu.obs.slo import summary_block
                res.slo = summary_block(report)
        if metrics is not None:
            metrics.campaign_finished(res.summary(),
                                      convergence=res.convergence)
        return res

    def _campaign_spec(self, n: int, seed: int = 0,
                       batch_size: int = 4096, start_num: int = 0,
                       stop_when: "Optional[object]" = None
                       ) -> CampaignSpec:
        """This campaign's identity as the shared
        :class:`~coast_tpu.inject.spec.CampaignSpec`.  The runner
        supplies the program-derived axes (fault model, equivalence)
        from its own state, so a header serialized from this spec can
        never disagree with the schedule the runner generates.  The
        build-vocabulary fields (opt flags, section) stay at their
        defaults -- the runner knows the *built* program, and the
        header pins it through config_sha instead."""
        return CampaignSpec(
            benchmark=self.prog.region.name, n=int(n), seed=int(seed),
            batch_size=int(batch_size), start_num=int(start_num),
            fault_model=self.fault_model.spec(),
            equiv=self.equiv_partition is not None,
            stop_when=(stop_when.spec() if stop_when is not None
                       else None),
            collect=self.collect,
            placement=self.placement)

    def _journal_header(self, mode: str, **fields) -> Dict[str, object]:
        """The identity block every journal header shares: resuming under
        a different program, strategy, protection config, or fault model
        must refuse.  Single-bit campaigns omit the fault-model key so
        journals written before the model existed still resume."""
        header = {"mode": mode,
                  "benchmark": self.prog.region.name,
                  "strategy": self.strategy_name,
                  "config_sha": config_fingerprint(self.prog.cfg)}
        if self.fault_model.kind != "single":
            header["fault_model"] = self.fault_model.spec()
        if self.collect != "dense":
            # Absent-means-dense: every journal written before sparse
            # collection existed keeps resuming unchanged.
            header["collect"] = self.collect
        if self.placement != "compute":
            # Absent-means-compute (the registry build): pre-placement
            # journals keep resuming unchanged; an exchange-then-vote
            # journal refuses a vote-then-exchange resume with the
            # typed PlacementMismatchError.
            header["placement"] = self.placement
        if getattr(self.prog.cfg, "fuse_step", False):
            # Absent-means-unfused: pre-fusion journals keep resuming
            # unchanged; a fused journal refuses an unfused resume (and
            # vice versa) with the typed FuseStepMismatchError.
            header["fuse"] = True
        if self.equiv_partition is not None:
            # Partition = campaign identity (the reduced rows are only
            # meaningful under it); per-section fingerprints are the
            # delta-campaign vocabulary and deliberately volatile --
            # they may differ on resume of an unchanged campaign only
            # if the program changed, which config_sha/schedule_sha
            # already refuse.
            header["equiv"] = {
                "partition": self.equiv_partition.fingerprint,
                "clean_steps": self.equiv_partition.clean_steps}
            header["section_fingerprints"] = {
                name: sig.fingerprint
                for name, sig in sorted(
                    self.equiv_partition.signatures.items())}
        header.update(fields)
        return header

    def _open_journal(self, journal, header: Dict[str, object]):
        """``journal`` as accepted by the run methods: None, a path (opened
        -- and resume-validated -- here), or an already-open
        CampaignJournal (validated against this campaign's header)."""
        if journal is None:
            return None, False
        if isinstance(journal, CampaignJournal):
            CampaignJournal._validate(journal.header,
                                      {**journal.header, **header},
                                      journal.path)
            return journal, False
        return CampaignJournal.open(str(journal), header), True

    def _seeded_part(self, n: int, seed: int, start_num: int):
        """generate -> start_num slice -> (optional) equivalence
        reduction: the ONE schedule-preparation path shared by ``run``
        and ``run_delta``, so the reduced rows a delta splices against
        cannot drift from the rows a run journals.  Reduction happens
        AFTER the slice: the representatives (and weights) describe
        exactly the rows this campaign covers."""
        with self.telemetry.activate():   # generate() records its span
            sched = generate(self.mmap, start_num + n, seed,
                             self.prog.region.nominal_steps,
                             model=self.fault_model)
        part = sched.slice(start_num, start_num + n)
        if self.equiv_partition is not None:
            with self.telemetry.activate(), \
                    self.telemetry.span("schedule_equiv"):
                part = self.equiv_partition.reduce(part)
        return part

    def run(self, n: int, seed: int = 0,
            batch_size: int = 4096, start_num: int = 0,
            progress: Optional[
                Callable[[int, Dict[str, int]], None]] = None,
            journal: "Optional[object]" = None,
            stream: "Optional[object]" = None,
            stop_when: "Optional[object]" = None
            ) -> CampaignResult:
        """``start_num`` resumes a seeded campaign at injection #start_num:
        the schedule stream for (seed, start_num+n) is generated and the
        first start_num rows skipped, so a resumed campaign injects exactly
        the faults the interrupted one would have (the --start-num counter
        of gdbClient.py:401).

        ``journal`` (a path or an open CampaignJournal) makes the campaign
        crash-safe: every collected batch is fsync'd to the journal, and
        rerunning the same call against the same path resumes at the
        first missing batch after validating that the journal's header
        -- including the regenerated schedule's fingerprint -- matches
        this campaign exactly (JournalMismatchError otherwise).

        ``stream`` (a :class:`coast_tpu.inject.logs.StreamLogWriter`)
        serializes each collected batch in the background as it lands;
        the caller calls ``stream.finish(result)`` when done (and
        ``stream.abort()`` on failure).

        ``stop_when`` (:class:`coast_tpu.obs.convergence.StopWhen`)
        arms statistical early stop (see ``run_schedule``); the
        condition joins the journal header, so resuming under a
        different -- or no -- condition refuses exactly like a changed
        seed."""
        tel = self.telemetry
        mark = tel.mark()
        part = self._seeded_part(n, seed, start_num)
        j, owned = (None, False)
        if journal is not None:
            # The header's spec-owned fields serialize through the ONE
            # identity vocabulary (CampaignSpec), built FROM the
            # runner's own model/partition so header and schedule can
            # never disagree.  Key order is run_header_fields' -- the
            # header's historical byte order, pinned in tests.
            spec = self._campaign_spec(n, seed=seed, batch_size=batch_size,
                                       start_num=start_num,
                                       stop_when=stop_when)
            header = self._journal_header(
                "run", **spec.run_header_fields(),
                schedule_sha=schedule_fingerprint(part))
            if spec.stop_when:
                header["stop_when"] = spec.stop_when
            j, owned = self._open_journal(journal, header)
            if self.equiv_partition is not None and not j.resumed:
                # Persist the representatives: run_delta splices by site
                # identity, which a reduced schedule cannot regenerate
                # from the seed alone once the partition drifts.
                j.append({
                    "kind": "equiv_schedule",
                    "class_weight": part.class_weight.tolist(),
                    **{k: np.asarray(getattr(part, k)).tolist()
                       for k in ("leaf_id", "lane", "word", "bit", "t")},
                })
        try:
            res = self.run_schedule(part, batch_size, progress=progress,
                                    _telemetry_mark=mark, journal=j,
                                    stream=stream, stop_when=stop_when)
        finally:
            if owned and j is not None:
                j.close()
        res.start_num = start_num
        return res

    @staticmethod
    def _take_rows(part: FaultSchedule, idx: np.ndarray) -> FaultSchedule:
        """Arbitrary-row subset of a single-site schedule (the delta
        paths' working shape: equiv-reduced, no flip groups)."""
        return _rows_subset(part, idx)

    def run_delta(self, n: int, delta_from: str, seed: int = 0,
                  batch_size: int = 4096, start_num: int = 0,
                  progress: Optional[
                      Callable[[int, Dict[str, int]], None]] = None,
                  stop_when: "Optional[object]" = None,
                  static_budget: "bool | object" = False
                  ) -> CampaignResult:
        """Delta campaign: rerun the seeded campaign recorded in the
        journal at ``delta_from``, but physically re-inject ONLY the
        sections whose propagation fingerprint changed since that
        journal was written -- every other row's outcome is spliced
        from the journal (its dataflow cone is provably unchanged, so
        the recorded outcome still holds).  A no-op rebuild re-injects
        zero rows; a one-section edit re-injects exactly that section.

        ``stop_when`` (:class:`coast_tpu.obs.convergence.StopWhen`)
        arms statistical early stop PER RE-INJECTED SECTION: each
        changed section's rows run as their own convergence-tracked
        sub-campaign, so one section's quick convergence can neither
        starve nor extend another's, and the spliced sections -- whose
        outcomes are exact journal records, not samples -- never enter
        any tracker's histogram (they keep their recorded counts
        verbatim).  Rows a section's early stop dropped are excluded
        from the result (codes/weights/counts all describe exactly the
        spliced + collected rows); ``CampaignResult.convergence``
        carries one report per section and ``delta["dropped_rows"]``
        the cut total.

        ``static_budget`` feeds the static vulnerability map
        (:mod:`coast_tpu.analysis.propagation`) into the re-injection
        loop: sections verdicted ``sdc-possible`` run FIRST (the
        uncertain sections get their convergence budget before anything
        else), and sections the analysis proves ``masked`` or
        ``detected-bounded`` run under a relaxed ``min_done`` floor
        (quartered, floored at 32) -- the floor exists so rare classes
        get a chance to appear, and for those sections the static proof
        already rules the silent classes out, so the same ``stop_when``
        confidence is reached with fewer physical injections.  Pass
        ``True`` to derive the map from this runner's partition, or an
        already-built :class:`~coast_tpu.analysis.propagation.
        VulnerabilityMap`.  Per-class thresholds are untouched -- the
        verdict statistics are identical, only the floor spend moves.
        ``delta["static_budget"]`` records the verdicts, order, and
        relaxed floors.

        Requires an equivalence-enabled runner (``equiv=True``): the
        partition supplies the per-section fingerprints, and the base
        journal must carry the fingerprint block (i.e. was itself
        written by an equiv run).  Incompatible bases refuse with the
        typed :class:`~coast_tpu.analysis.equiv.DeltaMismatchError`."""
        from coast_tpu.analysis.equiv import load_delta_base, plan_delta
        if self.equiv_partition is None:
            raise ValueError(
                "run_delta needs CampaignRunner(equiv=True): the "
                "equivalence partition supplies the per-section "
                "fingerprints a delta diffs")
        if self.collect != "dense":
            raise ValueError(
                "run_delta is dense by construction: the spliced rows "
                "are exact per-row journal records; build the runner "
                "with collect='dense'")
        tel = self.telemetry
        mark = tel.mark()
        base_header, base_sites, base_out, base_rows = load_delta_base(
            delta_from)
        part = self._seeded_part(n, seed, start_num)
        current_header = self._journal_header(
            "run", **self._campaign_spec(
                n, seed=seed, batch_size=batch_size,
                start_num=start_num).run_header_fields())
        section_names = {sig.leaf_id: name for name, sig in
                         self.equiv_partition.signatures.items()}
        plan = plan_delta(
            base_header, base_sites, base_out, base_rows,
            current_header,
            {name: sig.fingerprint for name, sig in
             self.equiv_partition.signatures.items()},
            part, section_names, base_path=delta_from)
        tel.instant("delta_plan", **plan.summary())

        # Base-side section attribution, captured BEFORE any filtering:
        # the recorded sites when the journal carries them, else the
        # positional rows the schedule sha proved identical.  Feeds the
        # per-changed-section distributions below -- the CI verdict's
        # unbiased comparison unit when early stop truncates sections.
        base_leaf = (np.asarray(base_sites["leaf_id"])
                     if base_sites is not None
                     else np.asarray(part.leaf_id).copy())
        base_w_col = (np.asarray(base_sites["class_weight"], np.int64)
                      if base_sites is not None
                      else np.asarray(part.class_weight, np.int64).copy())
        base_codes_col = base_out["codes"]

        run_idx = np.flatnonzero(plan.run_mask)
        part0_leaf = np.asarray(part.leaf_id).copy()   # pre-filter rows
        cols = {k: v.copy() for k, v in plan.spliced.items()}
        seconds = 0.0
        stages: Dict[str, float] = {}
        resilience: Dict[str, int] = {}
        # Progress covers the WHOLE delta campaign, spliced rows
        # included: the splice is instant, so it lands as one opening
        # beat (done = spliced rows, counts = their weighted histogram)
        # and the re-injected rows then count up from that base -- a
        # delta campaign's heartbeat is monotone like any other
        # campaign's.
        splice_idx = np.flatnonzero(~plan.run_mask)
        splice_counts: Dict[str, int] = {}
        if progress is not None and len(splice_idx):
            binc0 = cls.weighted_histogram(
                cols["codes"][splice_idx],
                part.class_weight[splice_idx])
            splice_counts = cls.counts_dict(binc0, self._train)
            splice_counts["cache_invalid"] = 0
            progress(int(len(splice_idx)), dict(splice_counts))
        keep = None
        convergence: Optional[Dict[str, object]] = None
        static_info: Optional[Dict[str, object]] = None
        static_verdicts: Dict[str, str] = {}
        if static_budget:
            from coast_tpu.analysis.propagation import (VERDICT_SDC,
                                                        VulnerabilityMap,
                                                        analyze_propagation)
            vmap = (static_budget
                    if isinstance(static_budget, VulnerabilityMap)
                    else analyze_propagation(
                        self.prog, partition=self.equiv_partition))
            static_verdicts = vmap.section_verdicts()
            static_info = {"verdicts": dict(sorted(
                static_verdicts.items()))}
            tel.instant("delta_static_budget",
                        sections=len(static_verdicts),
                        sdc_possible=sum(
                            1 for v in static_verdicts.values()
                            if v == "sdc-possible"))
        if len(run_idx) and stop_when is None:
            sub = self._take_rows(part, run_idx)
            chunk_progress = None
            if progress is not None:
                base_done = int(len(splice_idx))

                def chunk_progress(done, counts):
                    merged = dict(splice_counts)
                    for k, v in counts.items():
                        merged[k] = merged.get(k, 0) + v
                    progress(base_done + done, merged)
            sub_res = self.run_schedule(
                sub, batch_size=min(batch_size, len(sub)),
                progress=chunk_progress, _telemetry_mark=mark)
            for out_key, res_key in (("codes", "codes"),
                                     ("errors", "errors"),
                                     ("corrected", "corrected"),
                                     ("steps", "steps")):
                cols[out_key][run_idx] = getattr(sub_res, res_key)
            seconds = sub_res.seconds
            stages = sub_res.stages
            resilience = sub_res.resilience
        elif len(run_idx):
            # Per-section convergence: one sub-campaign (and one
            # tracker) per re-injected section, in sorted name order so
            # the row layout is deterministic.
            keep = ~plan.run_mask
            leaf_names = np.array([section_names.get(int(l), "?")
                                   for l in np.asarray(part.leaf_id)])
            groups: Dict[str, List[int]] = {}
            for i in run_idx:
                groups.setdefault(str(leaf_names[i]), []).append(int(i))
            per_section: Dict[str, object] = {}
            agg_counts = dict(splice_counts)
            agg_done = int(len(splice_idx))
            ordered = sorted(groups)
            relaxed: Dict[str, int] = {}
            if static_info is not None:
                # Static-prior budget allocation: uncertain
                # (sdc-possible) sections first, and the min_done floor
                # -- whose whole purpose is letting rare classes appear
                # -- quartered on sections the map proves cannot
                # silently corrupt.
                from coast_tpu.analysis.propagation import VERDICT_SDC
                _rank = {VERDICT_SDC: 0}
                ordered = sorted(
                    groups, key=lambda nm: (
                        _rank.get(static_verdicts.get(nm), 1), nm))
                static_info["order"] = list(ordered)
            for name in ordered:
                idx = np.asarray(groups[name], np.int64)
                sub = self._take_rows(part, idx)
                chunk_progress = None
                if progress is not None:
                    def chunk_progress(done, counts, _base=agg_done,
                                       _agg=dict(agg_counts)):
                        merged = dict(_agg)
                        for k, v in counts.items():
                            merged[k] = merged.get(k, 0) + v
                        progress(_base + done, merged)
                sub_stop = stop_when
                if static_info is not None and stop_when is not None \
                        and getattr(stop_when, "min_done", 0) \
                        and static_verdicts.get(name) is not None \
                        and static_verdicts[name] != VERDICT_SDC:
                    import dataclasses as _dc
                    floor = max(32, int(stop_when.min_done) // 4)
                    if floor < int(stop_when.min_done):
                        sub_stop = _dc.replace(stop_when, min_done=floor)
                        relaxed[name] = floor
                sub_res = self.run_schedule(
                    sub, batch_size=min(batch_size, len(sub)),
                    progress=chunk_progress, _telemetry_mark=mark,
                    stop_when=sub_stop)
                ran = len(sub_res.codes)
                sel = idx[:ran]
                for out_key, res_key in (("codes", "codes"),
                                         ("errors", "errors"),
                                         ("corrected", "corrected"),
                                         ("steps", "steps")):
                    cols[out_key][sel] = getattr(sub_res, res_key)
                keep[sel] = True
                seconds += sub_res.seconds
                # stage_totals is cumulative since ``mark``: the last
                # sub-run's totals already cover every earlier one.
                stages = sub_res.stages
                for k, v in sub_res.resilience.items():
                    resilience[k] = resilience.get(k, 0) + v
                per_section[name] = sub_res.convergence
                agg_done += ran
                for k, v in sub_res.counts.items():
                    agg_counts[k] = agg_counts.get(k, 0) + v
            convergence = {
                "stopped": any(bool((c or {}).get("stopped"))
                               for c in per_section.values()),
                "stop_when": stop_when.spec(),
                "per_section": per_section,
            }
            if static_info is not None and relaxed:
                static_info["relaxed_min"] = dict(sorted(relaxed.items()))
        dropped = 0
        if keep is not None and not keep.all():
            # Early stop cut some sections short: the result describes
            # exactly the spliced + collected rows.
            keep_idx = np.flatnonzero(keep)
            dropped = int(len(part) - len(keep_idx))
            part = self._take_rows(part, keep_idx)
            cols = {k: v[keep_idx] for k, v in cols.items()}
        # Same invalid-draw accounting as run(): a t<0 row never fired,
        # so it buckets as cache_invalid, never an outcome class --
        # keeping journal_result's re-derived counts (and the fleet
        # merge parity they feed) definitionally consistent.  Seeded
        # generate() streams have no such rows, so delta counts are
        # unchanged in practice.
        fired = np.asarray(part.t) >= 0
        w_col = np.asarray(part.class_weight, np.int64)
        binc = cls.weighted_histogram(cols["codes"][fired], w_col[fired])
        counts = cls.counts_dict(binc, self._train)
        counts["cache_invalid"] = int(w_col[~fired].sum())
        delta_summary: Dict[str, object] = {**plan.summary(),
                                            "base": delta_from}
        if static_info is not None:
            delta_summary["static_budget"] = static_info
        if stop_when is not None:
            delta_summary["dropped_rows"] = dropped
        if len(run_idx):
            # Per-section base-vs-candidate distributions for every
            # section that re-injected ANYTHING -- fingerprint-changed
            # sections plus conservative re-injects (unmatched sites /
            # drifted weights) in unchanged ones.  The spliced rows are
            # identical by construction, so drift can only originate
            # here -- and when early stop truncated a section, the
            # POOLED mix is biased (the section's share of the total
            # shrank), so consumers comparing distributions must
            # compare these per-section blocks instead.
            run_names = np.array([section_names.get(int(l), "?") for l
                                  in np.asarray(part0_leaf)[run_idx]])
            final_names = np.array([section_names.get(int(l), "?")
                                    for l in np.asarray(part.leaf_id)])
            base_names_col = np.array([section_names.get(int(l), "?")
                                       for l in base_leaf])
            sections: Dict[str, object] = {}
            for name in sorted(set(run_names)):
                bsel = base_names_col == name
                csel = final_names == name
                sections[name] = {
                    "base_n": int(base_w_col[bsel].sum()),
                    "base_counts": cls.counts_dict(
                        cls.weighted_histogram(base_codes_col[bsel],
                                               base_w_col[bsel]),
                        self._train),
                    "n": int(w_col[csel].sum()),
                    "counts": cls.counts_dict(
                        cls.weighted_histogram(cols["codes"][csel],
                                               w_col[csel]),
                        self._train),
                }
            delta_summary["sections"] = sections
        res = CampaignResult(
            benchmark=self.prog.region.name,
            strategy=self.strategy_name,
            n=part.effective_n,
            physical_n=len(part),
            counts=counts,
            seconds=seconds,
            codes=cols["codes"],
            errors=cols["errors"],
            corrected=cols["corrected"],
            steps=cols["steps"],
            schedule=part,
            seed=part.seed,
            stages=stages or tel.stage_totals(since=mark),
            resilience=resilience,
            delta=delta_summary,
        )
        res.convergence = convergence
        res.start_num = start_num
        return res

    def journal_result(self, res: CampaignResult, path: str,
                       n: Optional[int] = None,
                       batch_size: int = 4096) -> None:
        """Materialize a completed single-seed result as a ``mode:
        "run"`` journal at ``path``: header, the equiv representatives
        (for reduced schedules), and one batch record per ``batch_size``
        rows with cumulative counts -- exactly the records
        ``load_delta_base`` and ``merge_fleet`` read.

        Two consumers: the fleet's DELTA items (whose spliced rows
        never ran, so the live campaign writes no journal -- this gives
        their done records a journal to parity-check against), and the
        CI refresh path (the materialized journal is the next
        baseline's splice base).  ``n`` is the header's nominal
        campaign size (the spec's requested n; an early-stopped delta
        result covers fewer rows), defaulting to ``res.n``.

        Refuses an existing non-empty ``path``
        (:class:`~coast_tpu.inject.journal.JournalExistsError`) and
        raises ``JournalError`` if the re-derived cumulative counts do
        not reproduce ``res.counts`` -- the journal must be able to
        stand in for the result under the fleet merge's parity check."""
        from coast_tpu.inject.journal import JournalError
        if res.collect != "dense":
            raise ValueError(
                "journal_result materializes dense per-row batch "
                "records; a sparse result has no full columns to write")
        part = res.schedule
        spec = self._campaign_spec(
            int(n) if n is not None else int(res.n), seed=res.seed,
            batch_size=batch_size, start_num=res.start_num)
        header = self._journal_header(
            "run", **spec.run_header_fields(),
            schedule_sha=schedule_fingerprint(part))
        j = CampaignJournal.open(path, header, resume=False)
        try:
            if part.class_weight is not None:
                j.append({
                    "kind": "equiv_schedule",
                    "class_weight": part.class_weight.tolist(),
                    **{k: np.asarray(getattr(part, k)).tolist()
                       for k in ("leaf_id", "lane", "word", "bit", "t")},
                })
            live = np.zeros(cls.NUM_CLASSES, np.int64)
            live_invalid = 0
            t_col = np.asarray(part.t)
            w = part.class_weight
            counts: Dict[str, int] = {}
            for lo in range(0, len(part), batch_size):
                hi = min(lo + batch_size, len(part))
                out = {"code": res.codes[lo:hi],
                       "errors": res.errors[lo:hi],
                       "corrected": res.corrected[lo:hi],
                       "steps": res.steps[lo:hi]}
                fired = t_col[lo:hi] >= 0
                if w is None:
                    live += np.bincount(out["code"][fired],
                                        minlength=cls.NUM_CLASSES)
                    live_invalid += int((~fired).sum())
                else:
                    ww = w[lo:hi]
                    live += cls.weighted_histogram(out["code"][fired],
                                                   ww[fired])
                    live_invalid += int(ww[~fired].sum())
                counts = cls.counts_dict(live, self._train)
                counts["cache_invalid"] = live_invalid
                j.append_batch(lo, out, counts, {})
            want = {k: int(v) for k, v in res.counts.items()}
            if len(part) and counts != want:
                raise JournalError(
                    f"journal_result parity failure at {path!r}: "
                    f"re-derived cumulative counts {counts} != result "
                    f"counts {want}")
        finally:
            j.close()

    def _result_from_chunk(self, rec: Dict[str, object]) -> CampaignResult:
        """Rebuild one journaled chunk's CampaignResult without touching
        the device: the seeded schedule regenerates deterministically,
        the per-run columns come from the journal record."""
        seed, n = int(rec["seed"]), int(rec["n"])
        start_num = int(rec.get("start_num", 0))
        with self.telemetry.activate():
            sched = generate(self.mmap, start_num + n, seed,
                             self.prog.region.nominal_steps,
                             model=self.fault_model
                             ).slice(start_num, start_num + n)
            if self.equiv_partition is not None:
                sched = self.equiv_partition.reduce(sched)
        return CampaignResult(
            benchmark=self.prog.region.name,
            strategy=self.strategy_name,
            n=sched.effective_n,
            physical_n=(len(sched) if sched.class_weight is not None
                        else None),
            counts={k: int(v) for k, v in rec["counts"].items()},
            seconds=float(rec.get("seconds", 0.0)),
            codes=np.asarray(rec["codes"], np.int32),
            errors=np.asarray(rec["errors"], np.int32),
            corrected=np.asarray(rec["corrected"], np.int32),
            steps=np.asarray(rec["steps"], np.int32),
            schedule=sched,
            seed=seed,
            stages={k: float(v)
                    for k, v in (rec.get("stage_seconds") or {}).items()},
            start_num=start_num,
        )

    def _chunk_runner(self, journal, header: Dict[str, object],
                      batch_size: int,
                      progress: Optional[
                          Callable[[int, Dict[str, int]], None]]):
        """Shared per-chunk machinery of ``run_until_errors`` and
        ``replay_chunks``: a ``next_chunk(n, seed, start_num)`` closure
        that replays completed chunks from the journal (validating the
        identity of each against the deterministic loop's expectation),
        runs + journals the rest, and threads the ``progress`` heartbeat
        across chunk boundaries (cumulative done/counts, so
        error-bounded flagship loops are no longer silent for minutes).
        Returns (next_chunk, finish) -- call ``finish`` when done."""
        if self.collect != "dense":
            raise ValueError(
                "multi-chunk campaigns (run_until_errors / "
                "replay_chunks) record full per-chunk columns; run "
                "them with collect='dense' (sparse campaigns use "
                "run/run_schedule)")
        j, owned = self._open_journal(journal, header)
        replayed = j.chunk_records() if j is not None else []
        replay_idx = 0
        agg_counts: Dict[str, int] = {}
        agg_done = 0

        def next_chunk(n_req: int, seed: int,
                       start_num: int = 0) -> CampaignResult:
            nonlocal replay_idx, agg_done
            from_journal = replay_idx < len(replayed)
            if from_journal:
                rec = replayed[replay_idx]
                expect = (int(rec["seed"]), int(rec["n"]),
                          int(rec.get("start_num", 0)))
                if expect != (int(seed), int(n_req), int(start_num)):
                    raise JournalMismatchError(
                        f"journal chunk {replay_idx} records (seed, n, "
                        f"start_num)={expect} but the campaign loop "
                        f"expects {(int(seed), int(n_req), int(start_num))}"
                        "; refusing to resume")
                replay_idx += 1
                res = self._result_from_chunk(rec)
            else:
                chunk_progress = None
                if progress is not None:
                    def chunk_progress(done, counts, _base=agg_done,
                                       _agg=dict(agg_counts)):
                        merged = dict(_agg)
                        for k, v in counts.items():
                            merged[k] = merged.get(k, 0) + v
                        progress(_base + done, merged)
                res = self.run(n_req, seed=seed, batch_size=batch_size,
                               start_num=start_num,
                               progress=chunk_progress)
                if j is not None:
                    j.append_chunk(res)
            agg_done += res.n
            for k, v in res.counts.items():
                agg_counts[k] = agg_counts.get(k, 0) + v
            if progress is not None and from_journal:
                # journal-replayed chunks fire one heartbeat apiece so a
                # resumed loop's progress is monotone from the start
                progress(agg_done, dict(agg_counts))
            return res

        def finish() -> None:
            if owned and j is not None:
                j.close()

        return next_chunk, finish

    def run_until_errors(self, min_errors: int, seed: int = 0,
                         batch_size: int = 4096,
                         round_to: int = 1000,
                         max_n: int = 1_000_000,
                         progress: Optional[
                             Callable[[int, Dict[str, int]], None]] = None,
                         journal: "Optional[object]" = None
                         ) -> CampaignResult:
        """The reference's campaign-sizing convention: inject until N SDC
        errors are seen, then round the campaign up to the next ``round_to``
        (supervisor.py:339; threadFunctions.py:534-558).

        The result's ``chunks`` records every chunk's exact (seed, n), and
        ``replay_chunks(result.chunks)`` reproduces the campaign
        bit-for-bit -- the merged schedule spans several seed streams, so
        the master seed alone cannot.

        ``progress(done, counts_so_far)`` fires per collected batch with
        done/counts cumulative *across* chunks.  ``journal`` (path or
        open CampaignJournal) appends one fsync'd record per completed
        chunk; resuming replays the completed-chunk prefix from disk --
        the sizing loop is deterministic given the per-chunk results, so
        the resumed campaign continues exactly where it stopped."""
        next_chunk, finish = self._chunk_runner(
            journal, self._journal_header(
                "until_errors", seed=int(seed), min_errors=int(min_errors),
                round_to=int(round_to), max_n=int(max_n),
                batch_size=int(batch_size)),
            batch_size, progress)
        try:
            results: List[CampaignResult] = []
            total = 0
            errors_seen = 0
            chunk_seed = seed
            while total < max_n:
                res = next_chunk(batch_size, chunk_seed)
                results.append(res)
                total += res.n
                errors_seen += res.sdc_total
                chunk_seed += 1
                if errors_seen >= min_errors:
                    break
            target = ((total + round_to - 1) // round_to) * round_to
            while total < target and total < max_n:
                res = next_chunk(min(batch_size, target - total), chunk_seed)
                results.append(res)
                total += res.n
                chunk_seed += 1
        finally:
            finish()
        return _merge_results(results, seed)

    def replay_chunks(self, chunks: Sequence[Dict[str, int]],
                      batch_size: int = 4096,
                      progress: Optional[
                          Callable[[int, Dict[str, int]], None]] = None,
                      journal: "Optional[object]" = None) -> CampaignResult:
        """Re-run a recorded multi-chunk campaign exactly.

        ``chunks`` is ``CampaignResult.chunks`` (each entry ``{"seed",
        "n"}`` plus an optional ``"start_num"`` resume offset, honored so
        a resumed-chunk campaign -- e.g. the flagship loop's
        ``run(seed, start_num=done)`` chunks -- replays the exact rows it
        ran); the replay regenerates each chunk's seeded schedule and
        merges in the same order, so ``codes`` matches the original
        bit-for-bit -- the campaign-resume guarantee of gdbClient.py:401
        extended to the error-bounded sizing loop.

        ``progress`` and ``journal`` behave as in ``run_until_errors``:
        cross-chunk heartbeats, per-chunk durable records, resume from
        the completed-chunk prefix."""
        if not chunks:
            raise ValueError(
                "replay_chunks got an empty chunk list: the recorded "
                "campaign produced no chunks (nothing to replay)")
        next_chunk, finish = self._chunk_runner(
            journal, self._journal_header(
                "replay",
                chunks=[{"seed": int(c["seed"]), "n": int(c["n"]),
                         "start_num": int(c.get("start_num", 0))}
                        for c in chunks],
                batch_size=int(batch_size)),
            batch_size, progress)
        try:
            results = [next_chunk(int(c["n"]), int(c["seed"]),
                                  int(c.get("start_num", 0)))
                       for c in chunks]
        finally:
            finish()
        return _merge_results(results, int(chunks[0]["seed"]))


def _merge_profiles(parts: List[CampaignResult]
                    ) -> Optional[Dict[str, object]]:
    """Merged device-time attribution for a multi-chunk campaign: sums
    of the per-chunk buckets (each chunk's identity holds, so the sums'
    does too), bucket-wise histogram merge, fractions recomputed over
    the summed wall, and the mfu block re-derived from the summed
    runs/device seconds (the analytic inputs are per-run constants of
    the one shared program, so the first chunk's carry over)."""
    profs = [p.profile for p in parts if p.profile]
    if not profs:
        return None
    out: Dict[str, object] = {
        "dispatches": sum(int(p["dispatches"]) for p in profs),
        "rows": sum(int(p["rows"]) for p in profs),
    }
    for key in ("wall_s", "device_busy_s", "host_gap_s", "host_other_s"):
        out[key] = round(sum(float(p[key]) for p in profs), 6)
    wall = float(out["wall_s"]) or 1.0
    out["device_busy_fraction"] = round(
        float(out["device_busy_s"]) / wall, 6)
    out["dispatch_gap_fraction"] = round(
        float(out["host_gap_s"]) / wall, 6)
    per_phase: Dict[str, float] = {}
    for p in profs:
        for name, s in (p.get("per_phase_device_s") or {}).items():
            per_phase[name] = per_phase.get(name, 0.0) + float(s)
    out["per_phase_device_s"] = {k: round(v, 6)
                                 for k, v in per_phase.items()}
    for key in ("device_seconds_histogram", "host_gap_seconds_histogram"):
        hists = [p.get(key) for p in profs if p.get(key)]
        if hists and all(h["le"] == hists[0]["le"] for h in hists):
            out[key] = {
                "le": list(hists[0]["le"]),
                "counts": [sum(h["counts"][i] for h in hists)
                           for i in range(len(hists[0]["le"]))],
                "count": sum(int(h["count"]) for h in hists),
                "sum": round(sum(float(h["sum"]) for h in hists), 6)}
    out["backend"] = profs[0].get("backend")
    mfus = [p.get("mfu") for p in profs if p.get("mfu")]
    if mfus:
        mfu = dict(mfus[0])            # per-run analytic constants
        mfu["runs"] = int(out["rows"])
        mfu["device_busy_s"] = out["device_busy_s"]
        mfu["dispatch_gap_fraction"] = out["dispatch_gap_fraction"]
        useful = float(mfu.get("useful_ops_per_run") or 0.0)
        busy = float(out["device_busy_s"])
        achieved = useful * mfu["runs"] / busy if busy > 0 else 0.0
        mfu["achieved_ops_per_s"] = round(achieved, 1)
        mfu["achieved_ops_per_s_wall"] = round(
            useful * mfu["runs"] / wall, 1)
        peak = mfu.get("peak_gflops")
        if peak:
            mfu["achieved_mfu"] = round(achieved / (peak * 1e9), 8)
            mfu["achieved_mfu_wall"] = round(
                useful * mfu["runs"] / wall / (peak * 1e9), 8)
        out["mfu"] = mfu
    return out


def _merge_results(parts: List[CampaignResult], seed: int) -> CampaignResult:
    if not parts:
        raise ValueError(
            "campaign produced no chunks: _merge_results got an empty "
            "parts list (the sizing loop never ran a batch -- check "
            "min_errors/max_n/target arithmetic)")
    first = parts[0]
    if len({p.collect for p in parts}) > 1:
        raise ValueError(
            "cannot merge campaigns with mixed collect modes "
            f"({sorted({p.collect for p in parts})})")
    counts = {k: sum(p.counts[k] for p in parts) for k in first.counts}
    stages: Dict[str, float] = {}
    resilience: Dict[str, int] = {}
    transfer: Dict[str, int] = {}
    for p in parts:
        for k, v in p.stages.items():
            stages[k] = stages.get(k, 0.0) + v
        for k, v in p.resilience.items():
            resilience[k] = resilience.get(k, 0) + v
        for k, v in p.transfer.items():
            transfer[k] = transfer.get(k, 0) + int(v)
    interesting = None
    if first.collect != "dense":
        # Sparse chunks: per-part rows are schedule-local; rebase each
        # by its part's physical offset so the merged indices stay
        # schedule-global (exactly the codes-concatenation order).
        offsets = np.cumsum([0] + [len(p.schedule) for p in parts[:-1]])
        interesting = np.concatenate(
            [p.interesting_rows + int(off)
             for p, off in zip(parts, offsets)])
    extra = None
    first_sched = first.schedule
    if first_sched.extra is not None:
        # Flip-group rows concatenate like the base rows, but each part's
        # group column indexes ITS OWN injections: rebase by the running
        # injection offset so the merged group ids stay schedule-global.
        offsets = np.cumsum([0] + [p.n for p in parts[:-1]])
        extra = {k: np.concatenate([p.schedule.extra[k] for p in parts])
                 for k in first_sched.extra if k != "group"}
        extra["group"] = np.concatenate(
            [p.schedule.extra["group"] + np.int32(off)
             for p, off in zip(parts, offsets)]).astype(np.int32)
    weights = None
    if first_sched.class_weight is not None:
        weights = np.concatenate(
            [p.schedule.class_weight for p in parts])
    sched = FaultSchedule(
        *(np.concatenate([getattr(p.schedule, f) for p in parts])
          for f in ("leaf_id", "lane", "word", "bit", "t", "section_idx")),
        seed=seed, extra=extra, model=first_sched.model,
        class_weight=weights, equiv_sha=first_sched.equiv_sha)
    physical = None
    if any(p.physical_n is not None for p in parts):
        physical = sum(p.physical_n if p.physical_n is not None else p.n
                       for p in parts)
    return CampaignResult(
        benchmark=first.benchmark,
        strategy=first.strategy,
        n=sum(p.n for p in parts),
        physical_n=physical,
        counts=counts,
        seconds=sum(p.seconds for p in parts),
        codes=np.concatenate([p.codes for p in parts]),
        errors=np.concatenate([p.errors for p in parts]),
        corrected=np.concatenate([p.corrected for p in parts]),
        steps=np.concatenate([p.steps for p in parts]),
        schedule=sched,
        seed=seed,
        chunks=[{"seed": p.seed, "n": p.n, "start_num": p.start_num}
                for p in parts],
        stages=stages,
        resilience=resilience,
        collect=first.collect,
        interesting_rows=interesting,
        transfer=transfer,
        profile=_merge_profiles(parts),
    )
