"""Batched fault-injection campaigns: the supervisor.py replacement.

The reference campaign loop costs seconds per injection: spawn QEMU + GDB,
sleep to a random point, interrupt, GDB round-trips to flip one bit, run to
a breakpoint, parse UART, restart everything when a run wedges
(threadFunctions.py:315-953; supervisor.py:400-509).  Here an entire batch
of injections is ONE jitted XLA program:

    vmap over campaigns ( scan over steps ( flip-at-t  +  N-lane step ) )

so the per-injection cost is amortised to a few microseconds, and the only
host<->device traffic is one classification tensor per batch (the north-star
>=1000x injections/sec of BASELINE.json).  Campaign scale-out across chips
-- the reference runs multiple supervisors side-by-side on disjoint port
ranges (supervisor.py:335,386-391) -- is the batch axis sharded over a
device mesh (coast_tpu.parallel.mesh).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu import obs
from coast_tpu.inject import classify as cls
from coast_tpu.inject.mem import MemoryMap
from coast_tpu.inject.schedule import FaultSchedule, generate
from coast_tpu.passes.dataflow_protection import ProtectedProgram


@dataclasses.dataclass
class CampaignResult:
    """Aggregate + per-run results of one campaign (host-side)."""

    benchmark: str
    strategy: str
    n: int
    counts: Dict[str, int]            # class name -> count
    seconds: float
    codes: np.ndarray                 # int32 [n] class code per run
    errors: np.ndarray                # int32 [n] E per run
    corrected: np.ndarray             # int32 [n] F per run
    steps: np.ndarray                 # int32 [n] T per run
    schedule: FaultSchedule
    seed: int
    # For merged multi-chunk campaigns (run_until_errors, resumable
    # flagship loops): the exact (seed, n, start_num) of every chunk, in
    # order.  The merged ``schedule`` concatenates several seeded
    # streams, so ``seed`` alone cannot regenerate it; replaying these
    # chunks (CampaignRunner.replay_chunks) reproduces ``codes``
    # bit-for-bit.  None for single-seed campaigns, where ``seed`` +
    # ``n`` suffice -- including externally-sliced ones
    # (scripts/campaign_1m.py): slices of one seed stream are NOT
    # replayable as independent chunk records, because generate(n)'s t
    # column depends on the stream length n.
    chunks: Optional[List[Dict[str, int]]] = None
    # Per-stage wall-clock attribution (schedule/pad/dispatch/collect/
    # classify seconds, plus serialize once a logs writer ran), recorded
    # by the runner's Telemetry; {} when telemetry is disabled.
    stages: Dict[str, float] = dataclasses.field(default_factory=dict)
    # First injection number of this campaign within its seed stream
    # (CampaignRunner.run's resume offset); chunk records carry it so
    # replay_chunks can regenerate resumed chunks exactly.
    start_num: int = 0

    @property
    def injections_per_sec(self) -> float:
        return self.n / self.seconds if self.seconds > 0 else float("inf")

    def record_stage(self, name: str, seconds: float) -> None:
        """Accumulate ``seconds`` into one stage bucket (log writers add
        'serialize' here after the campaign object already exists)."""
        self.stages[name] = self.stages.get(name, 0.0) + float(seconds)

    @property
    def due(self) -> int:
        """DUE bucket: aborts (and the stack-overflow / assert-fail
        sub-buckets) also count as timeouts in the reference's summary
        (jsonParser.py:165-172)."""
        return sum(self.counts[k] for k in cls.DUE_CLASSES)

    def summary(self) -> Dict[str, object]:
        out = {
            "benchmark": self.benchmark,
            "strategy": self.strategy,
            "injections": self.n,
            **self.counts,
            "due": self.due,
            "seconds": round(self.seconds, 6),
            "injections_per_sec": round(self.injections_per_sec, 2),
            "seed": self.seed,
            "stages": {k: round(v, 6) for k, v in self.stages.items()},
        }
        if self.chunks is not None:
            out["chunks"] = self.chunks
        return out


class CampaignRunner:
    """Runs seeded bit-flip campaigns against one protected program."""

    def __init__(self, prog: ProtectedProgram,
                 sections: Optional[Sequence[str]] = None,
                 strategy_name: Optional[str] = None,
                 unroll: int = 1,
                 telemetry: Optional[obs.Telemetry] = None,
                 preflight: "bool | str" = False):
        """``unroll`` forwards to ``ProtectedProgram.run``: how many
        early-exit steps each loop iteration executes.  Classification is
        identical at any value (overshoot sub-steps are masked no-ops);
        it trades per-iteration loop overhead against masked work.
        MEASURED on-chip (artifacts/unroll_sweep.json, 2026-08-01): with
        one-hot indexing the knob is noise (48.4-57.7k inj/s across
        {1,2,4,8}) and under the slice lowering it HURTS (5.8k -> 3.7k),
        so the default stays 1; the win the hypothesis predicted belonged
        to the indexing mode, not the unroll.

        ``telemetry`` is the runner's stage recorder (coast_tpu.obs);
        default a fresh enabled one (COAST_TELEMETRY=0 disables).  Every
        campaign records per-stage wall-clock into it and exposes the
        totals as ``CampaignResult.stages``; export the full timeline
        with ``obs.write_trace(runner.telemetry, path)``.

        ``preflight`` runs the replication-integrity linter before any
        schedule is built and raises ``ReplicationLintError`` on an error
        finding -- a multi-hour campaign must refuse to start on a
        program whose redundancy was compiled away (every injection
        would measure a protection that no longer exists).  ``True`` or
        ``"full"`` runs both the static lane-provenance rules and the
        post-XLA survival checks; ``"static"`` skips the survival
        compile for quick iteration."""
        if preflight:
            from coast_tpu.analysis import lint as lint_mod
            lint_mod.check(prog, survival=(preflight != "static"))
        self.prog = prog
        self.telemetry = telemetry if telemetry is not None \
            else obs.Telemetry()
        with self.telemetry.activate():
            self.mmap = MemoryMap(prog, sections)
        self.strategy_name = strategy_name or f"N={prog.cfg.num_clones}"
        self.unroll = max(1, int(unroll))
        out_words = int(np.prod(jax.eval_shape(
            prog.region.output, jax.eval_shape(prog.region.init)).shape))

        def run_one(fault: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
            rec = prog.run(fault, unroll=self.unroll)
            return {
                "code": cls.classify(rec, out_words),
                "errors": rec["errors"],
                "corrected": rec["corrected"],
                "steps": rec["steps"],
            }

        self._run_one = run_one
        self._run_batch = jax.jit(jax.vmap(run_one))

    # -- overridable batching hooks (ShardedCampaignRunner replaces these) --
    def _round_batch(self, batch_size: int) -> int:
        # Floor at one row: call sites clamp to len(schedule) to avoid
        # padding waste, and an empty schedule (cache draws all invalid,
        # zero budget) must step range() by 1, not 0.
        return max(1, batch_size)

    @staticmethod
    def _padded_fault(part: FaultSchedule, batch_size: int):
        """Device fault arrays for one batch, edge-padded to batch_size so
        every batch hits the same compiled program.  Returns (fault, n_valid);
        callers drop or mask the padded tail."""
        n_part = len(part)
        pad = batch_size - n_part if n_part < batch_size else 0
        fault = {k: jnp.asarray(np.pad(v, (0, pad), mode="edge"))
                 for k, v in part.device_arrays().items()}
        return fault, n_part

    def _dispatch(self, fault: Dict[str, jax.Array]):
        """Launch one batch; returns the (async) device result."""
        return self._run_batch(fault)

    @staticmethod
    def _collect(pending) -> Dict[str, np.ndarray]:
        """Block on a dispatched batch and fetch it to the host."""
        return jax.device_get(pending)

    # -- execution ----------------------------------------------------------
    def run_schedule(self, sched: FaultSchedule,
                     batch_size: int = 4096,
                     progress: Optional[
                         Callable[[int, Dict[str, int]], None]] = None,
                     _telemetry_mark: Optional[int] = None
                     ) -> CampaignResult:
        """Run every row of ``sched`` in edge-padded batches.

        ``progress(done, counts_so_far)`` is called after each collected
        batch (for heartbeats; ``counts_so_far`` is the cumulative class
        histogram of the rows fetched so far).  Stage wall-clock (pad /
        dispatch / collect / classify, plus per-batch pad-waste) is
        recorded into ``self.telemetry`` and summed onto the result's
        ``stages``; ``_telemetry_mark`` lets ``run`` extend the stage
        window back over its schedule-generation span.
        """
        # Deliberately no clamp to len(sched) here: every batch is
        # edge-padded to batch_size so all chunks (including a caller's
        # externally-sliced tail, e.g. scripts/campaign_1m.py) share one
        # compiled program.  One-shot small campaigns clamp at the call
        # site (advisor, supervisor) where a single smaller compile beats
        # padding waste.
        batch_size = self._round_batch(batch_size)
        tel = self.telemetry
        mark = tel.mark() if _telemetry_mark is None else _telemetry_mark
        t0 = time.perf_counter()
        outs: List[Dict[str, np.ndarray]] = []
        done = 0
        live_counts = np.zeros(cls.NUM_CLASSES, np.int64)
        live_invalid = 0

        def _grab(pending, n_prev: int, part_t: np.ndarray) -> None:
            """Block on one batch; update progress accounting."""
            nonlocal done, live_invalid
            with tel.span("collect", n=n_prev):
                got = self._collect(pending)
            outs.append({k: v[:n_prev] for k, v in got.items()})
            done += n_prev
            if progress is not None:
                fired = part_t[:n_prev] >= 0
                live_counts[:] += np.bincount(
                    outs[-1]["code"][fired], minlength=cls.NUM_CLASSES)
                live_invalid += int(n_prev - fired.sum())
                counts_so_far = {name: int(live_counts[i])
                                 for i, name in enumerate(cls.CLASS_NAMES)}
                counts_so_far["cache_invalid"] = live_invalid
                progress(done, counts_so_far)

        # Double-buffered: dispatch batch i+1 before collecting batch i, so
        # the host-side fetch (one tunnel round-trip per batch) overlaps the
        # device work -- jax dispatch is async, the device_get is the only
        # blocking point.  The dispatch span therefore times the host-side
        # enqueue; device execution time lands in the matching collect span.
        in_flight: List[Tuple[object, int, np.ndarray]] = []
        for lo in range(0, len(sched), batch_size):
            with tel.span("pad", lo=lo):
                part = sched.slice(lo, min(lo + batch_size, len(sched)))
                fault, n_part = self._padded_fault(part, batch_size)
            if batch_size - n_part:
                tel.count("pad_waste_rows", batch_size - n_part)
            with tel.span("dispatch", n=n_part):
                pending = self._dispatch(fault)
            in_flight.append((pending, n_part, part.t))
            if len(in_flight) > 1:
                _grab(*in_flight.pop(0))
        for flight in in_flight:
            _grab(*flight)
        with tel.span("classify"):
            if outs:
                merged = {k: np.concatenate([o[k] for o in outs])
                          for k in outs[0]}
            else:
                merged = {k: np.zeros(0, np.int32)
                          for k in ("code", "errors", "corrected", "steps")}
            # Cache draws outside the program footprint (t < 0) never fire
            # a flip: a clean run that injected nothing is not a "survived
            # injection", so they get their own bucket instead of inflating
            # success -- the analogue of the reference summary's cacheValids
            # column (jsonParser.py summarizeRuns counts lines whose
            # cacheInfo says the chosen line was not dirty).
            invalid_draw = np.asarray(sched.t) < 0
            binc = np.bincount(merged["code"][~invalid_draw],
                               minlength=cls.NUM_CLASSES)
            counts = {name: int(binc[i])
                      for i, name in enumerate(cls.CLASS_NAMES)}
            counts["cache_invalid"] = int(invalid_draw.sum())
        seconds = time.perf_counter() - t0
        return CampaignResult(
            benchmark=self.prog.region.name,
            strategy=self.strategy_name,
            n=len(sched),
            counts=counts,
            seconds=seconds,
            codes=merged["code"],
            errors=merged["errors"],
            corrected=merged["corrected"],
            steps=merged["steps"],
            schedule=sched,
            seed=sched.seed,
            stages=tel.stage_totals(since=mark),
        )

    def run(self, n: int, seed: int = 0,
            batch_size: int = 4096, start_num: int = 0,
            progress: Optional[
                Callable[[int, Dict[str, int]], None]] = None
            ) -> CampaignResult:
        """``start_num`` resumes a seeded campaign at injection #start_num:
        the schedule stream for (seed, start_num+n) is generated and the
        first start_num rows skipped, so a resumed campaign injects exactly
        the faults the interrupted one would have (the --start-num counter
        of gdbClient.py:401)."""
        tel = self.telemetry
        mark = tel.mark()
        with tel.activate():        # generate() records its schedule span
            sched = generate(self.mmap, start_num + n, seed,
                             self.prog.region.nominal_steps)
        res = self.run_schedule(sched.slice(start_num, start_num + n),
                                batch_size, progress=progress,
                                _telemetry_mark=mark)
        res.start_num = start_num
        return res

    def run_until_errors(self, min_errors: int, seed: int = 0,
                         batch_size: int = 4096,
                         round_to: int = 1000,
                         max_n: int = 1_000_000) -> CampaignResult:
        """The reference's campaign-sizing convention: inject until N SDC
        errors are seen, then round the campaign up to the next ``round_to``
        (supervisor.py:339; threadFunctions.py:534-558).

        The result's ``chunks`` records every chunk's exact (seed, n), and
        ``replay_chunks(result.chunks)`` reproduces the campaign
        bit-for-bit -- the merged schedule spans several seed streams, so
        the master seed alone cannot."""
        results: List[CampaignResult] = []
        total = 0
        errors_seen = 0
        chunk_seed = seed
        while total < max_n:
            res = self.run(batch_size, seed=chunk_seed, batch_size=batch_size)
            results.append(res)
            total += res.n
            errors_seen += res.counts["sdc"]
            chunk_seed += 1
            if errors_seen >= min_errors:
                break
        target = ((total + round_to - 1) // round_to) * round_to
        while total < target and total < max_n:
            res = self.run(min(batch_size, target - total), seed=chunk_seed,
                           batch_size=batch_size)
            results.append(res)
            total += res.n
            chunk_seed += 1
        return _merge_results(results, seed)

    def replay_chunks(self, chunks: Sequence[Dict[str, int]],
                      batch_size: int = 4096) -> CampaignResult:
        """Re-run a recorded multi-chunk campaign exactly.

        ``chunks`` is ``CampaignResult.chunks`` (each entry ``{"seed",
        "n"}`` plus an optional ``"start_num"`` resume offset, honored so
        a resumed-chunk campaign -- e.g. the flagship loop's
        ``run(seed, start_num=done)`` chunks -- replays the exact rows it
        ran); the replay regenerates each chunk's seeded schedule and
        merges in the same order, so ``codes`` matches the original
        bit-for-bit -- the campaign-resume guarantee of gdbClient.py:401
        extended to the error-bounded sizing loop."""
        results = [self.run(int(c["n"]), seed=int(c["seed"]),
                            batch_size=batch_size,
                            start_num=int(c.get("start_num", 0)))
                   for c in chunks]
        return _merge_results(results, int(chunks[0]["seed"]) if chunks
                              else 0)


def _merge_results(parts: List[CampaignResult], seed: int) -> CampaignResult:
    first = parts[0]
    counts = {k: sum(p.counts[k] for p in parts) for k in first.counts}
    stages: Dict[str, float] = {}
    for p in parts:
        for k, v in p.stages.items():
            stages[k] = stages.get(k, 0.0) + v
    sched = FaultSchedule(
        *(np.concatenate([getattr(p.schedule, f) for p in parts])
          for f in ("leaf_id", "lane", "word", "bit", "t", "section_idx")),
        seed=seed)
    return CampaignResult(
        benchmark=first.benchmark,
        strategy=first.strategy,
        n=sum(p.n for p in parts),
        counts=counts,
        seconds=sum(p.seconds for p in parts),
        codes=np.concatenate([p.codes for p in parts]),
        errors=np.concatenate([p.errors for p in parts]),
        corrected=np.concatenate([p.corrected for p in parts]),
        steps=np.concatenate([p.steps for p in parts]),
        schedule=sched,
        seed=seed,
        chunks=[{"seed": p.seed, "n": p.n, "start_num": p.start_num}
                for p in parts],
        stages=stages,
    )
