"""Fault-tolerant dispatch: retries, OOM degradation, collect watchdog.

The reference supervisor's hardest-won machinery is surviving its own
runtime: a wedged QEMU/GDB pair is detected by a watchdog timer,
killed, restarted, and the campaign resumes where it stopped
(supervisor.py:400-509, threadFunctions.py:315-953).  The batched
engine's analogues of those failures are:

  * **transient XLA/device errors** -- tunnel drops, preempted device
    contexts, DATA_LOSS/UNAVAILABLE runtime errors: the batch is simply
    re-dispatched (the schedule is seeded, a re-run is bit-identical);
  * **OOM** (RESOURCE_EXHAUSTED): the batch geometry was too ambitious
    for the live HBM headroom -- retrying the same shape would fail the
    same way, so the runner *degrades*: halve ``batch_size``, recompile
    at the new shape, re-pad, and journal the new geometry;
  * **a wedged collect** -- the blocking ``device_get`` never returns
    (the QEMU-wedge analogue).  A configurable watchdog raises a typed
    :class:`CampaignWedgedError` that the retry loop converts into a
    re-dispatch of the same batch.

:class:`RetryPolicy` is the knob bundle (max attempts, exponential
backoff + jitter, per-error-class handling, collect timeout, degradation
floor).  The campaign loop (:mod:`coast_tpu.inject.campaign`) consults
``classify`` on every failure; everything it cannot class as transient /
oom / wedged is fatal and re-raised unchanged -- a typo'd benchmark or a
real bug must never be retried into silence.  All retries, degradations,
and watchdog fires land as obs counters and in
``CampaignResult.summary()["resilience"]``.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Optional, Tuple

__all__ = ["CampaignWedgedError", "RetryPolicy", "watchdog_collect"]


class CampaignWedgedError(RuntimeError):
    """The blocking collect (``jax.device_get``) exceeded the watchdog
    timeout: the batch is considered wedged, like a QEMU run that stops
    answering GDB.  The retry loop re-dispatches the batch."""


#: Message substrings that identify an out-of-memory failure.  XLA's
#: allocator raises RESOURCE_EXHAUSTED; some backends say "out of
#: memory" or "OOM" in prose.
OOM_PATTERNS: Tuple[str, ...] = (
    "RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM",
    "Attempting to allocate",
)

#: Message substrings that identify a transient runtime failure worth
#: re-dispatching: device preemption, tunnel drops, transport errors.
TRANSIENT_PATTERNS: Tuple[str, ...] = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "DATA_LOSS",
    "INTERNAL", "CANCELLED", "Socket closed", "connection reset",
    "Connection reset", "failed to connect", "preempted",
)

#: Exception class names (any class in the MRO) whose messages are
#: eligible for pattern classification.  Arbitrary Python exceptions
#: (KeyError from a bug, KeyboardInterrupt) stay fatal no matter what
#: their message happens to contain.
_RUNTIME_ERROR_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "RuntimeError", "OSError",
    "ConnectionError", "InternalError", "ResourceExhaustedError",
})


def _is_runtime_error(exc: BaseException) -> bool:
    return any(t.__name__ in _RUNTIME_ERROR_NAMES
               for t in type(exc).__mro__)


@dataclasses.dataclass
class RetryPolicy:
    """Retry/degradation knobs for one campaign runner.

    ``max_attempts`` counts the first try: 3 means one dispatch plus up
    to two retries per batch.  Backoff before retry *k* (1-based) is
    ``min(max_delay, base_delay * 2**(k-1))`` scaled by up to
    ``jitter`` of random spread, so a fleet of resumed campaigns does
    not re-dispatch in lockstep.

    ``collect_timeout`` (seconds) arms the collect watchdog: a blocking
    ``device_get`` that exceeds it raises
    :class:`CampaignWedgedError`, which this policy classes as a
    re-dispatch.  ``None``/0 disables the watchdog (no extra thread).

    ``oom_degrade``: on an OOM the runner halves ``batch_size`` (never
    below ``min_batch_size``), recompiles, re-pads, and journals the
    new geometry instead of retrying a shape that cannot fit.

    ``transient_types`` / ``oom_types`` / ``fatal_types`` extend the
    built-in classification with exact exception types (tests inject
    fakes this way; ``fatal_types`` wins)."""

    max_attempts: int = 3
    base_delay: float = 0.25
    max_delay: float = 30.0
    jitter: float = 0.25
    collect_timeout: Optional[float] = None
    oom_degrade: bool = True
    min_batch_size: int = 1
    transient_types: Tuple[type, ...] = ()
    oom_types: Tuple[type, ...] = ()
    fatal_types: Tuple[type, ...] = ()

    # -- classification ------------------------------------------------------
    def classify(self, exc: BaseException) -> str:
        """'wedged' | 'oom' | 'transient' | 'fatal' for one failure."""
        if isinstance(exc, CampaignWedgedError):
            return "wedged"
        if self.fatal_types and isinstance(exc, self.fatal_types):
            return "fatal"
        if self.oom_types and isinstance(exc, self.oom_types):
            return "oom"
        if self.transient_types and isinstance(exc, self.transient_types):
            return "transient"
        if _is_runtime_error(exc):
            msg = str(exc)
            if any(p in msg for p in OOM_PATTERNS):
                return "oom"
            if any(p in msg for p in TRANSIENT_PATTERNS):
                return "transient"
        return "fatal"

    def backoff(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): exponential with
        jitter."""
        base = min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))
        if self.jitter:
            base *= 1.0 + self.jitter * random.random()
        return base

    def degraded_batch(self, batch_size: int) -> Optional[int]:
        """The next batch size after an OOM, or None when degradation is
        off / already at the floor (the OOM is then fatal)."""
        if not self.oom_degrade:
            return None
        new = max(self.min_batch_size, batch_size // 2)
        return new if new < batch_size else None


def watchdog_collect(fn, timeout: Optional[float]):
    """Run the blocking collect ``fn()`` under a watchdog.

    Without a timeout this is a plain call (no thread).  With one, the
    collect runs in a daemon thread; if it has not returned within
    ``timeout`` seconds a :class:`CampaignWedgedError` is raised and the
    wedged thread is abandoned (it holds no locks -- ``device_get``
    releases the GIL -- and a daemon thread cannot keep the process
    alive, exactly like the reference abandoning a wedged QEMU)."""
    if not timeout or timeout <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def _target():
        try:
            box["value"] = fn()
        except BaseException as e:          # noqa: BLE001 - re-raised below
            box["error"] = e
        finally:
            done.set()

    th = threading.Thread(target=_target, daemon=True,
                          name="coast-collect-watchdog")
    th.start()
    if not done.wait(timeout):
        # Visible in the trace and on every live-metrics surface: a
        # watchdog fire is exactly the event an operator watching a
        # long campaign needs to see the moment it happens.
        from coast_tpu.obs import spans as _spans
        _spans.current().count("watchdog_fired", timeout_s=timeout)
        # Forensics BEFORE abandoning the wedged thread: the bundle's
        # all-thread stacks still contain the hung collect, which is
        # exactly the evidence a one-line diagnosis never carried.
        from coast_tpu.obs import flightrec as _flightrec
        _flightrec.record("watchdog_fired", timeout_s=timeout)
        _flightrec.current().dump("watchdog_wedge",
                                  extra={"timeout_s": timeout})
        raise CampaignWedgedError(
            f"collect did not return within {timeout}s; batch presumed "
            "wedged (device_get hung) -- re-dispatching")
    if "error" in box:
        raise box["error"]
    return box["value"]
