"""train_mlp: a protected training step as a multi-phase region.

A 2-layer f32 MLP (6 -> 8 -> 4, full-batch of 8 samples) trained for a
fixed number of iterations; each training iteration is three protected
micro-steps -- the region's *phases*:

    phase 0 (fwd):    loss <- MSE(forward(params, x), y); the loss
                      monitor compares it against the fault-free
                      (golden) loss trajectory.
    phase 1 (bwd):    grads <- jax.grad(loss_fn)(params, x, y), traced
                      INSIDE the replicated lane -- under full TMR every
                      replica differentiates its own parameter copy;
                      under selective xMR the ``grad_step`` sub-function
                      is ``-skipLibCalls``-scoped and runs once.
    phase 2 (commit): optimizer update (SGD+momentum or Adam) applied to
                      the parameter and optimizer-state leaves; the
                      region's ``store_slice`` hints gate the
                      param/opt-state votes to exactly this phase, so
                      the protected build votes the APPLIED UPDATE once
                      per training iteration, not every micro-step.

Leaf kinds: parameters are ``KIND_PARAM``, optimizer state (momentum
buffers / Adam moments) ``KIND_OPT_STATE`` -- both replicated and voted
at the commit under their own sync classes (the lint re-derives the
expectation independently).  Training data is ``KIND_RO``; the live loss
and gradients are ``KIND_REG`` registers; iteration/phase counters and
the loss-trajectory monitor are ``KIND_CTRL``.

**Golden trajectory.**  ``make_train_region`` runs the training loop
fault-free at build time (the same stepped program, single lane) and
bakes the final parameters plus the per-iteration loss trajectory into
read-only leaves.  ``check()`` compares final weights bit-for-bit
against the golden weights (any surviving perturbation is an SDC);
``train_probe`` reads the loss monitor to split that SDC into transient
(self-healed: the loss re-converged to the golden trajectory for the
final ``HEAL_WINDOW`` iterations) vs persistent (still diverged at the
end).  As with the mm benchmarks' golden matrix, the golden leaves are
themselves injectable (.rodata is a real target): a flip there
perturbs the *reference*, not the computation.  A ``g_loss`` flip
disturbs the monitor and rides the normal probe split; a golden-weight
flip leaves the monitor untouched (``dev == 0``) so the run reports
``errors > 0`` with probe 0 and classifies ``train_self_heal`` --
i.e. unlike mm (where golden flips land in the counted ``sdc``
bucket), the train taxonomy's fidelity envelope keeps reference
corruption out of the error rate, attributed to the golden section in
the per-kind table.

The probe's verdict is only as fresh as the last fwd monitor sample: a
fault landing in the FINAL iteration's bwd/commit micro-steps (2 of
the 3*ITERS steps) corrupts the saved weights after the loss was last
evaluated, so re-convergence was never observed yet the run classifies
``train_self_heal`` (``dev == 0``).  This blind window is a documented
residual of post-hoc trajectory monitoring, not a healing claim; see
docs/training.md.

The monitor tolerance is relative (``TOL_REL`` of the golden loss, plus
``TOL_ABS`` floor): a clean run's loss equals the golden bitwise, a
low-mantissa weight flip perturbs it within tolerance (self-heal), a
sign/exponent flip blows past it (persistent unless the optimizer pulls
the trajectory back within the heal window).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.models.common import lcg_words
from coast_tpu.ir.region import (KIND_CTRL, KIND_OPT_STATE, KIND_PARAM,
                                 KIND_REG, KIND_RO, LeafSpec, Region)

# Model / data geometry (kept tiny: a campaign run is a whole training
# trajectory, ITERS * PHASES micro-steps of it).
B, IN, HID, OUT = 8, 6, 8, 4
ITERS = 12
PHASES = 3
FWD, BWD, COMMIT = 0, 1, 2
SEED = 7

# Optimizer hyper-parameters.
LR = 0.05
MOMENTUM = 0.9
ADAM_B1, ADAM_B2, ADAM_EPS = 0.9, 0.999, 1e-8

# Adam's bias-correction powers B^(it+1), precomputed host-side as an
# f32 table and indexed by iteration instead of calling jnp.power on
# device: pow is the one APPROXIMATE transcendental in the update chain
# (sqrt/divide/mul/add round exactly), and XLA's vectorized pow may
# differ by an ulp between SIMD widths / lane counts.  A table lookup is
# bit-identical in every build shape, which removes one whole class of
# golden-check instability (see _golden_trajectory on the one that
# remains).
_ADAM_B1_POW = np.cumprod(np.full(64, ADAM_B1, np.float64)) \
    .astype(np.float32)
_ADAM_B2_POW = np.cumprod(np.full(64, ADAM_B2, np.float64)) \
    .astype(np.float32)

# Loss-trajectory monitor: "self-healed" means the loss stayed within
# TOL of the golden trajectory for the final HEAL_WINDOW iterations.
TOL_REL = 0.10
TOL_ABS = 1e-3
HEAL_WINDOW = 3

_PARAM_NAMES = ("w1", "b1", "w2", "b2")
_PARAM_SHAPES = {"w1": (IN, HID), "b1": (HID,),
                 "w2": (HID, OUT), "b2": (OUT,)}


def _f32_fill(seed: int, shape, scale: float) -> jnp.ndarray:
    """Deterministic f32 values in [-scale, scale) from the shared LCG."""
    n = int(np.prod(shape))
    raw = lcg_words(seed, n).astype(np.float32)     # 15-bit ints
    vals = (raw / 16384.0 - 1.0) * scale
    return jnp.asarray(vals.reshape(shape), jnp.float32)


def _forward_loss(w1, b1, w2, b2, x, y):
    """MSE of the 2-layer relu MLP -- the one loss definition shared by
    the fwd phase, the bwd phase's jax.grad, and the golden run."""
    h = jnp.maximum(x @ w1 + b1, 0.0)
    pred = h @ w2 + b2
    d = pred - y
    return jnp.mean(d * d)


def _grad_step(w1, b1, w2, b2, x, y):
    """Backward pass: gradients of the loss w.r.t. every parameter.
    A named region sub-function so the protection engine can scope it --
    replicated (full TMR differentiates per lane) or ``-skipLibCalls``
    (selective xMR computes it once, an accepted single-lane call)."""
    return jax.grad(_forward_loss, argnums=(0, 1, 2, 3))(
        w1, b1, w2, b2, x, y)


def _opt_leaf_names(optimizer: str):
    if optimizer == "sgd":
        return tuple(f"m_{p}" for p in _PARAM_NAMES)
    return tuple(f"m_{p}" for p in _PARAM_NAMES) + \
        tuple(f"v_{p}" for p in _PARAM_NAMES)


def _build(optimizer: str, golden):
    """Construct the region; ``golden`` is None (proto build used only to
    capture the fault-free trajectory) or the ``{final params, losses}``
    dict to bake into the golden leaves."""
    if optimizer not in ("sgd", "adam"):
        raise ValueError(f"unknown optimizer {optimizer!r} "
                         "(one of: sgd, adam)")
    x = _f32_fill(SEED, (B, IN), 1.0)
    y = _f32_fill(SEED + 1, (B, OUT), 1.0)
    init_params = {
        name: _f32_fill(SEED + 2 + i, shape,
                        0.5 / float(np.sqrt(shape[0] if len(shape) > 1
                                            else HID)))
        for i, (name, shape) in enumerate(_PARAM_SHAPES.items())
    }
    g_params = {name: (jnp.asarray(golden["params"][name])
                       if golden else jnp.zeros_like(init_params[name]))
                for name in _PARAM_NAMES}
    g_loss = (jnp.asarray(golden["losses"], jnp.float32)
              if golden else jnp.zeros((ITERS,), jnp.float32))

    adam = optimizer == "adam"

    def init():
        state = {
            **init_params,
            "x": x, "y": y,
            **{f"g_{n}": g_params[n] for n in _PARAM_NAMES},
            "g_loss": g_loss,
            **{f"gr_{n}": jnp.zeros(_PARAM_SHAPES[n], jnp.float32)
               for n in _PARAM_NAMES},
            **{f"m_{n}": jnp.zeros(_PARAM_SHAPES[n], jnp.float32)
               for n in _PARAM_NAMES},
            "loss": jnp.float32(0),
            "it": jnp.int32(0),
            "phase": jnp.int32(0),
            "heal": jnp.int32(0),
            "dev": jnp.int32(0),
        }
        if adam:
            state.update({f"v_{n}": jnp.zeros(_PARAM_SHAPES[n], jnp.float32)
                          for n in _PARAM_NAMES})
        return state

    def step(state, t, fns):
        phase, it = state["phase"], state["it"]
        params = [state[n] for n in _PARAM_NAMES]

        # -- phase 0: forward + loss-trajectory monitor ------------------
        cur_loss = _forward_loss(*params, state["x"], state["y"])
        in_fwd = phase == FWD
        loss = jnp.where(in_fwd, cur_loss, state["loss"])
        gl = jnp.take(state["g_loss"], jnp.clip(it, 0, ITERS - 1))
        within = jnp.abs(loss - gl) <= TOL_ABS + TOL_REL * jnp.abs(gl)
        heal = jnp.where(in_fwd,
                         jnp.where(within, state["heal"] + 1, 0),
                         state["heal"])
        dev = jnp.where(in_fwd,
                        jnp.maximum(state["dev"],
                                    jnp.logical_not(within)
                                    .astype(jnp.int32)),
                        state["dev"])

        # -- phase 1: backward (jax.grad inside the lane) ----------------
        g = fns.grad_step(*params, state["x"], state["y"])
        in_bwd = phase == BWD
        grads = {n: jnp.where(in_bwd, gv, state[f"gr_{n}"])
                 for n, gv in zip(_PARAM_NAMES, g)}

        # -- phase 2: optimizer commit -----------------------------------
        in_commit = phase == COMMIT
        out = {}
        for n in _PARAM_NAMES:
            p, gr, m = state[n], grads[n], state[f"m_{n}"]
            if adam:
                v = state[f"v_{n}"]
                idx = jnp.clip(it, 0, _ADAM_B1_POW.shape[0] - 1)
                b1p = jnp.take(jnp.asarray(_ADAM_B1_POW), idx)
                b2p = jnp.take(jnp.asarray(_ADAM_B2_POW), idx)
                m_new = ADAM_B1 * m + (1.0 - ADAM_B1) * gr
                v_new = ADAM_B2 * v + (1.0 - ADAM_B2) * gr * gr
                mhat = m_new / (1.0 - b1p)
                vhat = v_new / (1.0 - b2p)
                p_new = p - LR * mhat / (jnp.sqrt(vhat) + ADAM_EPS)
                out[f"v_{n}"] = jnp.where(in_commit, v_new, v)
            else:
                m_new = MOMENTUM * m + gr
                p_new = p - LR * m_new
            out[n] = jnp.where(in_commit, p_new, p)
            out[f"m_{n}"] = jnp.where(in_commit, m_new, m)

        return {
            **state,
            **out,
            **{f"gr_{n}": grads[n] for n in _PARAM_NAMES},
            "loss": loss,
            "heal": heal,
            "dev": dev,
            "it": jnp.where(in_commit, it + 1, it),
            "phase": jnp.where(phase >= COMMIT, 0, phase + 1),
        }

    def done(state):
        return state["it"] >= ITERS

    def check(state):
        """Bit-exact final-weight compare against the golden weights
        (uint32 views, so a NaN-poisoned weight still counts)."""
        err = jnp.int32(0)
        for n in _PARAM_NAMES:
            a = jax.lax.bitcast_convert_type(state[n], jnp.uint32)
            b = jax.lax.bitcast_convert_type(state[f"g_{n}"], jnp.uint32)
            err = err + jnp.sum(a != b).astype(jnp.int32)
        return err

    def output(state):
        return jnp.concatenate([
            jax.lax.bitcast_convert_type(state[n], jnp.uint32).reshape(-1)
            for n in _PARAM_NAMES])

    def train_probe(state):
        """0 = loss trajectory never left tolerance; 1 = deviated but
        back within tolerance for the final HEAL_WINDOW iterations
        (self-healed); 2 = still diverged at the end (persistent)."""
        healed = state["heal"] >= HEAL_WINDOW
        return jnp.where(state["dev"] == 0, jnp.int32(0),
                         jnp.where(healed, jnp.int32(1), jnp.int32(2)))

    opt_names = _opt_leaf_names(optimizer)
    spec = {
        **{n: LeafSpec(KIND_PARAM) for n in _PARAM_NAMES},
        **{n: LeafSpec(KIND_OPT_STATE) for n in opt_names},
        "x": LeafSpec(KIND_RO), "y": LeafSpec(KIND_RO),
        **{f"g_{n}": LeafSpec(KIND_RO) for n in _PARAM_NAMES},
        "g_loss": LeafSpec(KIND_RO),
        **{f"gr_{n}": LeafSpec(KIND_REG) for n in _PARAM_NAMES},
        "loss": LeafSpec(KIND_REG),
        "it": LeafSpec(KIND_CTRL),
        "phase": LeafSpec(KIND_CTRL),
        "heal": LeafSpec(KIND_CTRL),
        "dev": LeafSpec(KIND_CTRL),
    }

    # Selective votes: gate every param/opt-state commit vote to the
    # optimizer phase -- one whole-leaf vote per training iteration at
    # the weight-update commit, zero voter work in the fwd/bwd phases.
    def _commit_hint(shape):
        starts = (0,) * len(shape)
        def hint(view, t, _starts=starts, _sizes=tuple(shape)):
            return _starts, _sizes, view["phase"] == COMMIT
        return hint

    store_slice = {n: _commit_hint(_PARAM_SHAPES[n]) for n in _PARAM_NAMES}
    store_slice.update({n: _commit_hint(_PARAM_SHAPES[n[2:]])
                        for n in opt_names})

    shapes = jax.eval_shape(init)
    total_words = sum(int(np.prod(s.shape)) for s in shapes.values())
    opt_words = sum(int(np.prod(shapes[n].shape)) for n in opt_names)
    param_words = sum(int(np.prod(_PARAM_SHAPES[n])) for n in _PARAM_NAMES)

    # Analytic FLOPs per training iteration (MACs x 2): the per-strategy
    # overhead column of the MWTF report.  Every micro-step computes all
    # three phases behind jnp.where selects, but that wash is
    # strategy-independent and cancels in the overhead ratio.
    fwd_flops = 2.0 * B * (IN * HID + HID * OUT)
    bwd_flops = 2.0 * fwd_flops
    update_flops = float((5 if adam else 3) * param_words)

    name = "train_mlp" if optimizer == "sgd" else "train_mlp_adam"
    return Region(
        name=name,
        init=init,
        step=step,
        done=done,
        check=check,
        output=output,
        nominal_steps=PHASES * ITERS,
        max_steps=2 * PHASES * ITERS,
        spec=spec,
        default_xmr=True,
        functions={"grad_step": _grad_step},
        train_probe=train_probe,
        meta={
            "oracle": "Number of errors: 0",
            "store_slice": store_slice,
            "state_bytes": 4 * total_words,
            "opt_state_bytes": 4 * opt_words,
            "param_bytes": 4 * param_words,
            "train": {
                "optimizer": optimizer,
                "iters": ITERS,
                "phases": PHASES,
                "heal_window": HEAL_WINDOW,
                "tol_rel": TOL_REL,
                "tol_abs": TOL_ABS,
                "selective_skip": ("grad_step",),
                "flops": {"fwd": fwd_flops, "bwd": bwd_flops,
                          "update": update_flops},
                "golden_final_loss": (float(golden["losses"][-1])
                                      if golden else None),
                "golden_first_loss": (float(golden["losses"][0])
                                      if golden else None),
            },
        },
    )


@functools.lru_cache(maxsize=4)
def _golden_trajectory(optimizer: str):
    """Fault-free training trajectory: per-iteration losses + final
    params -- the FuzzyFlow differential baseline.  Cached per
    optimizer: every make_train_region() call shares one compile.

    The final params are captured through the ENGINE's own compiled
    fault-free run of the proto region (``unprotected(proto).run``):
    the bit-exact ``check()`` pin only holds if the golden weights come
    out of the same XLA program shape the campaigns execute -- a plain
    ``lax.scan`` over ``bound_step`` fuses Adam's rsqrt/divide chain
    differently and drifts by an ulp (SGD's multiply-add chain happens
    to agree; Adam's does not).  The per-iteration LOSS trajectory still
    comes from the scan: the monitor compares losses under a relative
    tolerance, which absorbs last-ulp capture skew.

    Known residual (documented in docs/training.md, pinned in
    tests/test_train.py): XLA compiles the Adam chain's float rounding
    context-dependently, and on XLA:CPU the 2-lane DWC build of
    ``train_mlp_adam`` lands ulps away from every other build (1-lane
    capture, 3-lane TMR/selective all agree; fori/per-step compiles of
    the DWC step itself also agree -- only its early-exit while-body
    differs).  No graph-level construction pins it (optimization
    barriers around the step, the commit chain, and grad_step, and
    fixed-order explicit contractions were all tried; the decision is
    made below the jaxpr, in instruction selection).  The taxonomy
    absorbs it honestly: a clean DWC-adam run classifies
    TRAIN_SELF_HEAL (ulp-different weights, converged loss), never
    train_sdc/DUE, and DWC's detection latch is unaffected."""
    proto = _build(optimizer, None)
    step = proto.bound_step()

    def body(carry, t):
        state, halted = carry
        new = step(state, t)
        new = jax.tree.map(lambda o, n: jnp.where(halted, o, n), state, new)
        halted = jnp.logical_or(halted, proto.done(new))
        return (new, halted), new["loss"]

    def run(state):
        (final, _), losses = jax.lax.scan(
            body, (state, jnp.bool_(False)),
            jnp.arange(proto.nominal_steps, dtype=jnp.int32))
        return losses

    losses = np.asarray(jax.jit(run)(proto.init()))

    from coast_tpu.ops.bitflip import noop_fault
    from coast_tpu.passes.strategies import unprotected
    rec = unprotected(proto).run(noop_fault(), return_state=True)
    if not bool(rec["done"]):
        raise AssertionError(
            f"golden {optimizer} proto run did not halt in "
            f"{proto.nominal_steps} steps")
    final = rec["final_state"]
    # The loss leaf is written at each iteration's fwd micro-step
    # (t = PHASES*k) and held through the commit: that value IS the
    # golden loss of iteration k.
    per_iter = losses[::PHASES][:ITERS].copy()
    if not per_iter[-1] < per_iter[0]:
        raise AssertionError(
            f"golden {optimizer} training did not reduce the loss "
            f"({per_iter[0]} -> {per_iter[-1]}); the self-heal semantics "
            "need a converging trajectory")
    return {
        "params": {n: np.asarray(final[n]) for n in _PARAM_NAMES},
        "losses": per_iter,
    }


def make_train_region(optimizer: str = "sgd") -> Region:
    """The registered builder: ``train_mlp`` (SGD+momentum) /
    ``train_mlp_adam``."""
    return _build(optimizer, _golden_trajectory(optimizer))


def make_region() -> Region:
    return make_train_region("sgd")


def make_region_adam() -> Region:
    return make_train_region("adam")


def selective_xmr(region: Region, **overrides):
    """Selective xMR: TMR over the persistent training state with the
    backward dataflow computed once.

    3 replica lanes carry the parameters and optimizer state; the
    ``grad_step`` sub-function is ``-skipLibCalls``-scoped (single call
    on lane 0's arguments -- the linted, allowlisted SPOF), and the
    region's store_slice hints already gate the param/opt-state votes to
    the update commit.  Coverage intuition: every fault site in the
    weights or moments (the dominant share of the injectable bits) is
    repaired at the next commit vote exactly as under full TMR; what is
    given up is redundancy over one transient gradient computation,
    whose corruption the training dynamics usually absorb (the
    self-heal class).  FLOPs: ~1 backward instead of 3
    (:func:`flops_overhead`)."""
    from coast_tpu.passes.strategies import TMR
    skip = tuple(region.meta["train"]["selective_skip"])
    return TMR(region, skip_lib_calls=skip, **overrides)


def flops_overhead(region: Region, num_clones: int,
                   selective: bool = False) -> float:
    """Per-training-iteration FLOPs of a strategy relative to the
    unprotected step: lanes x (fwd + update) plus bwd computed either
    per lane (full replication) or once (selective xMR's single-lane
    ``grad_step``)."""
    f = region.meta["train"]["flops"]
    base = f["fwd"] + f["bwd"] + f["update"]
    lanes = max(1, int(num_clones))
    bwd_lanes = 1 if selective else lanes
    return (lanes * (f["fwd"] + f["update"]) + bwd_lanes * f["bwd"]) / base
