"""coast_tpu.train: a fault-injectable ML-training workload.

The workload class the TPU backend uniquely enables (ROADMAP item 5b):
fault injection into a *training step*.  The reference's QEMU+GDB loop
could never afford this scenario -- one SGD step under gdb costs seconds,
a statistically meaningful campaign over a training run costs days --
while here an entire seeded campaign over thousands of perturbed
training trajectories batches as one XLA program.

A protected training step is a first-class multi-phase
:class:`~coast_tpu.ir.region.Region` (:mod:`coast_tpu.train.mlp`): a
small MLP whose forward, backward (``jax.grad`` traced inside the
replicated lane), and optimizer phases run as distinct protected
micro-steps, with the parameters and optimizer state declared as the
new ``KIND_PARAM`` / ``KIND_OPT_STATE`` leaf kinds.  Full-program
ML-to-TPU compilation (arXiv:1810.09868) is the precedent for treating
fwd/bwd/optimizer as ONE compiled protected region rather than three
framework calls.

**Selective xMR.**  Replicating the whole training dataflow (full TMR)
triples the FLOPs; most of the *fault sites*, though, live in the
persistent HBM state -- weights and optimizer moments -- not in the
transient backward dataflow.  :func:`selective_xmr` therefore replicates
the persistent state and votes it at the weight-update commit (the
region's ``store_slice`` hints gate the param/opt-state votes to the
optimizer phase), while the gradient computation runs ONCE via the
``-skipLibCalls`` single-lane scope (an accepted, linted SPOF): a flip
in any weight or moment replica is repaired at the next commit, and the
unreplicated gradient's exposure is one transient update -- which the
training dynamics themselves absorb (the self-heal outcome class).
The recorded campaign (``artifacts/train_campaign.json``) measures how
much of full TMR's coverage this recovers at a fraction of the FLOPs.

**Outcome semantics.**  Training refines what "silent corruption"
means: a completed run whose final weights differ bit-for-bit from the
fault-free run may still have *converged* -- the loss trajectory
returned to the golden trajectory within the heal window.  The region's
``train_probe`` reports that verdict and the classifier splits the SDC
bucket into ``TRAIN_SELF_HEAL`` (transient loss perturbation) vs
``TRAIN_SDC`` (persistent weight SDC), carried end-to-end through
classify -> logs -> json_parser -> mwtf_report.  FuzzyFlow
(arXiv:2306.16178) supplies the validation idiom: the protected step's
fault-free trajectory is pinned bit-identical to the unprotected
baseline (the differential artifact), so every divergence a campaign
observes is attributable to the injected fault, never to the transform.
"""

from __future__ import annotations

from coast_tpu.train.mlp import (HEAL_WINDOW, ITERS, PHASES, flops_overhead,
                                 make_train_region, selective_xmr)

__all__ = ["make_train_region", "selective_xmr", "flops_overhead",
           "ITERS", "PHASES", "HEAL_WINDOW"]
