"""``python -m coast_tpu ci`` -- the protection-regression CI CLI.

    # record ground truth once (and commit the artifact)
    python -m coast_tpu ci baseline --baseline artifacts/ci_baseline.json

    # per-commit gate: exit 0 pass, 1 drift, 2 infra failure
    python -m coast_tpu ci check --baseline artifacts/ci_baseline.json

    # check, then overwrite the baseline on pass
    python -m coast_tpu ci refresh --baseline artifacts/ci_baseline.json

See docs/ci.md for the artifact format, verdict semantics, and exit
codes.  ``python -m coast_tpu.ci`` works too; the package dispatcher
(coast_tpu/__main__.py) routes the ``ci`` verb here.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from coast_tpu.ci import engine
from coast_tpu.ci.baseline import BaselineError, load_baseline, \
    write_baseline
from coast_tpu.inject.spec import CampaignSpec


def _parse_target(text: str, default_seed: int) -> CampaignSpec:
    """``benchmark|opt_passes|section|seed`` (later fields optional,
    ``s``-prefixed seed tolerated): the target_id grammar.  A target
    without its own seed field takes the CLI-wide ``--seed``."""
    parts = text.split("|")
    if not parts or not parts[0]:
        raise ValueError(f"bad --target {text!r}: want "
                         "benchmark|opt_passes[|section[|seed]]")
    seed = int(default_seed)
    if len(parts) > 3 and parts[3]:
        seed = int(parts[3].lstrip("s"))
    return CampaignSpec(
        benchmark=parts[0],
        n=1,                              # resized by -t below
        opt_passes=parts[1] if len(parts) > 1 and parts[1] else "-TMR",
        section=parts[2] if len(parts) > 2 and parts[2] else "memory",
        seed=seed, equiv=True)


def parse_command_line(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        prog="python -m coast_tpu ci",
        description="Protection-regression CI: diff section dataflow "
                    "fingerprints against a committed baseline, delta-"
                    "re-inject only what changed through the fleet, and "
                    "gate on classification-distribution drift "
                    "(per-class Wilson intervals + new/vanished "
                    "classes).  Exit codes: 0 pass, 1 drift, 2 infra")
    sub = parser.add_subparsers(dest="cmd", required=True)

    def _common(p, with_check_knobs: bool) -> None:
        p.add_argument("--baseline", default="artifacts/ci_baseline.json",
                       metavar="PATH", help="baseline artifact path")
        p.add_argument("--workers", type=int, default=1, metavar="N",
                       help="fleet workers (1 = in-process; more spawn "
                       "`python -m coast_tpu.fleet worker` processes)")
        p.add_argument("--queue", default=None, metavar="DIR",
                       help="working directory for the fleet queue and "
                       "materialized journals (default: a temp dir; "
                       "pass one to inspect journals afterwards)")
        if with_check_knobs:
            p.add_argument("--stop-when", default=engine.DEFAULT_STOP_WHEN,
                           metavar="SPEC",
                           help="convergence bound applied to EACH "
                           "re-injected section (StopWhen grammar; "
                           "'none' disables; default "
                           f"{engine.DEFAULT_STOP_WHEN!r})")
            p.add_argument("--no-isolation-gate", action="store_true",
                           help="skip the static lane-isolation "
                           "noninterference pre-gate that runs over "
                           "every target's current build before any "
                           "delta campaign is enqueued (a refuted "
                           "proof is an immediate drift verdict with "
                           "counterexample paths)")
            p.add_argument("--no-static-budget", action="store_true",
                           help="do not allocate the per-section "
                           "convergence budget by the static "
                           "vulnerability map (sdc-possible sections "
                           "first, relaxed min floor on statically-"
                           "proven sections)")
            p.add_argument("--z", type=float, default=1.96,
                           help="Wilson quantile for the drift verdict")
            p.add_argument("--report-json", default=None, metavar="PATH",
                           help="write the machine-readable per-target "
                           "report here")

    p = sub.add_parser("baseline", help="run the target campaigns in "
                       "full and write the baseline artifact")
    _common(p, with_check_knobs=False)
    p.add_argument("--target", action="append", default=None,
                   metavar="SPEC",
                   help="benchmark|opt_passes[|section[|seed]]; "
                   "repeatable.  Default: mm + crc16 x DWC/TMR")
    p.add_argument("-t", type=int, default=2048, metavar="N",
                   help="effective injections per target")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--batch-size", type=int, default=512)

    p = sub.add_parser("check", help="delta-check the current tree "
                       "against the baseline (exit 0/1/2)")
    _common(p, with_check_knobs=True)
    p.add_argument("--out", default=None, metavar="PATH",
                   help="write the refreshed baseline here ON PASS "
                   "(default: <baseline>.refreshed.json)")

    p = sub.add_parser("refresh", help="check, then overwrite the "
                       "baseline with the refreshed artifact on pass")
    _common(p, with_check_knobs=True)

    return parser.parse_args(argv)


def cmd_baseline(args) -> int:
    import dataclasses
    if args.target:
        try:
            specs = [dataclasses.replace(
                         _parse_target(t, args.seed), n=args.t,
                         batch_size=args.batch_size).validate()
                     for t in args.target]
        except ValueError as e:
            print(f"Error, {e}", file=sys.stderr)
            return engine.EXIT_INFRA
    else:
        specs = engine.default_specs(n=args.t, seed=args.seed,
                                     batch_size=args.batch_size)
    doc = engine.build_baseline(
        specs, queue_dir=args.queue, workers=args.workers,
        log=lambda s: print(s, file=sys.stderr, flush=True))
    write_baseline(doc, args.baseline)
    print(f"wrote {args.baseline} ({len(doc['targets'])} targets)")
    return engine.EXIT_PASS


def _run_check(args):
    doc = load_baseline(args.baseline)
    stop = args.stop_when
    if stop in ("none", ""):
        stop = None
    report = engine.check_baseline(
        doc, workdir=args.queue, stop_when=stop,
        workers=args.workers, z=args.z,
        static_budget=not args.no_static_budget,
        isolation_gate=not args.no_isolation_gate,
        log=lambda s: print(s, file=sys.stderr, flush=True))
    print(report.format())
    if args.report_json:
        with open(args.report_json, "w") as fh:
            json.dump(report.to_json(), fh, indent=1, sort_keys=True)
        print(f"# wrote {args.report_json}", file=sys.stderr)
    return report


def cmd_check(args) -> int:
    report = _run_check(args)
    if report.exit_code == engine.EXIT_PASS:
        out = args.out or f"{args.baseline}.refreshed.json"
        write_baseline(report.refreshed, out)
        print(f"wrote refreshed baseline {out}")
    return report.exit_code


def cmd_refresh(args) -> int:
    report = _run_check(args)
    if report.exit_code == engine.EXIT_PASS:
        write_baseline(report.refreshed, args.baseline)
        print(f"refreshed {args.baseline}")
    else:
        print("baseline NOT refreshed (check did not pass)",
              file=sys.stderr)
    return report.exit_code


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_command_line(argv)
    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    try:
        return {"baseline": cmd_baseline, "check": cmd_check,
                "refresh": cmd_refresh}[args.cmd](args)
    except (BaselineError, engine.CiInfraError) as e:
        print(f"Error, {e}", file=sys.stderr)
        return engine.EXIT_INFRA


if __name__ == "__main__":
    sys.exit(main())
