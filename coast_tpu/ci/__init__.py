"""Protection-regression CI: campaign analysis cheap enough to gate merges.

Every ingredient existed before this package; none had been composed
into one verb.  Equivalence classes cut physical injections ~10-26x
(:mod:`coast_tpu.analysis.equiv`), delta campaigns re-inject only the
sections whose dataflow fingerprint changed (``run_delta``),
``--stop-when`` bounds each campaign by Wilson-CI width
(:mod:`coast_tpu.obs.convergence`), and the fleet runs campaigns in
parallel workers behind a persistent compile cache
(:mod:`coast_tpu.fleet`).  Composed, they make FastFlip's
(arXiv:2403.13989) end-game practical: a per-commit fault-injection
verdict in minutes, not campaign-hours, with FuzzyFlow-style
(arXiv:2306.16178) differential discipline -- the reduced delta run is
only trusted because its splice base records exhaustive-equivalent
ground truth.

The pipeline (``python -m coast_tpu ci``, see docs/ci.md):

  1. **baseline** -- run the target campaigns once (equivalence-reduced,
     journaled) and commit the artifact: per-target counts, per-section
     dataflow fingerprints, and the journal records a later delta can
     splice from.
  2. **check** -- rebuild each target from the CURRENT tree, diff its
     section fingerprints against the baseline, enqueue one DELTA item
     per target on a fleet queue (re-injecting only changed sections,
     each convergence-bounded per section), drain it through fleet
     workers sharing the compile cache, and compare the resulting
     classification distribution against the baseline's: per-class
     Wilson intervals must overlap, and a new or vanished outcome class
     is drift by definition.  Exit codes are typed: 0 pass, 1 drift,
     2 infrastructure failure.
  3. **refresh** -- check, then overwrite the baseline with the
     refreshed artifact when (and only when) the check passed.

Identity throughout is the one shared
:class:`~coast_tpu.inject.spec.CampaignSpec` vocabulary: the baseline
stores specs in their queue-item encoding, the queue items ARE that
encoding, and the journals the deltas splice from validate against the
same fields.
"""

from __future__ import annotations

from coast_tpu.ci.baseline import (BASELINE_FORMAT, BASELINE_VERSION,
                                   load_baseline, materialize_journal,
                                   target_id, write_baseline)
from coast_tpu.ci.engine import (EXIT_DRIFT, EXIT_INFRA, EXIT_PASS,
                                 CiInfraError, CiReport, TargetReport,
                                 build_baseline, check_baseline,
                                 default_specs)

__all__ = [
    "BASELINE_FORMAT", "BASELINE_VERSION", "load_baseline",
    "write_baseline", "materialize_journal", "target_id",
    "CiInfraError", "CiReport", "TargetReport", "build_baseline",
    "check_baseline", "default_specs",
    "EXIT_PASS", "EXIT_DRIFT", "EXIT_INFRA",
]
