"""The CI engine: baseline building, delta checking, drift verdicts.

Both verbs run their campaigns **through the fleet** -- one
:class:`~coast_tpu.fleet.queue.CampaignQueue` item per target, drained
by stock :class:`~coast_tpu.fleet.worker.Worker` processes behind the
shared :class:`~coast_tpu.fleet.compile_cache.CompileCache` -- so the
CI inherits every fleet property for free: crash-safe journals, lease
requeue, idempotent completion, and one compile per config no matter
how many targets share it.

The check's work unit is a DELTA item: the worker rebuilds the target
from the current tree, diffs its per-section dataflow fingerprints
against the baseline journal's, re-injects ONLY changed sections (each
convergence-bounded on its own when a stop condition is set), splices
everything else from the baseline's recorded rows, and lands a done
record plus a materialized result journal.  The verdict then compares
classification distributions through
:func:`coast_tpu.analysis.json_parser.compare_runs` -- per-class Wilson
intervals must overlap, and a new or vanished outcome class is drift by
definition (a weakened protection often *creates* a class at a rate far
inside a Wilson interval of zero).

Exit codes are typed and script-stable:

  * ``EXIT_PASS`` (0)  -- every target's distribution is consistent
    with the baseline; a refreshed artifact was produced.
  * ``EXIT_DRIFT`` (1) -- at least one target drifted; the per-class
    report names which classes and which sections.
  * ``EXIT_INFRA`` (2) -- the check itself could not run to a verdict
    (build failure, unreadable baseline, identity mismatch, worker
    death).  A mismatched campaign identity -- changed seed/n, a
    changed memory map -- is deliberately infra, not drift: it means
    the baseline no longer describes these targets and must be rebuilt,
    not that the protection regressed.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import subprocess
import sys
import tempfile
from typing import Callable, Dict, List, Optional

from coast_tpu.ci import baseline as base_mod
from coast_tpu.inject.spec import CampaignSpec

__all__ = ["EXIT_PASS", "EXIT_DRIFT", "EXIT_INFRA", "CiInfraError",
           "TargetReport", "CiReport", "default_specs",
           "build_baseline", "check_baseline"]

EXIT_PASS = 0
EXIT_DRIFT = 1
EXIT_INFRA = 2

#: Default convergence bound for check items: each re-injected section
#: stops once its uncorrected-corruption rate is known to +-2% (floored
#: at 256 effective injections so rare classes get a chance to appear).
DEFAULT_STOP_WHEN = "sdc:0.02;min=256"


class CiInfraError(RuntimeError):
    """The CI could not reach a verdict (exit 2): infrastructure or
    identity failure, not a protection regression."""


def default_specs(n: int = 2048, seed: int = 7,
                  batch_size: int = 512) -> List[CampaignSpec]:
    """The repo's own CI target set: the two seed benchmarks whose
    equivalence behavior is differentially validated
    (artifacts/equiv_study.json) x both protection strategies."""
    return [CampaignSpec(bench, n, seed=seed, opt_passes=opt,
                         batch_size=batch_size, equiv=True).validate()
            for bench in ("matrixMultiply", "crc16")
            for opt in ("-DWC", "-TMR")]


# -- fleet plumbing ----------------------------------------------------------

def _safe_name(text: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", text).strip("_")


def _spawn_worker(queue_dir: str, wid: str) -> subprocess.Popen:
    """One fleet worker subprocess (the `python -m coast_tpu.fleet
    worker` the fleet supervisor itself spawns), resolving the same
    coast_tpu this process runs."""
    import coast_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(coast_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "coast_tpu.fleet", "worker",
         "--queue", queue_dir, "--worker-id", wid], env=env)


def _drain(queue, workers: int = 1,
           program_hook: Optional[Callable] = None) -> None:
    """Drain the queue through fleet workers: in-process for one worker
    (the default -- and the only mode that can carry a program_hook),
    subprocesses for more."""
    from coast_tpu.fleet.compile_cache import CompileCache
    from coast_tpu.fleet.worker import Worker
    if workers <= 1:
        cache = CompileCache(queue.cache_dir, program_hook=program_hook)
        Worker(queue, "ci-w0", cache=cache).drain()
        return
    if program_hook is not None:
        raise CiInfraError(
            "program_hook (the seeded-weakening test seam) needs the "
            "in-process worker; run with workers=1")
    procs = [_spawn_worker(queue.root, f"ci-w{i}")
             for i in range(workers)]
    rcs = [p.wait() for p in procs]
    if any(rcs):
        raise CiInfraError(
            f"fleet worker(s) exited nonzero: {rcs}")


def _collect_done(queue, wanted: Dict[str, str]) -> Dict[str, Dict]:
    """{target_id: done result} for every enqueued item; failed or
    missing items are a CiInfraError naming each failure."""
    done = {str(rec.get("id")): rec for rec in queue.items("done")}
    failed = {str(rec.get("id")): rec for rec in queue.items("failed")}
    stats = queue.stats()
    out: Dict[str, Dict] = {}
    problems: List[str] = []
    for item_id, tid in wanted.items():
        if item_id in done:
            out[tid] = dict(done[item_id].get("result") or {})
        elif item_id in failed:
            problems.append(
                f"{tid}: {failed[item_id].get('error')}")
        else:
            problems.append(f"{tid}: item {item_id} never completed "
                            f"(queue: {stats})")
    if problems:
        raise CiInfraError(
            "campaign item(s) did not complete:\n  "
            + "\n  ".join(problems))
    return out


# -- baseline ----------------------------------------------------------------

def build_baseline(specs: List[CampaignSpec],
                   queue_dir: Optional[str] = None,
                   workers: int = 1,
                   program_hook: Optional[Callable] = None,
                   log: Callable[[str], None] = lambda s: None
                   ) -> Dict[str, object]:
    """Run every spec as a full journaled fleet campaign and assemble
    the baseline artifact document."""
    from coast_tpu.fleet.queue import CampaignQueue
    specs = [s.validate() for s in specs]
    with tempfile.TemporaryDirectory(prefix="coast_ci_") as tmp:
        root = queue_dir or os.path.join(tmp, "queue")
        q = CampaignQueue(root)
        wanted: Dict[str, str] = {}
        journal_paths: Dict[str, str] = {}
        for spec in specs:
            tid = base_mod.target_id(spec)
            if tid in journal_paths:
                raise CiInfraError(f"duplicate target {tid!r}")
            item_id = q.enqueue(spec.to_item())
            wanted[item_id] = tid
            journal_paths[tid] = q.journal_path(item_id)
            log(f"# baseline: queued {tid} ({item_id})")
        _drain(q, workers=workers, program_hook=program_hook)
        results = _collect_done(q, wanted)
        targets: Dict[str, Dict[str, object]] = {}
        for spec in specs:
            tid = base_mod.target_id(spec)
            targets[tid] = base_mod.target_block(
                spec, results[tid], journal_paths[tid])
            log(f"# baseline: {tid}: n={targets[tid]['n']} "
                f"physical={targets[tid]['physical_n']}")
        return base_mod.assemble(targets)


# -- check -------------------------------------------------------------------

@dataclasses.dataclass
class TargetReport:
    """One target's check outcome."""

    target: str
    drift: bool
    changed_sections: List[str]
    reused_rows: int
    reinjected_rows: int
    dropped_rows: int
    base_n: int
    n: int
    base_counts: Dict[str, int]
    counts: Dict[str, int]
    comparison: Dict[str, object]     # pooled compare_runs output
    # Per-changed-section class_comparison blocks -- the verdict's
    # comparison unit whenever early stop dropped rows (see
    # _target_verdict).
    section_comparisons: Dict[str, Dict[str, object]] = \
        dataclasses.field(default_factory=dict)
    cache_event: Optional[str] = None
    # Static isolation pre-gate refutations (counterexample paths); a
    # non-empty list IS drift -- the tree's protection is broken before
    # any injection runs, so no campaign was enqueued for this target.
    isolation_leaks: List[str] = dataclasses.field(default_factory=list)
    # Per-target campaign cost: wall seconds plus the stage breakdown
    # (schedule/pad/dispatch/collect/... seconds) from the worker's done
    # record, so a protection-CI cost regression -- a target whose delta
    # suddenly re-injects everything, a compile that stopped caching --
    # is visible in the verdict artifact, not just in CI latency graphs.
    seconds: float = 0.0
    stage_seconds: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    def drift_lines(self) -> List[str]:
        from coast_tpu.analysis.json_parser import format_drift_lines
        if self.isolation_leaks:
            return [f"isolation: {l}" for l in self.isolation_leaks]
        if self.comparison.get("skipped"):
            return [str(self.comparison["skipped"])]
        if not self.comparison:
            return []
        if self.section_comparisons and self.dropped_rows:
            return [f"section {name}: {d}"
                    for name, cmp_ in sorted(
                        self.section_comparisons.items())
                    for d in format_drift_lines(cmp_)]
        return format_drift_lines(self.comparison)


@dataclasses.dataclass
class CiReport:
    """The whole check's outcome: per-target reports + the refreshed
    baseline document (written on pass)."""

    targets: List[TargetReport]
    refreshed: Dict[str, object]

    @property
    def drift(self) -> bool:
        return any(t.drift for t in self.targets)

    @property
    def exit_code(self) -> int:
        return EXIT_DRIFT if self.drift else EXIT_PASS

    def to_json(self) -> Dict[str, object]:
        def _strict(v):
            # compare_runs ratios can be inf/nan (zero-error baselines);
            # strict-JSON consumers reject bare Infinity, so encode them
            # as strings (the scripts/mwtf_report.py convention).
            if isinstance(v, float) and not math.isfinite(v):
                return "nan" if math.isnan(v) else "inf"
            if isinstance(v, dict):
                return {k: _strict(x) for k, x in v.items()}
            if isinstance(v, list):
                return [_strict(x) for x in v]
            return v

        return {
            "format": "coast-ci-report", "version": 1,
            "verdict": "drift" if self.drift else "pass",
            "targets": [_strict(dataclasses.asdict(t))
                        for t in self.targets],
        }

    def format(self) -> str:
        lines = []
        for t in self.targets:
            state = "DRIFT" if t.drift else (
                "skip" if t.comparison.get("skipped") else "ok")
            changed = (",".join(t.changed_sections)
                       if t.changed_sections else "none")
            lines.append(
                f"{state:>5}  {t.target}  changed={changed}  "
                f"reinjected={t.reinjected_rows}/"
                f"{t.reinjected_rows + t.reused_rows} rows"
                + (f" (early-stop cut {t.dropped_rows})"
                   if t.dropped_rows else "")
                + (f"  [{t.seconds:.2f}s campaign]"
                   if t.seconds else ""))
            for d in t.drift_lines():
                lines.append(f"         {d}")
        verdict = ("protection-regression DRIFT"
                   if self.drift else "protection unchanged: PASS")
        lines.append(f"ci: {len(self.targets)} target(s); {verdict}")
        return "\n".join(lines)


def _stage_seconds(result: Dict[str, object]) -> Dict[str, float]:
    """The done record's campaign stage breakdown (the worker's
    ``res.summary()["stages"]``), seconds only -- the ``overlap``
    fraction is a ratio, not a cost, and stays out of a seconds
    table."""
    stages = (result.get("summary") or {}).get("stages") or {}
    return {str(k): round(float(v), 6) for k, v in sorted(stages.items())
            if k != "overlap"}


def _verdict_summary(name: str, n: int, counts: Dict[str, int]):
    """A json_parser.Summary over OUTCOME classes only (cache_invalid is
    schedule bookkeeping, not an outcome)."""
    from coast_tpu.analysis.json_parser import Summary
    kept = {k: int(v) for k, v in counts.items()
            if k != "cache_invalid"}
    return Summary(name=name, n=int(n), counts=kept, seconds=0.0,
                   mean_steps=0.0)


def _target_verdict(tid: str, block: Dict[str, object],
                    result: Dict[str, object], z: float):
    """(drift, pooled_comparison, section_comparisons) for one target.

    The pooled distributions decide the verdict only when the delta
    covered every row.  When per-section early stop DROPPED rows, the
    pooled mix is biased -- a truncated section's share of the total
    shrank, so pooled rates move even when every section's distribution
    is unchanged -- and the verdict falls back to the per-changed-
    section comparisons run_delta recorded (sound: spliced rows are
    identical by construction, so drift can only originate in changed
    sections)."""
    from coast_tpu.analysis.json_parser import (class_comparison,
                                                compare_runs)
    cmp_ = compare_runs(
        _verdict_summary(f"{tid} (baseline)", block["n"],
                         block["counts"]),
        _verdict_summary(tid, result.get("injections", 0),
                         result.get("counts") or {}),
        z=z)
    delta = dict(result.get("delta") or {})
    section_cmps: Dict[str, Dict[str, object]] = {}
    for name, row in sorted((delta.get("sections") or {}).items()):
        section_cmps[name] = class_comparison(
            _verdict_summary(f"{name} (baseline)", row["base_n"],
                             row["base_counts"]),
            _verdict_summary(name, row["n"], row["counts"]),
            z=z)
    if int(delta.get("dropped_rows", 0)) and section_cmps:
        drift = any(c["distribution_drift"]
                    for c in section_cmps.values())
    else:
        drift = bool(cmp_["distribution_drift"])
    return drift, cmp_, section_cmps


def _isolation_pregate(targets: Dict[str, object],
                       program_hook: Optional[Callable],
                       log: Callable[[str], None]
                       ) -> Dict[str, List[str]]:
    """The fast static pre-gate: prove lane-isolation noninterference
    for every target's CURRENT build before any delta campaign is
    enqueued.  A refuted target returns its counterexample paths -- a
    statically-broken protection is a regression no campaign needs to
    measure (and a campaign against it would burn the whole convergence
    budget discovering what the prover shows in milliseconds).  Build
    failures raise :class:`CiInfraError` (any worker would fail the same
    way)."""
    from coast_tpu.analysis.propagation import prove_isolation
    from coast_tpu.inject.supervisor import build_program
    leaks: Dict[str, List[str]] = {}
    for tid in sorted(targets):
        spec = CampaignSpec.from_item(targets[tid]["spec"])
        try:
            prog, strategy = build_program(spec.benchmark,
                                           spec.opt_passes)
        except SystemExit as e:
            raise CiInfraError(
                f"{tid}: protected-program build failed "
                f"(exit {e.code})") from e
        if program_hook is not None:
            program_hook(prog)
        proof = prove_isolation(prog, strategy=strategy or "unprotected")
        log(f"# isolation pre-gate: {tid}: "
            f"{'HOLDS' if proof.holds else 'LEAK'}")
        if not proof.holds:
            leaks[tid] = [l.format() for l in proof.leaks]
    return leaks


def check_baseline(doc: Dict[str, object],
                   workdir: Optional[str] = None,
                   stop_when: Optional[str] = DEFAULT_STOP_WHEN,
                   workers: int = 1,
                   z: float = 1.96,
                   program_hook: Optional[Callable] = None,
                   static_budget: bool = True,
                   isolation_gate: bool = True,
                   log: Callable[[str], None] = lambda s: None
                   ) -> CiReport:
    """Check the current tree against a baseline document.

    First the static isolation pre-gate runs over every target's
    current build (``isolation_gate=False`` disables): a refuted
    noninterference proof is an immediate DRIFT verdict carrying the
    counterexample paths, and no campaign is enqueued.  Then, per
    target: materialize the baseline journal, enqueue a DELTA item
    (``stop_when`` bounding each re-injected section; None disables;
    ``static_budget`` points the convergence budget at the sections the
    static vulnerability map calls ``sdc-possible`` first), drain
    through fleet workers, and compare distributions
    (:func:`_target_verdict`).  Raises :class:`CiInfraError` when any
    target cannot reach a verdict."""
    from coast_tpu.fleet.queue import CampaignQueue, QueueError
    targets = doc["targets"]
    if isolation_gate:
        leaking = _isolation_pregate(targets, program_hook, log)
        if leaking:
            # The report covers EVERY target: leaking ones drift with
            # their counterexample paths, the rest are explicitly
            # "skipped" (the gate aborts before any campaign, so no
            # distribution verdict exists for them either).
            reports = [
                TargetReport(
                    target=tid, drift=tid in leaking,
                    changed_sections=[],
                    reused_rows=0, reinjected_rows=0, dropped_rows=0,
                    base_n=int(targets[tid]["n"]),
                    n=0, base_counts=dict(targets[tid]["counts"]),
                    counts={},
                    comparison=({} if tid in leaking else
                                {"skipped": "isolation pre-gate failed "
                                 "on another target; no campaign ran"}),
                    isolation_leaks=leaking.get(tid, []))
                for tid in sorted(targets)]
            return CiReport(targets=reports,
                            refreshed=base_mod.assemble(
                                {tid: json.loads(json.dumps(targets[tid]))
                                 for tid in sorted(targets)}))
    with tempfile.TemporaryDirectory(prefix="coast_ci_") as tmp:
        root = workdir or tmp
        q = CampaignQueue(os.path.join(root, "queue"))
        wanted: Dict[str, str] = {}
        journal_paths: Dict[str, str] = {}
        specs: Dict[str, CampaignSpec] = {}
        for tid in sorted(targets):
            block = targets[tid]
            spec = CampaignSpec.from_item(block["spec"])
            base_path = base_mod.materialize_journal(
                block["journal"],
                os.path.join(root, "base", f"{_safe_name(tid)}.journal"))
            item = dataclasses.replace(
                spec, delta_from=base_path, equiv=True,
                stop_when=(stop_when or None),
                static_budget=bool(static_budget and stop_when))
            try:
                item.validate()
            except (ValueError, QueueError) as e:
                raise CiInfraError(f"{tid}: bad check spec: {e}") from e
            item_id = q.enqueue(item.to_item())
            wanted[item_id] = tid
            journal_paths[tid] = q.journal_path(item_id)
            specs[tid] = spec
            log(f"# check: queued {tid} ({item_id})")
        _drain(q, workers=workers, program_hook=program_hook)
        results = _collect_done(q, wanted)

        reports: List[TargetReport] = []
        refreshed: Dict[str, Dict[str, object]] = {}
        for tid in sorted(targets):
            block = targets[tid]
            result = results[tid]
            delta = dict(result.get("delta") or {})
            drift, cmp_, section_cmps = _target_verdict(
                tid, block, result, z)
            report = TargetReport(
                target=tid,
                drift=drift,
                changed_sections=list(delta.get("changed_sections")
                                      or []),
                reused_rows=int(delta.get("reused_rows", 0)),
                reinjected_rows=int(delta.get("reinjected_rows", 0)),
                dropped_rows=int(delta.get("dropped_rows", 0)),
                base_n=int(block["n"]),
                n=int(result.get("injections", 0)),
                base_counts=dict(block["counts"]),
                counts={k: int(v) for k, v in
                        (result.get("counts") or {}).items()},
                comparison=cmp_,
                section_comparisons=section_cmps,
                cache_event=result.get("cache_event"),
                seconds=round(float(result.get("seconds", 0.0)), 6),
                stage_seconds=_stage_seconds(result),
            )
            reports.append(report)
            log(f"# check: {tid}: "
                f"{'DRIFT' if report.drift else 'ok'} "
                f"(reinjected {report.reinjected_rows})")
            if report.dropped_rows:
                # A truncated run cannot refresh ground truth: its
                # journal is missing the early-stop-dropped sites, and
                # baking it in would make every future no-op check
                # re-inject them (conservatively, as unmatched sites)
                # forever.  Keep the old block; the target keeps
                # re-checking until a full-coverage run (--stop-when
                # none, or `ci baseline`) rebases it.
                refreshed[tid] = json.loads(json.dumps(block))
            else:
                refreshed[tid] = base_mod.target_block(
                    specs[tid], result, journal_paths[tid])
        return CiReport(targets=reports,
                        refreshed=base_mod.assemble(refreshed))
