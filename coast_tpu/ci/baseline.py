"""CI baseline artifact: the committed ground truth a check diffs against.

One JSON document (``artifacts/ci_baseline.json`` is the repo's own),
format ``coast-ci-baseline`` version 1:

  * top level -- ``format``/``version``, informational provenance
    (``created_unix``, ``jax``, ``backend``), and ``targets``;
  * ``targets`` -- one block per campaign, keyed by :func:`target_id`
    (``benchmark|opt_passes|section|s<seed>``), each carrying

      - ``spec``: the campaign's identity in the shared
        :class:`~coast_tpu.inject.spec.CampaignSpec` queue-item
        encoding (what the check enqueues, delta_from added);
      - ``strategy`` / ``config_sha`` / ``partition`` /
        ``section_fingerprints``: the build the counts describe --
        the fingerprints are what the check diffs;
      - ``n`` / ``physical_n`` / ``counts``: the classification
        distribution (effective injections) the verdict compares
        Wilson intervals against;
      - ``journal``: the campaign's journal records as compact ndjson
        LINES (header + equiv representatives + batch rows, volatile
        span timing stripped).  Materialized back to a file at check
        time, this is the delta splice base -- the row-level ground
        truth that makes re-injecting only changed sections sound.

The journal rides INSIDE the artifact so ``check`` runs out of the box
from a fresh clone: no side-channel files, no object storage, one
committed JSON.  Size stays small because the stored rows are the
equivalence representatives (~10-26x fewer than effective injections).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

__all__ = ["BASELINE_FORMAT", "BASELINE_VERSION", "BaselineError",
           "target_id", "journal_lines", "materialize_journal",
           "load_baseline", "write_baseline", "target_block"]

BASELINE_FORMAT = "coast-ci-baseline"
BASELINE_VERSION = 1

#: Journal record kinds a baseline keeps: everything a delta base reader
#: (``load_delta_base``) consumes.  Retry/geometry/early_stop forensics
#: and per-batch span timing are run-time accidents, not ground truth.
_KEEP_KINDS = ("header", "equiv_schedule", "batch")
_STRIP_BATCH_KEYS = ("spans", "stage_seconds")


class BaselineError(RuntimeError):
    """An unreadable or malformed baseline artifact (CI infra failure)."""


def target_id(spec) -> str:
    """Human-readable stable key of one target: the build + campaign
    axes that distinguish baseline rows (n/batch ride in the spec)."""
    return (f"{spec.benchmark}|{spec.opt_passes}|{spec.section}"
            f"|s{spec.seed}")


def journal_lines(path: str) -> List[str]:
    """A journal file reduced to its baseline form: one compact JSON
    string per kept record, batch records stripped of volatile timing.
    Raises :class:`BaselineError` on anything unparseable -- a baseline
    must never embed a journal it cannot re-materialize."""
    out: List[str] = []
    try:
        with open(path) as fh:
            raw_lines = fh.read().splitlines()
    except OSError as e:
        raise BaselineError(f"cannot read journal {path!r}: {e}") from e
    for i, raw in enumerate(raw_lines):
        if not raw.strip():
            continue
        try:
            rec = json.loads(raw)
        except ValueError as e:
            raise BaselineError(
                f"journal {path!r} line {i + 1} is not JSON: {e}") from e
        if rec.get("kind") not in _KEEP_KINDS:
            continue
        if rec.get("kind") == "batch":
            rec = {k: v for k, v in rec.items()
                   if k not in _STRIP_BATCH_KEYS}
        out.append(json.dumps(rec, separators=(",", ":")))
    if not out:
        raise BaselineError(f"journal {path!r} has no records to keep")
    return out


def materialize_journal(lines: List[str], path: str) -> str:
    """Write baseline journal lines back to a file (the delta splice
    base ``check`` points items at).  Returns ``path``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return path


def target_block(spec, result: Dict[str, object],
                 journal_path: str) -> Dict[str, object]:
    """One baseline target from a fleet done-record ``result`` and the
    item's journal.  The build facts (strategy, config_sha, partition,
    section_fingerprints) come from the journal HEADER -- the one
    record that already pins them -- not from a second derivation."""
    lines = journal_lines(journal_path)
    header = json.loads(lines[0])
    if header.get("kind") != "header":
        raise BaselineError(
            f"journal {journal_path!r} does not start with a header")
    counts = {k: int(v)
              for k, v in (result.get("counts") or {}).items()}
    block: Dict[str, object] = {
        # The CALLER's spec, not the done record's: a check item's spec
        # carries its temp delta_from path and stop-when override, and a
        # refreshed baseline must store the clean campaign identity.
        "spec": spec.to_item(),
        "strategy": header.get("strategy"),
        "config_sha": header.get("config_sha"),
        "partition": (header.get("equiv") or {}).get("partition"),
        "section_fingerprints": dict(
            header.get("section_fingerprints") or {}),
        "n": int(result.get("injections", 0)),
        "physical_n": int(result.get("physical_injections",
                                     result.get("injections", 0))),
        "counts": counts,
        "journal": lines,
    }
    if not block["section_fingerprints"]:
        raise BaselineError(
            f"journal {journal_path!r} carries no section fingerprints "
            "(was the campaign run without equiv?); a baseline without "
            "fingerprints cannot seed delta checks")
    return block


def load_baseline(path: str) -> Dict[str, object]:
    """Read + validate a baseline artifact."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except OSError as e:
        raise BaselineError(f"cannot read baseline {path!r}: {e}") from e
    except ValueError as e:
        raise BaselineError(
            f"baseline {path!r} is not JSON: {e}") from e
    if doc.get("format") != BASELINE_FORMAT:
        raise BaselineError(
            f"baseline {path!r} has format {doc.get('format')!r}; "
            f"want {BASELINE_FORMAT!r}")
    if int(doc.get("version", 0)) > BASELINE_VERSION:
        raise BaselineError(
            f"baseline {path!r} is version {doc.get('version')}, newer "
            f"than this tool understands ({BASELINE_VERSION}); update "
            "the tree or rebuild the baseline")
    if not doc.get("targets"):
        raise BaselineError(f"baseline {path!r} has no targets")
    return doc


def write_baseline(doc: Dict[str, object], path: str) -> None:
    """Atomically write a baseline artifact.  ``indent=1`` keeps the
    committed file diffable per target/record (the journal records are
    pre-compacted strings, so the bulk stays one line each)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def assemble(targets: Dict[str, Dict[str, object]],
             extra: Optional[Dict[str, object]] = None
             ) -> Dict[str, object]:
    """The top-level artifact document around a targets map."""
    import time
    doc: Dict[str, object] = {
        "format": BASELINE_FORMAT, "version": BASELINE_VERSION,
        "created_unix": round(time.time(), 3),
        "targets": targets,
    }
    try:
        import jax
        doc["jax"] = jax.__version__
        doc["backend"] = jax.default_backend()
    except Exception:                    # noqa: BLE001 - provenance only
        pass
    if extra:
        doc.update(extra)
    return doc
