"""Dynamic row indexing that stays dense on the TPU.

The guest models step one row (or element) at a time with a *traced* index
-- the reference's benchmarks walk arrays with a loop counter that faults
can corrupt (e.g. matrixMultiply.c's ``i``).  The natural JAX spelling,
``lax.dynamic_index_in_dim`` / ``lax.dynamic_update_index_in_dim``, is a
dynamic-slice at batch=1 -- but under the campaign's ``vmap`` the start
index becomes batch-varying and XLA lowers the pair to gather/scatter,
which the TPU executes far off its dense-op roofline (the tiny-benchmark
campaign's per-iteration cost is dominated by exactly these ops).

``row_select``/``row_update`` offer the same clamped semantics with a
selectable lowering:

* ``"slice"``  -- the dynamic-slice spelling (gather/scatter under vmap);
* ``"onehot"`` -- a dense formulation: select is a one-hot contraction,
  update is a broadcast-where over a one-hot row mask.  Both are plain
  elementwise/reduction ops, so the vmapped campaign stays on the VPU.
* ``"auto"``   -- ``"onehot"`` when the default backend is a TPU AND the
  indexed axis is small (<= ``ONEHOT_MAX_ROWS``), else ``"slice"``.
  MEASURED on-chip (v5 lite, 2026-08-01, 50k injections/cell,
  ``artifacts/unroll_sweep.json``): one-hot carries the mm-TMR campaign
  at 27.2-27.7k inj/s across unroll {1,2,4,8} vs 5.8k for the slice
  lowering at unroll=1 (degrading to 2.2k at unroll=8) -- a 4.7x win at
  the defaults, 10x at the bench batch (``artifacts/mfu_sweep.json``
  "unroll" grid: ~54k vs ~5.5k).  The dense form reads every row per
  access (O(n * row) vs the slice's O(row)), so the win is confined to
  small indexed axes where gather/scatter dispatch dominates; long
  arrays (e.g. lifted scans over big inputs) keep the slice lowering.
  Gathers are cheap on CPU and the host fallback's throughput record
  lives there, so CPU always resolves to ``"slice"``.

Both lowerings treat an out-of-range index exactly like dynamic-slice
does -- one python-style negative wrap, then clamp into range (a
corrupted loop counter reads/writes a wrong row rather than trapping;
the documented fidelity envelope vs the A9's data aborts, SURVEY.md
§7) -- so campaign classifications are bit-identical across modes
(tests/test_benchmarks.py::test_indexing_modes_bit_identical).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


# Auto-mode bound: above this row count the dense lowering's whole-array
# read per access is assumed to cost more than the gather it replaces.
ONEHOT_MAX_ROWS = 64


def _resolve(mode: str, n_rows: int) -> str:
    if mode == "auto":
        # Resolved at TRACE time; COAST_INDEXING_MODE forces a lowering
        # for A/B measurement (scripts/mfu_sweep.py) without touching
        # model code.
        forced = os.environ.get("COAST_INDEXING_MODE")
        if forced in ("onehot", "slice"):
            return forced
        return ("onehot" if (jax.default_backend() == "tpu"
                             and n_rows <= ONEHOT_MAX_ROWS) else "slice")
    if mode not in ("onehot", "slice"):
        raise ValueError(f"unknown indexing mode '{mode}'")
    return mode


def _clamped_onehot(i: jax.Array, n: int, dtype) -> jax.Array:
    # Match lax.dynamic_slice index semantics exactly: one python-style
    # negative wrap, then clamp into range.  Campaign classifications of
    # corrupted loop counters depend on this being bit-identical to the
    # dynamic-slice lowering.
    ic = jnp.clip(jnp.where(i < 0, i + n, i), 0, n - 1)
    return (jnp.arange(n, dtype=jnp.int32) == ic).astype(dtype)


def row_select(mat: jax.Array, i: jax.Array, mode: str = "auto") -> jax.Array:
    """``mat[clamp(i)]`` along axis 0, any rank >= 1."""
    if _resolve(mode, mat.shape[0]) == "slice":
        return jax.lax.dynamic_index_in_dim(mat, i, axis=0, keepdims=False)
    if mat.dtype == jnp.bool_:
        # No integer-multiply trick for bools; reduce through int32.
        return row_select(mat.astype(jnp.int32), i, mode).astype(jnp.bool_)
    if jnp.issubdtype(mat.dtype, jnp.inexact):
        # Float arithmetic cannot implement an exact select: 0*inf=nan in
        # a masked-out row would poison the sum and a selected -0.0 would
        # come back +0.0.  Faulted guests hold exactly such values (a bit
        # flip in an exponent makes inf/nan), so select through the bit
        # pattern instead -- integer one-hot math is exact, and the
        # round-trip preserves every payload bit.
        bits = jax.lax.bitcast_convert_type(
            mat, jnp.dtype(f"uint{mat.dtype.itemsize * 8}"))
        return jax.lax.bitcast_convert_type(
            row_select(bits, i, mode), mat.dtype)
    hot = _clamped_onehot(i, mat.shape[0], mat.dtype)
    hot = hot.reshape((mat.shape[0],) + (1,) * (mat.ndim - 1))
    # dtype pinned: jnp.sum would promote sub-word ints (uint16 -> uint32),
    # and the float path bitcasts the result back expecting the same width.
    return jnp.sum(hot * mat, axis=0, dtype=mat.dtype)


def row_update(mat: jax.Array, row: jax.Array, i: jax.Array,
               mode: str = "auto") -> jax.Array:
    """``mat.at[clamp(i)].set(row)`` along axis 0, any rank >= 1."""
    if _resolve(mode, mat.shape[0]) == "slice":
        return jax.lax.dynamic_update_index_in_dim(mat, row, i, axis=0)
    hot = _clamped_onehot(i, mat.shape[0], jnp.bool_)
    hot = hot.reshape((mat.shape[0],) + (1,) * (mat.ndim - 1))
    return jnp.where(hot, jnp.asarray(row, mat.dtype)[None], mat)
