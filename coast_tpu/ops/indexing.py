"""Dynamic row indexing that stays dense on the TPU.

The guest models step one row (or element) at a time with a *traced* index
-- the reference's benchmarks walk arrays with a loop counter that faults
can corrupt (e.g. matrixMultiply.c's ``i``).  The natural JAX spelling,
``lax.dynamic_index_in_dim`` / ``lax.dynamic_update_index_in_dim``, is a
dynamic-slice at batch=1 -- but under the campaign's ``vmap`` the start
index becomes batch-varying and XLA lowers the pair to gather/scatter,
which the TPU executes far off its dense-op roofline (the tiny-benchmark
campaign's per-iteration cost is dominated by exactly these ops).

``row_select``/``row_update`` offer the same clamped semantics with a
selectable lowering:

* ``"slice"``  -- the dynamic-slice spelling (gather/scatter under vmap);
* ``"onehot"`` -- a dense formulation: select is a one-hot contraction,
  update is a broadcast-where over a one-hot row mask.  Both are plain
  elementwise/reduction ops, so the vmapped campaign stays on the VPU.
* ``"auto"``   -- ``"onehot"`` when the default backend is a TPU AND the
  indexed axis is small (<= ``ONEHOT_MAX_ROWS``) AND the per-row
  payload is small (<= ``ONEHOT_MAX_ROW_BYTES``), else ``"slice"``.
  MEASURED on-chip (v5 lite, 2026-08-01, 50k injections/cell,
  ``artifacts/unroll_sweep.json``): one-hot carries the mm-TMR campaign
  at 48.4-57.7k inj/s across unroll {1,2,4,8} vs 5.8k for the slice
  lowering at unroll=1 (degrading to 3.7k at unroll=8) -- a ~10x win.
  The dense form reads every row per access (O(n * row) vs the slice's
  O(row)), so the win is confined to small indexed axes where
  gather/scatter dispatch dominates; long arrays (e.g. lifted scans
  over big inputs) and MB-scale rows (the flagships' block panels,
  pending ``scripts/flagship_indexing_ab.py``'s on-chip record) keep
  the slice lowering.  Gathers are cheap on CPU and the host
  fallback's throughput record lives there, so CPU always resolves to
  ``"slice"``.

Both lowerings treat an out-of-range index exactly like dynamic-slice
does -- one python-style negative wrap, then clamp into range (a
corrupted loop counter reads/writes a wrong row rather than trapping;
the documented fidelity envelope vs the A9's data aborts, SURVEY.md
§7) -- so campaign classifications are bit-identical across modes
(tests/test_benchmarks.py::test_indexing_modes_bit_identical).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


def _tag(x: jax.Array, role: str) -> jax.Array:
    """Identity at runtime; a ``name[name=coast:<role>]`` marker in the
    jaxpr.  The provenance pass (passes/verification.py) classifies
    address-forming ctrl leaves by scanning for gather/dynamic-slice
    primitives -- which the dense lowering deliberately has none of --
    so BOTH lowerings tag the index here and the pass reads the tag:
    a region's sync structure (load-addr pre-votes, store-addr votes,
    syncGEP's GEP-operand classification) is therefore identical
    whichever lowering resolves, not an artifact of the mode."""
    return checkpoint_name(x, f"coast:{role}")


# Auto-mode bounds: above this row count the dense lowering's whole-array
# read per access is assumed to cost more than the gather it replaces.
ONEHOT_MAX_ROWS = 64
# Row-size bound: the measured one-hot win (unroll_sweep.json) is for the
# toy benchmarks' KiB-scale leaves (36-byte rows); whether it survives at
# the flagships' MB-scale block panels (a 2 MB "row" for mm1024b512's
# block walk) is exactly what scripts/flagship_indexing_ab.py measures
# on-chip.  Until that artifact exists, auto stays on the measured side
# of the line: dense only for small rows.
ONEHOT_MAX_ROW_BYTES = 4096


def _resolve(mode: str, n_rows: int, row_bytes: int) -> str:
    if mode == "auto":
        # Resolved at TRACE time; COAST_INDEXING_MODE forces a lowering
        # for A/B measurement (scripts/mfu_sweep.py) without touching
        # model code.
        forced = os.environ.get("COAST_INDEXING_MODE")
        if forced in ("onehot", "slice"):
            return forced
        return ("onehot" if (jax.default_backend() == "tpu"
                             and n_rows <= ONEHOT_MAX_ROWS
                             and row_bytes <= ONEHOT_MAX_ROW_BYTES)
                else "slice")
    if mode not in ("onehot", "slice"):
        raise ValueError(f"unknown indexing mode '{mode}'")
    return mode


def _row_bytes(mat: jax.Array) -> int:
    n = mat.dtype.itemsize
    for d in mat.shape[1:]:
        n *= d
    return n


def _clamped_onehot(i: jax.Array, n: int, dtype) -> jax.Array:
    # Match lax.dynamic_slice index semantics exactly: one python-style
    # negative wrap, then clamp into range.  Campaign classifications of
    # corrupted loop counters depend on this being bit-identical to the
    # dynamic-slice lowering.
    ic = jnp.clip(jnp.where(i < 0, i + n, i), 0, n - 1)
    return (jnp.arange(n, dtype=jnp.int32) == ic).astype(dtype)


def row_select(mat: jax.Array, i: jax.Array, mode: str = "auto") -> jax.Array:
    """``mat[clamp(i)]`` along axis 0, any rank >= 1."""
    i = _tag(i, "load_addr")
    if _resolve(mode, mat.shape[0], _row_bytes(mat)) == "slice":
        return jax.lax.dynamic_index_in_dim(mat, i, axis=0, keepdims=False)
    if mat.dtype == jnp.bool_:
        # No integer-multiply trick for bools; reduce through int32.
        return row_select(mat.astype(jnp.int32), i, mode).astype(jnp.bool_)
    if jnp.issubdtype(mat.dtype, jnp.inexact):
        # Float arithmetic cannot implement an exact select: 0*inf=nan in
        # a masked-out row would poison the sum and a selected -0.0 would
        # come back +0.0.  Faulted guests hold exactly such values (a bit
        # flip in an exponent makes inf/nan), so select through the bit
        # pattern instead -- integer one-hot math is exact, and the
        # round-trip preserves every payload bit.
        bits = jax.lax.bitcast_convert_type(
            mat, jnp.dtype(f"uint{mat.dtype.itemsize * 8}"))
        return jax.lax.bitcast_convert_type(
            row_select(bits, i, mode), mat.dtype)
    hot = _clamped_onehot(i, mat.shape[0], mat.dtype)
    hot = hot.reshape((mat.shape[0],) + (1,) * (mat.ndim - 1))
    # dtype pinned: jnp.sum would promote sub-word ints (uint16 -> uint32),
    # and the float path bitcasts the result back expecting the same width.
    return jnp.sum(hot * mat, axis=0, dtype=mat.dtype)


def row_update(mat: jax.Array, row: jax.Array, i: jax.Array,
               mode: str = "auto") -> jax.Array:
    """``mat.at[clamp(i)].set(row)`` along axis 0, any rank >= 1."""
    i = _tag(i, "store_addr")
    mat = _tag(mat, "stored_into")
    if _resolve(mode, mat.shape[0], _row_bytes(mat)) == "slice":
        return jax.lax.dynamic_update_index_in_dim(mat, row, i, axis=0)
    hot = _clamped_onehot(i, mat.shape[0], jnp.bool_)
    hot = hot.reshape((mat.shape[0],) + (1,) * (mat.ndim - 1))
    return jnp.where(hot, jnp.asarray(row, mat.dtype)[None], mat)
