"""Fused protected-step path: the in-step overhead collapse (-fuseStep).

PR 15's profiler pinned the attribution: campaigns are device-bound with
~zero host gap, yet achieved MFU sits far below the voter-traffic
roofline because the waste lives *inside* the compiled step -- 19.98x /
9.82x FLOPs overhead for mm x TMR / DWC, dominated by per-step work that
is provably identity (``artifacts/profile_mm.json``, docs/perf.md
"Attribution").  This module holds the fused-step machinery the engine
(passes/dataflow_protection.py) activates under
``ProtectionConfig.fuse_step``:

  * :class:`FusePlan` / :func:`build_plan` -- the static plan: which
    per-step ops are provably identity and get pruned, which loop shape
    applies, how the flip lowers.  Every pruning below is bit-identity-
    preserving by construction (the differential pin: dense campaign
    ndjson is sha-equal fused vs unfused, tests/test_fused.py):

      - *done-cone pruning*: ``region.done`` is evaluated on a voted
        view of EVERY replicated leaf, but its jaxpr consumes only the
        control cone (mm: the single scalar ``i``).  Voting a leaf the
        predicate never reads cannot change ``done_now`` (votes are
        pure); leaves outside the cone pass a sanctioned lane-0 view.
      - *freeze pruning*: the halt freeze ``where(commit_halt, old,
        new)`` is identity for leaves whose stepped value provably
        equals their pre-step value -- not written, not commit-voted,
        not pre-step repaired.  Those leaves commit ``pstate[name]``
        directly (bit-equal even mid-flip: the flip lands on ``pstate``
        before the step, and the lane passthrough preserves it).
      - *sparse flip*: the per-site XOR costs one select+XOR over every
        word of every leaf per step under the hoisted masks; the sparse
        form dynamic-slices the single target word, XORs a scalar, and
        writes it back -- a handful of scalar ops per leaf.  Off-TPU
        only: dynamic-index scatter under a vmapped batch serialises on
        TPU (ops/bitflip.py), so the TPU path keeps the masked XOR and
        fuses it into the Pallas commit kernel instead.
      - *packed latches*: the five terminal latches (done / dwc / cfc /
        stack / assert) carry as bits of one uint32 word, collapsing
        the per-trip scalar OR-chain (``_halted`` = 4 ORs -> ``latch !=
        0``; the boundary gate = 4 ANDs -> one compare).
      - *bounded scan*: when ``region.max_steps == region.nominal_steps``
        the early-exit ``while_loop`` buys nothing (a batch pays the
        watchdog bound anyway) and ``lax.scan`` drops the per-trip cond
        evaluation; the freeze makes post-halt trips value-identical.

    The prunings above are proven identity over the *values the program
    computes* -- which is only the whole story when the region's
    dataflow is exact (integer/bool leaves).  Float dataflow is not
    schedule-independent at the bit level: XLA's fusion clustering and
    FMA/reduction lowering legitimately re-round differently for
    different surrounding programs, so ANY restructuring -- even one
    that touches no float op, like packing the scalar latches -- can
    shift a float leaf by 1 ulp, and an iterated region (training)
    amplifies that ulp into a different classification.  Measured, not
    hypothetical: the same train_mlp fault classifies differently under
    ``jit(scan(body))`` vs ``jit(while(body))`` of the IDENTICAL
    unfused body.  ``FusePlan.exact_dataflow`` is therefore the master
    eligibility gate: regions with any floating/complex leaf keep the
    legacy schedule bit-for-bit (the engine leaves ``_fuse_plan``
    unset), while ``fuse_step`` still participates in campaign identity
    (inject/journal.py) -- the knob records the requested engine, the
    plan records what the region's numerics allow.

  * :func:`make_sparse_flipper` -- the sparse flip lowering (exact
    ops/bitflip.py semantics, different cost model).

  * :func:`vote_flip_commit` / the Pallas commit kernel -- the
    data-plane fusion: per-site XOR flip application, majority/compare
    reduction, miscompare flag, and the TMR repair broadcast in ONE
    VMEM pass per eligible leaf (extending ops/pallas_voters.py, which
    reads the replica set once for the vote and leaves the repair
    broadcast and the flip as separate XLA passes).  Replica compute
    between kernel invocations stays XLA-scheduled -- the kernel owns
    the replica data plane, the packed-latch restructure owns the
    scalar plane.  ``interpret=True`` runs the same kernel everywhere
    for the differential tests; the on-chip wiring is gated on the
    bench spawn-wedge fix landing a reachable TPU backend (bench.py).

The portable restructured-scan fallback (prunings + packed latches +
sparse flip) is the path that must win on every backend; the measured
A/B lives in ``artifacts/profile_mm.json`` (``make profile``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, FrozenSet, List, Optional, Tuple

import jax
import jax.numpy as jnp

from coast_tpu.ir.region import Region, State

try:  # pallas is TPU-only at runtime but importable everywhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover - minimal builds
    _HAVE_PALLAS = False

__all__ = [
    "FusePlan", "build_plan", "done_cone", "flags_init", "unpack_latch",
    "latch_or", "latch_get", "make_sparse_flipper", "vote_flip_commit",
    "LATCH_DONE", "LATCH_DWC", "LATCH_CFC", "LATCH_STACK", "LATCH_ASSERT",
    "LATCH_DONE_ONLY",
]

# Latch word bit assignment (stable: the journal/rec extraction and the
# boundary gate compare against these).
LATCH_DONE = 0
LATCH_DWC = 1
LATCH_CFC = 2
LATCH_STACK = 3
LATCH_ASSERT = 4

#: ``latch == LATCH_DONE_ONLY`` <=> completed with zero fault latches --
#: the region-boundary ``reached_call`` gate as one compare.
LATCH_DONE_ONLY = 1 << LATCH_DONE

_LATCH_NAMES = (("done", LATCH_DONE), ("dwc_fault", LATCH_DWC),
                ("cfc_fault", LATCH_CFC), ("stack_fault", LATCH_STACK),
                ("assert_fault", LATCH_ASSERT))


def flags_init() -> Dict[str, jax.Array]:
    """Fused-mode flags: the five bool latches packed into one uint32
    word; the counters stay separate int32 accumulators."""
    return {
        "latch": jnp.uint32(0),
        "tmr_cnt": jnp.int32(0),
        "sync_cnt": jnp.int32(0),
        "steps": jnp.int32(0),
    }


def latch_or(latch: jax.Array, bit: int, cond: jax.Array) -> jax.Array:
    """OR ``cond`` into latch bit ``bit`` (the packed analogue of the
    engine's ``logical_or`` flag updates)."""
    word = cond.astype(jnp.uint32)
    if bit:
        word = word << bit
    return latch | word


def latch_get(latch: jax.Array, bit: int) -> jax.Array:
    """Read one latch bit back as a bool."""
    word = latch
    if bit:
        word = word >> bit
    return (word & jnp.uint32(1)) != 0


def unpack_latch(flags: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Expand the packed flags word back to the engine's historical flag
    dict -- the run-record extraction point (one-time cost per run)."""
    latch = flags["latch"]
    out = {name: latch_get(latch, bit) for name, bit in _LATCH_NAMES}
    out["tmr_cnt"] = flags["tmr_cnt"]
    out["sync_cnt"] = flags["sync_cnt"]
    out["steps"] = flags["steps"]
    return out


# -- the static plan ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FusePlan:
    """Static decisions of the fused-step build, derived once at
    ProtectedProgram construction.  Every field is a pruning/lowering
    choice proven bit-identity-preserving (module docstring)."""

    #: Region-spec leaves the ``done()`` predicate's jaxpr actually
    #: consumes: only these are voted in the per-step terminator view.
    done_leaves: FrozenSet[str]
    #: Region-spec leaves whose committed value can differ from their
    #: pre-step image (written, commit-voted, or pre-step repaired):
    #: only these keep the halt-freeze ``where``.
    frozen_leaves: FrozenSet[str]
    #: Lower the per-site XOR via dynamic word slices (non-TPU backends)
    #: instead of the hoisted full-leaf masks.
    sparse_flip: bool
    #: Replace the early-exit while_loop with a fixed-trip lax.scan
    #: (sound whenever max_steps == nominal_steps: there is no early
    #: exit to exploit and post-halt trips are frozen no-ops).
    bounded_scan: bool
    #: Master eligibility gate: True iff every region leaf is exact
    #: (integer/bool) dataflow.  Float leaves re-round under ANY
    #: program restructuring (XLA fusion/FMA lowering is context
    #: dependent), so the engine activates the fused schedule only when
    #: this holds -- otherwise the build keeps the legacy program
    #: bit-for-bit and the knob only marks campaign identity.
    exact_dataflow: bool = True


def done_cone(region: Region) -> FrozenSet[str]:
    """Leaves consumed by ``region.done``'s jaxpr: a backward liveness
    walk from the predicate's outputs.  Falls back to every leaf (the
    unfused behaviour, always sound) if the trace fails."""
    try:
        from jax.extend.core import Literal
        state = jax.eval_shape(region.init)
        names = sorted(state)
        closed = jax.make_jaxpr(region.done)(state)
        jaxpr = closed.jaxpr
        if len(jaxpr.invars) != len(names):
            return frozenset(names)
        needed = set(map(id, jaxpr.outvars))
        for eqn in reversed(jaxpr.eqns):
            if any(id(ov) in needed for ov in eqn.outvars):
                # Conservative: a live equation keeps every operand
                # (sub-jaxpr params close over eqn.invars, so this also
                # covers scan/cond/pjit bodies).
                needed.update(id(iv) for iv in eqn.invars
                              if not isinstance(iv, Literal))
        return frozenset(name for name, var in zip(names, jaxpr.invars)
                         if id(var) in needed)
    except Exception:            # noqa: BLE001 - pruning must not break builds
        return frozenset(jax.eval_shape(region.init))


def build_plan(prog) -> FusePlan:
    """Derive the fused-step plan for a built ProtectedProgram."""
    region = prog.region
    flow = prog.flow
    names = list(region.spec)

    cone = done_cone(region)

    if region.wants_fns():
        # Sub-function wrappers can mutate state outside the provenance
        # pass's written-set view; keep the full freeze (the prunings
        # below each degrade independently and stay bit-identical).
        frozen = frozenset(names)
    else:
        frozen = frozenset(
            name for name in names
            if (name in flow.written
                or prog.step_sync.get(name, False)
                or prog.pre_sync.get(name, False)))

    # Exactness: bit-parity of a restructured schedule is provable only
    # when no leaf carries rounding state.  eval_shape avoids
    # materializing the init state just to read dtypes.
    state = jax.eval_shape(region.init)
    exact = not any(
        jnp.issubdtype(leaf.dtype, jnp.floating)
        or jnp.issubdtype(leaf.dtype, jnp.complexfloating)
        for leaf in jax.tree.leaves(state))

    return FusePlan(
        done_leaves=cone,
        frozen_leaves=frozen,
        # Dynamic-index scatter under a vmapped batch serialises on TPU
        # (ops/bitflip.py): the TPU path keeps the masked XOR (fused
        # into the Pallas commit kernel); everywhere else the sparse
        # word slice wins by ~2 orders of magnitude in per-step ops.
        sparse_flip=jax.default_backend() != "tpu",
        bounded_scan=region.max_steps == region.nominal_steps,
        exact_dataflow=exact,
    )


# -- sparse flip lowering ----------------------------------------------------

def make_sparse_flipper(leaf_order: List[str]):
    """Sparse lowering of ops/bitflip.py's maskwise flip: identical
    semantics (one-hot XOR of word ``lane*words_per_lane + word``, XOR 0
    for every non-target leaf), but per step it costs a 1-word dynamic
    slice + scalar XOR + write-back per leaf instead of a select+XOR
    over every word of every leaf.  Data-movement ops are free in the
    analytic op model and cheap in XLA; the masked path's per-word
    selects were ~1/3 of the whole fused-step budget."""

    def build_site(state: State, replicated: Dict[str, bool],
                   leaf_id: jax.Array, lane: jax.Array, word: jax.Array,
                   bit: jax.Array):
        """Per-leaf (flat word index, xor word) pairs, built once
        outside the loop (step-invariant, like build_masks)."""
        one = jnp.left_shift(jnp.uint32(1), bit.astype(jnp.uint32))
        site = {}
        for i, name in enumerate(leaf_order):
            arr = state[name]
            nwords = 1
            for d in arr.shape:
                nwords *= int(d)
            if replicated[name]:
                words_per_lane = nwords // arr.shape[0]
                idx = lane * words_per_lane + word
            else:
                idx = word
            # Zero unless this leaf is the target: XOR 0 keeps the
            # program uniform (no lax.switch over leaves).
            site[name] = (idx,
                          jnp.where(leaf_id == i, one, jnp.uint32(0)))
        return site

    def apply_site(state: State, site, enable: jax.Array) -> State:
        new: State = {}
        for name in leaf_order:
            arr = state[name]
            idx, mask = site[name]
            u32 = jax.lax.bitcast_convert_type(arr, jnp.uint32)
            flat = u32.reshape(-1)
            cur = jax.lax.dynamic_slice(flat, (idx,), (1,))
            hit = cur ^ jnp.where(enable, mask, jnp.uint32(0))
            flat = jax.lax.dynamic_update_slice(flat, hit, (idx,))
            new[name] = jax.lax.bitcast_convert_type(
                flat.reshape(u32.shape), arr.dtype)
        return new

    return build_site, apply_site


# -- the Pallas commit kernel ------------------------------------------------

def _commit_kernel(n_lanes: int, in_ref, mask_ref, lanes_ref, voted_ref,
                   mis_ref):
    """One VMEM pass over a replica-set tile: XOR the per-site flip mask
    in, vote/compare, write the repaired lanes, the voted value, and the
    per-tile miscompare flag block.

    Mirrors ops/pallas_voters.py's ``_vote_kernel`` discipline: per-tile
    flag BLOCKS (any-reduced by the caller), no cross-step accumulation
    and no ``program_id`` reads -- both break when a vmapped campaign
    batch prepends its axis to the grid.
    """
    lanes = in_ref[:]
    bits = jax.lax.bitcast_convert_type(lanes, jnp.uint32) ^ mask_ref[:]
    flipped = jax.lax.bitcast_convert_type(bits, lanes.dtype)
    l0, l1 = flipped[0], flipped[1]
    if n_lanes == 3:
        l2 = flipped[2]
        agree01 = l0 == l1
        voted = jnp.where(agree01, l0, l2)
        mismatch = jnp.logical_or(jnp.logical_not(jnp.all(agree01)),
                                  jnp.logical_not(jnp.all(l1 == l2)))
        repaired = jnp.broadcast_to(voted[None], flipped.shape)
    else:
        voted = l0
        mismatch = jnp.logical_not(jnp.all(l0 == l1))
        # DWC has no majority: detection only, lanes commit as flipped.
        repaired = flipped
    lanes_ref[:] = repaired
    voted_ref[:] = voted
    # Per-tile flag block, same discipline as _vote_kernel: no cross-
    # step accumulation, no pl.program_id (both break under a vmapped
    # campaign batch, which prepends its axis to the grid).
    mis_ref[:] = jnp.broadcast_to(mismatch.astype(jnp.int32), (1, 8, 128))


def _tile_rows(n: int, m: int, k: int) -> int:
    """Row-tile height: whole rows, ~2 MiB of VMEM for the n-lane input
    block, must divide m (pallas_voters._tm with the lane count as a
    parameter: the fused kernel streams TWO n-lane blocks per step)."""
    budget_rows = max(8, (2 * 1024 * 1024) // (n * 4 * k) // 8 * 8)
    tm = min(m, budget_rows)
    while m % tm:
        tm -= 8            # m % 8 == 0 (kernel_eligible) -> terminates at 8
    return tm


def kernel_eligible(lanes_shape: Tuple[int, ...]) -> bool:
    """Same shape contract as ops/pallas_voters.eligible, minus the
    backend gate (interpret mode runs the kernel anywhere)."""
    if not _HAVE_PALLAS or len(lanes_shape) != 3:
        return False
    n, m, k = lanes_shape
    return (n in (2, 3) and m % 8 == 0 and k % 128 == 0
            and m * k >= 16384)


@functools.partial(jax.jit, static_argnames=("num_clones", "interpret"))
def _vote_flip_call(lanes, masks, num_clones: int, interpret: bool):
    n, m, k = lanes.shape
    tm = _tile_rows(n, m, k)
    kernel = functools.partial(_commit_kernel, num_clones)
    repaired, voted, mis = pl.pallas_call(
        kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((n, tm, k), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n, tm, k), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((n, tm, k), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, m, k), lanes.dtype),
            jax.ShapeDtypeStruct((m, k), lanes.dtype),
            jax.ShapeDtypeStruct((m // tm, 8, 128), jnp.int32),
        ],
        interpret=interpret,
    )(lanes, masks)
    return repaired, voted, jnp.any(mis != 0)


def vote_flip_commit(lanes: jax.Array, masks: Optional[jax.Array],
                     num_clones: int, interpret: bool = False
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused commit for one replica set: apply the (already fire-gated)
    per-site XOR ``masks``, vote/compare, repair.  Returns ``(lanes,
    voted, miscompare)`` -- the repaired replica set, the collapsed
    value, and the bool flag.

    Eligible shapes go through the Pallas kernel (one VMEM pass instead
    of the separate flip / vote / repair-broadcast XLA passes); anything
    else falls back to the jnp composition, which is also the
    differential reference the kernel is pinned against
    (tests/test_fused.py, interpret mode)."""
    from coast_tpu.ops import voters

    if masks is None:
        masks = jnp.zeros(lanes.shape, jnp.uint32)
    use_kernel = kernel_eligible(tuple(lanes.shape)) and (
        interpret or jax.default_backend() == "tpu")
    if use_kernel:
        from jax.ad_checkpoint import checkpoint_name
        # Same sanction marker the jnp voters carry (voters.TAG_VOTER):
        # the lane collapse happens inside the opaque Pallas kernel.
        lanes = checkpoint_name(lanes, voters.TAG_VOTER)
        return _vote_flip_call(lanes, masks, num_clones, interpret)
    bits = jax.lax.bitcast_convert_type(lanes, jnp.uint32) ^ masks
    flipped = jax.lax.bitcast_convert_type(bits, lanes.dtype)
    voted, mis = voters.vote(flipped, num_clones)
    if num_clones == 3:
        repaired = jnp.broadcast_to(voted, flipped.shape)
    else:
        repaired = flipped
    return repaired, voted, mis
