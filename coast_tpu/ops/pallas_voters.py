"""Pallas TPU kernels for the hot voter path.

The jnp voters (coast_tpu/ops/voters.py) are what XLA fuses for small
leaves; for the flagship-scale leaves (mm256's 256 KiB tensors) the vote
is a pure HBM-bandwidth op, and a hand-tiled Pallas kernel fuses the
majority select, the miscompare reduction, and the per-lane repair
broadcast into ONE pass over the replica set -- the role the reference
assigns to its native components (SURVEY.md §7: the bit-flip/vote kernels
are the XLA custom-call/Pallas obligations of the design).

Contract: bit-identical to ``voters.tmr_vote`` / ``voters.dwc_check``.
Eligibility is checked by the caller-facing wrappers, which fall back to
the jnp voters off-TPU, for unsupported shapes/dtypes, or when the leaf
is too small to be worth a kernel launch (``eligible``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from coast_tpu.ops import voters

try:  # pallas is TPU-only at runtime but importable everywhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover - minimal builds
    _HAVE_PALLAS = False

_32BIT = (jnp.float32, jnp.int32, jnp.uint32)
# Below this many words a kernel launch costs more than it saves.
MIN_WORDS = 16384


def eligible(lanes: jax.Array) -> bool:
    """True when the Pallas path applies: TPU backend, 32-bit dtype, a
    (lanes, M, N) shape with VPU-aligned tiles, and a big enough leaf."""
    if not _HAVE_PALLAS or jax.default_backend() != "tpu":
        return False
    if lanes.ndim != 3 or lanes.dtype not in _32BIT:
        return False
    n, m, k = lanes.shape
    if n not in (2, 3):
        return False
    if m % 8 or k % 128:          # f32/i32 min tile (8, 128)
        return False
    return m * k >= MIN_WORDS


def _tm(m: int, k: int) -> int:
    """Row-tile height: whole rows per step, bounded to ~2 MiB of VMEM for
    the 3-lane input block.  Must DIVIDE m -- a partial last block would
    feed Pallas's undefined padding rows into the miscompare reduction."""
    budget_rows = max(8, (2 * 1024 * 1024) // (3 * 4 * k) // 8 * 8)
    tm = min(m, budget_rows)
    while m % tm:
        tm -= 8            # m % 8 == 0 (eligible), so this terminates at 8
    return tm


def _vote_kernel(n_lanes, in_ref, voted_ref, mis_ref):
    l0 = in_ref[0]
    l1 = in_ref[1]
    if n_lanes == 3:
        l2 = in_ref[2]
        agree01 = l0 == l1
        voted_ref[:] = jnp.where(agree01, l0, l2)
        mismatch = jnp.logical_or(jnp.logical_not(jnp.all(agree01)),
                                  jnp.logical_not(jnp.all(l1 == l2)))
    else:
        voted_ref[:] = l0
        mismatch = jnp.logical_not(jnp.all(l0 == l1))
    # Every grid step writes its own tile-aligned flag block -- no cross-
    # step accumulation, no pl.program_id, no revisited output.  Those
    # patterns all break when pallas_call is vmapped (the campaign path):
    # the batch axis is prepended to the grid, so "first tile" tests fire
    # on the wrong steps and revisited VMEM windows start uninitialised.
    # The host ORs the (grid, 8, 128) flags afterwards; the extra output
    # traffic is 4 KiB per tile, noise next to the lane data.
    mis_ref[:] = jnp.broadcast_to(mismatch.astype(jnp.int32), (1, 8, 128))


@jax.jit
def _vote_pallas(lanes: jax.Array):
    n, m, k = lanes.shape
    tm = _tm(m, k)
    grid = m // tm
    voted, mis = pl.pallas_call(
        functools.partial(_vote_kernel, n),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((n, tm, k), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, k), lanes.dtype),
            jax.ShapeDtypeStruct((grid, 8, 128), jnp.int32),
        ],
    )(lanes)
    return voted, jnp.any(mis != 0)


def vote(lanes: jax.Array, num_clones: int):
    """Drop-in for voters.vote with the Pallas fast path when eligible."""
    if num_clones > 1 and eligible(lanes):
        from jax.ad_checkpoint import checkpoint_name
        # Same sanction marker the jnp voters carry (voters.TAG_VOTER):
        # the lane collapse happens inside the opaque Pallas kernel, so
        # the linter must learn from the tag that this is a voter.
        return _vote_pallas(checkpoint_name(lanes, voters.TAG_VOTER))
    return voters.vote(lanes, num_clones)
