"""Voter lowering: COAST's `insertVoters` as jnp reductions over the lane axis.

The reference materialises voters as IR instruction sequences at each sync
point: for TMR a ``cmp eq(orig, clone1)`` + ``select(cmp, orig, clone2)``
named "vote" (synchronization.cpp:439-448, 512-529); for DWC a compare plus a
conditional branch to a per-function error block that aborts
(synchronization.cpp:1117-1267).  On TPU the replicas are lanes of a leading
axis, so a voter is an elementwise reduction over axis 0 -- no communication,
fused by XLA into the surrounding computation.

All voters return ``(value, miscompare)`` where ``miscompare`` is a bool
scalar: "some lane disagreed somewhere in this tensor".  TMR uses it to bump
the ``TMR_ERROR_CNT`` analogue (synchronization.cpp:1354-1465); DWC uses it
to raise the abort flag.

Every voter tags its lane input with a ``name[name=coast:voter]`` marker
(the identity-tag idiom of ops/indexing.py): the replication-integrity
linter (analysis/lint) reads these to tell a *sanctioned* lane collapse --
the voter's own ``lanes[0]``/``lanes[1]`` reads -- from an accidental one
that silently turns xMR into a single point of failure.  Call sites
additionally classify their vote with :func:`sync_tag` so the linter can
check voter coverage per sync class against the ProtectionConfig.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

# Tag namespace shared with analysis/lint: any ``name`` eqn whose tag
# starts with one of these marks its output as a sanctioned lane source.
TAG_VOTER = "coast:voter"
TAG_SYNC = "coast:sync:"      # coast:sync:<class>:<leaf> -- classified vote
TAG_SPOF = "coast:spof:"      # coast:spof:<fn> -- accepted single-lane call
TAG_VIEW = "coast:view:"      # boundary lane-0 views (DWC _voted_view)


def sync_tag(lanes: jax.Array, klass: str, leaf: str) -> jax.Array:
    """Identity at runtime; marks ``lanes`` as the input of a vote at sync
    class ``klass`` covering ``leaf`` (the linter's voter-coverage unit)."""
    return checkpoint_name(lanes, f"{TAG_SYNC}{klass}:{leaf}")


def lane_view(lanes: jax.Array) -> jax.Array:
    """Lane 0 of a replica set, tagged as a sanctioned boundary view --
    the DWC ``_voted_view`` read (no majority exists to vote; the final
    compare has already latched any divergence).  Without the tag the
    linter would report this deliberate read as a single point of
    failure."""
    return checkpoint_name(lanes, TAG_VIEW + "lane0")[0]


def tmr_vote(lanes: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Majority vote over 3 lanes (axis 0).

    Exactly the reference's two-instruction voter: ``select(l0==l1, l0, l2)``
    (synchronization.cpp:439-448).  With a single flipped lane the majority is
    always correct; the returned value is broadcast back to every lane by the
    caller, which is what repairs the corrupted replica (the reference stores
    the voted value through the original *and* cloned store instructions,
    syncStoreInst synchronization.cpp:476-561).
    """
    lanes = checkpoint_name(lanes, TAG_VOTER)
    l0, l1, l2 = lanes[0], lanes[1], lanes[2]
    agree01 = l0 == l1
    voted = jnp.where(agree01, l0, l2)
    miscompare = jnp.logical_not(
        jnp.logical_and(jnp.all(agree01), jnp.all(l1 == l2)))
    return voted, miscompare


def dwc_check(lanes: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Duplication-with-compare over 2 lanes.

    Detection only: the value is *not* repaired (there is no majority), the
    caller must latch ``miscompare`` into the abort lattice -- the batched
    analogue of branching to ``FAULT_DETECTED_DWC`` -> ``abort()``
    (insertErrorFunction, synchronization.cpp:1198-1267).  The OR-reduction of
    per-element compares mirrors processCallSync's OR of per-arg compares
    (synchronization.cpp:709-726).
    """
    lanes = checkpoint_name(lanes, TAG_VOTER)
    miscompare = jnp.logical_not(jnp.all(lanes[0] == lanes[1]))
    return lanes[0], miscompare


def vote(lanes: jax.Array, num_clones: int) -> Tuple[jax.Array, jax.Array]:
    """Dispatch on replica count: 3 -> TMR majority, 2 -> DWC compare."""
    if num_clones == 3:
        return tmr_vote(lanes)
    if num_clones == 2:
        return dwc_check(lanes)
    raise ValueError(f"unsupported replica count {num_clones} (COAST supports 2 or 3)")
