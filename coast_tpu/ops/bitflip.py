"""Bit-flip primitives: the TPU replacement for the QEMU/GDB fault injector.

The reference flips one bit by reading a word over the GDB remote-serial
protocol, XOR-ing a one-hot mask on the host, and writing it back
(resources/injector.py:202-207 ``flipOneBit``), at a cost of several process
round-trips per injection.  Here the flip is *part of the traced program*: a
one-hot XOR into the state pytree, selected by (leaf, lane, word, bit) indices
that arrive as device data.  Keeping the flip inside the jitted scan is also
what stops XLA from CSE-ing the three identical lanes into one (SURVEY.md §7
"Avoiding XLA de-duplication").

All injectable leaves must be 32-bit typed (int32/uint32/float32); the memory
map (coast_tpu.inject.mem) addresses them in 32-bit words, matching the
reference's word-granular memory injections (injector.py:125-200).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from coast_tpu.ir.region import State


def _flip_word(arr: jax.Array, word: jax.Array, bit: jax.Array) -> jax.Array:
    """XOR bit ``bit`` of flat 32-bit word ``word`` of ``arr`` (any shape)."""
    u32 = jax.lax.bitcast_convert_type(arr, jnp.uint32)
    flat = u32.reshape(-1)
    mask = jnp.left_shift(jnp.uint32(1), bit.astype(jnp.uint32))
    flat = flat.at[word].set(flat[word] ^ mask, mode="promise_in_bounds")
    return jax.lax.bitcast_convert_type(flat.reshape(u32.shape), arr.dtype)


def make_flipper(leaf_order: List[str]):
    """Build ``flip(state, leaf_id, lane, word, bit) -> state``.

    ``leaf_id`` indexes ``leaf_order`` (the memory-map section order); the
    dispatch is a ``lax.switch`` so the target leaf is data-dependent --
    one compiled program serves every injection in a campaign.

    For replicated leaves (leading lane axis) ``word`` addresses the flat
    words of a single lane and ``lane`` picks the replica; for shared leaves
    ``lane`` is ignored.  Replicated leaves being independently corruptible
    is the point of the lane axis: it is what the reference gets from cloned
    globals living at distinct addresses (cloning.cpp:2417-2462).
    """

    def flip(state: State, replicated: Dict[str, bool], leaf_id: jax.Array,
             lane: jax.Array, word: jax.Array, bit: jax.Array) -> State:
        def branch_for(name):
            def br(st):
                arr = st[name]
                if replicated[name]:
                    new_lane = _flip_word(arr[lane], word, bit)
                    new = arr.at[lane].set(new_lane, mode="promise_in_bounds")
                else:
                    new = _flip_word(arr, word, bit)
                return {**st, name: new}
            return br

        branches = [branch_for(n) for n in leaf_order]
        return jax.lax.switch(leaf_id, branches, state)

    return flip
