"""Bit-flip primitives: the TPU replacement for the QEMU/GDB fault injector.

The reference flips one bit by reading a word over the GDB remote-serial
protocol, XOR-ing a one-hot mask on the host, and writing it back
(resources/injector.py:202-207 ``flipOneBit``), at a cost of several process
round-trips per injection.  Here the flip is *part of the traced program*: a
one-hot XOR into the state pytree, selected by (leaf, lane, word, bit)
indices that arrive as device data.  Keeping the flip inside the jitted scan
is also what stops XLA from CSE-ing the three identical lanes into one
(SURVEY.md §7 "Avoiding XLA de-duplication").

Leaf dispatch is maskwise, not branchwise: every leaf is XORed with a mask
that is zero unless the leaf is the target (XOR 0 = identity).  That keeps
one uniform program for any target -- no ``lax.switch`` whose branches XLA
must type-match (which breaks under ``shard_map``, where only the touched
leaf would become axis-varying) -- and vectorises cleanly under ``vmap``.

All injectable leaves must be 32-bit typed (int32/uint32/float32); the
memory map (coast_tpu.inject.mem) addresses them in 32-bit words, matching
the reference's word-granular memory injections (injector.py:125-200).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from coast_tpu.ir.region import State


def make_flipper(leaf_order: List[str]):
    """Build ``flip(state, replicated, leaf_id, lane, word, bit) -> state``.

    ``leaf_id`` indexes ``leaf_order`` (the memory-map section order).  For
    replicated leaves (leading lane axis) ``word`` addresses the flat words
    of a single lane and ``lane`` picks the replica; for shared leaves
    ``lane`` is ignored.  Replicated leaves being independently corruptible
    is the point of the lane axis: it is what the reference gets from cloned
    globals living at distinct addresses (cloning.cpp:2417-2462).
    """

    def build_masks(state: State, replicated: Dict[str, bool],
                    leaf_id: jax.Array, lane: jax.Array, word: jax.Array,
                    bit: jax.Array) -> State:
        """Materialise the per-leaf one-hot XOR masks ONCE (they do not
        depend on the step index).  Inside a stepped loop the flip then
        costs one select+XOR per leaf instead of rebuilding the iota
        compares every iteration -- the in-loop rebuild measured ~2/3 of
        small-benchmark campaign runtime."""
        one = jnp.left_shift(jnp.uint32(1), bit.astype(jnp.uint32))
        masks: State = {}
        for i, name in enumerate(leaf_order):
            arr = state[name]
            sel = jnp.where(leaf_id == i, one, jnp.uint32(0))
            u32_shape = jax.eval_shape(
                lambda a: jax.lax.bitcast_convert_type(a, jnp.uint32),
                arr).shape
            nwords = 1
            for d in u32_shape:
                nwords *= d
            if replicated[name]:
                words_per_lane = nwords // arr.shape[0]
                idx = lane * words_per_lane + word
            else:
                idx = word
            masks[name] = jnp.where(
                jax.lax.iota(jnp.int32, nwords) == idx,
                sel, jnp.uint32(0)).reshape(u32_shape)
        return masks

    def apply_masks(state: State, masks: State,
                    enable: jax.Array) -> State:
        """XOR the precomputed masks in, gated by ``enable`` (identity is
        XOR 0, so the program stays uniform for vmap/shard_map)."""
        new: State = {}
        for name in leaf_order:
            arr = state[name]
            u32 = jax.lax.bitcast_convert_type(arr, jnp.uint32)
            u32 = u32 ^ jnp.where(enable, masks[name], jnp.uint32(0))
            new[name] = jax.lax.bitcast_convert_type(u32, arr.dtype)
        return new

    def flip(state: State, replicated: Dict[str, bool], leaf_id: jax.Array,
             lane: jax.Array, word: jax.Array, bit: jax.Array,
             enable: jax.Array = True) -> State:
        """``enable`` folds any fire condition (step match, not-halted) into
        the mask, so callers never need lax.cond around the flip -- identity
        is XOR 0, and the program stays uniform for shard_map/vmap.

        The one-hot is materialised as an iota-compare (word index == target
        index) rather than a scatter: dynamic-index scatter under a vmapped
        campaign batch lowers to a serialised read-modify-write on TPU and
        dominated the whole campaign runtime (measured ~10x off the toy
        benchmark's roofline); the compare+XOR is a pure vector op XLA
        fuses into the surrounding step.  One-shot composition of the two
        halves; stepped loops call them separately so the mask build is
        hoisted out of the loop."""
        return apply_masks(
            state,
            build_masks(state, replicated, leaf_id, lane, word, bit),
            enable)

    flip.build_masks = build_masks
    flip.apply_masks = apply_masks
    return flip


def noop_fault():
    """A well-formed fault that never fires: ``t = -1`` matches no
    step index, so the armed select+XOR is a per-step no-op.

    Use as a TRACED jit input when timing single runs: a zero-argument
    jitted run has only compile-time-constant inputs and XLA may fold
    the whole computation, timing buffer returns instead of compute (a
    recorded mfu_sweep row measured 85% of bf16 peak this way).
    Campaigns always run fault-armed, so the armed-but-inert path is
    also the representative per-run cost."""
    return {"leaf_id": jnp.int32(0), "lane": jnp.int32(0),
            "word": jnp.int32(0), "bit": jnp.int32(0),
            "t": jnp.int32(-1)}
