"""Region IR: the protected-dataflow-region abstraction.

COAST (the reference, /root/reference) protects a program by cloning LLVM IR
instructions in place (projects/dataflowProtection/cloning.cpp).  The TPU-native
re-expression does not mutate an instruction stream; instead a *region* is a
pure, stepped JAX program over an explicit state pytree:

    state = init()
    for t in range(max_steps):        # lowered to lax.scan
        if not done(state):
            state = step(state, t)
    errors = check(state)             # benchmark self-check (golden compare)

The state pytree is the region's *memory image* -- the analogue of the ELF
sections (.data/.bss/registers) that the reference fault-injector targets
(simulation/platform/resources/mem.py:56-85).  Each leaf carries a
:class:`LeafSpec` declaring:

  * ``kind``   -- which sync-point class writes to it map to (``mem`` for
    store-sync, ``ctrl`` for terminator-sync / loop-carried control,
    ``reg`` for loop-carried data registers, ``ro`` for read-only input).
  * ``xmr``    -- replication scope, the analogue of the ``__xMR`` /
    ``__NO_xMR`` annotations in tests/COAST.h:11-64 and the per-global
    scope lists of interface.cpp:244-362.

The stepped shape is what makes *cycle-uniform* fault injection possible on
TPU: the reference stops the guest at a uniformly random cycle
(threadFunctions.py:451-520); we flip a bit at a uniformly random step index
inside the traced scan, so an entire campaign batches as one XLA program.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

State = Dict[str, jax.Array]

# Leaf kinds -- the sync-point classes of synchronization.cpp:95-259 mapped
# onto state-pytree leaves.
KIND_MEM = "mem"    # written memory (store sync points)
KIND_REG = "reg"    # loop-carried data registers
KIND_CTRL = "ctrl"  # control state: loop counters, predicates (terminator sync)
KIND_RO = "ro"      # read-only inputs (.rodata); never written by step()
# Per-task call stacks of an RTOS kernel region (coast_tpu.rtos): memory
# semantics (store-synced when written) but its own section kind so
# campaign attribution can separate stack hits from heap/TCB hits --
# exactly the reference injector's distinct 'stack' ELF section
# (supervisor.py:340 section list).  Votes on these leaves are tagged
# with the 'stack' sync class.
KIND_STACK = "stack"
# ML-training regions (coast_tpu.train): model parameters and optimizer
# state (momentum buffers / Adam moments).  Both follow the KIND_MEM
# store rule -- written leaves get a commit-boundary vote -- but carry
# their own section kinds so campaign attribution separates weight hits
# from optimizer-moment hits (the axes the training outcome semantics
# distinguish), and their votes are tagged with the 'param' /
# 'opt_state' sync classes the lint re-derives independently.
KIND_PARAM = "param"
KIND_OPT_STATE = "opt_state"
# Sharded regions (coast_tpu.models.stencil): the in-flight halo/exchange
# buffer of a cross-chip collective -- the words that sit "on the link"
# between a ppermute send and its receive.  Memory semantics for the
# engine (a shared single-copy leaf), but its own section kind so the
# ``link`` fault model (inject/schedule.py) can target exactly the
# interconnect surface, and campaign attribution separates compute
# upsets from link upsets.
KIND_LINK = "link"

_VALID_KINDS = (KIND_MEM, KIND_REG, KIND_CTRL, KIND_RO, KIND_STACK,
                KIND_PARAM, KIND_OPT_STATE, KIND_LINK)


@dataclasses.dataclass(frozen=True)
class LeafSpec:
    """Replication/injection metadata for one state leaf.

    ``xmr=None`` defers to the region default, mirroring how COAST treats
    unannotated globals (scope rules in interface.cpp:364-532 and the
    ``__DEFAULT_NO_xMR`` region-level default of tests/COAST.h).
    """

    kind: str = KIND_MEM
    xmr: Optional[bool] = None
    inject: bool = True   # is this leaf part of the injectable memory map?
    # Opt this leaf out of SoR verification, mirroring the parameterized
    # ``no-verify-<glbl>`` annotation (interface.cpp:364-532).
    no_verify: bool = False
    # Marks call-stack / return-address state: the target of the
    # experimental ``-protectStack`` voting on llvm.returnaddress copies
    # (insertStackProtection, synchronization.cpp:1579-1812).  When
    # ProtectionConfig.protect_stack is set these leaves are voted every
    # step regardless of the per-kind sync flags.
    stack: bool = False
    # Shared (non-xMR) leaves only: declare that writes to this leaf
    # deliberately do NOT get the engine's SoR-crossing vote -- the region
    # carries per-replica data through the shared leaf itself (e.g. a
    # replicated halo buffer exchanged over the link under the
    # exchange-then-vote placement, where voting happens on the RECEIVE
    # side after the collective).  The engine commits ``out[0]`` raw; the
    # replication linter exempts the leaf from expecting a 'sor_crossing'
    # vote, and the lane-isolation prover honestly reports the collapse.
    # Setting this on a replicated leaf is a build error.
    unvoted_crossing: bool = False
    # KIND_STACK leaves only: the flat word index (within each lane) of the
    # canary/watermark word guarding the stack -- the FreeRTOS
    # tskSTACK_FILL_BYTE pattern at the stack limit that
    # taskCHECK_FOR_STACK_OVERFLOW inspects.  Pure metadata for tooling
    # (lint preflight verifies the init image holds the declared canary);
    # the region's ``stack_guard`` owns the runtime check.
    canary_word: Optional[int] = None

    def __post_init__(self):
        if self.kind not in _VALID_KINDS:
            raise ValueError(f"bad leaf kind {self.kind!r}; one of {_VALID_KINDS}")
        if self.canary_word is not None and self.kind != KIND_STACK:
            raise ValueError(
                f"canary_word is only meaningful on {KIND_STACK!r} leaves, "
                f"not {self.kind!r}")


class FnNamespace:
    """Attribute/byname access to a region's sub-functions, plus a log of
    call-boundary miscompares the engine's wrappers append to during
    tracing (the per-call compare results of processCallSync,
    synchronization.cpp:563-738)."""

    def __init__(self, fns: Dict[str, Callable]):
        self._fns = fns
        self.miscompares = []   # bool tracers appended by scope wrappers

    def __getattr__(self, name: str) -> Callable:
        try:
            return self.__dict__["_fns"][name]
        except KeyError:
            raise AttributeError(
                f"region has no function {name!r} "
                f"(have: {', '.join(sorted(self.__dict__['_fns']))})") from None

    def __getitem__(self, name: str) -> Callable:
        return getattr(self, name)


@dataclasses.dataclass
class Region:
    """A protected dataflow region (the unit `opt -TMR` operates on).

    Semantics contract (all callables must be jit-traceable, static shapes):

      * ``init()``                -> state pytree (dict name -> array)
      * ``step(state, t)``        -> state; one micro-step of the program.
        ``t`` is an int32 scalar tracer.  Must be pure.
      * ``done(state)``           -> bool scalar; program has terminated.
      * ``check(state)``          -> int32 scalar: the benchmark's own error
        count (golden compare), the analogue of the guest's
        ``C: E: F: T:`` UART line field ``E`` (resources/decoder.py:66).
      * ``output(state)``         -> flat uint32 vector of the result, used
        for SDC attribution in analysis.

    ``nominal_steps`` is the fault-free runtime in steps (the injection
    window upper bound, like ``maxSleepTime`` in resources/benchmarks.py:27-73);
    ``max_steps`` is the watchdog bound (gdbHandlers.py:22-47): a run that has
    not set ``done`` by then is classified a timeout (DUE).
    """

    name: str
    init: Callable[[], State]
    step: Callable[[State, jax.Array], State]
    done: Callable[[State], jax.Array]
    check: Callable[[State], jax.Array]
    output: Callable[[State], jax.Array]
    nominal_steps: int
    max_steps: int
    spec: Dict[str, LeafSpec]
    default_xmr: bool = True
    # Optional control-flow graph for CFCSS (coast_tpu.ir.graph.BlockGraph);
    # regions without one can still be TMR/DWC protected.
    graph: Any = None
    # Named sub-functions (jittable callables) the step may invoke through
    # the ``fns`` namespace of a 3-argument ``step(state, t, fns)``.  These
    # are the region's "module functions": the unit the function-scope
    # lists (-ignoreFns/-cloneFns/-skipLibCalls/-replicateFnCalls/
    # -protectedLibFn/-cloneAfterCall/-cloneReturn, interface.cpp:82-164)
    # name and the engine re-wraps per scope class
    # (passes.dataflow_protection._fn_scope_of).
    functions: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    # Extra metadata (benchmark golden values etc.)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # RTOS kernel guards (None for regions without a kernel model).  Both
    # take a single-lane state view and return a bool scalar (True =
    # tripped).  The engine evaluates them PER LANE on the stepped,
    # pre-vote state -- the replicated kernel's own checks run inside each
    # replica in the reference rtos build, firing before any store-sync
    # vote repairs the corruption they saw:
    #   * ``stack_guard``: taskCHECK_FOR_STACK_OVERFLOW -- blown
    #     canary/watermark word or saved stack pointer out of bounds;
    #     latches DUE_STACK_OVERFLOW (decoder.py:69 hook line class).
    #   * ``assert_guard``: configASSERT -- a kernel/task invariant does
    #     not hold; latches DUE_ASSERT (decoder.py:67 class).
    stack_guard: Optional[Callable[[State], jax.Array]] = None
    assert_guard: Optional[Callable[[State], jax.Array]] = None
    # Training-workload regions (coast_tpu.train): outcome probe over the
    # VOTED final state view, returning an int32 scalar --
    #   0 = the loss trajectory never left tolerance of the fault-free
    #       (golden) trajectory,
    #   1 = it deviated but re-converged for the final heal window
    #       (transient perturbation the training dynamics absorbed),
    #   2 = it was still outside tolerance at the end (persistent
    #       divergence).
    # The classifier uses it to split the SDC bucket of a completed run
    # into TRAIN_SELF_HEAL vs TRAIN_SDC; regions without a probe keep
    # the pre-training taxonomy bit-for-bit (classify only reads the
    # probe when the record carries it).
    train_probe: Optional[Callable[[State], jax.Array]] = None

    def leaf_is_xmr(self, name: str) -> bool:
        """Resolve the replication scope of a leaf (annotation > default)."""
        s = self.spec[name]
        return self.default_xmr if s.xmr is None else s.xmr

    def wants_fns(self) -> bool:
        """True when step has the 3-argument form ``step(state, t, fns)``."""
        try:
            return len(inspect.signature(self.step).parameters) >= 3
        except (TypeError, ValueError):
            return False

    def bound_step(self, fns: Any = None) -> Callable:
        """The 2-argument step with the function namespace bound.

        With ``fns=None`` the raw sub-functions are bound unwrapped -- the
        view analysis passes and unprotected execution see (the original
        module before cloning).  The protection engine passes its own
        namespace with each function wrapped per its scope class."""
        if not self.wants_fns():
            return self.step
        if fns is None:
            fns = FnNamespace(dict(self.functions))
        return lambda state, t: self.step(state, t, fns)

    def validate(self) -> None:
        """Shape/spec sanity check; the lightweight analogue of
        verifyCloningSuccess (cloning.cpp:2305-2376)."""
        state = jax.eval_shape(self.init)
        missing = set(state) - set(self.spec)
        extra = set(self.spec) - set(state)
        if missing or extra:
            raise ValueError(
                f"region {self.name}: spec/state mismatch "
                f"(missing specs {sorted(missing)}, dangling specs {sorted(extra)})")
        stepped = jax.eval_shape(self.bound_step(), state, jnp.int32(0))
        for k in state:
            if (state[k].shape, state[k].dtype) != (stepped[k].shape, stepped[k].dtype):
                raise ValueError(
                    f"region {self.name}: step() changed leaf {k!r} from "
                    f"{state[k].dtype}{state[k].shape} to "
                    f"{stepped[k].dtype}{stepped[k].shape}")
        if self.max_steps < self.nominal_steps:
            raise ValueError("max_steps must be >= nominal_steps")

    # ------------------------------------------------------------------
    # Unprotected reference execution (the 'BOARD=x86, no OPT_PASSES' path,
    # tests/makefiles/Makefile.compile.x86:80-124).
    # ------------------------------------------------------------------
    def run_unprotected(self) -> State:
        state = self.init()
        step = self.bound_step()

        def body(carry, t):
            state, halted = carry
            new = step(state, t)
            new = jax.tree.map(lambda o, n: jnp.where(halted, o, n), state, new)
            halted = jnp.logical_or(halted, self.done(new))
            return (new, halted), None

        (state, _), _ = jax.lax.scan(
            body, (state, jnp.bool_(False)),
            jnp.arange(self.max_steps, dtype=jnp.int32))
        return state
