"""Block graphs: the control-flow-graph view of a region, for CFCSS.

The reference builds a BBNode graph over every function's basic blocks
(populateGraph, projects/CFCSS/CFCSS.cpp:149-185; struct BBNode
CFCSS.h:44-61).  A stepped region's analogue is coarser but the same shape:
the region declares its logical blocks and legal transitions, plus a
``block_of(state)`` classifier that says which block the next step executes
given the current (control) state.  Node 0 is the entry pseudo-block (the
state before step 0).

Illegal control flow -- a corrupted loop counter teleporting execution to a
block with no incoming edge from the current one -- is exactly what the
runtime signature check detects.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence, Tuple

import jax

from coast_tpu.ir.region import State


@dataclasses.dataclass
class BlockGraph:
    """names[0] is the entry pseudo-block; edges are (u, v) node indices;
    block_of maps (control) state -> int32 node index of the block the next
    step will execute (or a terminal block once done)."""

    names: List[str]
    edges: List[Tuple[int, int]]
    block_of: Callable[[State], jax.Array]

    @property
    def n(self) -> int:
        return len(self.names)

    def validate(self) -> None:
        for u, v in self.edges:
            if not (0 <= u < self.n and 0 <= v < self.n):
                raise ValueError(f"edge ({u},{v}) out of range for {self.n} blocks")
        targets = {v for _, v in self.edges}
        for v in range(1, self.n):
            if v not in targets:
                raise ValueError(f"block {v} ({self.names[v]}) is unreachable")
