"""Continuous-protection serving: a protected inference service that
measures its own SDC rate under live traffic (ROADMAP item #2).

Offline campaigns answer "what WAS this program's SDC rate"; a serving
system needs "what IS it, right now, on the binary actually taking
traffic".  This package fuses the two: every compiled dispatch packs
live **request lanes** and spare-capacity **injection lanes** into one
protected batch (``vmap`` rows of the same jitted step the campaign
engine runs), so the service continuously re-measures its own SDC/DUE
rates on the exact program serving users -- no shadow fleet, no stale
offline numbers.

The pieces, each reusing a subsystem from PRs 8-16:

  * :mod:`~coast_tpu.serve.admission` -- deadline-ordered request
    admission.  Load shedding shrinks the injection share first and the
    request share never (the measurement is the slack consumer, not the
    traffic).
  * :mod:`~coast_tpu.serve.engine` -- the batched dispatch loop:
    per-request strategy selection by latency budget (DWC detect-and-
    retry when a rerun fits the SLA, TMR when it doesn't), the
    injection-lane campaign journaled crash-safe like any other
    (:mod:`coast_tpu.inject.journal`), and the lane-isolation
    noninterference prover (:mod:`coast_tpu.analysis.propagation`) as a
    build gate -- a refuted proof refuses to start serving -- plus a
    runtime assert that armed-lane indices never intersect the response
    gather.
  * :mod:`~coast_tpu.serve.metrics` -- the serving hub: injection-lane
    outcomes feed :class:`~coast_tpu.obs.metrics.CampaignMetrics` /
    :class:`~coast_tpu.obs.slo.SLOSet` live, so ``/status`` and
    Prometheus report the service's own SDC rate (Wilson CI), DUE rate,
    availability, and p50/p99 dispatch latency as SLOs with burn
    verdicts.
  * :mod:`~coast_tpu.serve.front` -- the stdlib HTTP front (the
    ``obs/serve.py`` server shape) and the ``python -m coast_tpu
    serve`` CLI.

FastFlip (arXiv:2403.13989) motivates spending injection capacity
continuously where the evidence is thin; FuzzyFlow (arXiv:2306.16178)
motivates the differential contract the smoke driver pins: served
responses are bit-identical with injection lanes on vs off.
"""

from coast_tpu.serve.admission import AdmissionQueue, ServeRequest
from coast_tpu.serve.engine import (IsolationRefusedError, LaneLeakError,
                                    ServeEngine)
from coast_tpu.serve.front import ServeFront
from coast_tpu.serve.metrics import ServeMetrics

__all__ = [
    "AdmissionQueue", "ServeRequest", "ServeEngine", "ServeFront",
    "ServeMetrics", "IsolationRefusedError", "LaneLeakError",
]
