"""The serving dispatch loop: request lanes + injection lanes, one batch.

Every compiled dispatch is the campaign engine's shape -- one
``jax.jit(jax.vmap(run_one))`` over a fixed-size batch -- but its rows
are split three ways:

====================  ===================================================
rows ``[0, r)``       live REQUEST lanes: disarmed faults
                      (:func:`~coast_tpu.ops.bitflip.noop_fault`,
                      ``t = -1`` matches no step), outputs gathered into
                      responses;
rows ``[r, r+i)``     INJECTION lanes: the next ``i`` rows of a seeded
                      campaign schedule, outcomes journaled + fed to the
                      metrics hub -- the service's continuous
                      self-measurement;
rows ``[r+i, B)``     padding: disarmed, uncounted (every dispatch hits
                      the one compiled program).
====================  ===================================================

``vmap`` rows are independent by construction, which is what makes the
co-packing sound -- but "by construction" is exactly what a protection
bug (a voter reading across lanes) breaks, so the engine does not take
it on faith: the lane-isolation noninterference prover
(:func:`~coast_tpu.analysis.propagation.prove_isolation`) runs at build
time and a refuted proof REFUSES to serve
(:class:`IsolationRefusedError`); at runtime every dispatch re-checks
that the armed fault rows are exactly the injection span before any
response is gathered (:class:`LaneLeakError` + flight-recorder bundle
otherwise).  The differential contract follows: served responses are
bit-identical with injection lanes on vs off.

Strategy selection is per request, by latency budget: DWC
(detect-and-retry) when a rerun still fits the SLA, TMR (vote-through)
when it does not; a DWC detection whose retry no longer fits escalates
to TMR once, and the retry path is journaled like any campaign batch.
Injection work is backed by the fleet
:class:`~coast_tpu.fleet.queue.CampaignQueue` when one is attached --
the engine enqueues its standing measurement campaigns as queue items,
claims them back, journals them at the queue's canonical paths, and
lands worker-shaped done records, so fleet telemetry aggregates the
serving measurement like any campaign worker's.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from coast_tpu.inject import classify as cls
from coast_tpu.inject.journal import (CampaignJournal, config_fingerprint,
                                      schedule_fingerprint)
from coast_tpu.inject.mem import MemoryMap
from coast_tpu.inject.resilience import watchdog_collect
from coast_tpu.inject.schedule import generate
from coast_tpu.inject.supervisor import build_program, section_filter
from coast_tpu.obs import flightrec
from coast_tpu.obs.metrics import Histogram
from coast_tpu.ops.bitflip import noop_fault
from coast_tpu.serve.admission import (REJECT_DEADLINE, REJECT_SLA,
                                       AdmissionQueue, ServeRequest)
from coast_tpu.serve.metrics import ServeMetrics

__all__ = ["ServeEngine", "IsolationRefusedError", "LaneLeakError"]


class IsolationRefusedError(RuntimeError):
    """The lane-isolation prover refuted noninterference for a serving
    program: an injected lane could leak into a served response, so the
    engine refuses to start."""


class LaneLeakError(RuntimeError):
    """Runtime lane-leak assertion: an armed fault row landed outside
    the injection span of a dispatch.  Raised before any response is
    gathered from that batch."""


#: Classes a DWC lane treats as "the protection detected something":
#: the run did not complete cleanly, so the request must be re-run
#: (or escalated) rather than answered.
_DWC_DETECTED = frozenset(cls.DUE_CLASSES) | {"invalid"}


class _Lane:
    """Per-strategy serving state: the built program, its proof, its
    compiled batch fn, and the injection-campaign cursor/journal."""

    def __init__(self, strategy: str):
        self.strategy = strategy
        self.prog = None
        self.proof = None
        self.mmap: Optional[MemoryMap] = None
        self.run_batch: Optional[Callable] = None
        self.train = False
        # Standing (standalone) injection campaign.
        self.sched = None
        self.cursor = 0
        self.counts = np.zeros(cls.NUM_CLASSES, dtype=np.int64)
        self.journal: Optional[CampaignJournal] = None
        self.dispatch_s = 0.0
        self.t_last_collect = 0.0
        self.est_s: Optional[float] = None   # EWMA dispatch wall clock
        # Queue-backed injection item (None between items).
        self.item = None
        self.item_sched = None
        self.item_cursor = 0
        self.item_counts = np.zeros(cls.NUM_CLASSES, dtype=np.int64)
        self.item_codes: List[np.ndarray] = []
        self.item_journal: Optional[CampaignJournal] = None
        self.item_hists: Dict[str, Histogram] = {}
        self.item_t0 = 0.0
        self.item_lease_t = 0.0

    def inject_remaining(self) -> int:
        if self.item is not None:
            return int(self.item_spec_n() - self.item_cursor)
        if self.sched is None:
            return 0
        return int(len(self.sched) - self.cursor)

    def item_spec_n(self) -> int:
        return int(self.item.spec["n"]) if self.item is not None else 0


class ServeEngine:
    """Batched protected inference with continuous self-measurement.

    Construction IS the gate: both strategy programs are built
    (``build_program``, the opt-CLI parser's own flag semantics) and
    each must pass the lane-isolation prover before the engine exists.
    ``start()`` launches the dispatch loop; ``submit()`` is the request
    path (the HTTP front's handler body and the loadtest's inner loop).

    ``inject_share`` is the fraction of each batch offered to injection
    lanes (0.0 turns self-measurement off -- the differential contract's
    control arm).  ``journal_dir`` makes the standing injection
    campaigns crash-safe (one journal per strategy, resumed bit-for-bit
    on restart); ``queue`` attaches a fleet CampaignQueue instead, with
    items enqueued/claimed/completed like a worker's.

    ``detect_hook(req, code)`` is the DWC detection seam for tests and
    chaos drills: called for every DWC request row with its class code,
    returning True forces the detect-and-retry path even though request
    rows carry disarmed faults (reality: a detection surfaces as a DUE
    class code, which is also honored).
    """

    def __init__(self, bench: str,
                 batch_size: int = 64,
                 inject_share: float = 0.5,
                 sla_default_s: float = 0.25,
                 retry_factor: float = 2.0,
                 seed: int = 0,
                 inject_n: int = 1_000_000,
                 section: str = "memory",
                 journal_dir: Optional[str] = None,
                 queue=None,
                 metrics: Optional[ServeMetrics] = None,
                 slo: Optional[object] = None,
                 wedge_timeout_s: float = 0.0,
                 idle_throttle_s: float = 0.0,
                 unroll: int = 1,
                 strategies: Tuple[str, ...] = ("DWC", "TMR")):
        if not 0.0 <= float(inject_share) <= 1.0:
            raise ValueError(f"inject_share must be in [0, 1], got "
                             f"{inject_share}")
        self.bench = bench
        self.batch_size = int(batch_size)
        self.inject_share = float(inject_share)
        self.sla_default_s = float(sla_default_s)
        self.retry_factor = float(retry_factor)
        self.seed = int(seed)
        self.inject_n = int(inject_n)
        self.section = section
        self.journal_dir = journal_dir
        self.queue = queue
        self.worker_id = f"serve-{os.getpid()}"
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.idle_throttle_s = float(idle_throttle_s)
        self.unroll = int(unroll)
        self.metrics = metrics if metrics is not None else ServeMetrics(
            slo=slo)
        if slo is not None and self.metrics.hub.slo_set is None:
            from coast_tpu.obs.slo import SLOSet
            self.metrics.hub.slo_set = (SLOSet.parse(slo)
                                        if isinstance(slo, str) else slo)
        self.detect_hook: Optional[Callable] = None
        self.admission = AdmissionQueue(strategies)
        self.error: Optional[str] = None
        self._rid = 0
        self._rid_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._noop: Dict[str, int] = {
            k: int(v) for k, v in noop_fault().items()}

        self._lanes: Dict[str, _Lane] = {}
        for strategy in strategies:
            self._lanes[strategy] = self._build_lane(strategy)
        self.benchmark = self._lanes[strategies[0]].prog.region.name
        if self.queue is not None:
            self._enqueue_standing_items()

    # -- build + prover gate -------------------------------------------------
    def _build_lane(self, strategy: str) -> _Lane:
        from coast_tpu.analysis.propagation import prove_isolation
        lane = _Lane(strategy)
        lane.prog, built = build_program(self.bench, f"-{strategy}")
        if built != strategy:
            raise ValueError(f"-{strategy} built a {built!r} program")
        lane.proof = prove_isolation(lane.prog, strategy=strategy)
        flightrec.record("serve_prover", strategy=strategy,
                         holds=lane.proof.holds,
                         vacuous=lane.proof.vacuous,
                         leak_paths=lane.proof.total_leak_paths)
        if not lane.proof.holds:
            raise IsolationRefusedError(
                f"lane-isolation proof REFUTED for {self.bench} "
                f"-{strategy}: an injected lane can reach a served "
                f"response; refusing to serve.\n{lane.proof.format()}")
        lane.train = lane.prog.region.train_probe is not None
        lane.mmap = MemoryMap(lane.prog,
                              section_filter(lane.prog, self.section))
        out_words = int(np.prod(jax.eval_shape(
            lane.prog.region.output,
            jax.eval_shape(lane.prog.region.init)).shape))
        prog, unroll = lane.prog, self.unroll

        def run_one(fault):
            rec = prog.run(fault, unroll=unroll)
            # Response digest, folded in-graph from the voted output:
            # position-mixed XOR so permuted corruptions cannot cancel.
            # Requests attest "protected compute ran, output was X"
            # without shipping the whole output vector per row.
            out = rec["output"].astype(jnp.uint32)
            idx = jnp.arange(out.shape[0], dtype=jnp.uint32)
            mixed = out * ((idx * jnp.uint32(2654435761))
                           | jnp.uint32(1))
            digest = jax.lax.reduce(mixed, jnp.uint32(0),
                                    jnp.bitwise_xor, (0,))
            return {"code": cls.classify(rec, out_words),
                    "errors": rec["errors"],
                    "corrected": rec["corrected"],
                    "steps": rec["steps"],
                    "digest": digest}

        lane.run_batch = jax.jit(jax.vmap(run_one))
        if self.inject_share > 0.0:
            lane.sched = generate(lane.mmap, self.inject_n, self.seed,
                                  lane.prog.region.nominal_steps)
            if self.journal_dir:
                os.makedirs(self.journal_dir, exist_ok=True)
                path = os.path.join(self.journal_dir,
                                    f"serve-{strategy}.journal")
                lane.journal = CampaignJournal.open(
                    path, self._journal_header(lane, lane.sched,
                                               self.seed, self.inject_n))
                self._replay(lane)
        return lane

    def _journal_header(self, lane: _Lane, sched, seed: int,
                        n: int) -> Dict[str, object]:
        """The campaign journal identity block, mode ``serve``: a serve
        journal resumed under a different program, strategy, protection
        config, seed, or schedule refuses exactly like a campaign's."""
        return {"mode": "serve",
                "benchmark": lane.prog.region.name,
                "strategy": lane.strategy,
                "config_sha": config_fingerprint(lane.prog.cfg),
                "seed": int(seed), "n": int(n), "start_num": 0,
                "batch_size": self.batch_size,
                "schedule_sha": schedule_fingerprint(sched)}

    def _replay(self, lane: _Lane) -> None:
        """Resume the standing campaign from its journal's contiguous
        batch prefix: cursor + cumulative counts come back exactly, so
        the restarted service injects precisely the rows the killed one
        never collected (the SIGKILL-restart bit-for-bit guarantee)."""
        prefix = lane.journal.batch_prefix(0, self.inject_n)
        if not prefix:
            return
        lane.cursor = int(prefix[0]["lo"]) + sum(
            int(r["n"]) for r in prefix)
        for name, v in prefix[-1]["counts"].items():
            lane.counts[cls.CLASS_NAMES.index(name)] = int(v)
        flightrec.record("serve_journal_replay", strategy=lane.strategy,
                         batches=len(prefix), cursor=lane.cursor)

    # -- fleet-queue backing -------------------------------------------------
    def _enqueue_standing_items(self) -> None:
        """Enqueue this service's standing measurement campaigns as
        ordinary fleet items (one per strategy) -- claimed back below,
        journaled at the queue's canonical paths, completed with
        worker-shaped done records, so fleet telemetry sees serving
        self-measurement exactly like campaign work."""
        from coast_tpu.fleet.queue import item_spec
        for strategy in self._lanes:
            self.queue.enqueue(item_spec(
                self.bench, self.inject_n, seed=self.seed,
                opt_passes=f"-{strategy}", section=self.section,
                batch_size=self.batch_size))

    def _claim_item(self, lane: _Lane) -> None:
        """Claim the oldest pending item if it matches this lane; a
        non-matching head is left alone (a dedicated serve queue only
        ever holds this engine's own items, so in the common deployment
        the head always matches)."""
        if self.queue is None or lane.item is not None:
            return
        head = self.queue.items("pending")
        if not head:
            return
        spec = head[0].get("spec", head[0])
        if not self._item_matches(lane, spec):
            return
        item = self.queue.claim(self.worker_id, lease_s=60.0)
        if item is None:
            return
        if not self._item_matches(lane, item.spec):
            # Raced with another enqueuer; serve it on the lane it
            # names instead.
            other = self._lanes.get(self._spec_strategy(item.spec))
            if other is None or other.item is not None:
                self.queue.fail(item.id, self.worker_id,
                                "serve engine cannot run this spec")
                return
            lane = other
        lane.item = item
        lane.item_sched = generate(
            lane.mmap, int(item.spec["n"]), int(item.spec["seed"]),
            lane.prog.region.nominal_steps)
        lane.item_cursor = 0
        lane.item_counts[:] = 0
        lane.item_codes = []
        lane.item_hists = {"device": Histogram(), "gap": Histogram()}
        lane.item_t0 = time.monotonic()
        lane.item_lease_t = time.monotonic()
        lane.item_journal = CampaignJournal.open(
            self.queue.journal_path(item.id),
            self._journal_header(lane, lane.item_sched,
                                 int(item.spec["seed"]),
                                 int(item.spec["n"])))
        prefix = lane.item_journal.batch_prefix(0, int(item.spec["n"]))
        for rec in prefix:
            codes = np.asarray(rec["codes"], dtype=np.int32)
            lane.item_codes.append(codes)
            lane.item_cursor += int(rec["n"])
        if prefix:
            for name, v in prefix[-1]["counts"].items():
                lane.item_counts[cls.CLASS_NAMES.index(name)] = int(v)
        flightrec.record("serve_item_claimed", item=item.id,
                         strategy=lane.strategy, resumed=len(prefix))

    @staticmethod
    def _spec_strategy(spec: Dict[str, object]) -> str:
        opt = str(spec.get("opt_passes", ""))
        if "-TMR" in opt.split():
            return "TMR"
        if "-DWC" in opt.split():
            return "DWC"
        return "unprotected"

    def _item_matches(self, lane: _Lane, spec: Dict[str, object]) -> bool:
        return (spec.get("benchmark") == self.bench
                and self._spec_strategy(spec) == lane.strategy
                and str(spec.get("fault_model", "single")) == "single"
                and not spec.get("equiv")
                and str(spec.get("collect", "dense")) == "dense"
                and int(spec.get("start_num", 0)) == 0)

    def _complete_item(self, lane: _Lane) -> None:
        codes = (np.concatenate(lane.item_codes)
                 if lane.item_codes else np.zeros(0, np.int32))
        from coast_tpu.fleet.worker import codes_sha256
        counts = cls.counts_dict(lane.item_counts, train=lane.train)
        seconds = time.monotonic() - lane.item_t0
        result = {
            "benchmark": lane.prog.region.name,
            "strategy": lane.strategy,
            "injections": int(lane.item_cursor),
            "seconds": round(seconds, 6),
            "counts": counts,
            "codes_sha256": codes_sha256(codes),
            "cache_event": "serve",
            "worker": self.worker_id,
            "summary": {
                "benchmark": lane.prog.region.name,
                "strategy": lane.strategy,
                "n": int(lane.item_cursor),
                "counts": counts,
                "profile": {
                    "device_seconds_histogram":
                        lane.item_hists["device"].snapshot(),
                    "host_gap_seconds_histogram":
                        lane.item_hists["gap"].snapshot(),
                },
            },
        }
        lane.item_journal.close()
        self.queue.complete(lane.item.id, self.worker_id, result)
        flightrec.record("serve_item_done", item=lane.item.id,
                         strategy=lane.strategy,
                         injections=int(lane.item_cursor))
        lane.item = None
        lane.item_journal = None
        lane.item_sched = None

    # -- request path --------------------------------------------------------
    def choose_strategy(self, sla_s: float) -> str:
        """Latency-budget strategy selection: DWC (detect-and-retry)
        when a rerun still fits the SLA, TMR (vote-through, no rerun)
        when it does not.  The estimate is the DWC lane's EWMA dispatch
        wall clock (a conservative 50 ms before the first dispatch)."""
        if "DWC" not in self._lanes:
            return next(iter(self._lanes))
        if "TMR" not in self._lanes:
            return "DWC"
        est = self._lanes["DWC"].est_s
        est = 0.05 if est is None else est
        return ("DWC" if sla_s >= self.retry_factor * est else "TMR")

    def submit(self, payload: str, sla_s: Optional[float] = None,
               strategy: Optional[str] = None) -> ServeRequest:
        """Admit one request (non-blocking); the caller waits on
        ``req.done`` and reads ``req.response`` / ``req.error``."""
        if self.error:
            raise RuntimeError(f"serve engine failed: {self.error}")
        sla = float(sla_s) if sla_s is not None else self.sla_default_s
        now = time.monotonic()
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        req = ServeRequest(rid=rid, payload=str(payload), sla_s=sla,
                           deadline=now + sla, t_submit=now,
                           strategy=(strategy
                                     or self.choose_strategy(sla)),
                           pinned=strategy is not None)
        self.admission.submit(req)
        self.metrics.note_admitted(req.strategy)
        flightrec.record("serve_admit", rid=rid, strategy=req.strategy,
                         sla_s=sla)
        return req

    def _reject(self, req: ServeRequest, reason: str) -> None:
        req.error = reason
        self.metrics.note_rejected(reason)
        flightrec.record("serve_reject", rid=req.rid, reason=reason,
                         strategy=req.strategy)
        req.done.set()

    # -- batch packing + the runtime lane-leak assert ------------------------
    def _pack(self, lane: _Lane, reqs: List[ServeRequest]
              ) -> Tuple[Dict[str, jax.Array], int, int, int]:
        """Pack one dispatch: request rows [0, r), injection rows
        [r, r+i), disarmed padding after.  Returns (fault, r, i, shed).
        The injection share yields to request pressure, never the other
        way around."""
        B = self.batch_size
        r = len(reqs)
        want = int(round(self.inject_share * B))
        fit = min(want, B - r)
        shed = want - fit
        i = min(fit, lane.inject_remaining())
        if lane.item is not None:
            cols = lane.item_sched.slice(
                lane.item_cursor, lane.item_cursor + i).device_arrays()
        elif i:
            cols = lane.sched.slice(
                lane.cursor, lane.cursor + i).device_arrays()
        else:
            cols = {}
        fault: Dict[str, jax.Array] = {}
        for key, noop_val in self._noop.items():
            col = np.full(B, noop_val, dtype=np.int32)
            if i:
                col[r:r + i] = np.asarray(cols[key], dtype=np.int32)
            fault[key] = jnp.asarray(col)
        # Runtime lane-leak assert, derived from the ACTUAL dispatch
        # inputs (not the intent): an armed row is any row whose fault
        # fires at some step (t >= 0; the disarmed noop is t = -1).
        # Armed rows must be exactly the injection span -- anything
        # else means an injected fault shares a row with a response
        # gather, the one thing the prover says cannot propagate and
        # the packer must never permit positionally.
        armed = np.flatnonzero(np.asarray(fault["t"]) >= 0)
        ok = bool(np.all((armed >= r) & (armed < r + i)))
        self.metrics.note_lane_leak_check(violated=not ok)
        if not ok:
            flightrec.record("serve_lane_leak", strategy=lane.strategy,
                             r=r, i=i, armed=armed.tolist()[:32])
            flightrec.current().dump("serve_lane_leak",
                                     extra={"strategy": lane.strategy,
                                            "r": r, "i": i})
            raise LaneLeakError(
                f"armed fault rows {armed.tolist()[:8]} outside the "
                f"injection span [{r}, {r + i}) of a {lane.strategy} "
                "dispatch")
        return fault, r, i, shed

    # -- one dispatch --------------------------------------------------------
    def _dispatch(self, lane: _Lane, reqs: List[ServeRequest]) -> None:
        fault, r, i, shed = self._pack(lane, reqs)
        saturated = (r >= self.batch_size
                     and self.inject_share > 0.0)
        self.metrics.note_dispatch(i, shed, saturated)
        if shed:
            flightrec.record("serve_shed", strategy=lane.strategy,
                             shed_lanes=shed, requests=r)
        t0 = time.monotonic()
        gap = (t0 - lane.t_last_collect) if lane.t_last_collect else 0.0
        pending = lane.run_batch(fault)
        out = watchdog_collect(lambda: jax.device_get(pending),
                               self.wedge_timeout_s)
        t1 = time.monotonic()
        lane.t_last_collect = t1
        dt = t1 - t0
        lane.dispatch_s += dt
        lane.est_s = dt if lane.est_s is None else (0.7 * lane.est_s
                                                    + 0.3 * dt)
        flightrec.record("serve_dispatch", strategy=lane.strategy,
                         requests=r, inject=i, seconds=round(dt, 6))
        self._finish_requests(lane, reqs, out, t1)
        if i:
            self._finish_injection(lane, out, r, i, gap, dt)

    def _finish_requests(self, lane: _Lane, reqs: List[ServeRequest],
                         out: Dict[str, np.ndarray], now: float) -> None:
        for k, req in enumerate(reqs):
            code = int(out["code"][k])
            name = cls.CLASS_NAMES[code]
            detected = (lane.strategy == "DWC"
                        and (name in _DWC_DETECTED
                             or (self.detect_hook is not None
                                 and self.detect_hook(req, code))))
            if detected:
                self._detected(lane, req, now)
                continue
            req.response = {
                "id": req.rid,
                "payload": req.payload,
                "digest": int(out["digest"][k]),
                "class": name,
                "strategy": lane.strategy,
            }
            req.done.set()
            self.metrics.note_served(now - req.t_submit)

    def _detected(self, lane: _Lane, req: ServeRequest,
                  now: float) -> None:
        """DWC detected a fault on a request row: rerun if a rerun still
        fits the SLA, escalate to TMR if only a single (vote-through)
        attempt does, reject otherwise.  The retry is journaled like any
        campaign record -- the service's own error path leaves the same
        durable trail a campaign batch does."""
        budget = req.budget_s(now)
        est = lane.est_s if lane.est_s is not None else 0.05
        j = lane.item_journal or lane.journal
        if budget >= self.retry_factor * est:
            req.retries += 1
            self.metrics.note_retry()
            if j is not None:
                j.append({"kind": "serve_retry", "rid": req.rid,
                          "attempt": req.retries,
                          "strategy": lane.strategy})
            flightrec.record("serve_retry", rid=req.rid,
                             attempt=req.retries)
            self.admission.requeue(req)
        elif ("TMR" in self._lanes and not req.escalated
              and budget >= est):
            req.strategy = "TMR"
            req.escalated = True
            self.metrics.note_escalation()
            if j is not None:
                j.append({"kind": "serve_escalate", "rid": req.rid,
                          "from": lane.strategy, "to": "TMR"})
            flightrec.record("serve_escalate", rid=req.rid,
                             budget_s=round(budget, 6),
                             est_s=round(est, 6))
            self.admission.requeue(req)
        else:
            self._reject(req, REJECT_SLA)

    def _finish_injection(self, lane: _Lane, out: Dict[str, np.ndarray],
                          r: int, i: int, gap: float, dt: float) -> None:
        codes = np.asarray(out["code"][r:r + i], dtype=np.int32)
        binc = np.bincount(codes, minlength=cls.NUM_CLASSES)
        sl = slice(r, r + i)
        batch_out = {k: np.asarray(out[k][sl]) for k in
                     ("code", "errors", "corrected", "steps")}
        if lane.item is not None:
            lo = lane.item_cursor
            lane.item_counts += binc
            lane.item_cursor += i
            lane.item_codes.append(codes)
            lane.item_hists["device"].observe(dt)
            lane.item_hists["gap"].observe(gap)
            counts = cls.counts_dict(lane.item_counts, train=lane.train)
            lane.item_journal.append_batch(
                lo, batch_out, counts,
                {"dispatch": round(lane.dispatch_s, 6)})
            if lane.item_cursor >= lane.item_spec_n():
                self._complete_item(lane)
            elif time.monotonic() - lane.item_lease_t > 20.0:
                self.queue.renew(lane.item.id, self.worker_id,
                                 lease_s=60.0)
                lane.item_lease_t = time.monotonic()
        else:
            lo = lane.cursor
            lane.counts += binc
            lane.cursor += i
            if lane.journal is not None:
                lane.journal.append_batch(
                    lo, batch_out,
                    cls.counts_dict(lane.counts, train=lane.train),
                    {"dispatch": round(lane.dispatch_s, 6)})
        merged = np.zeros(cls.NUM_CLASSES, dtype=np.int64)
        done = 0
        for other in self._lanes.values():
            merged += other.counts + other.item_counts
            done += other.cursor + other.item_cursor
        self.metrics.hub.record_batch(
            done, i, cls.counts_dict(merged, train=lane.train),
            {"dispatch": round(sum(x.dispatch_s
                                   for x in self._lanes.values()), 6)},
            {}, profile={"device_s": dt, "gap_s": gap})

    # -- the loop ------------------------------------------------------------
    def start(self) -> "ServeEngine":
        if self._thread is not None:
            return self
        self.metrics.hub.campaign_started(
            self.benchmark, "serve",
            total_rows=self.inject_n * len(self._lanes),
            total_effective=self.inject_n * len(self._lanes))
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="coast-serve-loop",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                worked = False
                for lane in self._lanes.values():
                    reqs, expired = self.admission.take(
                        lane.strategy, self.batch_size)
                    for req in expired:
                        self._reject(req, REJECT_DEADLINE)
                    if self.inject_share > 0.0:
                        self._claim_item(lane)
                    if reqs or lane.inject_remaining():
                        self._dispatch(lane, reqs)
                        worked = True
                self.metrics.maybe_write_status()
                if not worked:
                    self.admission.wait(0.05)
                elif self.idle_throttle_s and not self.admission.pending():
                    time.sleep(self.idle_throttle_s)
        except BaseException as e:    # noqa: BLE001 - loop must not vanish
            self.error = f"{type(e).__name__}: {e}"
            flightrec.record("serve_loop_error", error=self.error)
            flightrec.current().dump("serve_loop_error",
                                     extra={"error": self.error})
            self._fail_pending()
            if not isinstance(e, LaneLeakError):
                raise

    def _fail_pending(self) -> None:
        for strategy in self._lanes:
            while True:
                reqs, expired = self.admission.take(strategy,
                                                    self.batch_size)
                if not reqs and not expired:
                    break
                for req in reqs + expired:
                    self._reject(req, "server_error")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None
        self._fail_pending()
        for lane in self._lanes.values():
            if lane.item_journal is not None:
                lane.item_journal.close()
                lane.item_journal = None
            if lane.journal is not None:
                lane.journal.close()
                lane.journal = None
        self.metrics.hub.campaign_finished(summary=None,
                                           error=self.error)
        self.metrics.maybe_write_status(force=True)

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- drains + artifact ---------------------------------------------------
    def drain_injection(self, timeout_s: float = 120.0) -> bool:
        """Block until every lane's standing schedule is fully injected
        and any queue items are completed (tests + bounded runs)."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            if self.error:
                return False
            if all(lane.inject_remaining() == 0 and lane.item is None
                   for lane in self._lanes.values()):
                return True
            time.sleep(0.01)
        return False

    def summary(self) -> Dict[str, object]:
        """The run artifact block: proofs, serving counters, injection
        counts + live SLO verdicts (the loadtest/smoke artifact body and
        the json_parser ``serving`` input)."""
        merged = np.zeros(cls.NUM_CLASSES, dtype=np.int64)
        train = False
        for lane in self._lanes.values():
            merged += lane.counts + lane.item_counts
            train = train or lane.train
        doc: Dict[str, object] = {
            "benchmark": self.benchmark,
            "strategies": sorted(self._lanes),
            "batch_size": self.batch_size,
            "inject_share": self.inject_share,
            "proofs": {s: lane.proof.summary()
                       for s, lane in self._lanes.items()},
            "counts": cls.counts_dict(merged, train=train),
            "serving": self.metrics.serving_block(),
        }
        slo = self.metrics.hub.slo_status()
        if slo is not None:
            from coast_tpu.obs.slo import summary_block
            doc["slo"] = summary_block(slo)
        if self.error:
            doc["error"] = self.error
        return doc

    def lane_codes(self, strategy: str) -> np.ndarray:
        """Concatenated injection-lane class codes for ``strategy`` from
        its standing journal FILE (the bit-for-bit resume pin's probe).
        Re-loaded from disk on every call: the open journal's in-memory
        records hold only what resume loaded, never live appends."""
        if not self.journal_dir:
            raise ValueError("engine has no standing journal_dir")
        path = os.path.join(self.journal_dir,
                            f"serve-{strategy}.journal")
        _, records, _ = CampaignJournal._load(path)
        codes = [np.asarray(r["codes"], dtype=np.int32)
                 for r in records if r.get("kind") == "batch"]
        if not codes:
            return np.zeros(0, dtype=np.int32)
        return np.concatenate(codes)
