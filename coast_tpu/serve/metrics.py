"""Serving metrics: the campaign hub plus request-plane counters.

The injection lanes of a serving dispatch ARE a campaign -- their
outcomes feed an ordinary :class:`~coast_tpu.obs.metrics.CampaignMetrics`
hub (per-class Wilson rates, dispatch-latency histograms, live SLO
verdicts), so every existing surface (``/metrics``, ``/status``, the
SLO engine, ``json_parser``) reads the service's self-measurement with
zero new plumbing.  What IS new is the request plane: admission /
shed / rejection / retry / escalation counters, the per-strategy mix,
request end-to-end latency, and the lane-leak assertion tally.  Those
live here, lock-guarded, and export as a ``serving`` block in the
status document plus ``coast_serve_*`` Prometheus rows.

``ServeMetrics`` duck-types the ``prometheus()``/``snapshot()`` pair
:class:`~coast_tpu.obs.serve.MetricsServer` expects, so the serve front
mounts it directly.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Mapping, Optional

from coast_tpu.inject.classify import SDC_CLASSES as _SDC_CLASSES
from coast_tpu.obs.convergence import wilson_interval
from coast_tpu.obs.metrics import CampaignMetrics, Histogram, _esc

__all__ = ["ServeMetrics"]


class ServeMetrics:
    """Thread-safe serving hub: a CampaignMetrics plus request counters.

    Writers are the engine's dispatch loop (injection outcomes through
    ``self.hub``, request events through the ``note_*`` methods) and the
    HTTP handler threads (``note_admitted``); readers are the metrics
    server and the smoke drivers."""

    def __init__(self, slo=None,
                 slo_baseline: Optional[Mapping[str, float]] = None,
                 status_path: Optional[str] = None,
                 status_interval_s: float = 0.0,
                 z: float = 1.96):
        self.hub = CampaignMetrics(slo=slo, slo_baseline=slo_baseline,
                                   status_path=None, z=z)
        # The status file is written from the SERVING snapshot (hub doc
        # + serving block), so ServeMetrics owns the path, not the hub.
        self.status_path = status_path
        self.status_interval_s = float(status_interval_s)
        self._last_status_write = float("-inf")
        self.z = float(z)
        self._lock = threading.Lock()
        self._t_start = time.monotonic()
        self.admitted = 0
        self.served = 0
        self.rejected: Dict[str, int] = {}
        self.retries = 0
        self.escalations = 0
        self.strategy_mix: Dict[str, int] = {}
        self.shed_inject_lanes = 0
        self.saturated_dispatches = 0
        self.lane_leak_checks = 0
        self.lane_leak_violations = 0
        self.inject_lanes_done = 0
        self.request_latency = Histogram()

    # -- writer side (engine loop + HTTP handlers) ---------------------------
    def note_admitted(self, strategy: str) -> None:
        with self._lock:
            self.admitted += 1
            self.strategy_mix[strategy] = (
                self.strategy_mix.get(strategy, 0) + 1)

    def note_served(self, latency_s: float) -> None:
        with self._lock:
            self.served += 1
            self.request_latency.observe(latency_s)

    def note_rejected(self, reason: str) -> None:
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def note_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def note_escalation(self) -> None:
        """A DWC detection whose retry no longer fit the SLA moved the
        request to TMR; the mix counts the FINAL strategy, so shift one
        unit of the admission tally across."""
        with self._lock:
            self.escalations += 1
            self.strategy_mix["DWC"] = max(
                0, self.strategy_mix.get("DWC", 0) - 1)
            self.strategy_mix["TMR"] = (
                self.strategy_mix.get("TMR", 0) + 1)

    def note_dispatch(self, inject_lanes: int, shed_lanes: int,
                      saturated: bool) -> None:
        with self._lock:
            self.inject_lanes_done += int(inject_lanes)
            self.shed_inject_lanes += int(shed_lanes)
            if saturated:
                self.saturated_dispatches += 1

    def note_lane_leak_check(self, violated: bool = False) -> None:
        with self._lock:
            self.lane_leak_checks += 1
            if violated:
                self.lane_leak_violations += 1

    # -- reader side ---------------------------------------------------------
    def serving_block(self) -> Dict[str, object]:
        """The request-plane summary: the status document's ``serving``
        key and (via the run artifact) the json_parser block.  The live
        SDC CI is Wilson over the hub's cumulative injection-lane
        counts -- the number the service exists to measure."""
        with self._lock:
            elapsed = max(time.monotonic() - self._t_start, 1e-9)
            block: Dict[str, object] = {
                "requests": {
                    "admitted": self.admitted,
                    "served": self.served,
                    "rejected": dict(self.rejected),
                },
                "req_per_sec": round(self.served / elapsed, 3),
                "strategy_mix": dict(self.strategy_mix),
                "retries": self.retries,
                "escalations": self.escalations,
                "shed": {
                    "inject_lanes": self.shed_inject_lanes,
                    "saturated_dispatches": self.saturated_dispatches,
                },
                "lane_leak": {
                    "checks": self.lane_leak_checks,
                    "violations": self.lane_leak_violations,
                },
                "request_latency": self.request_latency.snapshot(),
            }
        with self.hub._lock:
            counts = dict(self.hub.counts)
        total = int(sum(counts.values()))
        sdc = int(sum(counts.get(k, 0.0) for k in _SDC_CLASSES))
        lo, hi = wilson_interval(sdc, total, self.z) if total else (0.0,
                                                                    0.0)
        shed_denom = self.inject_lanes_done + self.shed_inject_lanes
        block["shed"]["shed_rate"] = round(
            self.shed_inject_lanes / shed_denom, 6) if shed_denom else 0.0
        block["inject"] = {
            "lanes_done": total,
            "sdc": sdc,
            "sdc_rate": round(sdc / total, 8) if total else 0.0,
            "sdc_ci": {"lo": round(lo, 8), "hi": round(hi, 8),
                       "half_width": round((hi - lo) / 2.0, 8)},
        }
        return block

    def snapshot(self) -> Dict[str, object]:
        doc = self.hub.snapshot()
        doc["format"] = "coast-serve-status"
        doc["serving"] = self.serving_block()
        return doc

    def prometheus(self) -> str:
        text = self.hub.prometheus()
        with self._lock:
            lines = []

            def metric(name, mtype, help_text, samples):
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} {mtype}")
                for label_str, value in samples:
                    body = (f"{int(value)}"
                            if float(value).is_integer()
                            else f"{value:.17g}")
                    sep = f"{{{label_str}}}" if label_str else ""
                    lines.append(f"{name}{sep} {body}")

            metric("coast_serve_requests_total", "counter",
                   "Admitted requests by final strategy.",
                   [(f'strategy="{_esc(k)}"', float(v))
                    for k, v in sorted(self.strategy_mix.items())]
                   or [('strategy="DWC"', 0.0)])
            metric("coast_serve_served_total", "counter",
                   "Requests answered within their SLA.",
                   [("", float(self.served))])
            metric("coast_serve_rejected_total", "counter",
                   "Rejected requests by reason.",
                   [(f'reason="{_esc(k)}"', float(v))
                    for k, v in sorted(self.rejected.items())]
                   or [('reason="deadline_expired"', 0.0)])
            metric("coast_serve_retries_total", "counter",
                   "DWC detect-and-retry reruns.",
                   [("", float(self.retries))])
            metric("coast_serve_escalations_total", "counter",
                   "DWC requests escalated to TMR (retry would blow "
                   "the SLA).", [("", float(self.escalations))])
            metric("coast_serve_shed_inject_lanes_total", "counter",
                   "Injection lanes shed to make room for requests.",
                   [("", float(self.shed_inject_lanes))])
            metric("coast_serve_saturated_dispatches_total", "counter",
                   "Dispatches whose injection share shed to zero.",
                   [("", float(self.saturated_dispatches))])
            metric("coast_serve_lane_leak_checks_total", "counter",
                   "Runtime armed-lane / response-gather disjointness "
                   "checks.", [("", float(self.lane_leak_checks))])
            metric("coast_serve_lane_leak_violations_total", "counter",
                   "Lane-leak assertion failures (must stay 0).",
                   [("", float(self.lane_leak_violations))])
            hist = self.request_latency
            full = "coast_serve_request_latency_seconds"
            lines.append(f"# HELP {full} End-to-end request latency "
                         "(submit to response) histogram.")
            lines.append(f"# TYPE {full} histogram")
            for bound, cum in zip(hist.bounds, hist.bucket_counts):
                lines.append(f'{full}_bucket{{le="{bound:g}"}} {cum}')
            lines.append(f'{full}_bucket{{le="+Inf"}} {hist.count}')
            lines.append(f"{full}_sum {hist.sum:.17g}")
            lines.append(f"{full}_count {hist.count}")
        return text + "\n".join(lines) + "\n"

    # -- status file (serving snapshot, atomically replaced) -----------------
    def maybe_write_status(self, force: bool = False) -> None:
        if not self.status_path:
            return
        now = time.monotonic()
        if not force and (now - self._last_status_write
                          < self.status_interval_s):
            return
        self._last_status_write = now
        from coast_tpu.obs.metrics import atomic_write_json
        atomic_write_json(self.status_path, self.snapshot())
