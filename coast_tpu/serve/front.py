"""The serving HTTP front + the ``python -m coast_tpu serve`` verb.

The :mod:`coast_tpu.obs.serve` server shape (stdlib threaded
``http.server``, daemon thread, handler class bound per-server, silent
logs, ephemeral-port fallback), extended with the one write endpoint a
protected inference service needs:

  * ``POST /v1/infer``  -- body ``{"payload": str, "sla_s"?: float,
    "strategy"?: "DWC"|"TMR"}``; blocks until the request is served,
    rejected, or its SLA (plus a small grace) elapses.  Responses are
    deterministic JSON (``sort_keys``, no timing fields): two identical
    request streams serialize byte-identically, injection lanes on or
    off -- the differential contract the smoke driver pins.
  * ``GET /metrics``    -- Prometheus text: the campaign hub's rows
    (injection-lane classes, dispatch-latency histograms, SLO verdicts)
    plus the ``coast_serve_*`` request-plane rows.
  * ``GET /status``     -- the serving status document (``format:
    coast-serve-status``: campaign snapshot + ``serving`` block + live
    ``slo`` block).
  * ``GET /healthz``    -- liveness.

Ingest threads do no protected compute: a handler submits into the
admission queue and parks on the request's completion event; the single
dispatch loop does all the batching.
"""

from __future__ import annotations

import argparse
import errno
import http.server
import json
import signal
import sys
import threading
import time
from typing import List, Optional

from coast_tpu.serve.engine import ServeEngine

__all__ = ["ServeFront", "main"]

#: Extra wait beyond a request's SLA before the HTTP handler gives up
#: on its completion event (the loop itself rejects at the deadline;
#: the grace only covers scheduling slop between loop and handler).
_HANDLER_GRACE_S = 1.0


class _Handler(http.server.BaseHTTPRequestHandler):
    # Bound per-server via the class factory in ServeFront.start.
    engine: ServeEngine

    protocol_version = "HTTP/1.1"   # keep-alive: loadtest connections

    def _send(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, doc) -> None:
        self._send(status,
                   json.dumps(doc, sort_keys=True).encode("utf-8"),
                   "application/json")

    def do_GET(self) -> None:          # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        metrics = self.engine.metrics
        if path == "/metrics":
            self._send(200, metrics.prometheus().encode("utf-8"),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path in ("/status", "/status.json"):
            self._send_json(200, metrics.snapshot())
        elif path in ("/", "/healthz"):
            body = (b"coast_tpu protected serving: POST /v1/infer, "
                    b"see /metrics, /status\n")
            self._send(200, body, "text/plain; charset=utf-8")
        else:
            self.send_error(404, "unknown path (want /v1/infer, "
                                 "/metrics, /status)")

    def do_POST(self) -> None:         # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        if path != "/v1/infer":
            self.send_error(404, "unknown path (POST /v1/infer)")
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            doc = json.loads(self.rfile.read(length) or b"{}")
            payload = str(doc.get("payload", ""))
            sla_s = doc.get("sla_s")
            strategy = doc.get("strategy")
            if strategy is not None and strategy not in \
                    self.engine.admission.strategies:
                raise ValueError(f"unknown strategy {strategy!r}")
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        try:
            req = self.engine.submit(payload, sla_s=sla_s,
                                     strategy=strategy)
        except RuntimeError as e:       # engine failed (lane leak etc.)
            self._send_json(503, {"error": str(e)})
            return
        if not req.done.wait(req.sla_s + _HANDLER_GRACE_S):
            self._send_json(504, {"error": "timeout", "id": req.rid})
            return
        if req.response is not None:
            self._send_json(200, req.response)
        else:
            status = 504 if req.error == "deadline_expired" else 503
            self._send_json(status, {"error": req.error, "id": req.rid})

    def log_message(self, fmt: str, *args: object) -> None:
        # Request traffic must not spam the server's terminal.
        pass


class ServeFront:
    """Threaded HTTP front over one ServeEngine (loopback by default:
    rebind explicitly to expose beyond the host)."""

    def __init__(self, engine: ServeEngine, port: int = 0,
                 host: str = "127.0.0.1"):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Start the engine loop and bind the HTTP front; returns the
        bound port (a taken port falls back to an ephemeral one, like
        the metrics server -- the service must not die over a reused
        port number)."""
        if self._httpd is not None:
            return self.port
        self.engine.start()
        handler = type("BoundHandler", (_Handler,),
                       {"engine": self.engine})
        try:
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), handler)
        except OSError as e:
            if self.port == 0 or e.errno not in (errno.EADDRINUSE,
                                                 errno.EACCES):
                raise
            print(f"# warning: serve port {self.port} on {self.host} "
                  f"is taken ({e.strerror}); falling back to an "
                  "ephemeral port", file=sys.stderr, flush=True)
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, 0), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="coast-serve-front", daemon=True)
        self._thread.start()
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            self._httpd = None
            self._thread = None
        self.engine.stop()

    def __enter__(self) -> "ServeFront":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m coast_tpu serve <benchmark> [flags]``."""
    p = argparse.ArgumentParser(
        prog="python -m coast_tpu serve",
        description="Protected inference service: live request lanes + "
                    "background fault-injection lanes in one compiled "
                    "batch, self-measuring its own SDC rate.")
    p.add_argument("benchmark",
                   help="registry name or guest .c path (the protected "
                        "region served and measured)")
    p.add_argument("--port", type=int, default=8321,
                   help="HTTP port (0 = ephemeral; default 8321)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (loopback by default)")
    p.add_argument("--batch-size", type=int, default=64,
                   help="rows per compiled dispatch (requests + "
                        "injection + padding)")
    p.add_argument("--inject-share", type=float, default=0.5,
                   help="fraction of each batch offered to injection "
                        "lanes (0 disables self-measurement)")
    p.add_argument("--sla-s", type=float, default=0.25,
                   help="default per-request SLA (seconds)")
    p.add_argument("--retry-factor", type=float, default=2.0,
                   help="a request picks DWC when its SLA covers "
                        "retry-factor x the estimated dispatch time")
    p.add_argument("--seed", type=int, default=0,
                   help="injection schedule seed")
    p.add_argument("--inject-n", type=int, default=1_000_000,
                   help="standing injection campaign length per "
                        "strategy")
    p.add_argument("--section", default="memory",
                   help="injected section set (supervisor section "
                        "vocabulary; default memory)")
    p.add_argument("--journal-dir", default=None,
                   help="directory for crash-safe standing injection "
                        "journals (resumed bit-for-bit on restart)")
    p.add_argument("--queue", default=None,
                   help="fleet CampaignQueue root: injection work is "
                        "enqueued/claimed/completed as fleet items")
    p.add_argument("--slo", default=None,
                   help="SLO spec string, e.g. "
                        "'sdc_rate<=0.002,availability>=0.99,"
                        "p99_dispatch<=0.05;min=1024'; add 'mwtf>=N' "
                        "with --baseline to gate on Mean-Work-To-"
                        "Failure improvement live")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="recorded UNPROTECTED run evidence (status "
                        "JSON, run doc with summary, summary JSON, or "
                        "NDJSON log -- the slo CLI's --baseline "
                        "vocabulary): feeds the mwtf objective's "
                        "improvement denominator so 'mwtf>=N' gets a "
                        "live verdict on /status and /metrics instead "
                        "of no-data")
    p.add_argument("--status-json", default=None,
                   help="atomically-rewritten serving status file")
    p.add_argument("--status-interval", type=float, default=2.0,
                   help="minimum seconds between status-file writes")
    p.add_argument("--wedge-timeout", type=float, default=0.0,
                   help="seconds before a hung dispatch dumps a "
                        "flight-recorder bundle and fails (0 = off)")
    p.add_argument("--idle-throttle", type=float, default=0.0,
                   help="sleep between injection-only dispatches when "
                        "no requests are queued (0 = free-run)")
    p.add_argument("--duration", type=float, default=0.0,
                   help="serve for N seconds then exit cleanly "
                        "(0 = until SIGINT/SIGTERM)")
    p.add_argument("--flightrec-dir", default=None,
                   help="flight-recorder bundle directory")
    args = p.parse_args(argv)

    from coast_tpu.obs import flightrec
    from coast_tpu.serve.metrics import ServeMetrics
    flightrec.install(dump_dir=args.flightrec_dir)

    queue = None
    if args.queue:
        from coast_tpu.fleet.queue import CampaignQueue
        queue = CampaignQueue(args.queue)
    slo_baseline = None
    if args.baseline:
        from coast_tpu.obs.slo import SLOError, baseline_from
        try:
            slo_baseline = baseline_from(args.baseline)
        except (OSError, ValueError, SLOError) as e:
            print(f"Error, cannot load --baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
    metrics = ServeMetrics(slo=args.slo, status_path=args.status_json,
                           slo_baseline=slo_baseline,
                           status_interval_s=args.status_interval)
    engine = ServeEngine(
        args.benchmark, batch_size=args.batch_size,
        inject_share=args.inject_share, sla_default_s=args.sla_s,
        retry_factor=args.retry_factor, seed=args.seed,
        inject_n=args.inject_n, section=args.section,
        journal_dir=args.journal_dir, queue=queue, metrics=metrics,
        wedge_timeout_s=args.wedge_timeout,
        idle_throttle_s=args.idle_throttle)
    for strategy, lane in engine._lanes.items():
        print(f"# {lane.proof.format()}", flush=True)

    stop = threading.Event()
    for signum in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(signum, lambda *_: stop.set())
        except ValueError:
            pass                        # not the main thread (tests)
    front = ServeFront(engine, port=args.port, host=args.host)
    with front:
        print(f"# serving {engine.benchmark} on {front.url} "
              f"(batch={args.batch_size}, "
              f"inject_share={args.inject_share})", flush=True)
        t_end = (time.monotonic() + args.duration
                 if args.duration > 0 else None)
        while not stop.is_set():
            if t_end is not None and time.monotonic() >= t_end:
                break
            if engine.error:
                break
            stop.wait(0.2)
    doc = engine.summary()
    print(json.dumps(doc, sort_keys=True), flush=True)
    return 1 if engine.error else 0


if __name__ == "__main__":
    sys.exit(main())
