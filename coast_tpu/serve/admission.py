"""SLA-aware request admission: deadline-ordered, shed-by-injection.

One in-process queue per protection strategy, ordered by absolute
deadline (earliest first) -- the serving analogue of the fleet
:class:`~coast_tpu.fleet.queue.CampaignQueue`'s pending directory,
which the engine uses for the *injection* work riding the same batches.
The shedding policy is asymmetric by design: when a dispatch cycle is
oversubscribed, the batch packer shrinks the injection share first
(measurement consumes slack capacity) and the request share never; a
request is only ever dropped when its own deadline has already passed,
and that drop is an explicit typed rejection, not a silent timeout.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["ServeRequest", "AdmissionQueue", "REJECT_DEADLINE",
           "REJECT_SLA"]

#: Rejection reasons (the response's ``error`` field vocabulary).
REJECT_DEADLINE = "deadline_expired"
REJECT_SLA = "sla_exceeded"


@dataclasses.dataclass
class ServeRequest:
    """One in-flight request: payload + SLA budget + completion event.

    ``deadline`` is monotonic-clock absolute; ``strategy`` is assigned
    at admission (latency-budget selection) and may change once -- a
    DWC detection whose retry no longer fits the SLA escalates the
    request to TMR (``escalated``).  ``response`` carries ONLY
    deterministic fields (id, payload echo, output digest, class,
    strategy): timing lives in the metrics hub, so two runs of the same
    request stream serialize byte-identically regardless of load or
    injection share."""

    rid: int
    payload: str
    sla_s: float
    deadline: float
    t_submit: float
    strategy: str = ""
    pinned: bool = False       # caller chose the strategy explicitly
    retries: int = 0
    escalated: bool = False
    response: Optional[Dict[str, object]] = None
    error: Optional[str] = None
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def budget_s(self, now: Optional[float] = None) -> float:
        """Remaining latency budget (seconds; negative = expired)."""
        return self.deadline - (time.monotonic() if now is None else now)


class AdmissionQueue:
    """Deadline-ordered admission over the configured strategies.

    Writers (``submit`` / ``requeue``) are the HTTP handler threads and
    the engine's retry path; the single reader is the dispatch loop
    (``take``).  ``take`` pops at most ``limit`` requests whose
    deadlines still hold and returns the expired ones separately so the
    engine rejects them explicitly (and counts them) instead of letting
    them rot in the heap."""

    def __init__(self, strategies: Tuple[str, ...] = ("DWC", "TMR")):
        self.strategies = tuple(strategies)
        self._heaps: Dict[str, List[Tuple[float, int, ServeRequest]]] = {
            s: [] for s in self.strategies}
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._seq = itertools.count()
        self.submitted = 0

    def submit(self, req: ServeRequest) -> None:
        if req.strategy not in self._heaps:
            raise ValueError(
                f"unknown strategy {req.strategy!r}; one of "
                f"{self.strategies}")
        with self._wake:
            heapq.heappush(self._heaps[req.strategy],
                           (req.deadline, next(self._seq), req))
            self.submitted += 1
            self._wake.notify()

    def requeue(self, req: ServeRequest) -> None:
        """Push a retried/escalated request back, keeping its original
        deadline (an SLA is a promise about the ORIGINAL submission; a
        retry does not reset the clock)."""
        with self._wake:
            heapq.heappush(self._heaps[req.strategy],
                           (req.deadline, next(self._seq), req))
            self._wake.notify()

    def take(self, strategy: str, limit: int,
             now: Optional[float] = None
             ) -> Tuple[List[ServeRequest], List[ServeRequest]]:
        """Pop up to ``limit`` live requests for ``strategy`` (deadline
        order) -> ``(admitted, expired)``.  Expired requests are popped
        past greedily even beyond ``limit`` -- they occupy no batch row,
        and leaving them queued would starve the heap head."""
        t = time.monotonic() if now is None else now
        admitted: List[ServeRequest] = []
        expired: List[ServeRequest] = []
        with self._lock:
            heap = self._heaps[strategy]
            while heap and len(admitted) < limit:
                _, _, req = heapq.heappop(heap)
                (expired if req.deadline < t else admitted).append(req)
        return admitted, expired

    def pending(self) -> int:
        with self._lock:
            return sum(len(h) for h in self._heaps.values())

    def wait(self, timeout: float) -> bool:
        """Block until a submit/requeue lands or ``timeout`` elapses;
        True if work may be pending (the dispatch loop's idle park)."""
        with self._wake:
            if any(self._heaps.values()):
                return True
            return self._wake.wait(timeout)
