"""Campaign-log analysis: the jsonParser.py equivalent.

Consumes the structured JSON logs written by :mod:`coast_tpu.inject.logs`
(whose per-run dicts follow the reference's ``InjectionLog.getDict`` schema,
supportClasses.py:338-353) and reproduces the reference's analyses
(simulation/platform/jsonParser.py):

  * per-file / per-dir run summaries -- success / SDC "errors" / corrected
    "faults" / DUE (timeout + abort) / invalid counts and percentages
    (``summarizeRuns``, jsonParser.py:148-201);
  * timing -- seconds per injection (``summarizeTiming`` :204-213);
  * A-vs-B comparison -- runtime x, error-rate x, and
    **MWTF = (delta error rate) / (delta runtime)** (``compareRuns``
    :458-506, mwtf :473);
  * per-section error attribution -- which injected section/symbol produced
    which outcome (per-register counts :259-287 + ``examineSymbolInjections``
    :340-455 / elfUtils.py:105-176 rolled into one table, since TPU
    "sections" already are named leaves);
  * injection-time histogram (``pcStats`` :216-230, cycle-count histogram --
    text, no matplotlib dependency);
  * pipeline stage breakdown -- the per-stage wall-clock block
    (schedule/pad/dispatch/collect/classify/serialize) the telemetry
    layer (coast_tpu.obs) records into every log's summary, printed
    under the timing line and summed key-wise over directories (the
    streaming writer's ``overlap`` entry is a fraction, rendered on its
    own line and averaged over a directory).  This has no reference
    analogue: at one injection every few seconds the reference never
    needed stage attribution.

``.gz`` logs (the writers' optional gzip container) are decompressed
transparently everywhere a plain log is accepted.

CLI (mirroring ``jsonParser.py logs/ -p | -k fileB | -d dirB``)::

    python -m coast_tpu.analysis run.json            # summarize one file
    python -m coast_tpu.analysis logs/               # summarize a directory
    python -m coast_tpu.analysis a.json -k b.json    # compare A vs B (MWTF)
    python -m coast_tpu.analysis dirA -d dirB        # compare directories
    python -m coast_tpu.analysis run.json -p         # + per-section table
    python -m coast_tpu.analysis run.json -r         # + register-kind table
    python -m coast_tpu.analysis run.json -t         # + trap/timeout counts
    python -m coast_tpu.analysis run.json -c         # + cycle histogram
    python -m coast_tpu.analysis run.json -n -p      # tables only (-n: no
                                                     #   summary block)
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple

# Outcome classes, matching coast_tpu.inject.classify codes / CLASS_NAMES.
_CLASSES = ("success", "corrected", "sdc", "due_abort", "due_timeout",
            "invalid", "due_stack_overflow", "due_assert",
            "train_self_heal", "train_sdc")
# DUE bucket membership (classify.DUE_CLASSES): aborts / stack overflows /
# assert fails all count as timeouts in the reference's summary
# (jsonParser.py:165-172; decoder classes decoder.py:67-69).
_DUE_CLASSES = ("due_abort", "due_timeout", "due_stack_overflow",
                "due_assert")
# Uncorrected silent corruption (classify.SDC_CLASSES): the error-rate /
# MWTF numerator.  train_self_heal is deliberately NOT an error -- the
# workload's output (the converged loss) was not corrupted.
_SDC_CLASSES = ("sdc", "train_sdc")
# Codes that ran to completion (reached the result line) and contribute
# to the mean-runtime statistic: success/corrected/sdc plus the train
# refinements of sdc (classify.COMPLETED_CLASSES).
_COMPLETED_CODES = (0, 1, 2, 8, 9)


def _completed_mask(codes):
    import numpy as np
    return np.isin(codes, _COMPLETED_CODES)


def mean_steps_or_nan(step_sum: float, step_n: int, n: int,
                      name: str) -> float:
    """Mean guest runtime over completed runs, or NaN (with a warning)
    for a non-empty campaign that completed none.  The single policy
    point for the zero-clean-runs case: the reference tool crashes here
    (statistics.mean over an empty list raises StatisticsError, its
    otherStats path); we report NaN so comparisons and MWTF propagate
    NaN rather than aborting.  Shared by both log readers and
    scripts/mwtf_report.py."""
    if step_n:
        return step_sum / step_n
    if n:
        print(f"warning: {name}: campaign has no completed runs; "
              "mean runtime (and any MWTF using it) is NaN",
              file=sys.stderr)
        return float("nan")
    return 0.0


def classify_run(run: Dict[str, object]) -> str:
    """Reconstruct the outcome class of one logged run.

    Dispatch on the result sub-dict's discriminating keys, exactly the
    ``InjectionLog.FromDict`` scheme (supportClasses.py:355-389): ``core`` ->
    RunResult, ``timeout`` -> TimeoutResult, ``message`` -> Abort-like,
    ``stackOverflow`` -> StackOverflowResult, ``assertion`` ->
    AssertionFailResult, ``invalid`` -> InvalidResult.  Priority mirrors
    classify.classify (INVALID > stack-overflow > assert > abort >
    timeout).
    """
    res = run.get("result") or {}
    if "invalid" in res:
        return "invalid"
    if "stackOverflow" in res:
        return "due_stack_overflow"
    if "assertion" in res:
        return "due_assert"
    if "trainSdc" in res:
        # Training refinements of SDC (coast_tpu.train): the result dict
        # carries the ordinary RunResult fields (core/runtime/errors)
        # plus the discriminating key, so these branches must sit above
        # the "core" dispatch.
        return "train_sdc"
    if "selfHeal" in res:
        return "train_self_heal"
    if "timeout" in res:
        return "due_timeout"
    if "message" in res:
        return "due_abort"
    if "core" in res:
        errors = int(res.get("errors", 0))
        faults = int(res.get("faults", 0))
        if errors > 0:
            return "sdc"
        if faults > 0:
            return "corrected"
        return "success"
    return "invalid"


@dataclasses.dataclass
class Summary:
    """One file/dir's aggregate, the ``summarizeRuns`` output row."""

    name: str
    n: int
    counts: Dict[str, int]
    seconds: float
    mean_steps: float            # mean guest runtime T over completed runs
    # Per-stage wall-clock breakdown (schedule/pad/dispatch/collect/
    # classify/serialize seconds) recorded by the telemetry layer into
    # each log's summary block; summed key-wise over a directory.  None
    # for logs written before the stages block existed.
    stages: Optional[Dict[str, float]] = None
    # Fault-tolerant-dispatch accounting (retry_transient / retry_wedged /
    # oom_degrade, coast_tpu.inject.resilience) from each log's summary
    # block; None for campaigns run without a RetryPolicy.
    resilience: Optional[Dict[str, int]] = None
    # Fault-model axis (inject/schedule.FaultModel.spec()) from the log
    # summary: None for single-bit campaigns (whose logs deliberately
    # omit the key, keeping pre-model byte parity), the spec string for
    # multi-site campaigns, "mixed" when a directory aggregates several
    # models -- rates aggregated across models are rarely meaningful.
    fault_model: Optional[str] = None
    # Equivalence-reduced campaigns (analysis/equiv): ``n``/``counts``
    # are over EFFECTIVE injections (per-run class weights multiplied
    # out); ``physical_n`` is how many representative runs were actually
    # dispatched.  None for exhaustive campaigns (no weight keys in the
    # log), so pre-equiv logs summarize exactly as before.
    physical_n: Optional[int] = None
    # Statistical-convergence block (coast_tpu.obs.convergence) from the
    # log summary: the stop condition, whether it tripped (``stopped``),
    # done-vs-planned effective injections, and the per-class Wilson
    # intervals the campaign ended with.  None for campaigns run without
    # ``stop_when`` and for directory aggregates mixing several logs
    # (intervals do not aggregate across campaigns).
    convergence: Optional[Dict[str, object]] = None
    # Measured host<->device traffic ({"up", "down"} bytes) from the log
    # summary's ``transfer_bytes`` block; summed over a directory.  None
    # for logs written before the block existed.
    transfer: Optional[Dict[str, int]] = None
    # Collection mode of the underlying log(s): "sparse" when the rows
    # cover only interesting outcomes (counts come from the summary's
    # device histogram), None/"dense" otherwise, "mixed" for a directory
    # aggregating both.
    collect: Optional[str] = None
    # Device-time attribution + roofline accounting (the
    # CampaignRunner(profile=True) summary blocks): device-busy /
    # host-gap / host-other seconds summing to the campaign wall clock,
    # per-phase device seconds, and the mfu block (achieved vs
    # roofline-predicted MFU, dispatch-gap fraction).  None for
    # unprofiled logs and for directory aggregates (attribution
    # fractions do not aggregate across campaigns).
    profile: Optional[Dict[str, object]] = None
    mfu: Optional[Dict[str, object]] = None
    # Reliability-SLO verdicts (obs/slo.summary_block) from the log
    # summary: per-objective attainment, budget remaining, burn rate,
    # worst verdict.  None for campaigns run without an SLO set and for
    # directory aggregates mixing several logs (a budget verdict
    # describes one campaign's evidence, like the Wilson intervals).
    slo: Optional[Dict[str, object]] = None
    # Serving request-plane block (serve.ServeMetrics.serving_block):
    # request counts / shed rate / strategy mix / live SDC CI from a
    # protected-inference-service log.  None for ordinary campaigns and
    # for directory aggregates mixing several logs (request rates and
    # the live Wilson CI describe one service's window, like slo).
    serving: Optional[Dict[str, object]] = None
    # Sharded-campaign accounting (ShardedCampaignRunner): the mesh
    # geometry the campaign ran on and each shard's interesting-row
    # count.  None for single-device logs and for directory aggregates
    # mixing several logs (a per-shard ledger describes one campaign's
    # batch split, like the convergence intervals).
    mesh: Optional[Dict[str, object]] = None

    @property
    def due(self) -> int:
        # Aborts (and the stack-overflow / assert-fail sub-buckets) also
        # count into the DUE/timeout bucket in the reference's summary
        # (jsonParser.py:165-172).
        return sum(self.counts.get(k, 0) for k in _DUE_CLASSES)

    @property
    def error_rate(self) -> float:
        # Persistent train SDCs count as errors (classify.SDC_CLASSES);
        # for every non-train campaign the extra key is absent/zero, so
        # the pre-training value is unchanged.
        sdc = sum(self.counts.get(k, 0) for k in _SDC_CLASSES)
        return sdc / self.n if self.n else 0.0

    def pct(self, cls: str) -> float:
        return 100.0 * self.counts.get(cls, 0) / self.n if self.n else 0.0

    def seconds_per_injection(self) -> float:
        # summarizeTiming (jsonParser.py:204-213).  Reduced campaigns
        # time the runs that physically dispatched, not the effective
        # injections they stand for.
        denom = self.physical_n if self.physical_n is not None else self.n
        return self.seconds / denom if denom else 0.0

    def format(self) -> str:
        lines = [f"=== {self.name}: {self.n} injections ==="]
        if self.physical_n is not None:
            # Effective vs physical as separate rows: the distribution
            # above is over effective injections; only the class
            # representatives physically ran.
            lines.append(f"  {'effective':<12} {self.n:>8}  (class-weighted)")
            red = self.n / self.physical_n if self.physical_n else 0.0
            lines.append(f"  {'physical':<12} {self.physical_n:>8}  "
                         f"({red:.1f}x equiv reduction)")
        if self.fault_model:
            lines.append(f"  fault model  {self.fault_model}")
        for cls in _CLASSES:
            if cls in ("due_stack_overflow", "due_assert",
                       "train_self_heal", "train_sdc"):
                continue          # printed as sub-count blocks below
            lines.append(f"  {cls:<12} {self.counts.get(cls, 0):>8}  "
                         f"({self.pct(cls):6.2f}%)")
        lines.append(f"  {'due (total)':<12} {self.due:>8}  "
                     f"({100.0 * self.due / self.n if self.n else 0.0:6.2f}%)")
        # The reference summary's three DUE sub-counts (its Timeouts row
        # folds aborts/stack-overflows/assert-fails in, then reports each
        # decoder class; decoder.py:67-69 / jsonParser.py:165-172).
        for label, key in (("aborts", "due_abort"),
                           ("stack overflows", "due_stack_overflow"),
                           ("assert fails", "due_assert")):
            lines.append(f"    {label:<16} {self.counts.get(key, 0):>6}")
        # Silent-training-corruption block (coast_tpu.train): only train
        # campaigns ever populate these classes, so every other summary's
        # text is unchanged.
        heals = self.counts.get("train_self_heal", 0)
        persists = self.counts.get("train_sdc", 0)
        if heals or persists:
            lines.append("  --- silent training corruption ---")
            lines.append(f"    {'self-healed':<16} {heals:>6}  "
                         "(loss re-converged)")
            lines.append(f"    {'persistent SDC':<16} {persists:>6}  "
                         "(weights + loss diverged)")
        lines.append(f"  error rate   {self.error_rate:.6f}")
        lines.append(f"  mean runtime {self.mean_steps:.1f} steps")
        if self.seconds:
            phys = self.physical_n if self.physical_n is not None else self.n
            lines.append(
                f"  {self.seconds_per_injection() * 1e6:.2f} usec per "
                f"injection ({phys / self.seconds:.1f} injections/sec)")
        if self.stages:
            lines.append("  --- stage breakdown ---")
            # 'overlap' is a FRACTION (share of serialization work the
            # streaming writer hid under dispatch), not a seconds
            # bucket: keep it out of the percentage table and print it
            # on its own line.
            seconds = {k: v for k, v in self.stages.items()
                       if k != "overlap"}
            total = sum(seconds.values()) or 1.0
            for stage, sec in sorted(seconds.items(),
                                     key=lambda kv: -kv[1]):
                lines.append(f"  {stage:<12} {sec:>10.4f}s "
                             f"({100.0 * sec / total:5.1f}%)")
            if "overlap" in self.stages:
                lines.append(f"  serialize overlap: "
                             f"{100.0 * self.stages['overlap']:.1f}% of "
                             "serialization hidden under dispatch")
        if self.transfer:
            # Host<->device traffic alongside the stage seconds it
            # explains -- the sparse-collect mode's headline number.
            up = int(self.transfer.get("up", 0))
            down = int(self.transfer.get("down", 0))
            mode = f" ({self.collect} collect)" if self.collect else ""
            lines.append("  --- host transfer ---")
            lines.append(f"  up   {up:>12} bytes ({up / 1e6:8.2f} MB)"
                         f"{mode}")
            lines.append(f"  down {down:>12} bytes ({down / 1e6:8.2f} MB)")
        if self.profile:
            prof = self.profile
            lines.append("  --- device attribution ---")
            wall = float(prof.get("wall_s") or 0.0) or 1.0

            def _frac(key):
                return 100.0 * float(prof.get(key) or 0.0) / wall

            lines.append(
                f"  device busy  {float(prof.get('device_busy_s', 0)):.4f}s"
                f" ({_frac('device_busy_s'):5.1f}%)   host gap "
                f"{float(prof.get('host_gap_s', 0)):.4f}s "
                f"({_frac('host_gap_s'):5.1f}%)   other "
                f"{float(prof.get('host_other_s', 0)):.4f}s")
            phases = prof.get("per_phase_device_s") or {}
            if phases:
                lines.append("  per-phase device: " + "  ".join(
                    f"{k} {float(v):.4f}s" for k, v in phases.items()))
        if self.mfu:
            mfu = self.mfu

            def _pct(v):
                return f"{100.0 * v:.4g}%" if v is not None else "-"

            lines.append(
                f"  MFU: achieved {_pct(mfu.get('achieved_mfu'))} "
                f"(roofline ceiling {_pct(mfu.get('roofline_mfu'))}, "
                f"dispatch-gap "
                f"{_pct(mfu.get('dispatch_gap_fraction') or 0.0)}, "
                f"flops overhead {mfu.get('flops_overhead')}x)")
        if self.resilience and any(self.resilience.values()):
            # Surface survived dispatch failures: a campaign that retried
            # or degraded its way to completion should say so in the same
            # place its rates are quoted.
            lines.append("  --- resilience ---")
            for key, count in sorted(self.resilience.items()):
                lines.append(f"  {key:<16} {count:>6}")
        if self.convergence:
            conv = self.convergence
            lines.append("  --- convergence ---")
            state = ("STOPPED early" if conv.get("stopped")
                     else "ran to completion")
            lines.append(
                f"  {state} at {conv.get('done_n', '?')}/"
                f"{conv.get('planned_n', '?')} effective injections"
                + (f"  (stop_when {conv['stop_when']})"
                   if conv.get("stop_when") else ""))
            intervals = conv.get("intervals") or {}
            targets = set()
            if conv.get("stop_when"):
                # The spec grammar has ONE owner (StopWhen.parse); an
                # unparseable spec (written by a future version) just
                # loses the target marks, never the summary.
                try:
                    from coast_tpu.obs.convergence import StopWhen
                    targets = set(
                        StopWhen.parse(str(conv["stop_when"])).targets)
                except Exception:      # noqa: BLE001 - cosmetic marks
                    targets = set()
            for cls_name, ci in intervals.items():
                # Rates the reader cares about: every class that
                # occurred, plus the stop targets (whose shrinking
                # zero-count upper bound is the convergence story).
                if not ci.get("count") and cls_name not in targets:
                    continue
                mark = "  <- target" if cls_name in targets else ""
                lines.append(
                    f"  {cls_name:<18} {100.0 * ci.get('rate', 0.0):7.3f}%"
                    f" +-{100.0 * ci.get('half_width', 0.0):6.3f}%"
                    f"  [{100.0 * ci.get('lo', 0.0):.3f}%,"
                    f" {100.0 * ci.get('hi', 0.0):.3f}%]{mark}")
        if self.slo:
            slo = self.slo
            lines.append("  --- slo ---")
            lines.append(f"  verdict {str(slo.get('verdict', '?')):<6}"
                         f" (spec {slo.get('spec')})")
            for oname, row in (slo.get("objectives") or {}).items():
                attained = row.get("attained")
                att = ("yes" if attained is True
                       else "NO" if attained is False else "n/a")
                budget = row.get("budget_remaining_frac")
                burn = row.get("burn_rate")
                lines.append(
                    f"  {oname:<18} {row.get('op', '')}"
                    f"{row.get('target')}"
                    f"  observed {row.get('observed')}"
                    f"  attained {att}"
                    + (f"  budget {100.0 * budget:6.1f}%"
                       if budget is not None else "")
                    + (f"  burn {burn:.2f}x" if burn is not None else "")
                    + f"  [{row.get('verdict')}]")
        if self.mesh:
            mesh = self.mesh
            axes = mesh.get("axes") or {}
            axes_str = " x ".join(f"{k}={v}" for k, v in axes.items()) \
                or "?"
            lines.append("  --- mesh ---")
            lines.append(f"  {mesh.get('devices', '?')} devices"
                         f"  ({axes_str})")
            ledger = mesh.get("per_shard_interesting")
            if ledger is not None:
                total = sum(int(v) for v in ledger) or 1
                lines.append("  interesting rows per shard: " + "  ".join(
                    f"[{i}] {int(v)} ({100.0 * int(v) / total:5.1f}%)"
                    for i, v in enumerate(ledger)))
        if self.serving:
            srv = self.serving
            reqs = srv.get("requests") or {}
            rejected = reqs.get("rejected") or {}
            lines.append("  --- serving ---")
            lines.append(
                f"  requests admitted {reqs.get('admitted', 0)}"
                f"  served {reqs.get('served', 0)}"
                f"  rejected {sum(rejected.values())}"
                f"  ({srv.get('req_per_sec', 0.0)} req/s)")
            mix = srv.get("strategy_mix") or {}
            if mix:
                mix_str = "  ".join(f"{k} {v}"
                                    for k, v in sorted(mix.items()))
                lines.append(
                    f"  strategy mix       {mix_str}"
                    f"  (retries {srv.get('retries', 0)},"
                    f" escalations {srv.get('escalations', 0)})")
            shed = srv.get("shed") or {}
            lines.append(
                f"  shed               "
                f"{100.0 * float(shed.get('shed_rate', 0.0)):7.3f}%"
                f"  ({shed.get('inject_lanes', 0)} inject lanes,"
                f" {shed.get('saturated_dispatches', 0)} saturated"
                " dispatches)")
            leak = srv.get("lane_leak") or {}
            lines.append(
                f"  lane leak          {leak.get('violations', 0)}"
                f" violations / {leak.get('checks', 0)} checks")
            inj = srv.get("inject") or {}
            ci = inj.get("sdc_ci") or {}
            lines.append(
                f"  live sdc           "
                f"{100.0 * float(inj.get('sdc_rate', 0.0)):7.4f}%"
                f" +-{100.0 * float(ci.get('half_width', 0.0)):6.4f}%"
                f"  [{100.0 * float(ci.get('lo', 0.0)):.4f}%,"
                f" {100.0 * float(ci.get('hi', 0.0)):.4f}%]"
                f"  over {inj.get('lanes_done', 0)} injection lanes")
        return "\n".join(lines)


def _sniff_ndjson_head(first_line):
    """The write_ndjson header, or None (shared by the materialising
    reader and the native fast path so the detection rule cannot
    drift)."""
    try:
        head = json.loads(first_line)
    except ValueError:
        return None
    if (isinstance(head, dict) and "summary" in head
            and isinstance(head["summary"], dict)
            and head["summary"].get("format") == "ndjson"):
        return head
    return None


def _open_log(path: str, mode: str = "r"):
    """Open a campaign log, transparently decompressing ``.gz`` files
    (the writers' optional gzip container: ``foo.ndjson.gz`` by
    extension).  Text mode decodes as the writers encoded (ASCII-safe
    JSON)."""
    if path.endswith(".gz"):
        import gzip
        return gzip.open(path, mode if "b" in mode else "rt")
    return open(path, mode)


def read_json_file(path: str) -> Dict[str, object]:
    with _open_log(path) as f:
        first = f.readline()
        nd_head = _sniff_ndjson_head(first)
        if nd_head is not None:
            # write_ndjson bulk log: summary line + one run per line.
            return {"summary": nd_head["summary"],
                    "runs": [json.loads(line) for line in f if line.strip()]}
        try:
            head = json.loads(first)
        except ValueError:
            head = None
        if isinstance(head, dict) and ("runs" in head or "columns" in head):
            # Single-line doc (write_columnar emits one line): the first
            # readline consumed and parsed the whole file already.
            return head
        if head is None and os.path.exists(first.strip()):
            # Reference container (write_reference_json / the reference's
            # own campaign logs): line 1 is the guest-executable path,
            # the rest one bare InjectionLog array (jsonParser.py:121-133).
            return {"summary": {"exec": first.strip()},
                    "runs": json.load(f)}
        f.seek(0)
        doc = json.load(f)
    if not isinstance(doc, dict) or not ("runs" in doc or "columns" in doc):
        raise ValueError(f"{path}: not a coast_tpu campaign log")
    return doc


def _iter_docs(path: str) -> Iterable[Tuple[str, Dict[str, object]]]:
    """Yield (name, doc) campaign logs under ``path``.

    A directory is scanned leniently: stray .json files that are not
    campaign logs are skipped with a warning (a log dir often accumulates
    other tooling's files).  An explicitly named file is strict.
    """
    if os.path.isdir(path):
        for fname in sorted(os.listdir(path)):
            if not fname.endswith((".json", ".json.gz")):
                continue
            try:
                yield fname, read_json_file(os.path.join(path, fname))
            except (ValueError, json.JSONDecodeError) as e:
                print(f"warning: skipping {fname}: {e}", file=sys.stderr)
    else:
        yield os.path.basename(path), read_json_file(path)


def summarize_runs(name: str, docs: Iterable[Dict[str, object]]) -> Summary:
    counts = {cls: 0 for cls in _CLASSES}
    n = 0
    physical = 0
    weighted = False
    seconds = 0.0
    step_sum = 0
    step_n = 0
    stages: Dict[str, float] = {}
    overlaps: List[float] = []
    resilience: Dict[str, int] = {}
    models: set = set()
    collects: set = set()
    transfer: Dict[str, int] = {}
    convergences: List[Dict[str, object]] = []
    profiles: List[Dict[str, object]] = []
    mfus: List[Dict[str, object]] = []
    slos: List[Dict[str, object]] = []
    servings: List[Dict[str, object]] = []
    meshes: List[Dict[str, object]] = []
    for doc in docs:
        head = doc.get("summary") or {}
        if head.get("collect") == "sparse":
            # Sparse-collect log: the class totals live in the summary
            # (the device histogram's counts; counts_histogram is the
            # dict->array bridge); the rows cover ONLY the interesting
            # outcomes, so they feed the runtime statistic (over
            # interesting completed runs, class weights applied exactly
            # as on the dense paths) and the per-section tables, never
            # the counts.
            import numpy as np
            from coast_tpu.inject.classify import counts_histogram
            binc = counts_histogram(head)
            for i, cname in enumerate(_CLASSES):
                counts[cname] += int(binc[i])
            n += int(head.get("injections", 0))
            physical += int(head.get("physical_injections",
                                     head.get("injections", 0)))
            weighted = weighted or ("physical_injections" in head)
            if "columns" in doc:
                codes = np.asarray(doc["columns"]["code"])
                steps = np.asarray(doc["columns"]["steps"])
                w = doc["columns"].get("weight")
                w = (np.asarray(w, np.int64) if w is not None
                     else np.ones(len(codes), np.int64))
                completed = _completed_mask(codes)
                step_sum += int((steps[completed] * w[completed]).sum())
                step_n += int(w[completed].sum())
            else:
                for run in doc.get("runs") or []:
                    res = run.get("result") or {}
                    if "core" in res:
                        rw = int(run.get("weight", 1))
                        step_sum += int(res.get("runtime", 0)) * rw
                        step_n += rw
        elif "columns" in doc:                    # vectorised columnar path
            import numpy as np
            col = doc["columns"]  # type: ignore
            codes = np.asarray(col["code"])
            steps = np.asarray(col["steps"])
            w = col.get("weight")
            if w is not None:
                # Equivalence-reduced log: each representative row is
                # multiplied by its class weight (effective counts).
                weighted = True
                w = np.asarray(w, np.int64)
                binc = np.round(np.bincount(
                    codes, weights=w.astype(np.float64),
                    minlength=len(_CLASSES))).astype(np.int64)
                n += int(w.sum())
                completed = _completed_mask(codes)
                step_sum += int((steps[completed]
                                 * w[completed]).sum())
                step_n += int(w[completed].sum())
            else:
                binc = np.bincount(codes, minlength=len(_CLASSES))
                n += len(codes)
                completed = _completed_mask(codes)
                step_sum += int(steps[completed].sum())
                step_n += int(completed.sum())
            for i, cls in enumerate(_CLASSES):
                counts[cls] += int(binc[i])
            physical += len(codes)
        else:
            runs: List[Dict[str, object]] = doc["runs"]  # type: ignore
            for run in runs:
                cls = classify_run(run)
                w = int(run.get("weight", 1))
                if "weight" in run:
                    weighted = True
                counts[cls] += w
                n += w
                physical += 1
                res = run.get("result") or {}
                if "core" in res:
                    step_sum += int(res.get("runtime", 0)) * w
                    step_n += w
        summary = doc.get("summary") or {}
        seconds += float(summary.get("seconds", 0.0))
        for stage, sec in (summary.get("stages") or {}).items():
            if stage == "overlap":
                continue          # a fraction, not seconds: meaned below
            stages[stage] = stages.get(stage, 0.0) + float(sec)
        ov = (summary.get("stages") or {}).get("overlap")
        if ov is not None:
            overlaps.append(float(ov))
        for key, cnt in (summary.get("resilience") or {}).items():
            resilience[key] = resilience.get(key, 0) + int(cnt)
        models.add(summary.get("fault_model") or "single")
        collects.add(summary.get("collect") or "dense")
        for key, b in (summary.get("transfer_bytes") or {}).items():
            transfer[key] = transfer.get(key, 0) + int(b)
        if summary.get("convergence"):
            convergences.append(summary["convergence"])
        if summary.get("profile"):
            profiles.append(summary["profile"])
        if summary.get("mfu"):
            mfus.append(summary["mfu"])
        if summary.get("slo"):
            slos.append(summary["slo"])
        if summary.get("serving"):
            servings.append(summary["serving"])
        if summary.get("mesh"):
            meshes.append(summary["mesh"])
    if overlaps:
        stages["overlap"] = round(sum(overlaps) / len(overlaps), 4)
    # The fault-model axis: absent key == the single-bit legacy model.
    # A directory mixing models gets the explicit "mixed" marker rather
    # than silently quoting one model's rates under another's name.
    fault_model = None
    if len(models) == 1:
        only = models.pop()
        fault_model = None if only == "single" else only
    elif models:
        fault_model = "mixed"
    collect = None
    if len(collects) == 1:
        only_c = collects.pop()
        collect = None if only_c == "dense" else only_c
    elif collects:
        collect = "mixed"
    return Summary(name=name, n=n, counts=counts, seconds=seconds,
                   mean_steps=mean_steps_or_nan(step_sum, step_n, n, name),
                   stages=stages or None,
                   resilience=resilience or None,
                   fault_model=fault_model,
                   transfer=transfer or None,
                   collect=collect,
                   physical_n=physical if weighted else None,
                   # Wilson intervals describe ONE campaign's sample;
                   # a directory mixing several logs has no aggregate
                   # interval, so only a lone convergence block is kept.
                   # Same rule for the device-attribution blocks.
                   convergence=(convergences[0]
                                if len(convergences) == 1 else None),
                   profile=(profiles[0] if len(profiles) == 1 else None),
                   mfu=(mfus[0] if len(mfus) == 1 else None),
                   slo=(slos[0] if len(slos) == 1 else None),
                   serving=(servings[0]
                            if len(servings) == 1 else None),
                   mesh=(meshes[0] if len(meshes) == 1 else None))


def _summarize_ndjson_native(path: str) -> Optional[Summary]:
    """Fast path for a single write_ndjson file: the native core
    re-classifies the rows in one C pass (bit-equal to classify_run; the
    per-line json.loads of read_json_file was ~40s at 10^6 rows).
    Returns None when the file is not ndjson or the core is unavailable."""
    from coast_tpu import native
    if not native.native_available():
        return None
    try:
        with _open_log(path, "rb") as f:
            head = _sniff_ndjson_head(f.readline())
            if head is None:
                return None
            if "physical_injections" in head["summary"]:
                # Equivalence-reduced log: rows carry class weights the
                # native classifier does not apply -- Python path.
                return None
            if head["summary"].get("collect") == "sparse":
                # Sparse log: the rows are only the interesting subset;
                # counts come from the summary histogram (Python path).
                return None
            try:
                got = native.ndjson_classify_stream(f.read)
            except ValueError:
                return None       # not InjectionLog-shaped: Python parser
        if got is None:
            return None
        counts, step_sum, step_n, n = got
        name = os.path.basename(path.rstrip("/")) or path
        return Summary(
            name=name,
            n=n,
            counts={cls: int(counts[i]) for i, cls in enumerate(_CLASSES)},
            seconds=float(head["summary"].get("seconds", 0.0)),
            mean_steps=mean_steps_or_nan(step_sum, step_n, n, name),
            stages=head["summary"].get("stages") or None,
            resilience=head["summary"].get("resilience") or None,
            fault_model=head["summary"].get("fault_model") or None,
            transfer=head["summary"].get("transfer_bytes") or None,
            convergence=head["summary"].get("convergence") or None,
            profile=head["summary"].get("profile") or None,
            mfu=head["summary"].get("mfu") or None,
            slo=head["summary"].get("slo") or None,
            serving=head["summary"].get("serving") or None,
            mesh=head["summary"].get("mesh") or None)
    except OSError:
        return None


def summarize_path(path: str) -> Summary:
    if os.path.isfile(path):
        fast = _summarize_ndjson_native(path)
        if fast is not None:
            return fast
    return summarize_runs(os.path.basename(path.rstrip("/")) or path,
                          (doc for _, doc in _iter_docs(path)))


# -- A-vs-B comparison (compareRuns, jsonParser.py:458-506) ------------------

def class_comparison(base: Summary, new: Summary,
                     z: float = 1.96) -> Dict[str, object]:
    """Per-class Wilson-interval comparison of two summaries: the
    distribution-drift half of :func:`compare_runs` and the verdict
    kernel of the protection-regression CI (``coast_tpu.ci``).

    Weight-aware by construction: a Summary's counts/n are over
    EFFECTIVE injections (equivalence-reduced logs multiply class
    weights out upstream), and the Wilson arithmetic takes the weighted
    counts as-is -- the same convention as the live convergence tracker.

    Returns ``classes`` ({cls: {base, new, overlap}} interval rows over
    every class either summary populated), ``new_classes`` /
    ``vanished_classes`` (outcome classes with a nonzero count on
    exactly one side -- a protection regression often *creates* a class,
    e.g. sdc under a weakened TMR, at rates far inside a Wilson interval
    of zero), and ``distribution_drift`` (any non-overlapping class, or
    any new/vanished class)."""
    from coast_tpu.obs.convergence import interval_table, intervals_overlap
    # One ensure= union keeps every row's denominator consistent: an
    # absent class is observed-zero out of THAT summary's own trials.
    names = tuple(sorted(set(base.counts) | set(new.counts)))
    base_tab = interval_table(base.counts, z, ensure=names)
    new_tab = interval_table(new.counts, z, ensure=names)
    classes: Dict[str, object] = {}
    new_classes: List[str] = []
    vanished: List[str] = []
    for cls_name in names:
        b = base_tab[cls_name]
        m = new_tab[cls_name]
        if not b["count"] and m["count"]:
            new_classes.append(cls_name)
        if b["count"] and not m["count"]:
            vanished.append(cls_name)
        classes[cls_name] = {"base": b, "new": m,
                             "overlap": intervals_overlap(b, m)}
    drift = (bool(new_classes) or bool(vanished)
             or any(not row["overlap"] for row in classes.values()))
    return {"classes": classes, "new_classes": new_classes,
            "vanished_classes": vanished, "distribution_drift": drift}


def format_drift_lines(cmp: Dict[str, object]) -> List[str]:
    """Render the drifting classes of a :func:`class_comparison` block,
    one line per class -- the ONE spelling shared by
    ``format_comparison`` and the CI's per-target report."""
    drifting = sorted(
        set(c for c, row in cmp["classes"].items() if not row["overlap"])
        | set(cmp["new_classes"]) | set(cmp["vanished_classes"]))
    out = []
    for cls_name in drifting:
        row = cmp["classes"][cls_name]
        tag = (" (new class)" if cls_name in cmp["new_classes"] else
               " (vanished class)" if cls_name in cmp["vanished_classes"]
               else "")
        out.append(
            f"{cls_name}: base [{100 * row['base']['lo']:.3f}%,"
            f" {100 * row['base']['hi']:.3f}%]  vs  "
            f"[{100 * row['new']['lo']:.3f}%,"
            f" {100 * row['new']['hi']:.3f}%]{tag}")
    return out


def compare_runs(base: Summary, new: Summary,
                 z: float = 1.96) -> Dict[str, object]:
    """Protection-cost metrics of ``new`` relative to ``base``.

    ``mwtf`` is the Mean-Work-To-Failure *ratio* of jsonParser.py:473:
    (error-rate improvement) / (runtime slowdown).  >1 means the protection
    buys more reliability than it costs in time.

    The runtime-slowdown denominator: the reference measures guest runtime
    of the protected binary.  Here both programs scan the same step count
    by construction (``steps_x`` is ~1); the replication cost (N lanes +
    voters) lands in wall-clock per injection, so ``runtime_x`` prefers the
    seconds-per-injection ratio and falls back to the step ratio when a
    summary carries no timing.

    Alongside the scalar ratios, the output carries the per-class
    distribution comparison of :func:`class_comparison` -- Wilson
    intervals (at quantile ``z``) for every outcome class on both
    sides, an ``overlap`` verdict per class, and the aggregate
    ``distribution_drift`` flag the protection-regression CI gates on.
    """
    import math

    def _ratio(a: float, b: float) -> float:
        if math.isnan(a) or math.isnan(b):
            # A campaign with no completed runs has no mean runtime: the
            # comparison is undefined, not infinite (the reference's
            # StatisticsError path, reported as NaN upstream).
            return float("nan")
        if b == 0.0:
            return float("inf") if a > 0 else 1.0
        return a / b

    steps_x = _ratio(new.mean_steps, base.mean_steps)
    if base.seconds and new.seconds:
        runtime_x = _ratio(new.seconds_per_injection(),
                           base.seconds_per_injection())
    else:
        runtime_x = steps_x
    error_rate_x = _ratio(new.error_rate, base.error_rate)
    improvement = _ratio(base.error_rate, new.error_rate)
    if math.isnan(runtime_x) or math.isnan(improvement):
        mwtf = float("nan")
    else:
        mwtf = improvement / runtime_x if runtime_x > 0 else float("inf")
    return {
        "runtime_x": runtime_x,
        "steps_x": steps_x,
        "error_rate_x": error_rate_x,
        "error_improvement_x": improvement,
        "mwtf": mwtf,
        **class_comparison(base, new, z),
    }


def format_comparison(base: Summary, new: Summary) -> str:
    cmp = compare_runs(base, new)
    lines = [f"=== {base.name} (base)  vs  {new.name} ===",
             base.format(), new.format(), "--- comparison ---"]
    lines.append(f"  runtime x          {cmp['runtime_x']:.3f} "
                 f"(steps x {cmp['steps_x']:.3f})")
    lines.append(f"  error rate x       {cmp['error_rate_x']:.4f}")
    lines.append(f"  error improvement  {cmp['error_improvement_x']:.2f}x")
    lines.append(f"  MWTF               {cmp['mwtf']:.2f}")
    # Distribution verdict (the CI's drift kernel): only the classes
    # that disagree are worth a line; agreement is the quiet default.
    verdict = "DRIFT" if cmp["distribution_drift"] else "consistent"
    lines.append(f"  distribution       {verdict}")
    lines.extend(f"    {d}" for d in format_drift_lines(cmp))
    return "\n".join(lines)


# -- per-section attribution (per-register counts :259-287 + per-symbol
#    examineSymbolInjections :340-455) ---------------------------------------

def section_stats(docs: Iterable[Dict[str, object]],
                  kinds: Optional[set] = None) -> Dict[str, Dict[str, int]]:
    """symbol -> {class -> count, 'injections' -> n}.

    On TPU the injected "section"/"symbol" is the state leaf recorded in each
    run's ``symbol`` key (fallback: parse the ``name`` field's ``sym[lane``
    shape), so register-style and symbol-style attribution coincide.
    ``kinds`` restricts the table to sections of those kinds (e.g.
    ``{"reg", "ctrl"}`` for the reference's per-register error counts,
    jsonParser.py:259-287).
    """
    table: Dict[str, Dict[str, int]] = {}
    for doc in docs:
        if "columns" in doc:                      # vectorised columnar path
            import numpy as np
            col = doc["columns"]  # type: ignore
            codes = np.asarray(col["code"])
            leaf_ids = np.asarray(col["leaf_id"]).copy()
            # Cache draws outside the program footprint (t < 0, never
            # fired) go to the '<invalid-line>' bucket, matching
            # to_injection_logs' symbol override.
            invalid_line = np.asarray(col["t"]) < 0
            leaf_ids[invalid_line] = -1
            sec_name = {s["leaf_id"]: s["name"]
                        for s in doc.get("sections", [])}  # type: ignore
            sec_name[-1] = "<invalid-line>"
            sec_kind = {s["leaf_id"]: s.get("kind")
                        for s in doc.get("sections", [])}  # type: ignore
            for lid in np.unique(leaf_ids):
                if kinds is not None and sec_kind.get(int(lid)) not in kinds:
                    continue
                sym = sec_name.get(int(lid), "?")
                row = table.setdefault(
                    sym, {**{cls: 0 for cls in _CLASSES}, "injections": 0})
                sel = codes[leaf_ids == lid]
                binc = np.bincount(sel, minlength=len(_CLASSES))
                row["injections"] += len(sel)
                for i, cls in enumerate(_CLASSES):
                    row[cls] += int(binc[i])
            continue
        for run in doc["runs"]:  # type: ignore
            if kinds is not None and run.get("section") not in kinds:
                continue
            sym = run.get("symbol")
            if not sym:
                sym = str(run.get("name", "?")).split("[", 1)[0]
            row = table.setdefault(
                sym, {**{cls: 0 for cls in _CLASSES}, "injections": 0})
            row["injections"] += 1
            row[classify_run(run)] += 1
    return table


def trap_counts(docs: Iterable[Dict[str, object]]) -> Tuple[int, int]:
    """(traps, timeouts): how many DUE timeouts were traps (``-t``,
    jsonParser.py countTrap).  TPU runs cannot trap -- there is no
    exception vector, the watchdog bound is the only hang detector -- so
    traps is 0 unless logs came from another platform; the flag exists
    for CLI parity and honest reporting of that difference."""
    traps = timeouts = 0
    for doc in docs:
        if "columns" in doc:
            import numpy as np
            codes = np.asarray(doc["columns"]["code"])  # type: ignore
            timeouts += int((codes == _CLASSES.index("due_timeout")).sum())
        else:
            for run in doc["runs"]:  # type: ignore
                res = run.get("result") or {}
                if "timeout" in res:
                    timeouts += 1
                    traps += 1 if res.get("trap") else 0
    return traps, timeouts


def format_section_stats(table: Dict[str, Dict[str, int]]) -> str:
    # ``sdc`` column = _SDC_CLASSES: train campaigns refine the raw sdc
    # bucket into train_sdc, which must still rank/print as corruption.
    def _sdc(row):
        return sum(row.get(k, 0) for k in _SDC_CLASSES)

    lines = ["--- per-section attribution ---",
             f"  {'symbol':<20} {'inj':>7} {'sdc':>6} {'corr':>6} "
             f"{'due':>6} {'inv':>5}  sdc%"]
    for sym in sorted(table, key=lambda s: -_sdc(table[s])):
        row = table[sym]
        due = sum(row.get(k, 0) for k in _DUE_CLASSES)
        sdc = _sdc(row)
        pct = 100.0 * sdc / row["injections"] if row["injections"] else 0
        lines.append(f"  {sym:<20} {row['injections']:>7} {sdc:>6} "
                     f"{row['corrected']:>6} {due:>6} {row['invalid']:>5}  "
                     f"{pct:5.1f}%")
    return "\n".join(lines)


# -- injection-time histogram (pcStats :216-230) -----------------------------

def cycle_histogram(docs: Iterable[Dict[str, object]],
                    bins: int = 20) -> List[Tuple[int, int, int]]:
    """[(lo, hi, count)] over the injection step index ('cycles' key)."""
    cycles = []
    for doc in docs:
        if "columns" in doc:
            cycles.extend(doc["columns"]["t"])  # type: ignore
        else:
            cycles.extend(int(run.get("cycles", 0))
                          for run in doc["runs"])  # type: ignore
    if not cycles:
        return []
    lo, hi = min(cycles), max(cycles)
    width = max(1, (hi - lo + bins) // bins)
    counts = [0] * bins
    for c in cycles:
        counts[min((c - lo) // width, bins - 1)] += 1
    return [(lo + i * width, lo + (i + 1) * width - 1, counts[i])
            for i in range(bins)]


def format_cycle_histogram(hist: List[Tuple[int, int, int]]) -> str:
    if not hist:
        return "--- cycle histogram: no runs ---"
    peak = max(c for _, _, c in hist) or 1
    lines = ["--- injection-step histogram ---"]
    for lo, hi, c in hist:
        bar = "#" * int(40 * c / peak)
        lines.append(f"  [{lo:>6}-{hi:>6}] {c:>7} {bar}")
    return "\n".join(lines)


# Eight-level bar glyphs for the one-line sparkline rendering.
_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def format_cycle_sparkline(hist: List[Tuple[int, int, int]]) -> str:
    """One-line rendering of the injection-step histogram (the pcStats
    cycle plot, jsonParser.py:216-230, without the matplotlib dependency):
    one block glyph per bin, height proportional to count."""
    if not hist:
        return "steps: (no runs)"
    peak = max(c for _, _, c in hist) or 1
    bars = "".join(
        _SPARK_GLYPHS[(c * (len(_SPARK_GLYPHS) - 1)) // peak]
        for _, _, c in hist)
    lo, hi = hist[0][0], hist[-1][1]
    return f"  steps {lo}-{hi}  {bars}  (peak {peak}/bin)"


def histogram_json(hist: List[Tuple[int, int, int]]) -> Dict[str, object]:
    """JSON document for ``--hist-out``: the pcStats data as machine-
    readable bins rather than rendered text."""
    return {"metric": "injection_step_histogram",
            "bins": [{"lo": int(lo), "hi": int(hi), "count": int(c)}
                     for lo, hi, c in hist],
            "total": int(sum(c for _, _, c in hist))}


# -- CLI ---------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    paths: List[str] = []
    compare_path: Optional[str] = None
    per_section = False
    histogram = False
    hist_out: Optional[str] = None
    registers = False
    count_trap = False
    no_summary = False
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg in ("-k", "-d"):
            # -k compares files, -d directories; _iter_docs walks either,
            # so both resolve to the same comparison path (jsonParser.py
            # compare-files :88 / compare-dirs :89).
            i += 1
            if i >= len(argv):
                print(f"ERROR: {arg} needs a path", file=sys.stderr)
                return 2
            compare_path = argv[i]
        elif arg == "-p":
            per_section = True
        elif arg == "-c":
            histogram = True
        elif arg == "--hist-out" or arg.startswith("--hist-out="):
            # pcStats JSON export; implies the histogram pass (-c).
            if arg.startswith("--hist-out="):
                hist_out = arg.partition("=")[2]
            else:
                i += 1
                if i >= len(argv):
                    print("ERROR: --hist-out needs a path", file=sys.stderr)
                    return 2
                hist_out = argv[i]
            if not hist_out:
                print("ERROR: --hist-out needs a path", file=sys.stderr)
                return 2
            histogram = True
        elif arg == "-r":
            registers = True
        elif arg == "-t":
            count_trap = True
        elif arg == "-n":
            no_summary = True
        elif arg.startswith("-"):
            print(f"ERROR: unknown flag {arg}", file=sys.stderr)
            return 2
        else:
            paths.append(arg)
        i += 1
    if not paths:
        print(__doc__, file=sys.stderr)
        return 2

    def _load(path: str):
        try:
            return [doc for _, doc in _iter_docs(path)]
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"ERROR: {path}: {e}", file=sys.stderr)
            return None

    # The per-run tables need materialised docs; a plain summary (or
    # comparison) can take the native ndjson fast path in summarize_path
    # instead of per-line json.loads (~40x at 10^6 rows).
    need_docs = per_section or registers or count_trap or histogram

    def _summary(path: str) -> Optional[Summary]:
        try:
            return summarize_path(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"ERROR: {path}: {e}", file=sys.stderr)
            return None

    compare_summary: Optional[Summary] = None
    if compare_path is not None:
        compare_summary = _summary(compare_path)
        if compare_summary is None:
            return 1

    for path in paths:
        docs = None
        if need_docs:
            docs = _load(path)
            if docs is None:
                return 1
            base = summarize_runs(
                os.path.basename(path.rstrip("/")) or path, docs)
        else:
            base = _summary(path)
            if base is None:
                return 1
        if compare_summary is not None:
            print(format_comparison(base, compare_summary))
        elif not no_summary:
            print(base.format())
        if per_section:
            print(format_section_stats(section_stats(docs)))
        if registers:
            print(format_section_stats(
                section_stats(docs, kinds={"reg", "ctrl", "cfcss"})))
        if count_trap:
            traps, timeouts = trap_counts(docs)
            print(f"traps: {traps} of {timeouts} timeouts")
        if histogram:
            hist = cycle_histogram(docs)
            print(format_cycle_histogram(hist))
            print(format_cycle_sparkline(hist))
            if hist_out:
                with open(hist_out, "w") as fh:
                    json.dump(histogram_json(hist), fh, indent=1)
                print(f"# wrote {hist_out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
