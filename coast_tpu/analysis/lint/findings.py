"""Finding/report model of the replication-integrity linter.

The reference refuses to emit a binary when its post-pass checks fail
(verifyCloningSuccess, cloning.cpp:2305-2376, gated by ``-noCloneOpsCheck``;
SoR verification exits -1).  The TPU linter reports *structured* findings
instead -- ``(rule id, severity, locus, message)`` -- so the same result
can gate ``opt`` (exit nonzero on errors), be exported as JSON next to
``-dumpModule``, and be baselined/suppressed for incremental adoption
(the FuzzyFlow/FastFlip workflow of PAPERS.md: per-cutout findings you
triage once and pin).

Severities:

  * ``error`` -- redundancy is broken or contradicts the config; gating.
  * ``warning`` -- suspicious but not provably wrong (e.g. an extra vote).
  * ``note``  -- accepted by configuration (the ``skipLibCalls`` SPOF
    allowlist); the SPOF report's "known single points of failure".

Suppression/baseline file: a JSON doc ``{"suppress": [<fingerprint>...]}``
where a fingerprint is ``benchmark:rule:locus`` (the stable identity of a
finding, deliberately excluding the message text; benchmark-scoped so a
baseline written for one program cannot mask the same-named error in
another).  ``LintReport.write_baseline`` emits one from the current
findings; ``apply_baseline`` marks matching findings suppressed so they
stop gating without being deleted from the report.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Set

SEVERITIES = ("error", "warning", "note")


@dataclasses.dataclass
class Finding:
    """One linter finding."""

    rule: str              # e.g. "lane-collapse", "voter-coverage"
    severity: str          # error | warning | note
    locus: str             # leaf/eqn locus, e.g. "leaf:buf" / "eqn:reduce_sum"
    message: str
    suppressed: bool = False

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "severity": self.severity,
                "locus": self.locus, "message": self.message,
                "suppressed": self.suppressed}

    def format(self) -> str:
        sup = " [suppressed]" if self.suppressed else ""
        return f"{self.severity}{sup}: [{self.rule}] {self.locus}: " \
               f"{self.message}"


@dataclasses.dataclass
class LintReport:
    """All findings for one protected program."""

    benchmark: str
    strategy: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    # Which passes ran (provenance / coverage / survival): honest scope
    # reporting -- a clean report that skipped survival is not a clean
    # survival report.
    passes_run: List[str] = dataclasses.field(default_factory=list)

    def add(self, rule: str, severity: str, locus: str, message: str) -> None:
        if severity not in SEVERITIES:
            raise ValueError(f"bad severity {severity!r}")
        self.findings.append(Finding(rule, severity, locus, message))

    def extend(self, other: "LintReport") -> None:
        self.findings.extend(other.findings)
        for p in other.passes_run:
            if p not in self.passes_run:
                self.passes_run.append(p)

    # -- gating ---------------------------------------------------------
    def errors(self) -> List[Finding]:
        return [f for f in self.findings
                if f.severity == "error" and not f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            if not f.suppressed:
                out[f.severity] += 1
        out["suppressed"] = sum(1 for f in self.findings if f.suppressed)
        return out

    # -- baseline / suppression -----------------------------------------
    def fingerprint_of(self, f: Finding) -> str:
        """Benchmark-scoped stable identity: generic loci (``hlo:select``
        and friends) repeat across programs, so an un-scoped fingerprint
        from one benchmark would silently suppress a NEW error anywhere
        else."""
        return f"{self.benchmark}:{f.rule}:{f.locus}"

    def apply_baseline(self, fingerprints: Set[str]) -> None:
        for f in self.findings:
            if self.fingerprint_of(f) in fingerprints:
                f.suppressed = True

    def write_baseline(self, path: str) -> None:
        write_baseline_set([self], path)

    # -- serialization ---------------------------------------------------
    def sorted_findings(self) -> List[Finding]:
        """Findings in deterministic artifact order: stable sort by
        ``rule:locus``, so exports diff cleanly in CI even when pass
        internals reorder their emission (dict/walk order is an
        implementation detail; the artifact's order must not be).
        Ties (same rule+locus, different message) keep emission order --
        the sort is stable."""
        return sorted(self.findings, key=lambda f: (f.rule, f.locus))

    def to_dict(self) -> Dict[str, object]:
        return {
            "benchmark": self.benchmark,
            "strategy": self.strategy,
            "passes_run": list(self.passes_run),
            "counts": self.counts(),
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.sorted_findings()],
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=1)
            fh.write("\n")

    def format(self, include_notes: bool = True) -> str:
        c = self.counts()
        lines = [f"=== lint {self.benchmark} [{self.strategy}] "
                 f"({', '.join(self.passes_run) or 'no passes'}): "
                 f"{c['error']} error(s), {c['warning']} warning(s), "
                 f"{c['note']} note(s), {c['suppressed']} suppressed ==="]
        for f in self.findings:
            if f.severity == "note" and not include_notes:
                continue
            lines.append("  " + f.format())
        return "\n".join(lines)


class ReplicationLintError(Exception):
    """Raised by gating call sites (opt's -noCloneOpsCheck default, the
    CampaignRunner pre-flight) when a lint report carries unsuppressed
    errors -- the analogue of verifyCloningSuccess's refusal to emit."""

    def __init__(self, report: LintReport):
        self.report = report
        super().__init__(report.format(include_notes=False))


def write_baseline_set(reports: Iterable[LintReport], path: str) -> None:
    """One baseline covering several reports, each finding fingerprinted
    under its own report's benchmark (NOT a merged report's placeholder
    name -- merging first would lose the scoping)."""
    fps: Set[str] = set()
    for r in reports:
        fps.update(r.fingerprint_of(f) for f in r.findings)
    with open(path, "w") as fh:
        json.dump({"suppress": sorted(fps)}, fh, indent=1)
        fh.write("\n")


def load_baseline(path: str) -> Set[str]:
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or not isinstance(
            doc.get("suppress"), list):
        raise ValueError(f"{path}: not a lint baseline "
                         '(expected {"suppress": [...]})')
    return set(str(s) for s in doc["suppress"])


def merge_reports(reports: Iterable[LintReport],
                  benchmark: str = "<multi>",
                  strategy: str = "<multi>") -> LintReport:
    out = LintReport(benchmark=benchmark, strategy=strategy)
    for r in reports:
        out.extend(r)
    return out
