"""Replication-integrity linter: does the protected program still carry
its redundancy?

Two levels (ISSUE: the real meaning of the reference's
``-noCloneOpsCheck`` post-pass check):

  * :mod:`provenance` -- static jaxpr lane-provenance rules over the
    traced protected step (lane-collapse, SPOF, voter coverage vs the
    ProtectionConfig, unreplicated imports);
  * :mod:`survival` -- post-XLA checks over the *compiled* step
    (voter ops present in optimized HLO, semantic lane-perturbation
    probe, segmented CSE fingerprint).

Entry points::

    from coast_tpu.analysis import lint
    report = lint.lint_program(prog)              # both levels
    report = lint.lint_program(prog, survival=False)   # static only
    lint.check(prog)        # raise ReplicationLintError on errors

CLI: ``python -m coast_tpu.analysis.lint -TMR matrixMultiply crc16``
(see __main__.py; ``--all`` sweeps the benchmark REGISTRY).
"""

from __future__ import annotations

from typing import Optional, Set

from coast_tpu.analysis.lint.findings import (Finding, LintReport,
                                              ReplicationLintError,
                                              load_baseline, merge_reports)
from coast_tpu.analysis.lint.provenance import (expected_sync_classes,
                                                lint_provenance, trace_step)
from coast_tpu.analysis.lint.survival import lint_survival

__all__ = ["Finding", "LintReport", "ReplicationLintError", "check",
           "expected_sync_classes", "lint_program", "lint_provenance",
           "lint_survival", "load_baseline", "merge_reports", "trace_step"]


def lint_program(prog, provenance: bool = True, survival: bool = True,
                 strategy: Optional[str] = None,
                 baseline: Optional[Set[str]] = None,
                 closed=None) -> LintReport:
    """Run the requested lint levels over a ProtectedProgram.
    ``closed`` forwards an already-traced step jaxpr (callers that also
    dump the jaxpr, e.g. opt, trace once and share)."""
    name = strategy or f"N={prog.cfg.num_clones}"
    report = LintReport(benchmark=prog.region.name, strategy=name)
    # One trace shared by both passes (flagship steps take seconds to
    # trace; the survival pass only needs the jaxpr for vote counting).
    if closed is None and (provenance or survival):
        closed = trace_step(prog)
    if provenance:
        lint_provenance(prog, report, closed=closed)
    if survival:
        lint_survival(prog, report, closed=closed)
    if baseline:
        report.apply_baseline(baseline)
    return report


def check(prog, provenance: bool = True, survival: bool = True,
          baseline: Optional[Set[str]] = None) -> LintReport:
    """Gate: lint and raise :class:`ReplicationLintError` on any
    unsuppressed error finding (the refuse-to-emit analogue)."""
    report = lint_program(prog, provenance=provenance, survival=survival,
                          baseline=baseline)
    if not report.ok:
        raise ReplicationLintError(report)
    return report
