"""Replication-integrity linter: does the protected program still carry
its redundancy?

Two levels (ISSUE: the real meaning of the reference's
``-noCloneOpsCheck`` post-pass check):

  * :mod:`provenance` -- static jaxpr lane-provenance rules over the
    traced protected step (lane-collapse, SPOF, voter coverage vs the
    ProtectionConfig, unreplicated imports);
  * :mod:`survival` -- post-XLA checks over the *compiled* step
    (voter ops present in optimized HLO, semantic lane-perturbation
    probe, segmented CSE fingerprint).

Entry points::

    from coast_tpu.analysis import lint
    report = lint.lint_program(prog)              # both levels
    report = lint.lint_program(prog, survival=False)   # static only
    lint.check(prog)        # raise ReplicationLintError on errors

CLI: ``python -m coast_tpu.analysis.lint -TMR matrixMultiply crc16``
(see __main__.py; ``--all`` sweeps the benchmark REGISTRY).
"""

from __future__ import annotations

from typing import Optional, Set

from coast_tpu.analysis.lint.findings import (Finding, LintReport,
                                              ReplicationLintError,
                                              load_baseline, merge_reports)
from coast_tpu.analysis.lint.provenance import (expected_sync_classes,
                                                lint_provenance, trace_step)
from coast_tpu.analysis.lint.survival import lint_survival

__all__ = ["Finding", "LintReport", "ReplicationLintError", "check",
           "expected_sync_classes", "lint_program", "lint_provenance",
           "lint_survival", "load_baseline", "merge_reports", "trace_step"]


def lint_program(prog, provenance: bool = True, survival: bool = True,
                 strategy: Optional[str] = None,
                 baseline: Optional[Set[str]] = None,
                 closed=None, propagation: bool = False,
                 facts=None) -> LintReport:
    """Run the requested lint levels over a ProtectedProgram.
    ``closed`` forwards an already-traced step jaxpr (callers that also
    dump the jaxpr, e.g. opt, trace once and share).

    ``propagation`` adds the third static pass: the lane-isolation
    noninterference prover (:mod:`coast_tpu.analysis.propagation`).
    Each refuted leak lands as an ``isolation-leak`` error finding
    carrying its counterexample dataflow path, so the standard gates
    (``opt``'s refuse-to-run, ``CampaignRunner(preflight=)``) cover it
    with no new plumbing.  Pure jaxpr analysis -- no extra compile;
    ``facts`` forwards an already-built shared walk
    (:func:`~coast_tpu.analysis.propagation.walker.analyze_step`) so
    callers that also build the vulnerability map walk once."""
    name = strategy or f"N={prog.cfg.num_clones}"
    report = LintReport(benchmark=prog.region.name, strategy=name)
    # One trace shared by all passes (flagship steps take seconds to
    # trace; the survival pass only needs the jaxpr for vote counting).
    if closed is None and (provenance or survival or propagation):
        closed = facts.closed if facts is not None else trace_step(prog)
    if provenance:
        lint_provenance(prog, report, closed=closed)
    if survival:
        lint_survival(prog, report, closed=closed)
    if propagation:
        from coast_tpu.analysis.propagation import prove_isolation
        report.passes_run.append("propagation")
        proof = prove_isolation(prog, closed=closed, facts=facts,
                                strategy=name)
        for leak in proof.leaks:
            report.add(
                "isolation-leak", "error", f"output:{leak.output}",
                f"noninterference refuted: {leak.source} reaches step "
                f"output '{leak.output}' without a sanctioned vote "
                "(counterexample: " + " -> ".join(leak.path) + ")")
        if proof.total_leak_paths > len(proof.leaks):
            report.add(
                "isolation-leak", "error", "output:<more>",
                f"{proof.total_leak_paths - len(proof.leaks)} further "
                "leak path(s) suppressed from the report")
    if baseline:
        report.apply_baseline(baseline)
    return report


def check(prog, provenance: bool = True, survival: bool = True,
          baseline: Optional[Set[str]] = None,
          propagation: bool = False) -> LintReport:
    """Gate: lint and raise :class:`ReplicationLintError` on any
    unsuppressed error finding (the refuse-to-emit analogue)."""
    report = lint_program(prog, provenance=provenance, survival=survival,
                          baseline=baseline, propagation=propagation)
    if not report.ok:
        raise ReplicationLintError(report)
    return report
