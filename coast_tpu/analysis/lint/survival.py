"""Post-XLA redundancy-survival pass.

The provenance pass proves the *traced* program keeps its replicas; this
pass checks that the redundancy survived **compilation** -- the hazard
named in ops/bitflip.py: XLA may CSE replicated computations into one,
quietly turning TMR into a single point of failure while every test still
passes (the reference's motivation for running verifyCloningSuccess on
the transformed module, not the source).

Three checks over the *compiled* protected step:

  * **hlo-voter-missing** (error): the optimized HLO must still contain
    at least one ``select`` (TMR majority) / ``compare`` (both modes) per
    vote the traced jaxpr carried.  A voter folded away by the compiler
    is a silent loss of repair/detection.
  * **lane-dedup** (error): a semantic probe of the compiled executable.
    For each probed replicated leaf and each lane, one input bit is
    flipped and the step re-run: a redundant program must respond --
    either the flip survives into the committed state (bitwise diff) or
    a voter observes the divergence (TMR correction count / DWC fault
    flag).  A lane whose perturbation provokes *no* response at any probe
    site is dead weight: its replica was deduplicated (or never
    distinct), and an injection there can neither be corrected nor
    detected.  This runs the actual XLA executable, so it catches
    compiler-introduced sharing the jaxpr cannot show.
  * **segment-cse** (error, segmented ``-s`` mode only): an opcode
    fingerprint of the optimized HLO.  The unrolled per-lane bodies must
    contribute ~``num_clones`` times the arithmetic of a single lane
    (lowered from the bare region step); a ratio collapsing toward 1x
    means the lanes were deduplicated into one fingerprint.

Probe-site selection is honest about observability: a leaf whose step
output does not depend on its own previous value (fully rewritten each
step) cannot show a one-step response and is skipped with a note; voted
TMR leaves need ``count_errors`` for the correction counter to witness
the repair.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from coast_tpu.analysis.lint.findings import LintReport

# Arithmetic opcodes counted by the segmented fingerprint.  Deliberately
# excludes select/compare/and/or/not (voter machinery) and data movement
# (broadcast/reshape/copy), which differ between the protected and bare
# lowering.
_SIG_OPS = ("add", "subtract", "multiply", "divide", "remainder", "xor",
            "shift-left", "shift-right-logical", "shift-right-arithmetic",
            "dot", "maximum", "minimum", "power")
_SIG_FLOOR = 8          # fingerprint is meaningless on near-empty steps
_MAX_PROBE_LEAVES = 4   # per-program probe budget (lanes x sites each)


def _count_ops(hlo: str, ops: Tuple[str, ...]) -> Dict[str, int]:
    counts = {op: 0 for op in ops}
    # HLO text: "%name = type op(operands...)" (also inside fusion bodies).
    for m in re.finditer(r"= \S+ ([a-z0-9-]+)\(", hlo):
        op = m.group(1)
        if op in counts:
            counts[op] += 1
    return counts


def _lower_hlo(fn, *args) -> str:
    return jax.jit(fn).lower(*args).compile().as_text()


def _count_votes(prog, closed=None) -> int:
    """Number of classified vote sites in the traced step (live or not:
    XLA decides liveness itself; the sync tags are inserted one per vote
    call)."""
    from coast_tpu.analysis.lint import provenance as P
    if closed is None:
        closed = P.trace_step(prog)
    n = 0

    def walk(jaxpr):
        nonlocal n
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "name":
                tag = str(eqn.params.get("name", ""))
                if P._parse_sync_tag(tag) is not None:
                    n += 1
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    walk(v.jaxpr if hasattr(v.jaxpr, "eqns") else v)
                elif isinstance(v, (list, tuple)):
                    for b in v:
                        if hasattr(b, "jaxpr"):
                            walk(b.jaxpr)

    walk(closed.jaxpr)
    return n


# Bits probed per site: a flip must SURVIVE the program's own arithmetic
# to be observable through an unvoted leaf, and real programs mask high
# bits (crc16's ``& 0xFFFF``) or low bits (flag words) freely -- so probe
# the bottom, middle, and top of the word and accept any responder.
_PROBE_BITS = (0, 15, 31)
# Successive program states probed (phase-gated leaves respond only in
# the micro-step that consumes them; 3 covers every 2-phase region with
# one spare).
_PROBE_STATES = 3


def _flip_bit(arr: np.ndarray, lane: int, word: int, bit: int) -> np.ndarray:
    """XOR one bit of flat 32-bit word ``word`` of ``lane``."""
    out = np.array(arr)
    flat = out.reshape(out.shape[0], -1).view(np.uint32)
    flat[lane, word] ^= np.uint32(1 << bit)
    return out


def _tree_bytes(tree) -> bytes:
    return b"".join(np.asarray(leaf).tobytes()
                    for leaf in jax.tree.leaves(tree))


def _probe_leaves(prog) -> Tuple[List[str], List[str]]:
    """(probed, skipped-with-reason) leaf selections for the lane probe."""
    from coast_tpu.passes.verification import analyze
    flow = analyze(prog.region)
    probed: List[str] = []
    skipped: List[str] = []
    for name in prog.leaf_order:
        if name not in prog.region.spec:
            continue                     # synthetic (CFCSS) leaves
        if not prog.replicated.get(name):
            continue
        self_dep = name in flow.deps.get(name, frozenset())
        passthrough = name not in flow.written
        if not (self_dep or passthrough):
            skipped.append(
                f"{name}: fully rewritten each step, no one-step response "
                "channel")
            continue
        voted = prog.step_sync.get(name) or prog.pre_sync.get(name)
        if (voted and prog.cfg.num_clones == 3
                and not prog.cfg.count_errors):
            skipped.append(
                f"{name}: voted leaf but -countErrors is off, repair "
                "leaves no witness")
            continue
        probed.append(name)
    for name in probed[_MAX_PROBE_LEAVES:]:
        # Honest coverage: a budget-dropped leaf must say so -- a clean
        # report that silently skipped a leaf is not a clean report.
        skipped.append(f"{name}: probe budget ({_MAX_PROBE_LEAVES} "
                       "leaves per program) exhausted")
    return probed[:_MAX_PROBE_LEAVES], skipped


def lint_survival(prog, report: Optional[LintReport] = None,
                  closed=None) -> LintReport:
    """Run the post-XLA checks.  Compiles the protected step for the
    current default backend and executes the lane probe on it.
    ``closed`` forwards an already-traced step jaxpr (lint_program's,
    so a full lint traces once)."""
    cfg = prog.cfg
    region = prog.region
    if report is None:
        report = LintReport(benchmark=region.name,
                            strategy=f"N={cfg.num_clones}")
    report.passes_run.append("survival")
    n = cfg.num_clones
    if n <= 1 or not prog._any_replicated:
        return report

    pstate_s, flags_s = jax.eval_shape(prog.init_pstate)
    t_s = jax.ShapeDtypeStruct((), jnp.int32)
    step = jax.jit(prog.step)
    hlo = step.lower(pstate_s, flags_s, t_s).compile().as_text()

    # -- voter survival -------------------------------------------------
    votes = _count_votes(prog, closed)
    counts = _count_ops(hlo, ("select", "compare"))
    if n == 3 and counts["select"] < votes:
        report.add(
            "hlo-voter-missing", "error", "hlo:select",
            f"optimized HLO contains {counts['select']} select op(s) for "
            f"{votes} traced TMR vote(s): majority voters were compiled "
            "away")
    if counts["compare"] < votes:
        report.add(
            "hlo-voter-missing", "error", "hlo:compare",
            f"optimized HLO contains {counts['compare']} compare op(s) "
            f"for {votes} traced vote(s): miscompare detection was "
            "compiled away")

    # -- semantic lane probe --------------------------------------------
    probed, skipped = _probe_leaves(prog)
    for reason in skipped:
        report.add("lane-probe", "note", f"leaf:{reason.split(':', 1)[0]}",
                   f"lane probe skipped -- {reason.split(': ', 1)[1]}")
    if probed:
        # Probe at several successive program states, not just init:
        # phase-gated leaves (e.g. a compute/store micro-step accumulator)
        # are only observable in the phase that consumes them.
        pstate_t, flags_t = jax.jit(prog.init_pstate)()
        states = []
        for t in range(_PROBE_STATES):
            states.append((pstate_t, flags_t, jnp.int32(t)))
            if t + 1 < _PROBE_STATES:
                pstate_t, flags_t = step(pstate_t, flags_t, jnp.int32(t))
        bases = [_tree_bytes(jax.device_get(step(*s))) for s in states]
        for name in probed:
            lane0 = np.asarray(states[0][0][name])[0]
            if lane0.nbytes % 4:
                # Defensive: the engine's init_pstate enforces 32-bit
                # leaves, but a probe must never crash on a future
                # exotic dtype -- skip with a note instead.
                report.add("lane-probe", "note", f"leaf:{name}",
                           "lane probe skipped -- leaf is not "
                           "32-bit-word addressable")
                continue
            words = lane0.nbytes // 4
            sites = sorted({0, words - 1, words // 2})
            for lane in range(n):
                responded = False
                for (pstate_s, flags_s, t_s), base in zip(states, bases):
                    arr = np.asarray(pstate_s[name])
                    for word in sites:
                        for bit in _PROBE_BITS:
                            perturbed = dict(pstate_s)
                            perturbed[name] = jnp.asarray(
                                _flip_bit(arr, lane, word, bit))
                            got = _tree_bytes(jax.device_get(
                                step(perturbed, flags_s, t_s)))
                            if got != base:
                                responded = True
                                break
                        if responded:
                            break
                    if responded:
                        break
                if not responded:
                    report.add(
                        "lane-dedup", "error", f"leaf:{name}:lane{lane}",
                        f"perturbing lane {lane} of replicated leaf "
                        f"'{name}' (bits {list(_PROBE_BITS)} of words "
                        f"{sites}, steps 0..{_PROBE_STATES - 1}) "
                        "produced no observable response in the "
                        "compiled step: this replica was deduplicated "
                        "or never distinct -- faults there are "
                        "invisible to voting and detection")

    # -- segmented CSE fingerprint --------------------------------------
    if cfg.segmented:
        base_hlo = _lower_hlo(region.bound_step(),
                              jax.eval_shape(region.init),
                              jax.ShapeDtypeStruct((), jnp.int32))
        base_counts = _count_ops(base_hlo, _SIG_OPS)
        prot_counts = _count_ops(hlo, _SIG_OPS)
        s1 = sum(base_counts.values())
        sn = sum(prot_counts.values())
        if s1 < _SIG_FLOOR:
            report.add(
                "segment-cse", "note", "hlo:fingerprint",
                f"fingerprint skipped: single-lane step has only {s1} "
                f"arithmetic op(s) (< {_SIG_FLOOR}), ratio would be "
                "noise")
        elif sn < (n - 0.5) * s1:
            report.add(
                "segment-cse", "error", "hlo:fingerprint",
                f"segmented lowering carries {sn} arithmetic op(s) vs "
                f"{s1} for a single lane (ratio {sn / s1:.2f} < "
                f"{n - 0.5}): the unrolled replica bodies were "
                "deduplicated into one fingerprint")
    return report
