"""Jaxpr lane-provenance pass: static replication-integrity rules.

The engine's replicas live as a leading lane axis on every replicated
state leaf; redundancy is intact exactly while every value derived from
replicated state keeps that axis until a *sanctioned* voter collapses it
(ops/voters.py tags each voter's lane input ``coast:voter`` /
``coast:sync:<class>:<leaf>``).  This pass traces the protected ``step``
to a jaxpr and propagates a replicated/shared lattice over its equation
vars -- the TPU-native analogue of the reference's post-pass cloning
check (``verifyCloningSuccess``, cloning.cpp:2305-2376, gated by
``-noCloneOpsCheck``):

  * **lane-collapse** (error): a reduction (reduce_*/dot contraction)
    merges the lane axis outside a sanctioned voter -- e.g. an averaging
    ``sum(lanes)/3`` that silently replaces majority voting.
  * **spof** (error/note): a single lane is extracted from live replicated
    dataflow outside a voter.  Extracting *every* lane of a source (the
    segmented scheduler's fan-out) is sanctioned; a ``coast:spof:<fn>``
    tag from the ``skipLibCalls``/``cloneAfterCall`` wrappers downgrades
    the finding to a note -- the SPOF report's accepted allowlist.
  * **voter-coverage** (error/warning): the classified vote tags found in
    the live jaxpr, compared against an *independently re-derived*
    expectation from the ``ProtectionConfig`` + region dataflow roles --
    ``-noStoreDataSync`` must remove exactly the store-data votes, a
    dropped terminator vote is an error even though the program still
    runs.
  * **unreplicated-import** (error): a mutable shared leaf is consumed by
    replicated dataflow while its own committed value never passed
    through a voter -- corrupt unprotected state imported identically
    into every replica (the NotProtected->Protected rule of
    verification.cpp:686-718, checked here *after* transformation).

Laned-ness propagation is structural: slice/squeeze/reduce/transpose/
broadcast/reshape/dot_general/control-flow primitives are modelled
exactly; any other primitive keeps the lane axis when the output shape
retains it and otherwise degrades the value to *unknown*, which never
produces findings -- the pass prefers false negatives through exotic ops
over a noisy report.  Findings are only emitted for equations that are
live (reach the step's outputs); dead collapses are XLA-DCE'd and harm
nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
from jax.extend.core import Literal

from coast_tpu.ir.region import (KIND_CTRL, KIND_MEM, KIND_OPT_STATE,
                                 KIND_PARAM, KIND_RO, KIND_STACK)
from coast_tpu.ops.voters import TAG_SPOF, TAG_SYNC, TAG_VIEW, TAG_VOTER
from coast_tpu.analysis.lint.findings import LintReport

# Sync classes with an independently derivable expectation; other classes
# (call_boundary, cfcss, boundary, view) are observed and reported but
# carry no per-leaf expectation from the config alone.  'param' /
# 'opt_state' are the training regions' weight-update commit votes
# (KIND_PARAM / KIND_OPT_STATE leaves follow the store rule under their
# own classes): the selective-xMR transform stands on exactly these
# votes, so a build that loses one must fail coverage, not pass
# vacuously.
COVERAGE_CLASSES = ("load_addr", "store_data", "ctrl", "stack",
                    "sor_crossing", "param", "opt_state")

_SHARED, _LANED, _UNKNOWN = "shared", "laned", "unknown"


@dataclasses.dataclass(frozen=True)
class _Val:
    """Lattice value of one jaxpr var."""

    status: str = _SHARED
    axis: int = 0                  # lane axis position when status == laned
    sanct: bool = False            # laned value inside a sanctioned voter
    voted: bool = False            # some upstream vote in the provenance
    deps: FrozenSet[str] = frozenset()

    def relaned(self, axis: int) -> "_Val":
        return dataclasses.replace(self, status=_LANED, axis=axis)

    def collapsed(self) -> "_Val":
        return dataclasses.replace(self, status=_SHARED, axis=0)


def _join(a: _Val, b: _Val) -> _Val:
    deps = a.deps | b.deps
    voted = a.voted or b.voted
    if a.status == b.status == _LANED and a.axis == b.axis:
        return _Val(_LANED, a.axis, a.sanct and b.sanct, voted, deps)
    if a.status == b.status == _SHARED:
        return _Val(_SHARED, 0, False, voted, deps)
    return _Val(_UNKNOWN, 0, False, voted, deps)


_REDUCE_PRIMS = ("reduce_sum", "reduce_max", "reduce_min", "reduce_and",
                 "reduce_or", "reduce_prod", "reduce_xor", "argmax",
                 "argmin")


class _Walker:
    """Forward lattice walk over a (recursively nested) jaxpr."""

    def __init__(self, n: int):
        self.n = n
        self.env: Dict[object, _Val] = {}
        # Collapse candidates keyed by id(eqn) (deduped across loop
        # fixpoint passes): eqn -> record dict.
        self.candidates: Dict[int, Dict[str, object]] = {}
        # Observed tag eqns: id(eqn) -> tag string.
        self.tags: Dict[int, str] = {}

    # -- var access -----------------------------------------------------
    def val(self, v) -> _Val:
        if isinstance(v, Literal):
            return _Val()
        return self.env.get(v, _Val())

    def _set(self, v, val: _Val) -> None:
        old = self.env.get(v)
        self.env[v] = val if old is None else _join(old, val)

    def seed(self, inner_vars, vals: Sequence[_Val]) -> None:
        for iv, val in zip(inner_vars, vals):
            self._set(iv, val)

    # -- candidate recording --------------------------------------------
    def _candidate(self, eqn, kind: str, src, lane: Optional[int],
                   deps: FrozenSet[str]) -> None:
        self.candidates[id(eqn)] = {
            "eqn": eqn, "kind": kind, "prim": eqn.primitive.name,
            "src": src, "lane": lane, "deps": deps}

    # -- one equation ---------------------------------------------------
    def _eqn_outs(self, eqn, ins: List[_Val]) -> List[_Val]:
        prim = eqn.primitive.name
        params = eqn.params
        n = self.n
        deps = frozenset().union(*(v.deps for v in ins)) if ins \
            else frozenset()
        voted = any(v.voted for v in ins)
        laned_ins = [v for v in ins if v.status == _LANED]
        unknown = any(v.status == _UNKNOWN for v in ins)

        def out_shapes():
            return [getattr(ov.aval, "shape", ()) for ov in eqn.outvars]

        if prim == "name":
            tag = str(params.get("name", ""))
            v = ins[0]
            if tag.startswith((TAG_VOTER, TAG_SYNC, TAG_SPOF, TAG_VIEW)):
                self.tags[id(eqn)] = tag
                v = dataclasses.replace(v, sanct=True, voted=True)
            return [v]

        if prim == "optimization_barrier":
            # An n-ary identity fence: provenance passes through per
            # position.  The generic fallback below would misjudge it --
            # it derives ONE lane axis from the first laned input, so a
            # fence mixing laned and shared operands would degrade the
            # shared ones to unknown and poison everything downstream.
            return list(ins)

        if unknown:
            return [_Val(_UNKNOWN, 0, False, voted, deps)
                    for _ in eqn.outvars]
        if not laned_ins:
            return [_Val(_SHARED, 0, False, voted, deps)
                    for _ in eqn.outvars]
        a = laned_ins[0].axis
        sanct = all(v.sanct for v in laned_ins)
        src = next(iv for iv, v in zip(eqn.invars, ins)
                   if v.status == _LANED)

        def laned_out(axis: int) -> _Val:
            return _Val(_LANED, axis, sanct, voted, deps)

        def unknown_out() -> _Val:
            return _Val(_UNKNOWN, 0, False, voted, deps)

        # -- structural primitives over the lane axis --
        if prim == "slice":
            start = params["start_indices"][a]
            limit = params["limit_indices"][a]
            strides = params["strides"]
            if strides is not None and strides[a] != 1:
                # A strided read of the lane axis keeps only some
                # replicas; that is not full replication -- degrade
                # rather than claim laned.
                return [unknown_out()]
            if limit - start >= n:
                return [laned_out(a)]
            if limit - start == 1:
                if not sanct:
                    self._candidate(eqn, "spof", src, int(start), deps)
                return [_Val(_SHARED, 0, sanct, voted, deps).collapsed()]
            return [unknown_out()]
        if prim == "dynamic_slice":
            if params["slice_sizes"][a] >= n:
                return [laned_out(a)]
            if params["slice_sizes"][a] == 1:
                if not sanct:
                    self._candidate(eqn, "spof", src, None, deps)
                return [_Val(_SHARED, 0, sanct, voted, deps)]
            return [unknown_out()]
        if prim == "squeeze":
            dims = params["dimensions"]
            if a in dims:
                # Only a size-1 axis can be squeezed; a laned axis has
                # size n >= 2, so this cannot be the lane axis anymore --
                # degrade rather than guess.
                return [unknown_out()]
            new_a = a - sum(1 for d in dims if d < a)
            return [laned_out(new_a)]
        if prim in _REDUCE_PRIMS:
            axes = params["axes"]
            if a in axes:
                if not sanct:
                    self._candidate(eqn, "lane-collapse", src, None, deps)
                return [_Val(_SHARED, 0, sanct, voted, deps)]
            new_a = a - sum(1 for d in axes if d < a)
            return [laned_out(new_a)] * len(eqn.outvars)
        if prim == "transpose":
            perm = params["permutation"]
            return [laned_out(list(perm).index(a))]
        if prim == "broadcast_in_dim":
            bdims = params["broadcast_dimensions"]
            return [laned_out(bdims[a])]
        if prim == "reshape":
            in_shape = getattr(eqn.invars[0].aval, "shape", None)
            new_sizes = params["new_sizes"]
            if (in_shape is not None and a < len(new_sizes)
                    and tuple(in_shape[:a + 1]) == tuple(
                        new_sizes[:a + 1])):
                return [laned_out(a)]
            return [unknown_out()]
        if prim == "dot_general":
            (cl, cr), (bl, br) = params["dimension_numbers"]
            outs = []
            lhs, rhs = ins[0], ins[1]
            for side, (c, b) in ((lhs, (cl, bl)), (rhs, (cr, br))):
                if side.status != _LANED:
                    continue
                ax = side.axis
                if ax in c:
                    if not side.sanct:
                        self._candidate(eqn, "lane-collapse",
                                        eqn.invars[0 if side is lhs else 1],
                                        None, deps)
                    outs.append(_Val(_SHARED, 0, side.sanct, voted, deps))
                elif ax in b:
                    outs.append(laned_out(list(b).index(ax)))
                else:
                    # Free dim: batch dims first, then lhs free, then rhs
                    # free (dot_general output layout).
                    if side is lhs:
                        pos = len(bl) + sum(
                            1 for d in range(ax)
                            if d not in bl and d not in cl)
                    else:
                        lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
                        lhs_free = len(lhs_shape) - len(bl) - len(cl)
                        pos = len(bl) + lhs_free + sum(
                            1 for d in range(ax)
                            if d not in br and d not in cr)
                    outs.append(laned_out(pos))
            out = outs[0]
            for o in outs[1:]:
                out = _join(out, o)
            return [out]

        # -- control flow / nested jaxprs --
        if prim == "cond" and "branches" in params:
            per_branch = []
            for br in params["branches"]:
                self.seed(br.jaxpr.invars, ins[1:])
                per_branch.append(self.walk(br.jaxpr))
            outs = []
            for i in range(len(eqn.outvars)):
                o = per_branch[0][i]
                for b in per_branch[1:]:
                    o = _join(o, b[i])
                outs.append(dataclasses.replace(
                    o, deps=o.deps | ins[0].deps,
                    voted=o.voted or voted))
            return outs
        if prim == "while":
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            cj, bj = params["cond_jaxpr"].jaxpr, params["body_jaxpr"].jaxpr
            carry = list(ins[cn + bn:])
            for _ in range(len(carry) + 2):
                self.seed(cj.invars, ins[:cn] + carry)
                self.walk(cj)
                self.seed(bj.invars, ins[cn:cn + bn] + carry)
                new_carry = self.walk(bj)
                joined = [_join(c, nc) for c, nc in zip(carry, new_carry)]
                if joined == carry:
                    break
                carry = joined
            return carry
        if prim == "scan":
            sub = params["jaxpr"].jaxpr
            nc, ncar = params["num_consts"], params["num_carry"]
            consts, carry = list(ins[:nc]), list(ins[nc:nc + ncar])
            xs = []
            for v in ins[nc + ncar:]:
                if v.status == _LANED:
                    # Scanning OVER the lane axis would be a collapse we
                    # cannot attribute; anything else loses one leading
                    # axis.
                    xs.append(dataclasses.replace(v, status=_UNKNOWN)
                              if v.axis == 0 else v.relaned(v.axis - 1))
                else:
                    xs.append(v)
            outs = None
            for _ in range(max(ncar, 1) + 2):
                self.seed(sub.invars, consts + carry + xs)
                outs = self.walk(sub)
                joined = [_join(c, nc_) for c, nc_ in
                          zip(carry, outs[:ncar])]
                if joined == carry:
                    break
                carry = joined
            ys = []
            for v in outs[ncar:]:
                ys.append(v.relaned(v.axis + 1) if v.status == _LANED
                          else v)
            return carry + ys
        for key in ("jaxpr", "call_jaxpr"):
            if key in params:
                sub = params[key]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                self.seed(sub.invars, ins)
                return self.walk(sub)

        # -- generic fallback: lane axis survives iff the output keeps a
        #    dim of size n at the same position; otherwise degrade --
        outs = []
        for shape in out_shapes():
            if len(shape) > a and shape[a] == n:
                outs.append(laned_out(a))
            else:
                outs.append(unknown_out())
        return outs

    def walk(self, jaxpr) -> List[_Val]:
        for eqn in jaxpr.eqns:
            ins = [self.val(v) for v in eqn.invars]
            outs = self._eqn_outs(eqn, ins)
            if len(outs) != len(eqn.outvars):
                deps = frozenset().union(*(v.deps for v in ins)) \
                    if ins else frozenset()
                outs = [_Val(_UNKNOWN if any(
                    v.status != _SHARED for v in ins) else _SHARED,
                    0, False, any(v.voted for v in ins), deps)
                    for _ in eqn.outvars]
            for v, val in zip(eqn.outvars, outs):
                self._set(v, val)
        return [self.val(v) for v in jaxpr.outvars]


# -- liveness ---------------------------------------------------------------

def _mark_all(jaxpr, live: Set[int]) -> None:
    for eqn in jaxpr.eqns:
        live.add(id(eqn))
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                sub = v.jaxpr if hasattr(v.jaxpr, "eqns") else v
                _mark_all(sub, live)
            elif isinstance(v, (list, tuple)):
                for b in v:
                    if hasattr(b, "jaxpr"):
                        _mark_all(b.jaxpr, live)


def _live_eqns(jaxpr, live_out, live: Set[int]) -> None:
    """Backward liveness: mark eqns whose outputs reach ``live_out``.
    Precise positional mapping into pjit/cond sub-jaxprs; loops (while/
    scan) conservatively keep their whole body live."""
    live_vars = set(v for v in live_out if not isinstance(v, Literal))
    for eqn in reversed(jaxpr.eqns):
        if not any(ov in live_vars for ov in eqn.outvars):
            continue
        live.add(id(eqn))
        prim = eqn.primitive.name
        params = eqn.params
        for v in eqn.invars:
            if not isinstance(v, Literal):
                live_vars.add(v)
        if prim == "cond" and "branches" in params:
            for br in params["branches"]:
                sub_live = [br.jaxpr.outvars[i]
                            for i, ov in enumerate(eqn.outvars)
                            if ov in live_vars]
                _live_eqns(br.jaxpr, sub_live, live)
        elif prim in ("while", "scan"):
            for key in ("jaxpr", "cond_jaxpr", "body_jaxpr"):
                if key in params:
                    _mark_all(params[key].jaxpr, live)
        else:
            for key in ("jaxpr", "call_jaxpr"):
                if key in params:
                    sub = params[key]
                    sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                    sub_live = [sub.outvars[i]
                                for i, ov in enumerate(eqn.outvars)
                                if ov in live_vars]
                    _live_eqns(sub, sub_live, live)


# -- expected voter coverage -------------------------------------------------

def expected_sync_classes(region, cfg) -> Dict[str, Set[str]]:
    """Per-leaf expected vote classes, re-derived from the config and the
    region's dataflow roles -- deliberately NOT read from the engine's
    ``step_sync``/``pre_sync`` tables, so an engine bug in the sync-point
    policy shows up as a coverage mismatch."""
    from coast_tpu.passes.verification import analyze
    flow = analyze(region)
    replicated = {name: cfg.resolve_xmr(region, name)
                  for name in region.spec}
    expected: Dict[str, Set[str]] = {name: set() for name in region.spec}
    if cfg.num_clones <= 1 or not any(replicated.values()):
        return expected
    for name, spec in region.spec.items():
        if replicated[name]:
            if cfg.protect_stack and spec.stack:
                expected[name].add("stack")
            if spec.kind == KIND_CTRL:
                in_load = name in flow.load_addr
                in_store = name in flow.store_addr
                if in_load and not cfg.no_load_sync:
                    expected[name].add("load_addr")
                if ((in_store and not cfg.no_store_addr_sync)
                        or not (in_load or in_store)):
                    if not (cfg.protect_stack and spec.stack):
                        expected[name].add("ctrl")
            elif spec.kind == KIND_MEM:
                if (not cfg.no_store_data_sync and name in flow.written
                        and not (cfg.protect_stack and spec.stack)):
                    expected[name].add("store_data")
            elif spec.kind == KIND_STACK:
                # Per-task kernel stacks: store-rule sync points voting
                # under the 'stack' class (the engine's _sync_class_of for
                # KIND_STACK leaves).
                if not cfg.no_store_data_sync and name in flow.written:
                    expected[name].add("stack")
            elif spec.kind in (KIND_PARAM, KIND_OPT_STATE):
                # Training leaves: the weight-update commit vote (store
                # rule under the leaf's own class).  The train regions
                # gate it to the optimizer phase via a store_slice hint,
                # which carries the same classified tag -- the
                # expectation is phase-agnostic on purpose: the vote must
                # EXIST in the live step, wherever it fires.
                if not cfg.no_store_data_sync and name in flow.written:
                    expected[name].add(spec.kind)
        else:
            if (spec.kind != KIND_RO and name in flow.written
                    and not spec.unvoted_crossing):
                # Declared unvoted crossings (exchange-then-vote halo
                # buffers) ship replica data raw on purpose: the engine
                # inserts no sor_crossing vote there, so expecting one
                # would flag every exchange-then-vote build as missing
                # coverage instead of surfacing the REAL finding (the
                # lane collapse the survival pass reports).
                expected[name].add("sor_crossing")
    return expected


def _parse_sync_tag(tag: str) -> Optional[Tuple[str, str]]:
    if not tag.startswith(TAG_SYNC):
        return None
    rest = tag[len(TAG_SYNC):]
    klass, _, leaf = rest.partition(":")
    return klass, leaf


# -- the pass ----------------------------------------------------------------

def trace_step(prog):
    """The protected step's ClosedJaxpr (shared by the provenance and
    survival passes so a full lint traces the step only once)."""
    pstate, flags = jax.eval_shape(prog.init_pstate)
    return jax.make_jaxpr(prog.step)(pstate, flags, jnp.int32(0))


def lint_provenance(prog, report: Optional[LintReport] = None,
                    closed=None) -> LintReport:
    """Run the lane-provenance rules over ``prog.step``'s jaxpr."""
    cfg = prog.cfg
    region = prog.region
    if report is None:
        report = LintReport(benchmark=region.name,
                            strategy=f"N={cfg.num_clones}")
    report.passes_run.append("provenance")
    n = cfg.num_clones

    pstate, flags = jax.eval_shape(prog.init_pstate)
    if closed is None:
        closed = trace_step(prog)
    jaxpr = closed.jaxpr

    state_names = sorted(pstate)
    flag_names = sorted(flags)
    assert len(jaxpr.invars) == len(state_names) + len(flag_names) + 1, (
        len(jaxpr.invars), len(state_names), len(flag_names))

    if n <= 1 or not any(prog.replicated.get(k) for k in pstate):
        # Nothing is replicated: no lanes to lose.  (The reference's
        # check likewise has nothing to verify on an empty clone set.)
        return report

    walker = _Walker(n)
    for name, var in zip(state_names, jaxpr.invars):
        if prog.replicated.get(name):
            walker.env[var] = _Val(_LANED, 0, False, False,
                                   frozenset({name}))
        else:
            walker.env[var] = _Val(_SHARED, 0, False, False,
                                   frozenset({name}))
    # Flags and t carry no leaf provenance.
    out_vals = walker.walk(jaxpr)

    live: Set[int] = set()
    _live_eqns(jaxpr, list(jaxpr.outvars), live)

    # -- lane-collapse / spof findings ----------------------------------
    # The surviving candidate set (all-lane fan-out filtered as the
    # segmented scheduler's sanctioned pattern) is shared with the
    # isolation prover: ONE acceptance rule, spelled once.
    from coast_tpu.analysis.propagation.walker import cross_lane_sites
    for c in cross_lane_sites(walker, live, n):
        leaves = "+".join(sorted(c["deps"])) or "?"
        if c["kind"] == "spof":
            lane = c["lane"]
            where = f"lane {lane}" if lane is not None \
                else "a traced lane index"
            report.add(
                "spof", "error", f"eqn:{c['prim']}:{leaves}",
                f"single lane ({where}) extracted from live "
                f"replicated dataflow of {leaves} outside a "
                "sanctioned voter: one corruptible copy now stands "
                "for all replicas")
        else:
            report.add(
                "lane-collapse", "error",
                f"eqn:{c['prim']}:{leaves}",
                f"{c['prim']} merges the lane axis of {leaves} "
                "outside a sanctioned voter: replicas are combined "
                "without majority voting")

    # -- observed tags (live only) --------------------------------------
    live_tags = [t for k, t in walker.tags.items() if k in live]
    observed: Dict[str, Set[str]] = {}
    spof_tags: Set[str] = set()
    for tag in live_tags:
        parsed = _parse_sync_tag(tag)
        if parsed is not None:
            klass, leaf = parsed
            observed.setdefault(leaf, set()).add(klass)
        elif tag.startswith(TAG_SPOF):
            spof_tags.add(tag[len(TAG_SPOF):])

    # -- SPOF allowlist report ------------------------------------------
    allow = set(cfg.skip_lib_calls) | set(cfg.clone_after_call_fns)
    for fn in sorted(spof_tags):
        if fn in allow:
            report.add(
                "spof", "note", f"fn:{fn}",
                f"accepted single point of failure: '{fn}' runs once on "
                "lane 0's arguments (skipLibCalls/cloneAfterCall "
                "allowlist)")
        else:
            report.add(
                "spof", "error", f"fn:{fn}",
                f"single-lane call to '{fn}' is not in the skipLibCalls/"
                "cloneAfterCall allowlist")

    # -- voter coverage vs. the config ----------------------------------
    expected = expected_sync_classes(region, cfg)
    for name in sorted(region.spec):
        want = expected.get(name, set())
        have = {k for k in observed.get(name, set())
                if k in COVERAGE_CLASSES}
        for klass in sorted(want - have):
            report.add(
                "voter-coverage", "error", f"leaf:{name}",
                f"expected a {klass} vote for leaf '{name}' under this "
                "ProtectionConfig, but the protected step contains none "
                "(the sync point was dropped or compiled around)")
        for klass in sorted(have - want):
            report.add(
                "voter-coverage", "warning", f"leaf:{name}",
                f"unexpected {klass} vote for leaf '{name}': the "
                "ProtectionConfig does not call for this sync point")

    # -- unreplicated-import --------------------------------------------
    out_by_name: Dict[str, _Val] = {}
    outvar_by_name: Dict[str, object] = {}
    invar_by_name = dict(zip(state_names, jaxpr.invars))
    for name, var, val in zip(state_names, jaxpr.outvars, out_vals):
        out_by_name[name] = val
        outvar_by_name[name] = var
    for name in sorted(region.spec):
        if prog.replicated.get(name):
            continue
        spec = region.spec[name]
        if spec.kind == KIND_RO:
            continue
        outvar = outvar_by_name.get(name)
        written = not (outvar is invar_by_name.get(name))
        if not written:
            continue
        consumers = [r for r in sorted(region.spec)
                     if prog.replicated.get(r)
                     and name in out_by_name.get(r, _Val()).deps]
        if consumers and not out_by_name[name].voted:
            report.add(
                "unreplicated-import", "error", f"leaf:{name}",
                f"mutable shared leaf '{name}' feeds replicated leaves "
                f"({', '.join(consumers)}) but its committed value never "
                "passes a voter: corrupt unprotected state would be "
                "imported identically into every replica")
    return report
