"""``python -m coast_tpu.analysis.lint``: replication-integrity linter CLI.

Takes the same single-dash protection flags as ``python -m coast_tpu.opt``
(one parser -- opt's -- so the semantics cannot drift) plus linter
options::

    python -m coast_tpu.analysis.lint -TMR matrixMultiply crc16
    python -m coast_tpu.analysis.lint -DWC -s sha256
    python -m coast_tpu.analysis.lint -TMR --all --json artifacts/lint.json
    python -m coast_tpu.analysis.lint -TMR crc16 --no-survival
    python -m coast_tpu.analysis.lint -TMR crc16 --baseline lint_baseline.json
    python -m coast_tpu.analysis.lint -TMR crc16 --write-baseline b.json
    python -m coast_tpu.analysis.lint -TMR crc16 --propagation

``--propagation`` adds the third static pass: the lane-isolation
noninterference prover gates alongside the other rules (leaks land as
``isolation-leak`` error findings with counterexample paths), and the
static vulnerability map -- per-section ``masked`` /
``detected-bounded`` / ``sdc-possible`` verdicts with ACE-bit counts --
is printed per target (and recorded under a ``propagation`` key in the
``--json`` export).  The map needs one compiled fault-free run per
target to bound the live flip window.

Exit status: 0 when every report is error-free (after baseline
suppression), 1 otherwise, 2 on usage errors.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv

    json_out = None
    baseline_path = None
    write_baseline = None
    survival = True
    propagation = False
    sweep_all = False
    rest: List[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg in ("--json", "--baseline", "--write-baseline"):
            i += 1
            if i >= len(argv):
                print(f"ERROR: {arg} needs a path", file=sys.stderr)
                return 2
            if arg == "--json":
                json_out = argv[i]
            elif arg == "--baseline":
                baseline_path = argv[i]
            else:
                write_baseline = argv[i]
        elif arg == "--no-survival":
            survival = False
        elif arg == "--propagation":
            propagation = True
        elif arg == "--all":
            sweep_all = True
        elif arg.startswith("--"):
            print(f"ERROR: unknown flag {arg}", file=sys.stderr)
            return 2
        else:
            rest.append(arg)
        i += 1

    from coast_tpu.opt import UsageError, build_overrides, parse_argv
    try:
        flags, positional = parse_argv(rest)
        overrides = build_overrides(flags)
    except UsageError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2

    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The axon site hook registers its PJRT plugin and
        # *programmatically* selects jax_platforms="axon,cpu" at
        # interpreter start, overriding the env var; honor the user's
        # CPU request explicitly (same idiom as opt.py).
        jax.config.update("jax_platforms", "cpu")

    from coast_tpu import DWC, TMR
    from coast_tpu.analysis import lint
    from coast_tpu.models import REGISTRY, resolve_region
    from coast_tpu.passes.verification import SoRViolation

    strategies = [s for s in ("TMR", "DWC") if flags.get(s)]
    if len(strategies) > 1:
        print("ERROR: choose one of -TMR/-DWC", file=sys.stderr)
        return 2
    strategy = strategies[0] if strategies else "TMR"
    make = {"TMR": TMR, "DWC": DWC}[strategy]

    benches = sorted(REGISTRY) if sweep_all else positional
    if not benches:
        print(__doc__, file=sys.stderr)
        print(f"benchmarks: {', '.join(sorted(REGISTRY))}", file=sys.stderr)
        return 2
    unknown = [b for b in benches
               if b not in REGISTRY and not b.endswith(".c")]
    if unknown:
        print(f"ERROR: unknown benchmark(s): {', '.join(unknown)}",
              file=sys.stderr)
        return 2

    base = None
    if baseline_path is not None:
        try:
            base = lint.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return 2

    reports = []
    prop_maps = {}
    for bench in benches:
        try:
            region = resolve_region(bench)
            prog = make(region, **overrides)
        except SoRViolation as e:
            print(str(e), file=sys.stderr)
            return 1
        closed = lint.trace_step(prog)
        facts = None
        if propagation:
            from coast_tpu.analysis.propagation import analyze_step
            facts = analyze_step(prog, closed=closed)
        rep = lint.lint_program(prog, survival=survival,
                                strategy=strategy, baseline=base,
                                closed=closed, propagation=propagation,
                                facts=facts)
        reports.append(rep)
        print(rep.format())
        if propagation:
            from coast_tpu.analysis.propagation import analyze_propagation
            vmap = analyze_propagation(prog, facts=facts)
            prop_maps[f"{bench}:{strategy}"] = vmap.summary()
            print(vmap.format())

    if write_baseline is not None:
        from coast_tpu.analysis.lint.findings import write_baseline_set
        write_baseline_set(reports, write_baseline)
        print(f"baseline written: {write_baseline}", file=sys.stderr)
    if json_out is not None:
        doc = {"strategy": strategy,
               "survival": survival,
               "reports": [r.to_dict() for r in reports]}
        if propagation:
            doc["propagation"] = prop_maps
        os.makedirs(os.path.dirname(json_out) or ".", exist_ok=True)
        with open(json_out, "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")

    return 0 if all(r.ok for r in reports) else 1


if __name__ == "__main__":
    sys.exit(main())
