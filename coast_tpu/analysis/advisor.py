"""Selective-hardening advisor: data-driven xMR scope recommendations.

The reference leaves protection scope to the user: docs tell you to hand-
compose ``-ignoreGlbls/-cloneGlbls`` lists per target and iterate against
fault-injection campaigns by hand (the canonical dozens-name scope list of
rtos/pynq/Makefile:8-30 was produced that way).  A batched campaign engine
makes that loop automatic: inject into the *unprotected* program, attribute
SDC/DUE outcomes to the state leaf that was hit (the per-symbol attribution
of jsonParser.py:340-455), and greedily protect the highest-harm leaves --
closed over the SoR rules so the verifier accepts the result -- until a
target residual harm rate (SDC + DUE + INVALID) is met.  The output is both region annotations and
a functions.config-compatible snippet (``cloneGlbls=``/``ignoreGlbls=``),
so the recommendation plugs straight into the reference-style interface
layer.

This is a beyond-parity capability: nothing in the reference automates
scope selection.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

import math

from coast_tpu.inject import classify as cls
from coast_tpu.inject.campaign import CampaignResult, CampaignRunner
from coast_tpu.inject.schedule import generate_stratified_total
from coast_tpu.ir.region import KIND_CTRL, KIND_RO, LeafSpec, Region
from coast_tpu.passes.strategies import TMR, unprotected
from coast_tpu.passes.verification import RegionDataflow, analyze


@dataclasses.dataclass
class LeafHarm:
    """Campaign attribution for one injectable leaf of the unprotected run."""

    name: str
    injections: int
    sdc: int
    due: int
    invalid: int
    words: int

    @property
    def harm(self) -> int:
        """Bad outcomes attributed to this leaf.  INVALID counts: a flip
        that corrupts the check machinery itself (classify.py) is still a
        corruption that protection would have masked."""
        return self.sdc + self.due + self.invalid

    @property
    def harm_rate(self) -> float:
        """P(SDC, DUE or INVALID | flip lands in this leaf)."""
        return self.harm / self.injections if self.injections else 0.0

    @property
    def harm_ci95(self) -> Tuple[float, float]:
        """Wilson 95% interval on harm_rate -- honest uncertainty for the
        leaves a size-weighted campaign would have starved."""
        n = self.injections
        if not n:
            return (0.0, 1.0)
        z = 1.959963984540054
        phat = self.harm / n
        denom = 1 + z * z / n
        centre = phat + z * z / (2 * n)
        half = z * math.sqrt(phat * (1 - phat) / n + z * z / (4 * n * n))
        return (max(0.0, (centre - half) / denom),
                min(1.0, (centre + half) / denom))


@dataclasses.dataclass
class Advice:
    region_name: str
    target_harm: float
    ranked: List[LeafHarm]              # harm-descending attribution table
    protect: List[str]                  # leaves to replicate (SoR-closed)
    annotations: Dict[str, LeafSpec]    # selective spec (xmr islands)
    baseline: Dict[str, object]         # unprotected campaign summary
    achieved: Optional[Dict[str, object]] = None   # selective TMR summary
    full: Optional[Dict[str, object]] = None       # full TMR summary
    protected_words: int = 0
    total_words: int = 0
    baseline_rate: float = 0.0          # post-stratified population estimate
    # static_seed=True: the per-leaf static vulnerability verdicts the
    # probe campaign was seeded with (analysis/propagation).
    static_verdicts: Optional[Dict[str, str]] = None

    @property
    def config_text(self) -> str:
        """functions.config-style snippet (interface/config.py FILE_KEYS):
        the protect list as cloneGlbls, the rest as ignoreGlbls."""
        ignore = [h.name for h in self.ranked if h.name not in self.protect]
        return ("# selective xMR scope recommended by coast_tpu advisor\n"
                f"cloneGlbls={','.join(self.protect)}\n"
                f"ignoreGlbls={','.join(ignore)}\n")

    def format(self) -> str:
        lines = [f"--- selective-hardening advice: {self.region_name} ---",
                 f"  {'leaf':<18} {'inj':>6} {'sdc':>6} {'due':>5} "
                 f"{'inv':>5} {'words':>6}  harm% (95% CI)      protect"]
        for h in self.ranked:
            mark = "xMR" if h.name in self.protect else "-"
            lo, hi = h.harm_ci95
            lines.append(
                f"  {h.name:<18} {h.injections:>6} {h.sdc:>6} {h.due:>5} "
                f"{h.invalid:>5} {h.words:>6}  {100 * h.harm_rate:5.1f} "
                f"[{100 * lo:4.1f},{100 * hi:5.1f}]  {mark}")
        lines.append(f"  replicated words: {self.protected_words}"
                     f"/{self.total_words}")

        def rate(s):
            n = s["injections"]
            sdc = sum(s.get(k, 0) for k in cls.SDC_CLASSES)
            bad = (sdc + s["due_abort"] + s["due_timeout"]
                   + s["invalid"])
            return bad / n if n else 0.0

        lines.append(f"  unprotected harm rate: "
                     f"{100 * self.baseline_rate:.2f}% "
                     f"(post-stratified estimate)")
        if self.achieved is not None:
            lines.append(f"  selective TMR harm rate: "
                         f"{100 * rate(self.achieved):.2f}%")
        if self.full is not None:
            lines.append(f"  full TMR harm rate: {100 * rate(self.full):.2f}%")
        return "\n".join(lines)


def _leaf_harms(res: CampaignResult, runner: CampaignRunner) -> List[LeafHarm]:
    codes = res.codes
    lids = res.schedule.leaf_id
    harms = []
    for sec in runner.mmap.sections:
        sel = codes[lids == sec.leaf_id]
        binc = np.bincount(sel, minlength=cls.NUM_CLASSES)
        harms.append(LeafHarm(
            name=sec.name,
            injections=int(len(sel)),
            sdc=int(binc[cls.SDC] + binc[cls.TRAIN_SDC]),
            due=int(binc[cls.DUE_ABORT] + binc[cls.DUE_TIMEOUT]),
            invalid=int(binc[cls.INVALID]),
            words=int(sec.words * sec.lanes)))
    harms.sort(key=lambda h: (-h.harm_rate, h.name))
    return harms


def _sor_closure(region: Region, flow: RegionDataflow,
                 chosen: FrozenSet[str]) -> FrozenSet[str]:
    """Close the protect-set under the verifier's rules (verification.py;
    reference rules table verification.cpp:686-718) so the recommended
    config always builds:

    * NotProtected->Protected: a replicated leaf may not read a *mutable*
      unprotected leaf, so every mutable transitive source joins the set;
    * unvoted control: once anything is replicated, every KIND_CTRL leaf
      must be too (branch predicates are voted before the branch,
      synchronization.cpp:741-1113), so all ctrl leaves join the set.
    """
    closed = set(chosen)
    if closed:
        closed |= {n for n, s in region.spec.items() if s.kind == KIND_CTRL}
    frontier = list(closed)
    while frontier:
        name = frontier.pop()
        for src in flow.deps.get(name, frozenset()):
            if src != name and src in flow.written and src not in closed:
                closed.add(src)
                frontier.append(src)
    return frozenset(closed)


def _selective_region(region: Region, protect_set: FrozenSet[str]) -> Region:
    spec = {}
    for name, s in region.spec.items():
        spec[name] = dataclasses.replace(s, xmr=(name in protect_set))
    return dataclasses.replace(region, spec=spec, default_xmr=False)


def advise(region: Region,
           budget: int = 8192,
           target_harm: float = 0.0,
           seed: int = 0,
           batch_size: int = 2048,
           validate: bool = True,
           stratified: bool = True,
           cost_aware: bool = False,
           static_seed: bool = False) -> Advice:
    """Recommend a selective xMR scope for ``region``.

    ``budget`` faults are injected into the unprotected program
    (equal-allocation stratified across leaves by default, so small
    control words are measured as well as large buffers); leaves are
    protected greedily by population harm contribution (SoR-closed at
    every step) until the post-stratified residual harm rate is <=
    ``target_harm``.  ``cost_aware=True`` switches the greedy to marginal
    harm removed per replicated word added (the MWTF-shaped ordering),
    which can reach the same target with a smaller replication footprint.
    ``validate=True`` re-runs the campaign against the recommended
    selective TMR and full TMR for the achieved rates.

    ``static_seed=True`` seeds the loop with the static vulnerability
    prior (:mod:`coast_tpu.analysis.propagation`): leaves the map proves
    ``masked`` are dropped from the probe schedule (their strata are
    reallocated to leaves that can actually harm -- a flip the analysis
    proves dead needs no samples), and the recommended protect list is
    ordered by the static ranking -- verdict tier first (``sdc-possible``
    before statically-covered leaves), measured population harm
    contribution within a tier.  The contribution ordering is what makes
    a quarter-budget probe reproduce the full-budget ranking: per-leaf
    conditional rates of similar-harm leaves swap under sampling noise,
    their size-weighted contributions do not (pinned on mm in tests).
    """
    runner = CampaignRunner(unprotected(region), strategy_name="none")
    static_verdicts: Optional[Dict[str, str]] = None
    masked_names: FrozenSet[str] = frozenset()
    if static_seed:
        from coast_tpu.analysis.propagation import (VERDICT_MASKED,
                                                    analyze_propagation)
        vmap = analyze_propagation(runner.prog)
        static_verdicts = vmap.section_verdicts()
        masked_names = frozenset(n for n, v in static_verdicts.items()
                                 if v == VERDICT_MASKED)
    if stratified:
        # Equal-allocation stratified attribution: every leaf measured at
        # the same resolution (size-weighted sampling starves 1-word ctrl
        # leaves next to KiB buffers); population rates recovered below by
        # size-reweighting (post-stratification).
        n_sections = len(runner.mmap.sections)
        n_live = max(1, n_sections - len(masked_names))
        # Static seeding reallocates the provably-masked strata: same
        # total budget, more probes per leaf that can actually harm.
        probe_total = budget * n_sections // n_live if masked_names \
            else budget
        sched = generate_stratified_total(runner.mmap, probe_total, seed,
                                          region.nominal_steps)
        if masked_names:
            lid_of = {s.leaf_id: s.name for s in runner.mmap.sections}
            keep = np.flatnonzero(np.array(
                [lid_of.get(int(l), "?") not in masked_names
                 for l in np.asarray(sched.leaf_id)]))
            sched = runner._take_rows(sched, keep)
        # One-shot campaign: clamp the batch to the schedule (run_schedule
        # edge-pads every batch, and a small stratified budget would
        # otherwise pay for padding rows -- 4x waste at the defaults).
        base = runner.run_schedule(sched, min(batch_size, len(sched)))
    else:
        base = runner.run(budget, seed=seed, batch_size=batch_size)
    harms = _leaf_harms(base, runner)
    flow = analyze(region)

    # Post-stratified population estimate: weight each leaf's conditional
    # harm rate by its share of the injectable bit space.  Exact for
    # stratified campaigns and consistent with the count ratio for
    # size-weighted ones.
    weight = {s.name: s.bits / runner.mmap.total_bits
              for s in runner.mmap.sections}

    def pop_rate(excluded: FrozenSet[str]) -> float:
        return sum(weight[h.name] * h.harm_rate for h in harms
                   if h.name not in excluded)

    protect_set: FrozenSet[str] = frozenset()
    by_name = {h.name: h for h in harms}

    def protectable(h: LeafHarm) -> bool:
        # Never-cloned rule (cloning.cpp:62-288): read-only leaves are
        # unprotectable; flips into them corrupt the oracle itself.
        # Their harm stays in the residual -- a tight target may be
        # unreachable, exactly as on the reference.
        return (h.harm > 0 and h.name in region.spec
                and region.spec[h.name].kind != KIND_RO)

    if cost_aware:
        # MWTF-shaped greedy: each step protects the candidate whose
        # SoR-closed addition removes the most population harm per
        # replicated word added -- the benefit/cost ratio MWTF's
        # (error-rate change)/(runtime change) measures after the fact
        # (jsonParser.py:458-506).  O(n^2) closures; fine at leaf counts.
        while pop_rate(protect_set) > target_harm:
            cur = pop_rate(protect_set)
            best = None
            for h in harms:
                if h.name in protect_set or not protectable(h):
                    continue
                cand = _sor_closure(region, flow, protect_set | {h.name})
                benefit = cur - pop_rate(cand)
                if benefit <= 0:
                    continue
                cost = sum(by_name[n].words for n in cand - protect_set
                           if n in by_name)
                score = benefit / max(cost, 1)
                if best is None or score > best[0]:
                    best = (score, cand)
            if best is None:
                break
            protect_set = best[1]
    else:
        # Greedy by population harm *contribution* (weight x rate), not
        # the conditional rate: a 1-word leaf at 100% harm contributes
        # less campaign harm than a KiB buffer at 30%, and protecting it
        # first would inflate the scope for no residual benefit.
        for h in sorted(harms,
                        key=lambda x: (-weight[x.name] * x.harm_rate,
                                       x.name)):
            if pop_rate(protect_set) <= target_harm:
                break
            if h.harm == 0:
                break
            if h.name in protect_set or not protectable(h):
                continue
            protect_set = _sor_closure(region, flow, protect_set | {h.name})

    annotations = _selective_region(region, protect_set).spec
    if static_verdicts is not None:
        # The static ranking: verdict tier first (sdc-possible leaves
        # lead), size-weighted harm CONTRIBUTION within a tier -- the
        # statistic that stays stable at a quarter of the probe budget
        # where per-leaf conditional rates of neighbouring leaves swap
        # under noise.
        tier = {"sdc-possible": 0, "detected-bounded": 1, "masked": 2}
        protect_list = sorted(
            (h.name for h in harms if h.name in protect_set),
            key=lambda nm: (tier.get(static_verdicts.get(nm, ""), 0),
                            -(weight.get(nm, 0.0)
                              * by_name[nm].harm_rate),
                            nm)) + sorted(protect_set - set(by_name))
    else:
        protect_list = ([h.name for h in harms if h.name in protect_set]
                        + sorted(protect_set - set(by_name)))
    advice = Advice(
        region_name=region.name,
        target_harm=target_harm,
        ranked=harms,
        # protect lists the full closed set (harm-table order first, then
        # any closure members outside it, e.g. non-injectable leaves), so
        # config_text round-trips to exactly the validated scope.
        protect=protect_list,
        annotations=annotations,
        baseline=base.summary(),
        protected_words=sum(by_name[n].words for n in protect_set
                            if n in by_name),
        total_words=sum(h.words for h in harms),
        baseline_rate=pop_rate(frozenset()),
        static_verdicts=static_verdicts,
    )

    if validate and protect_set:
        sel_prog = TMR(_selective_region(region, protect_set))
        sel = CampaignRunner(sel_prog, strategy_name="TMR-selective").run(
            budget, seed=seed, batch_size=batch_size)
        advice.achieved = sel.summary()
        full = CampaignRunner(TMR(region), strategy_name="TMR").run(
            budget, seed=seed, batch_size=batch_size)
        advice.full = full.summary()
    return advice


def main(argv=None) -> int:
    """``python -m coast_tpu.analysis.advisor <benchmark> [-e N] [-t RATE]
    [--seed S] [-o functions.config]`` -- recommend a selective scope for
    a registered benchmark and optionally write the config snippet."""
    import argparse
    import sys

    from coast_tpu.models import REGISTRY

    ap = argparse.ArgumentParser(
        prog="coast_tpu.analysis.advisor",
        description="data-driven selective-xMR scope recommendation")
    ap.add_argument("benchmark",
                    help="registry name (one of: "
                         + ", ".join(sorted(REGISTRY))
                         + ") or a .c source path ('+'-joined for "
                         "multi-TU programs), like the other CLIs")
    ap.add_argument("-e", type=int, default=8192, metavar="N",
                    help="injection budget (default 8192)")
    ap.add_argument("-t", type=float, default=0.0, metavar="RATE",
                    help="target residual harm rate, SDC+DUE+INVALID (default 0: minimal)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-validate", action="store_true",
                    help="skip the selective/full TMR validation campaigns")
    ap.add_argument("--cost-aware", action="store_true",
                    help="greedy by harm removed per replicated word "
                         "(smaller footprint for the same target)")
    ap.add_argument("--static-seed", action="store_true",
                    help="seed the loop with the static vulnerability "
                         "prior (analysis/propagation): masked leaves "
                         "are not probed, and the protect ranking is "
                         "verdict tier + harm contribution (stable at a "
                         "fraction of the probe budget)")
    ap.add_argument("-o", metavar="PATH",
                    help="write the functions.config snippet here")
    args = ap.parse_args(argv)

    import jax
    if __import__("os").environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from coast_tpu.frontend import LiftError
    from coast_tpu.models import resolve_region
    # Name/path validation FIRST, so an internal KeyError inside a valid
    # model's make_region() surfaces as itself, not as 'unknown
    # benchmark'.
    if not args.benchmark.endswith(".c") and args.benchmark not in REGISTRY:
        ap.error(f"unknown benchmark: {args.benchmark!r} (or pass a .c "
                 "source path)")
    try:
        region = resolve_region(args.benchmark)
    except FileNotFoundError as e:
        ap.error(f"file {e.args[0]} does not exist")
    except LiftError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1

    adv = advise(region, budget=args.e,
                 target_harm=args.t, seed=args.seed,
                 validate=not args.no_validate,
                 cost_aware=args.cost_aware,
                 static_seed=args.static_seed)
    print(adv.format())
    if args.o:
        with open(args.o, "w") as f:
            f.write(adv.config_text)
        print(f"wrote {args.o}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
