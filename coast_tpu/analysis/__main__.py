import sys

from coast_tpu.analysis.json_parser import main

sys.exit(main())
