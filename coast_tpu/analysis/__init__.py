"""Results analysis (the reference's simulation/platform/jsonParser.py)."""

from coast_tpu.analysis.json_parser import (  # noqa: F401
    Summary, classify_run, compare_runs, cycle_histogram, read_json_file,
    section_stats, summarize_path, summarize_runs)
