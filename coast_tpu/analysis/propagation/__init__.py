"""Static fault-propagation analysis: know the outcome before injecting.

The third static pass (after the replication-integrity lint and the
fault-site equivalence partition), built on the same shared
fault-propagation walker (:mod:`walker` -- one abstract interpretation
of the protected step feeds all three):

  * :mod:`vulnmap` -- the ACE-style **static vulnerability map**: each
    (memory-map section, bit class) gets a provable verdict --
    ``masked`` (dead, un-ACE), ``detected-bounded`` (every escape path
    crosses a sanctioned voter/guard/boundary sync), or ``sdc-possible``
    (an unvoted escape path exists, reported with its witness dataflow
    path) -- cross-validated against recorded campaign distributions.
  * :mod:`isolation` -- the **lane-isolation noninterference prover**:
    flips in replica lanes cannot flow into other lanes, shared state,
    or step flags except through sanctioned voted commits; refutations
    carry counterexample paths, and :func:`seeded_voter_bypass` is the
    generic seeded regression.

Wired as: the ``opt`` build gate + ``-propOut=`` JSON, ``python -m
coast_tpu.analysis.lint --propagation``, ``CampaignRunner(preflight=
"propagation")``, the ``coast_tpu ci`` isolation pre-gate, and the
delta-campaign budget allocator (``run_delta(static_budget=True)``
spends convergence budget on ``sdc-possible`` sections first).
"""

from __future__ import annotations

from coast_tpu.analysis.propagation.walker import (StepFacts, TraceTaint,
                                                   analyze_step,
                                                   cross_lane_sites)
from coast_tpu.analysis.propagation.vulnmap import (VERDICT_DETECTED,
                                                    VERDICT_MASKED,
                                                    VERDICT_SDC, VERDICTS,
                                                    VulnRow,
                                                    VulnerabilityMap,
                                                    analyze_propagation,
                                                    crossvalidate_counts)
from coast_tpu.analysis.propagation.isolation import (IsolationProof, Leak,
                                                      prove_isolation,
                                                      seeded_voter_bypass)

__all__ = ["StepFacts", "TraceTaint", "analyze_step", "cross_lane_sites",
           "VERDICT_MASKED", "VERDICT_DETECTED", "VERDICT_SDC", "VERDICTS",
           "VulnRow", "VulnerabilityMap", "analyze_propagation",
           "crossvalidate_counts", "IsolationProof", "Leak",
           "prove_isolation", "seeded_voter_bypass"]
