"""Static fault-propagation vulnerability map: ACE-style verdicts per
(section, bit-class), before any campaign runs.

FastFlip (arXiv:2403.13989) shows SDC-propagation analysis can be
computed statically from program structure; COAST's engine invariants
(unconditional region-boundary sync, sanctioned vote tags, structural
word routing -- the same soundness arguments the equivalence partition
stands on) make three verdicts provable per memory-map section from the
shared fault-propagation walk alone:

  * ``masked`` -- a flip provably never changes the outcome: the leaf is
    dead state (never influences another leaf, a flag, or the check()
    verdict), so every injected bit is un-ACE.
  * ``detected-bounded`` -- every path a corrupted word can take to a
    step output crosses a sanctioned voter/guard/boundary sync: TMR
    corrects it, DWC latches it, the boundary sync witnesses it.  No
    silent escape exists; ACE bits are covered bits.
  * ``sdc-possible`` -- an unvoted escape path exists (value-fed
    arithmetic, a shared leaf visible to every lane identically, a
    check()-read oracle leaf, per-lane guards/CFCSS, single-lane
    scopes), reported with the WITNESS dataflow path the taint walk
    recorded.  This is where injection budget belongs.

Soundness contract (cross-validated, pinned in tests/test_propagation.py
against the recorded ``artifacts/equiv_study.json`` per-section
distributions and ``artifacts/train_campaign.json`` kind attribution):
a section this pass calls ``masked`` or ``detected-bounded`` must show
ZERO silent-data-corruption outcomes in the recorded campaigns.
Training regions inherit the equivalence pass's typed fallback
(:data:`~coast_tpu.analysis.equiv.partition.TRAIN_FALLBACK`): their
outcome classes are bit-VALUE-dependent (a low-mantissa weight flip
self-heals where the same word's exponent bit diverges persistently --
the PR 10 counterexample), so every section is ``sdc-possible`` and
never ``masked``.

Bit classes refine the map along the axis that matters for f32 training
state (sign / exponent / mantissa -- the self-heal-vs-persist split);
integer state gets one ``word`` class (no static bit distinction is
sound there -- mm's ``phase`` and crc16's ``crc`` are the pinned
counterexamples).

ACE accounting (Mukherjee's architectural-correct-execution bits): each
row carries ``bits`` (lanes x words x class width) and ``ace_bits``
(bits that can affect the outcome, scaled by the live-time fraction --
sites firing at or past the fault-free halt step are dead by the
equivalence pass's argument).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax

from coast_tpu.analysis.propagation.walker import StepFacts, analyze_step

__all__ = ["VERDICT_MASKED", "VERDICT_DETECTED", "VERDICT_SDC",
           "VERDICTS", "VulnRow", "VulnerabilityMap",
           "analyze_propagation", "crossvalidate_counts"]

VERDICT_MASKED = "masked"
VERDICT_DETECTED = "detected-bounded"
VERDICT_SDC = "sdc-possible"
#: Worst-last ordering: the section verdict is the max over bit classes,
#: and the CI budget allocator sorts sdc-possible first.
VERDICTS = (VERDICT_MASKED, VERDICT_DETECTED, VERDICT_SDC)

_CLASS_BITS = {"word": 32, "sign": 1, "exponent": 8, "mantissa": 23}
_F32_CLASSES = ("sign", "exponent", "mantissa")
_WORD_CLASSES = ("word",)


@dataclasses.dataclass(frozen=True)
class VulnRow:
    """One (section, bit-class) cell of the static vulnerability map."""

    section: str
    kind: str
    bit_class: str            # word | sign | exponent | mantissa
    verdict: str              # masked | detected-bounded | sdc-possible
    reason: str
    witness: Tuple[str, ...]  # dataflow path for sdc-possible, else ()
    bits: int                 # lanes x words x class width
    ace_bits: int             # bits that can affect the outcome

    def to_dict(self) -> Dict[str, object]:
        doc: Dict[str, object] = {
            "section": self.section, "kind": self.kind,
            "bit_class": self.bit_class, "verdict": self.verdict,
            "reason": self.reason, "bits": self.bits,
            "ace_bits": self.ace_bits,
        }
        if self.witness:
            doc["witness"] = list(self.witness)
        return doc


@dataclasses.dataclass
class VulnerabilityMap:
    """Per-section x per-bit-class static verdicts for one protected
    program, plus the ACE accounting the CI budget allocator reads."""

    benchmark: str
    num_clones: int
    clean_steps: int
    nominal_steps: int
    live_fraction: float
    rows: Dict[str, List[VulnRow]]       # section -> bit-class rows
    fallback_reason: Optional[str] = None
    #: Cross-shard influence reach (sharded regions only -- present iff
    #: the region's ``meta['shard_of']`` names a shard per section):
    #: leaf -> {reach, shards_reached, cross_shard}.  The transitive
    #: closure of :attr:`StepFacts.out_taint` over steps: which leaves a
    #: surviving corruption can eventually change.  Under
    #: vote-then-exchange a grid leaf's influence dies at the halo's
    #: pack-commit vote (``cross_shard`` false: blast radius one shard);
    #: under exchange-then-vote it ships raw through the unvoted commit
    #: and reaches the neighbor shard (``cross_shard`` true) -- the
    #: static prediction the stencil campaign pins against measurement.
    shard_reach: Optional[Dict[str, Dict[str, object]]] = None

    def section_verdicts(self) -> Dict[str, str]:
        """Worst verdict per section (the CI budget unit)."""
        rank = {v: i for i, v in enumerate(VERDICTS)}
        return {name: max((r.verdict for r in rows), key=rank.get)
                for name, rows in self.rows.items()}

    def verdict(self, section: str) -> str:
        return self.section_verdicts()[section]

    def counts(self) -> Dict[str, int]:
        out = {v: 0 for v in VERDICTS}
        for v in self.section_verdicts().values():
            out[v] += 1
        return out

    def ace_summary(self) -> Dict[str, int]:
        total = ace = covered = exposed = 0
        for rows in self.rows.values():
            for r in rows:
                total += r.bits
                ace += r.ace_bits
                if r.verdict == VERDICT_DETECTED:
                    covered += r.ace_bits
                elif r.verdict == VERDICT_SDC:
                    exposed += r.ace_bits
        return {"total_bits": total, "ace_bits": ace,
                "detected_bounded_ace_bits": covered,
                "sdc_possible_ace_bits": exposed}

    def summary(self) -> Dict[str, object]:
        return {
            "benchmark": self.benchmark,
            "num_clones": self.num_clones,
            "clean_steps": self.clean_steps,
            "nominal_steps": self.nominal_steps,
            "live_fraction": round(self.live_fraction, 6),
            **({"fallback_reason": self.fallback_reason}
               if self.fallback_reason else {}),
            "verdict_counts": self.counts(),
            "ace": self.ace_summary(),
            **({"shard_reach": self.shard_reach}
               if self.shard_reach is not None else {}),
            "sections": {
                name: {"verdict": self.section_verdicts()[name],
                       "kind": rows[0].kind if rows else "?",
                       "bit_classes": [r.to_dict() for r in rows]}
                for name, rows in sorted(self.rows.items())},
        }

    def format(self) -> str:
        lines = [f"--- static vulnerability map: {self.benchmark} "
                 f"(N={self.num_clones}, live "
                 f"{100 * self.live_fraction:.0f}% of the flip window) ---"]
        verdicts = self.section_verdicts()
        for name in sorted(self.rows):
            rows = self.rows[name]
            ace = sum(r.ace_bits for r in rows)
            bits = sum(r.bits for r in rows)
            lines.append(f"  {name:<18} {verdicts[name]:<17} "
                         f"ace {ace}/{bits} bits  [{rows[0].kind}]")
            for r in rows:
                if r.verdict == VERDICT_SDC and r.witness:
                    lines.append(f"      {r.bit_class}: "
                                 + " -> ".join(r.witness))
        if self.shard_reach:
            crossers = sorted(n for n, d in self.shard_reach.items()
                              if d.get("cross_shard"))
            lines.append("  cross-shard reach: "
                         + (", ".join(crossers) if crossers
                            else "none (blast radius bounded per shard)"))
        c = self.counts()
        lines.append(f"  verdicts: {c[VERDICT_SDC]} sdc-possible, "
                     f"{c[VERDICT_DETECTED]} detected-bounded, "
                     f"{c[VERDICT_MASKED]} masked")
        return "\n".join(lines)

    def write_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.summary(), fh, indent=1, sort_keys=True)
            fh.write("\n")


def _bit_classes(dtype) -> Sequence[str]:
    try:
        import numpy as np
        if np.dtype(dtype) == np.float32:
            return _F32_CLASSES
    except Exception:       # noqa: BLE001 - unknown dtype: one word class
        pass
    return _WORD_CLASSES


def _shard_reach(facts: StepFacts, shard_of: Mapping[str, Optional[int]]
                 ) -> Dict[str, Dict[str, object]]:
    """Transitive closure of the per-step influence edges, attributed to
    shards.  ``reach[leaf]`` is every leaf whose committed value a
    surviving corruption of ``leaf`` can eventually change (over any
    number of steps); ``cross_shard`` is True when that set includes a
    section owned by a DIFFERENT shard than the source's own."""
    names = set(facts.out_taint)
    for srcs in facts.out_taint.values():
        names |= srcs
    adj: Dict[str, set] = {n: set() for n in names}
    for dst, srcs in facts.out_taint.items():
        for src in srcs:
            adj.setdefault(src, set()).add(dst)
    reach = {n: set(dsts) for n, dsts in adj.items()}
    changed = True
    while changed:
        changed = False
        for n in reach:
            step = set()
            for m in reach[n]:
                step |= reach.get(m, set())
            if not step <= reach[n]:
                reach[n] |= step
                changed = True
    out: Dict[str, Dict[str, object]] = {}
    for name in sorted(reach):
        own = shard_of.get(name)
        shards = sorted({shard_of.get(d) for d in reach[name]
                         if shard_of.get(d) is not None})
        doc: Dict[str, object] = {
            "reach": sorted(reach[name]),
            "shards_reached": shards,
        }
        if own is not None:
            doc["cross_shard"] = any(s != own for s in shards)
        out[name] = doc
    return out


def analyze_propagation(prog, closed=None, facts: Optional[StepFacts] = None,
                        partition=None) -> VulnerabilityMap:
    """Derive the static vulnerability map of ``prog``.

    ``closed``/``facts`` forward an already-traced step jaxpr / shared
    walk (one walk serves lint + equivalence + propagation);
    ``partition`` forwards an already-built
    :class:`~coast_tpu.analysis.equiv.EquivPartition` so the fault-free
    halt step (one compiled clean run) is measured once per program, not
    once per pass."""
    from coast_tpu.analysis.equiv.partition import (TRAIN_FALLBACK,
                                                    _clean_steps,
                                                    _cone_entries)
    region = prog.region
    if facts is None:
        facts = analyze_step(prog, closed=closed)
    clean_steps = (partition.clean_steps if partition is not None
                   else _clean_steps(prog))
    nominal = max(int(getattr(region, "nominal_steps", 1)), 1)
    live_fraction = max(0.0, min(1.0, clean_steps / nominal))

    state_shapes = jax.eval_shape(region.init)
    witnesses = getattr(facts.taint, "witness", {})

    rows: Dict[str, List[VulnRow]] = {}
    for name, kind, lanes, words in prog.injectable_sections():
        replicated = bool(prog.replicated.get(name, kind == "cfcss"))
        is_written = name in facts.written
        is_consumed = name in facts.consumed
        value_fed = name in facts.taint.value_fed
        is_pre_voted = bool(getattr(prog, "pre_sync", {}).get(name, False))
        check_read = name in facts.check_reads

        def cone_witness() -> Tuple[str, ...]:
            cone: List[str] = []
            _cone_entries(facts.jaxpr, facts.walker.env, facts.live,
                          name, cone)
            if not cone and facts.check_closed is not None \
                    and facts.check_walker is not None:
                cone.append("|check|")
                _cone_entries(facts.check_closed.jaxpr,
                              facts.check_walker.env, None, name, cone)
            return tuple(cone[:8])

        witness: Tuple[str, ...] = ()
        if facts.train_fallback:
            # The typed train fallback (PR 10 counterexample): outcome
            # classes are bit-VALUE-dependent, so no static masking or
            # detection bound is sound -- and in particular no section
            # may ever be called masked.
            verdict, reason = VERDICT_SDC, TRAIN_FALLBACK
            witness = tuple(witnesses.get(name, ())) or cone_witness()
        elif replicated:
            if facts.cfcss or kind == "cfcss":
                verdict = VERDICT_SDC
                reason = ("CFCSS signature dataflow reads raw lane "
                          "values; detection is value-dependent")
                witness = tuple(witnesses.get(name, ())) or cone_witness()
            elif facts.guards:
                verdict = VERDICT_SDC
                reason = ("per-lane guards read raw replica values and "
                          "trip value-dependently")
                witness = tuple(witnesses.get(name, ())) or cone_witness()
            elif facts.fn_unsafe:
                verdict = VERDICT_SDC
                reason = ("single-lane function scope consumes raw lane "
                          "values (skipLibCalls/cloneAfterCall SPOF)")
                witness = tuple(witnesses.get(name, ())) or cone_witness()
            elif name in facts.lane_flagged:
                verdict = VERDICT_SDC
                reason = ("a live single-lane extraction consumes this "
                          "leaf's replicas outside a sanctioned voter")
                witness = tuple(witnesses.get(name, ())) or cone_witness()
            elif is_pre_voted:
                verdict = VERDICT_DETECTED
                reason = ("pre-step vote repairs (TMR) or latches (DWC) "
                          "the flip before any read")
            elif not is_written:
                verdict = VERDICT_DETECTED
                reason = ("unwritten replica: the flipped lane survives "
                          "verbatim, so the region-boundary sync "
                          "witnesses any divergence")
            elif not value_fed:
                verdict = VERDICT_DETECTED
                reason = ("structural routing only: every surviving word "
                          "reaches a sanctioned vote verbatim; "
                          "overwritten words are masked to the clean "
                          "outcome")
            else:
                verdict = VERDICT_SDC
                reason = ("value-fed: the flipped value enters arithmetic "
                          "that can mask or transform bits before any "
                          "voter (the crc shift-out / phase "
                          "predicate-steering class)")
                witness = tuple(witnesses.get(name, ())) or cone_witness()
        else:
            if not is_consumed and not check_read:
                verdict = VERDICT_MASKED
                reason = ("dead state: never influences another leaf, a "
                          "flag, or the check() verdict -- every bit is "
                          "un-ACE")
            else:
                verdict = VERDICT_SDC
                reason = ("shared state: corruption enters every lane "
                          "identically, so no replica disagreement "
                          "exists to vote on"
                          + ("; read by check() (oracle corruption "
                             "classifies as SDC)" if check_read else ""))
                witness = tuple(witnesses.get(name, ())) or cone_witness()

        dtype = (state_shapes[name].dtype
                 if name in state_shapes else None)
        section_rows: List[VulnRow] = []
        for bc in _bit_classes(dtype):
            bits = int(lanes) * int(words) * _CLASS_BITS[bc]
            ace = 0 if verdict == VERDICT_MASKED \
                else int(round(bits * live_fraction))
            note = reason
            if facts.train_fallback and bc == "mantissa":
                note = (reason + "; low-mantissa flips may re-converge "
                        "(train_self_heal) where the same word's "
                        "exponent bit persists -- the pinned "
                        "counterexample")
            section_rows.append(VulnRow(
                section=name, kind=kind, bit_class=bc, verdict=verdict,
                reason=note, witness=witness, bits=bits, ace_bits=ace))
        rows[name] = section_rows

    shard_of = (getattr(region, "meta", None) or {}).get("shard_of")
    return VulnerabilityMap(
        benchmark=region.name,
        num_clones=facts.num_clones,
        clean_steps=clean_steps,
        nominal_steps=nominal,
        live_fraction=live_fraction,
        rows=rows,
        fallback_reason=(TRAIN_FALLBACK if facts.train_fallback
                         else None),
        shard_reach=(_shard_reach(facts, shard_of)
                     if shard_of is not None else None))


def crossvalidate_counts(vmap: VulnerabilityMap,
                         section_counts: Mapping[str, Mapping[str, int]],
                         sdc_keys: Sequence[str] = ("sdc", "train_sdc"),
                         ) -> List[str]:
    """Soundness cross-validation against a recorded campaign's
    per-section outcome distributions (the FuzzyFlow idiom: static
    claims checked against differential ground truth).

    ``section_counts`` maps section name -> {class name: count}.
    Returns one violation string per section whose static verdict rules
    out silent corruption (``masked`` or ``detected-bounded``) but whose
    recorded distribution shows any -- an empty list is the proof
    obligation tests pin."""
    verdicts = vmap.section_verdicts()
    violations: List[str] = []
    for name, counts in sorted(section_counts.items()):
        verdict = verdicts.get(name)
        if verdict is None or verdict == VERDICT_SDC:
            continue
        recorded = sum(int(counts.get(k, 0)) for k in sdc_keys)
        if recorded:
            violations.append(
                f"{vmap.benchmark}:{name}: static verdict {verdict!r} "
                f"but the recorded campaign shows {recorded} "
                "silent-corruption outcome(s)")
    return violations
