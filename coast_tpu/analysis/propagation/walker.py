"""The shared fault-propagation walker: one abstract interpretation of
the protected step, consumed by three analyses.

Before this module, two passes each re-derived the same facts about the
protected step's jaxpr: the equivalence partition
(:mod:`coast_tpu.analysis.equiv.partition`) ran the lint lane-provenance
lattice (:class:`~coast_tpu.analysis.lint.provenance._Walker`) plus its
own structural-taint walk, and the linter ran the lattice again with its
own finding rules.  The static vulnerability map and the isolation
prover (this package) need exactly the same facts a third and fourth
time -- so the walk lives here once, as :func:`analyze_step` returning a
:class:`StepFacts` bundle:

  * the **lane-provenance lattice** walk (replicated/shared/unknown per
    var, sanctioned-tag tracking, cross-lane collapse candidates);
  * the **structural-taint walk** (verbatim-word flow through selects/
    slices/DUS, killed at sanctioned vote tags, ``value_fed`` where a
    live equation consumes taint non-structurally) -- with optional
    **witness-path tracking** (:class:`TraceTaint`): the first dataflow
    chain that carries a leaf's words to each value-feeding consumer,
    the raw material of the vulnerability map's SDC witnesses;
  * backward **liveness** over the step outputs;
  * per-leaf roles (consumed / written from the region's own dataflow
    analysis / lane-flagged / pre- and step-voted) and region-level
    hazards (guards, CFCSS, single-lane function scopes, the training
    fallback);
  * the **check() cone** (which leaves the self-check reads -- a flip
    invisible to both the step and the check provably cannot change the
    outcome).

One trace, one walk, N consumers: ``scripts/lint_sweep.py`` passes one
``closed`` jaxpr and one ``StepFacts`` through lint + equivalence +
propagation, so adding the third pass did not add a third trace.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import jax

from coast_tpu.analysis.lint.provenance import (_Val, _Walker, _live_eqns,
                                                trace_step)
from coast_tpu.ops.voters import TAG_SPOF, TAG_SYNC, TAG_VIEW, TAG_VOTER

__all__ = ["StepFacts", "TraceTaint", "analyze_step", "cross_lane_sites",
           "eqn_entry"]

# Primitives that move words verbatim: a flipped word passes through
# them unchanged (or is dropped), never arithmetically transformed.
# Operand positions listed in _VALUE_OPERANDS are *steering* inputs
# (predicates, indices): a flipped value there changes WHICH words move,
# which is value-dependent -- consuming a tainted steering operand marks
# the leaf value-fed.
_STRUCTURAL_PRIMS = frozenset({
    "select_n", "dynamic_update_slice", "dynamic_slice", "slice",
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "concatenate",
    "rev", "copy", "gather", "scatter", "pad", "stop_gradient",
    # Cross-device permutation collectives move words verbatim between
    # shards: a flipped word on the wire arrives flipped, never
    # transformed.  Listing them keeps the walk honest across shard_map
    # boundaries (the sharded stencil's halo exchange).
    "ppermute", "pshuffle",
})

_VALUE_OPERANDS = {
    "select_n": lambda eqn: (0,),
    "dynamic_slice": lambda eqn: tuple(range(1, len(eqn.invars))),
    "dynamic_update_slice": lambda eqn: tuple(range(2, len(eqn.invars))),
    "gather": lambda eqn: (1,),
    "scatter": lambda eqn: (1,),
    "pad": lambda eqn: (),
}

# Sync classes whose tag marks a *detector* on the tagged value: taint
# entering one is guaranteed either masked (lanes equal) or latched/
# repaired there, so it stops propagating.  'guard' is deliberately NOT
# in this set -- kernel guards read raw per-lane values and trip
# value-dependently, so their consumption must count as value-feeding.
_DETECTOR_CLASSES = frozenset({
    "load_addr", "store_data", "ctrl", "stack", "sor_crossing",
    "boundary", "call_boundary", "cfcss",
    # Training regions' weight-update commit votes (KIND_PARAM /
    # KIND_OPT_STATE leaves).  Note these detectors never LICENSE a
    # merge on a train region -- the train fallback forces every
    # section exhaustive first; the membership only keeps the taint walk
    # honest about where votes kill verbatim-word flow.
    "param", "opt_state",
})


def _detector_tag(tag: str) -> bool:
    if tag.startswith(TAG_VOTER) and not tag.startswith(TAG_VIEW):
        return True
    if tag.startswith(TAG_SYNC):
        klass = tag[len(TAG_SYNC):].partition(":")[0]
        return klass in _DETECTOR_CLASSES
    return False


def eqn_entry(eqn) -> str:
    """``prim(shape)`` display entry for one equation -- the witness
    paths' vocabulary (same shape the fingerprint cones use)."""
    shape = ()
    if eqn.outvars:
        shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
    return f"{eqn.primitive.name}{shape}"


class _TaintWalk:
    """Forward word-verbatim taint over a (nested) jaxpr.

    ``env[var]`` is the frozenset of leaf names whose unmodified words
    may be present in ``var``.  Taint passes through structural
    primitives, dies at detector tags (sanctioned votes), and marks a
    leaf ``value_fed`` wherever a live equation consumes its taint
    non-structurally (arithmetic, reductions, steering operands, guard
    inputs).
    """

    def __init__(self, live: Optional[Set[int]],
                 shared_surviving: Optional[FrozenSet[str]] = None):
        self.env: Dict[object, FrozenSet[str]] = {}
        self.value_fed: Set[str] = set()
        self.live = live
        # Leaves whose corruption SURVIVES a sanctioned vote: a shared
        # single-copy leaf (the stencil's link-kind halo) corrupts every
        # replica identically, so lanes agree on the corrupted value and
        # a detector tag passes it instead of killing it.  None keeps
        # the historical kill-at-detector semantics (the equivalence
        # partition's fingerprints depend on them bit-for-bit).
        self.shared_surviving = shared_surviving

    def val(self, v) -> FrozenSet[str]:
        from jax.extend.core import Literal
        if isinstance(v, Literal):
            return frozenset()
        return self.env.get(v, frozenset())

    def _set(self, v, taint: FrozenSet[str]) -> None:
        old = self.env.get(v)
        self.env[v] = taint if old is None else (old | taint)

    def seed(self, inner_vars, taints) -> None:
        for iv, t in zip(inner_vars, taints):
            self._set(iv, t)

    def _is_live(self, eqn) -> bool:
        return self.live is None or id(eqn) in self.live

    def _feed(self, eqn, taint: FrozenSet[str]) -> None:
        if taint and self._is_live(eqn):
            self.value_fed |= taint

    def walk(self, jaxpr) -> List[FrozenSet[str]]:
        for eqn in jaxpr.eqns:
            ins = [self.val(v) for v in eqn.invars]
            outs = self._eqn_outs(eqn, ins)
            for v, t in zip(eqn.outvars, outs):
                self._set(v, t)
        return [self.val(v) for v in jaxpr.outvars]

    def _eqn_outs(self, eqn, ins):
        prim = eqn.primitive.name
        params = eqn.params
        union = frozenset().union(*ins) if ins else frozenset()

        if prim == "name":
            tag = str(params.get("name", ""))
            if _detector_tag(tag):
                if self.shared_surviving is not None and ins:
                    return [ins[0] & self.shared_surviving]
                return [frozenset()]
            if tag.startswith(TAG_SPOF):
                # Single-lane call boundary: the callee sees raw lane-0
                # values -- value consumption by definition.
                self._feed(eqn, union)
                return [frozenset()]
            return [ins[0] if ins else frozenset()]

        if prim == "optimization_barrier":
            # n-ary identity fence: words pass through verbatim, per
            # position -- neither consumed nor mixed.
            return list(ins)

        if prim in _STRUCTURAL_PRIMS:
            value_pos = _VALUE_OPERANDS.get(prim, lambda e: ())(eqn)
            data = frozenset()
            for i, t in enumerate(ins):
                if i in value_pos:
                    self._feed(eqn, t)
                else:
                    data |= t
            return [data for _ in eqn.outvars]

        # -- control flow / nested jaxprs --
        if prim == "cond" and "branches" in params:
            self._feed(eqn, ins[0])
            per_branch = []
            for br in params["branches"]:
                self.seed(br.jaxpr.invars, ins[1:])
                per_branch.append(self.walk(br.jaxpr))
            outs = []
            for i in range(len(eqn.outvars)):
                o = frozenset()
                for b in per_branch:
                    o |= b[i]
                outs.append(o)
            return outs
        if prim == "while":
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            cj, bj = params["cond_jaxpr"].jaxpr, params["body_jaxpr"].jaxpr
            carry = list(ins[cn + bn:])
            for _ in range(len(carry) + 2):
                self.seed(cj.invars, ins[:cn] + carry)
                cond_out = self.walk(cj)
                self._feed(eqn, cond_out[0] if cond_out else frozenset())
                self.seed(bj.invars, ins[cn:cn + bn] + carry)
                new_carry = self.walk(bj)
                joined = [c | nc for c, nc in zip(carry, new_carry)]
                if joined == carry:
                    break
                carry = joined
            return carry
        if prim == "scan":
            sub = params["jaxpr"].jaxpr
            nc, ncar = params["num_consts"], params["num_carry"]
            consts, carry = list(ins[:nc]), list(ins[nc:nc + ncar])
            xs = list(ins[nc + ncar:])
            outs = None
            for _ in range(max(ncar, 1) + 2):
                self.seed(sub.invars, consts + carry + xs)
                outs = self.walk(sub)
                joined = [c | nc_ for c, nc_ in zip(carry, outs[:ncar])]
                if joined == carry:
                    break
                carry = joined
            return carry + list(outs[ncar:])
        for key in ("jaxpr", "call_jaxpr"):
            if key in params:
                sub = params[key]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                self.seed(sub.invars, ins)
                return self.walk(sub)

        # Any other primitive transforms values: tainted inputs are
        # value-fed, outputs carry no verbatim words.
        self._feed(eqn, union)
        return [frozenset() for _ in eqn.outvars]


class _InfluenceWalk(_TaintWalk):
    """Value-influence closure over one protected step: which leaves'
    OUTPUT values a corrupted leaf can change at all.

    Where the base walk tracks verbatim words (dying at arithmetic),
    this walk tracks influence: every primitive's outputs inherit the
    union of their operands' influence -- an added, voted-over, or
    majority-merged corrupted operand still corrupts the result.
    Sanctioned detector tags still kill influence (the vote repairs a
    single-lane divergence) EXCEPT for ``shared_surviving`` leaves,
    whose corruption is lane-homogeneous and sails through any vote.
    Single-lane call boundaries (``TAG_SPOF``) pass influence: the
    callee computes from the raw lane-0 value.

    The per-step leaf->leaf edges this walk yields (``StepFacts.
    out_taint``) are the raw material of the vulnerability map's
    cross-shard reach closure: under vote-then-exchange a grid leaf's
    influence dies at the halo's pack-commit vote (blast radius one
    shard), under exchange-then-vote it ships raw and reaches the
    neighbor -- the static prediction the stencil campaigns pin against
    measured truth."""

    def _eqn_outs(self, eqn, ins):
        prim = eqn.primitive.name
        params = eqn.params
        union = frozenset().union(*ins) if ins else frozenset()
        if prim == "name" and str(params.get("name", "")).startswith(
                TAG_SPOF):
            return [union]
        if (prim == "name" or prim == "optimization_barrier"
                or (prim == "cond" and "branches" in params)
                or prim in ("while", "scan")):
            # Tags (detector kill / passthrough) and loop joins keep the
            # base semantics; recursion re-enters this override.
            return super()._eqn_outs(eqn, ins)
        for key in ("jaxpr", "call_jaxpr"):
            if key in params:
                # Nested calls walk in a FRESH env: jax reuses one traced
                # jaxpr object across same-shape call sites, and because
                # the env is keyed by var identity a shared env would
                # leak the first call site's influence into the second
                # (observed: golden0 "influencing" golden1 through a
                # shared broadcast pjit).  Influence propagates through
                # everything, so the leak is not masked downstream the
                # way verbatim taint is -- isolate the call instead.
                sub = params[key]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                fresh = _InfluenceWalk(self.live, self.shared_surviving)
                fresh.seed(sub.invars, ins)
                return fresh.walk(sub)
        # Everything else -- structural moves AND arithmetic -- taints
        # every output with every operand (steering operands included:
        # a corrupted predicate or index changes the result too).
        return [union for _ in eqn.outvars]

    def _feed(self, eqn, taint: FrozenSet[str]) -> None:
        # Influence is not consumption: value_fed verdicts stay owned by
        # the base walk.
        pass


#: Witness paths are display artifacts, not proofs: cap their length so
#: a deep loop nest cannot balloon the report.
_PATH_MAX = 12


class TraceTaint(_TaintWalk):
    """:class:`_TaintWalk` plus witness-path tracking.

    ``witness[leaf]`` is the first dataflow chain (program order,
    ``prim(shape)`` entries, last entry suffixed ``!`` for the
    value-feeding consumer) observed carrying ``leaf``'s verbatim words
    to a live non-structural consumer -- the concrete escape path the
    vulnerability map reports for an ``sdc-possible`` verdict.  Taint
    semantics are bit-identical to the base walk; the paths are a
    side-channel.
    """

    def __init__(self, live: Optional[Set[int]]):
        super().__init__(live)
        # var -> {leaf: path tuple}; first path wins (program order).
        self.path: Dict[object, Dict[str, Tuple[str, ...]]] = {}
        self.witness: Dict[str, Tuple[str, ...]] = {}

    def _in_path(self, eqn, leaf: str) -> Tuple[str, ...]:
        from jax.extend.core import Literal
        for iv in eqn.invars:
            if isinstance(iv, Literal):
                continue
            d = self.path.get(iv)
            if d is not None and leaf in d:
                return d[leaf]
            if leaf in self.val(iv):
                return (leaf,)        # the seeded leaf input itself
        return (leaf,)

    def _feed(self, eqn, taint: FrozenSet[str]) -> None:
        if taint and self._is_live(eqn):
            for leaf in taint:
                if leaf not in self.witness:
                    self.witness[leaf] = (self._in_path(eqn, leaf)
                                          + (eqn_entry(eqn) + "!",))
        super()._feed(eqn, taint)

    def walk(self, jaxpr) -> List[FrozenSet[str]]:
        for eqn in jaxpr.eqns:
            ins = [self.val(v) for v in eqn.invars]
            outs = self._eqn_outs(eqn, ins)
            entry = eqn_entry(eqn)
            for v, t in zip(eqn.outvars, outs):
                self._set(v, t)
                if t:
                    d = self.path.setdefault(v, {})
                    for leaf in t:
                        if leaf not in d:
                            p = self._in_path(eqn, leaf)
                            d[leaf] = (p + (entry,) if len(p) < _PATH_MAX
                                       else p)
        return [self.val(v) for v in jaxpr.outvars]


def cross_lane_sites(walker: _Walker, live: Set[int],
                     n: int) -> List[Dict[str, object]]:
    """The live unsanctioned cross-lane dataflow sites: collapse and
    single-lane-extraction candidates from the lattice walk, with the
    segmented scheduler's all-lane fan-out pattern (every lane of a
    source extracted exactly once) filtered out as sanctioned -- the
    same acceptance rule :func:`~coast_tpu.analysis.lint.provenance.
    lint_provenance` applies before reporting.  These sites are the
    isolation prover's interference sources: each one moves one lane's
    (possibly corrupted) value across the lane boundary without a
    sanctioned voter."""
    live_cands = [c for k, c in walker.candidates.items() if k in live]
    by_src: Dict[int, List[Dict[str, object]]] = {}
    for c in live_cands:
        by_src.setdefault(id(c["src"]), []).append(c)
    out: List[Dict[str, object]] = []
    for cands in by_src.values():
        lanes_seen = {c["lane"] for c in cands}
        if (all(c["kind"] == "spof" for c in cands)
                and None not in lanes_seen
                and lanes_seen == set(range(n))):
            continue
        out.extend(cands)
    return out


@dataclasses.dataclass
class StepFacts:
    """Everything the static passes know about one protected step, from
    one trace and one walk.  Consumed by the equivalence partition, the
    vulnerability map, and the isolation prover."""

    closed: object                      # the step's ClosedJaxpr
    state_names: List[str]
    flag_names: List[str]
    walker: _Walker                     # lattice walk (env/candidates/tags)
    out_vals: List[_Val]                # lattice values of the step outputs
    live: Set[int]                      # id(eqn) liveness set
    taint: _TaintWalk                   # value_fed (+ witness when traced)
    consumed: Set[str]                  # leaves feeding OTHER outputs/flags
    written: Set[str]                   # region dataflow write set
    lane_flagged: Set[str]              # leaves behind live unsanctioned
    #                                     cross-lane candidates
    check_reads: Set[str]               # leaves check()'s verdict reads
    check_walker: Optional[_Walker]     # check() cone (fingerprints)
    check_closed: Optional[object]
    guards: bool
    cfcss: bool
    fn_unsafe: bool
    train_fallback: bool
    num_clones: int
    #: Per-step influence edges: output leaf -> the leaves whose
    #: surviving corruption can change its committed value this step
    #: (:class:`_InfluenceWalk`; votes kill replicated-leaf influence,
    #: shared single-copy leaves survive them).  The vulnerability map
    #: closes these transitively into cross-shard reach.
    out_taint: Dict[str, FrozenSet[str]] = dataclasses.field(
        default_factory=dict)

    @property
    def jaxpr(self):
        return self.closed.jaxpr

    @property
    def out_names(self) -> List[str]:
        return self.state_names + self.flag_names


def analyze_step(prog, closed=None, track_paths: bool = True) -> StepFacts:
    """Run the shared fault-propagation walk over ``prog``'s protected
    step.  ``closed`` forwards an already-traced step jaxpr (callers
    that lint, partition, and map in one session trace once);
    ``track_paths=False`` skips witness-path bookkeeping for consumers
    that only need the boolean facts."""
    if getattr(prog.cfg, "fuse_step", False) and closed is None:
        # Fused builds (-fuseStep) are differentially pinned bit-identical
        # to their unfused twin (ops/fused_step.py); the protection
        # STRUCTURE the static analyses read -- sync coverage, dataflow
        # cones, merge modes -- is the twin's.  Walking the twin keeps
        # every equiv partition fingerprint, vulnerability-map verdict,
        # and isolation proof unchanged by fusion.
        prog = prog.unfused_twin()
    cfg = prog.cfg
    region = prog.region
    n = cfg.num_clones
    if closed is None:
        closed = trace_step(prog)
    jaxpr = closed.jaxpr

    pstate, flags = jax.eval_shape(prog.init_pstate)
    state_names = sorted(pstate)
    flag_names = sorted(flags)
    assert len(jaxpr.invars) == len(state_names) + len(flag_names) + 1, (
        len(jaxpr.invars), len(state_names), len(flag_names))

    # -- lattice walk ----------------------------------------------------
    walker = _Walker(n)
    taints: List[FrozenSet[str]] = []
    for name, var in zip(state_names, jaxpr.invars):
        status = "laned" if prog.replicated.get(name) else "shared"
        walker.env[var] = _Val(status, 0, False, False, frozenset({name}))
        taints.append(frozenset({name}))
    out_vals = walker.walk(jaxpr)

    live: Set[int] = set()
    _live_eqns(jaxpr, list(jaxpr.outvars), live)

    # -- value-feeding taint walk ----------------------------------------
    taint = TraceTaint(live) if track_paths else _TaintWalk(live)
    for var, t in zip(jaxpr.invars, taints):
        taint._set(var, t)
    taint.walk(jaxpr)

    # -- per-step influence edges (cross-shard reach raw material) --------
    shared_names = frozenset(
        name for name in state_names if not prog.replicated.get(name))
    infl = _InfluenceWalk(live, shared_surviving=shared_names)
    for var, t in zip(jaxpr.invars, taints):
        infl._set(var, t)
    infl_outs = infl.walk(jaxpr)
    out_taint = {
        name: infl_outs[i]
        for i, name in enumerate(state_names + flag_names)
        if i < len(infl_outs)}

    # -- per-leaf facts ---------------------------------------------------
    out_names = state_names + flag_names
    consumed: Set[str] = set()
    for out_name, val in zip(out_names, out_vals):
        for dep in val.deps:
            if dep != out_name:
                consumed.add(dep)
    # The write set comes from the REGION's dataflow roles (the same
    # analysis the engine derives its store syncs from): in the
    # protected step's jaxpr every leaf gets fresh outvars (vmap,
    # freeze-select), so var identity cannot tell a semantic write from
    # a passthrough.  Synthetic (CFCSS) leaves are not region leaves.
    from coast_tpu.passes.verification import analyze
    written = set(analyze(region).written)

    # Live single-lane extractions / unsanctioned collapses implicate
    # their provenance leaves: lane symmetry is not provable there.
    # (Unfiltered, matching the equivalence pass's conservatism; the
    # isolation prover applies the fan-out filter via cross_lane_sites.)
    lane_flagged: Set[str] = set()
    for key, cand in walker.candidates.items():
        if key in live:
            lane_flagged |= set(cand["deps"])

    guards = (region.stack_guard is not None
              or region.assert_guard is not None)
    train_fallback = getattr(region, "train_probe", None) is not None
    cfcss = getattr(prog, "_cfcss_step", None) is not None
    fn_unsafe = n > 1 and any(
        scope not in ("replicated", "replicated_return")
        for scope in getattr(prog, "fn_scope", {}).values())

    # -- check() cone: which leaves the self-check verdict reads ---------
    check_walker: Optional[_Walker] = _Walker(n)
    check_closed = None
    check_reads: Set[str] = set()
    try:
        init_shape = jax.eval_shape(region.init)
        check_closed = jax.make_jaxpr(region.check)(init_shape)
        check_names = sorted(init_shape)
        for name, var in zip(check_names, check_closed.jaxpr.invars):
            check_walker.env[var] = _Val("shared", 0, False, False,
                                         frozenset({name}))
        for val in check_walker.walk(check_closed.jaxpr):
            check_reads |= set(val.deps)
    except Exception:       # noqa: BLE001 - analysis must not break builds
        check_closed = None
        check_walker = None
        # Unanalyzable check: conservatively assume it reads everything
        # (nothing may claim "invisible to check" below).
        check_reads = set(region.spec)

    return StepFacts(
        closed=closed, state_names=state_names, flag_names=flag_names,
        walker=walker, out_vals=out_vals, live=live, taint=taint,
        consumed=consumed, written=written, lane_flagged=lane_flagged,
        check_reads=check_reads, check_walker=check_walker,
        check_closed=check_closed, guards=guards, cfcss=cfcss,
        fn_unsafe=fn_unsafe, train_fallback=train_fallback, num_clones=n,
        out_taint=out_taint)
