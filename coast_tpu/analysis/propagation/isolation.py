"""Lane-isolation noninterference prover.

The continuous-protection serving scenario (ROADMAP item 5) runs fault
injection on spare replica lanes while live lanes serve traffic -- which
is only safe if a flipped lane's value provably cannot reach anything
outside its own lane except through a sanctioned, voted commit.  This
module proves exactly that property over the protected step's jaxpr:

**Theorem (lane noninterference).**  For a protected program whose step
contains no *live unsanctioned cross-lane dataflow site* -- no lane-axis
collapse and no single-lane extraction outside a ``coast:voter`` /
``coast:sync:*`` / ``coast:view:*`` tag (modulo the configured
single-lane call allowlist, reported as explicit assumptions) -- a fault
injected into one replica lane can influence another lane, a shared
leaf, or a step flag only through a sanctioned voted commit.  Combined
with the engine's unconditional region-boundary sync, any surviving
divergence is detected (DWC) or corrected (TMR) before the served view.

The proof is constructive both ways:

  * when it HOLDS, the prover reports the discharged obligations -- the
    live sanctioned vote tags (every cross-lane commit the program
    makes) and the configured single-lane-call assumptions;
  * when it FAILS, every leak carries a **counterexample path**: the
    dataflow chain from the unsanctioned cross-lane site to the step
    output it reaches.  Leak taint deliberately does NOT die at later
    voter tags -- once a single lane's value has fanned out to every
    replica, all lanes agree on the corrupt value and no majority can
    witness it (that is precisely why the bypass is a bug).

The seeded regression (:func:`seeded_voter_bypass`) builds exactly that
bug generically for any registry target: every vote returns lane 0's
value with no sanction tag and no miscompare, i.e. an injected-lane
value routed around the voter.  ``scripts/lint_sweep.py`` proves the
clean build AND catches the seeded bypass for every registry target
under TMR and DWC; tests pin the subset live.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from coast_tpu.analysis.propagation.walker import (StepFacts, _detector_tag,
                                                   analyze_step,
                                                   cross_lane_sites,
                                                   eqn_entry)
from coast_tpu.ops.voters import TAG_SPOF

__all__ = ["Leak", "IsolationProof", "prove_isolation",
           "seeded_voter_bypass"]

#: Cap the reported leaks (every output a pervasive leak reaches would
#: otherwise repeat the same counterexample dozens of times).
_MAX_LEAKS = 16
_PATH_MAX = 12


@dataclasses.dataclass(frozen=True)
class Leak:
    """One noninterference counterexample."""

    rule: str                 # "spof" | "lane-collapse"
    source: str               # the cross-lane site (prim + leaves)
    output: str               # step output (leaf or flag) reached
    path: Tuple[str, ...]     # dataflow chain site -> output

    def format(self) -> str:
        return (f"[{self.rule}] {self.source} -> output '{self.output}' "
                f"via " + " -> ".join(self.path))

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "source": self.source,
                "output": self.output, "path": list(self.path)}


@dataclasses.dataclass
class IsolationProof:
    """The prover's verdict for one protected program."""

    benchmark: str
    strategy: str
    num_clones: int
    holds: bool
    vacuous: bool                       # nothing replicated: no lanes
    leaks: List[Leak]
    total_leak_paths: int               # before the report cap
    voted_commits: List[str]            # live sanctioned tags (obligations
    #                                     discharged by the engine)
    assumptions: List[str]              # accepted single-lane calls

    def summary(self) -> Dict[str, object]:
        return {
            "benchmark": self.benchmark,
            "strategy": self.strategy,
            "num_clones": self.num_clones,
            "holds": self.holds,
            "vacuous": self.vacuous,
            "leaks": [l.to_dict() for l in self.leaks],
            "total_leak_paths": self.total_leak_paths,
            "voted_commits": list(self.voted_commits),
            "assumptions": list(self.assumptions),
        }

    def format(self) -> str:
        if self.vacuous:
            return (f"isolation {self.benchmark} [{self.strategy}]: "
                    "vacuously holds (nothing replicated)")
        if self.holds:
            return (f"isolation {self.benchmark} [{self.strategy}]: "
                    f"HOLDS ({len(self.voted_commits)} voted commit(s), "
                    f"{len(self.assumptions)} single-lane-call "
                    "assumption(s))")
        lines = [f"isolation {self.benchmark} [{self.strategy}]: "
                 f"LEAK ({self.total_leak_paths} path(s))"]
        for l in self.leaks:
            lines.append("  " + l.format())
        return "\n".join(lines)


class _LeakFlow:
    """Forward leak-reachability with counterexample paths.

    Taint elements are integer leak ids injected at the unsanctioned
    cross-lane sites; they propagate through EVERYTHING (arithmetic,
    steering, control flow, even later voters -- an already-fanned-out
    corruption is lane-identical and invisible to any majority) and are
    collected at the jaxpr outputs."""

    def __init__(self, inject: Dict[int, int],
                 roots: Dict[int, Tuple[str, ...]]):
        self.inject = inject              # id(eqn) -> leak id
        self.roots = roots                # leak id -> root path
        self.env: Dict[object, FrozenSet[int]] = {}
        self.path: Dict[object, Dict[int, Tuple[str, ...]]] = {}

    def val(self, v) -> FrozenSet[int]:
        from jax.extend.core import Literal
        if isinstance(v, Literal):
            return frozenset()
        return self.env.get(v, frozenset())

    def _set(self, v, taint: FrozenSet[int]) -> None:
        old = self.env.get(v)
        self.env[v] = taint if old is None else (old | taint)

    def seed(self, inner_vars, taints) -> None:
        for iv, t in zip(inner_vars, taints):
            self._set(iv, t)

    def _in_path(self, eqn, lid: int) -> Tuple[str, ...]:
        from jax.extend.core import Literal
        for iv in eqn.invars:
            if isinstance(iv, Literal):
                continue
            d = self.path.get(iv)
            if d is not None and lid in d:
                return d[lid]
        return self.roots.get(lid, ())

    def walk(self, jaxpr) -> List[FrozenSet[int]]:
        for eqn in jaxpr.eqns:
            ins = [self.val(v) for v in eqn.invars]
            outs = self._eqn_outs(eqn, ins)
            inj = self.inject.get(id(eqn))
            entry = eqn_entry(eqn)
            for v, t in zip(eqn.outvars, outs):
                if inj is not None:
                    t = t | frozenset({inj})
                self._set(v, t)
                if t:
                    d = self.path.setdefault(v, {})
                    for lid in t:
                        if lid not in d:
                            p = (self.roots[lid] if lid == inj
                                 and lid not in d else
                                 self._in_path(eqn, lid))
                            d[lid] = (p + (entry,) if len(p) < _PATH_MAX
                                      else p)
        return [self.val(v) for v in jaxpr.outvars]

    def _eqn_outs(self, eqn, ins):
        prim = eqn.primitive.name
        params = eqn.params
        union = frozenset().union(*ins) if ins else frozenset()

        if prim == "optimization_barrier":
            return list(ins)
        if prim == "cond" and "branches" in params:
            per_branch = []
            for br in params["branches"]:
                self.seed(br.jaxpr.invars, ins[1:])
                per_branch.append(self.walk(br.jaxpr))
            outs = []
            for i in range(len(eqn.outvars)):
                o = frozenset(ins[0])       # a leaked predicate steers
                for b in per_branch:
                    o |= b[i]
                outs.append(o)
            return outs
        if prim == "while":
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            cj, bj = params["cond_jaxpr"].jaxpr, params["body_jaxpr"].jaxpr
            carry = list(ins[cn + bn:])
            for _ in range(len(carry) + 2):
                self.seed(cj.invars, ins[:cn] + carry)
                cond_out = self.walk(cj)
                steer = cond_out[0] if cond_out else frozenset()
                self.seed(bj.invars, ins[cn:cn + bn] + carry)
                new_carry = self.walk(bj)
                joined = [c | nc | steer
                          for c, nc in zip(carry, new_carry)]
                if joined == carry:
                    break
                carry = joined
            return carry
        if prim == "scan":
            sub = params["jaxpr"].jaxpr
            nc, ncar = params["num_consts"], params["num_carry"]
            consts, carry = list(ins[:nc]), list(ins[nc:nc + ncar])
            xs = list(ins[nc + ncar:])
            outs = None
            for _ in range(max(ncar, 1) + 2):
                self.seed(sub.invars, consts + carry + xs)
                outs = self.walk(sub)
                joined = [c | nc_ for c, nc_ in zip(carry, outs[:ncar])]
                if joined == carry:
                    break
                carry = joined
            return carry + list(outs[ncar:])
        for key in ("jaxpr", "call_jaxpr"):
            if key in params:
                sub = params[key]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                self.seed(sub.invars, ins)
                return self.walk(sub)

        # Everything else -- arithmetic, compares, name tags, structural
        # moves: leak influence propagates.  (A sanctioned voter cannot
        # un-leak a value that already fanned out lane-identically.)
        return [union for _ in eqn.outvars]


def prove_isolation(prog, closed=None,
                    facts: Optional[StepFacts] = None,
                    strategy: Optional[str] = None) -> IsolationProof:
    """Prove (or refute, with counterexample paths) lane noninterference
    for ``prog``'s protected step.  Pure static analysis -- no compile,
    no clean run; safe as a pre-gate on every build."""
    if facts is None:
        facts = analyze_step(prog, closed=closed, track_paths=False)
    n = facts.num_clones
    strategy = strategy or f"N={n}"
    benchmark = prog.region.name

    if n <= 1 or not any(prog.replicated.get(k)
                         for k in prog.region.spec):
        return IsolationProof(
            benchmark=benchmark, strategy=strategy, num_clones=n,
            holds=True, vacuous=True, leaks=[], total_leak_paths=0,
            voted_commits=[], assumptions=[])

    # Discharged obligations + configured assumptions, from the live tags.
    voted: Set[str] = set()
    assumptions: Set[str] = set()
    for key, tag in facts.walker.tags.items():
        if key not in facts.live:
            continue
        if _detector_tag(tag):
            voted.add(tag)
        elif tag.startswith(TAG_SPOF):
            assumptions.add(tag[len(TAG_SPOF):])

    # The interference sources: live unsanctioned cross-lane sites.
    sites = cross_lane_sites(facts.walker, facts.live, n)
    inject: Dict[int, int] = {}
    roots: Dict[int, Tuple[str, ...]] = {}
    site_desc: Dict[int, Tuple[str, str]] = {}
    for lid, cand in enumerate(sites):
        eqn = cand["eqn"]
        leaves = "+".join(sorted(cand["deps"])) or "?"
        desc = f"{cand['prim']} over {leaves}"
        if cand["kind"] == "spof" and cand.get("lane") is not None:
            desc += f" (lane {cand['lane']})"
        inject[id(eqn)] = lid
        roots[lid] = (desc,)
        site_desc[lid] = (str(cand["kind"]), desc)

    leaks: List[Leak] = []
    total = 0
    if inject:
        flow = _LeakFlow(inject, roots)
        out_taints = flow.walk(facts.jaxpr)
        for out_name, outvar, taint in zip(facts.out_names,
                                           facts.jaxpr.outvars,
                                           out_taints):
            for lid in sorted(taint):
                total += 1
                if len(leaks) >= _MAX_LEAKS:
                    continue
                kind, desc = site_desc[lid]
                path = flow.path.get(outvar, {}).get(lid, roots[lid])
                leaks.append(Leak(rule=kind, source=desc,
                                  output=out_name, path=path))

    return IsolationProof(
        benchmark=benchmark, strategy=strategy, num_clones=n,
        holds=total == 0, vacuous=False, leaks=leaks,
        total_leak_paths=total, voted_commits=sorted(voted),
        assumptions=sorted(assumptions))


@contextlib.contextmanager
def seeded_voter_bypass():
    """Regression seam: build protected programs whose votes route lane
    0's value around the voter -- no majority, no miscompare, no
    sanction tag.  The generic "injected-lane value reaches the served
    state" bug the isolation prover must catch on every target.

    Must wrap BOTH the program construction and the analysis trace (the
    engine binds ``voters.vote`` at construction and applies
    ``voters.sync_tag`` at trace time)::

        with seeded_voter_bypass():
            prog = TMR(region)
            proof = prove_isolation(prog)
        assert not proof.holds and proof.leaks[0].path
    """
    from coast_tpu.ops import voters

    orig_vote = voters.vote
    orig_sync = voters.sync_tag
    orig_view = voters.lane_view

    def bypass_sync(lanes, klass, leaf):
        return lanes                     # the sanction tag is dropped

    def bypass_vote(lanes, num_clones):
        import jax.numpy as jnp
        del num_clones
        # Lane 0 verbatim, and the miscompare that would have latched
        # the divergence is constant-false: the voter is fully bypassed.
        return lanes[0], jnp.array(False)

    def bypass_view(lanes):
        # The DWC boundary read without its coast:view sanction: the
        # served view consumes a raw injected lane.  (DWC's voters are
        # detect-only -- the voted value is discarded, so the committed
        # state carries no cross-lane flow to leak; the boundary view
        # is where lane 0 reaches the response.)
        return lanes[0]

    voters.vote = bypass_vote
    voters.sync_tag = bypass_sync
    voters.lane_view = bypass_view
    try:
        yield
    finally:
        voters.vote = orig_vote
        voters.sync_tag = orig_sync
        voters.lane_view = orig_view
