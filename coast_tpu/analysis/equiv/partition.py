"""Propagation-equivalence partition over the fault-site space.

A seeded campaign draws sites ``(leaf, lane, word, bit, t)`` uniformly
over the injectable bits; most draws are redundant -- they land in state
whose downstream dataflow provably carries any single-bit corruption to
the same classification.  This pass walks the protected step's jaxpr
with the lint provenance lattice (:class:`analysis.lint.provenance
._Walker`) and derives, per memory-map section, a *merge mode*: which
site coordinates provably cannot change the outcome class.

The soundness arguments are the engine's own invariants
(passes/dataflow_protection.py); each mode names the coordinates that
remain in the class key:

  * ``FREE`` (class = leaf) -- the flip cannot interact with the step's
    trajectory at all.  Two shapes qualify: an unconsumed shared leaf
    whose only use is an equality-compare cone in ``check()`` (the
    ``golden`` matrix: any flipped bit turns exactly one compare, E
    becomes 1, SDC regardless of lane/word/bit/t); and an unconsumed,
    unwritten replicated leaf (divergence sits untouched until the
    region-boundary sync detects it).
  * ``LT`` (class = leaf x t) -- a replicated leaf that is either
    pre-step voted before any consumption (the ``load_addr`` sync:
    the flip is repaired/latched before the step reads it) or never
    written by the step (the flipped lane survives verbatim in the leaf
    itself, so the region-boundary sync is a guaranteed witness; which
    *other* state the corruption reached on the way does not change the
    class -- TMR corrects, DWC aborts).
  * ``LTW`` (class = leaf x t x word) -- a written replicated leaf whose
    value flows ONLY through structural primitives (selects, slices,
    dynamic-update-slices, reshapes) between its flip and either a
    sanctioned vote input or the leaf commit.  Words travel verbatim, so
    the flip is either overwritten this step (masked -> the clean-run
    outcome) or survives word-for-word to a voter/the boundary
    (detected); which of the two is a deterministic function of
    ``(t, word)`` because the structural routing follows the fault-free
    trajectory.  Bit and lane cannot matter: compares see any bit, and
    the routing is lane-uniform.
  * ``EXH`` (class = the site itself) -- no merge.  Applied to every
    value-fed leaf (its flipped value enters arithmetic that can mask
    bits -- the crc shift-out case), to shared consumed leaves, to any
    leaf implicated in a live single-lane extraction, and to every
    replicated leaf when the region carries per-lane guards, CFCSS, or
    single-lane function scopes (those read raw lane values, so
    detection is value-dependent).

Additionally every site whose ``t`` lies at or past the fault-free halt
step joins one global ``dead`` class: the run is already halted when the
flip would fire, so it provably never fires (SUCCESS).

The partition is *validated differentially* (FuzzyFlow's idiom): the
reduced campaign's weighted classification distribution must equal the
exhaustive one's exactly -- tests/test_equiv.py pins it on seeded TMR
and DWC targets, scripts/equiv_study.py records it.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, FrozenSet, List, Optional, Set

import jax
import numpy as np

from coast_tpu.analysis.lint.provenance import (_Val, _Walker, _live_eqns,
                                                trace_step)
from coast_tpu.ops.voters import TAG_SPOF, TAG_SYNC, TAG_VIEW, TAG_VOTER

# Merge modes, coarsest first.  The class key keeps only the coordinates
# the mode names; everything else is proven outcome-irrelevant.
MODE_FREE = 0      # class = (leaf,)
MODE_LT = 1        # class = (leaf, t)
MODE_LTW = 2       # class = (leaf, t, word)
MODE_EXH = 3       # class = (leaf, t, word, bit, lane) -- no merge

MODE_NAMES = ("free", "lt", "ltw", "exhaustive")

# Primitives that move words verbatim: a flipped word passes through
# them unchanged (or is dropped), never arithmetically transformed.
# Operand positions listed in _VALUE_OPERANDS are *steering* inputs
# (predicates, indices): a flipped value there changes WHICH words move,
# which is value-dependent -- consuming a tainted steering operand marks
# the leaf value-fed.
_STRUCTURAL_PRIMS = frozenset({
    "select_n", "dynamic_update_slice", "dynamic_slice", "slice",
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "concatenate",
    "rev", "copy", "gather", "scatter", "pad", "stop_gradient",
})

_VALUE_OPERANDS = {
    "select_n": lambda eqn: (0,),
    "dynamic_slice": lambda eqn: tuple(range(1, len(eqn.invars))),
    "dynamic_update_slice": lambda eqn: tuple(range(2, len(eqn.invars))),
    "gather": lambda eqn: (1,),
    "scatter": lambda eqn: (1,),
    "pad": lambda eqn: (),
}

# Sync classes whose tag marks a *detector* on the tagged value: taint
# entering one is guaranteed either masked (lanes equal) or latched/
# repaired there, so it stops propagating.  'guard' is deliberately NOT
# in this set -- kernel guards read raw per-lane values and trip
# value-dependently, so their consumption must count as value-feeding.
_DETECTOR_CLASSES = frozenset({
    "load_addr", "store_data", "ctrl", "stack", "sor_crossing",
    "boundary", "call_boundary", "cfcss",
    # Training regions' weight-update commit votes (KIND_PARAM /
    # KIND_OPT_STATE leaves).  Note these detectors never LICENSE a
    # merge on a train region -- the train fallback below forces every
    # section exhaustive first; the membership only keeps the taint walk
    # honest about where votes kill verbatim-word flow.
    "param", "opt_state",
})

#: EquivPartition.fallback_reason value for training regions: the
#: outcome class of a train SDC is a function of the *numeric value* of
#: the flip (a low-mantissa weight flip self-heals where the same
#: word's exponent bit diverges persistently), so every bit/word/lane
#: coordinate is outcome-relevant and no merge mode except the dead
#: class is sound.  The pass degrades to exhaustive -- documented,
#: typed, and pinned by a counterexample test -- rather than deriving
#: weights that would silently misreport wrong-weight outcomes.
TRAIN_FALLBACK = ("train_probe outcome semantics are bit-value-dependent; "
                  "all sections forced exhaustive")


def _detector_tag(tag: str) -> bool:
    if tag.startswith(TAG_VOTER) and not tag.startswith(TAG_VIEW):
        return True
    if tag.startswith(TAG_SYNC):
        klass = tag[len(TAG_SYNC):].partition(":")[0]
        return klass in _DETECTOR_CLASSES
    return False


class _TaintWalk:
    """Forward word-verbatim taint over a (nested) jaxpr.

    ``env[var]`` is the frozenset of leaf names whose unmodified words
    may be present in ``var``.  Taint passes through structural
    primitives, dies at detector tags (sanctioned votes), and marks a
    leaf ``value_fed`` wherever a live equation consumes its taint
    non-structurally (arithmetic, reductions, steering operands, guard
    inputs).
    """

    def __init__(self, live: Optional[Set[int]]):
        self.env: Dict[object, FrozenSet[str]] = {}
        self.value_fed: Set[str] = set()
        self.live = live

    def val(self, v) -> FrozenSet[str]:
        from jax.extend.core import Literal
        if isinstance(v, Literal):
            return frozenset()
        return self.env.get(v, frozenset())

    def _set(self, v, taint: FrozenSet[str]) -> None:
        old = self.env.get(v)
        self.env[v] = taint if old is None else (old | taint)

    def seed(self, inner_vars, taints) -> None:
        for iv, t in zip(inner_vars, taints):
            self._set(iv, t)

    def _is_live(self, eqn) -> bool:
        return self.live is None or id(eqn) in self.live

    def _feed(self, eqn, taint: FrozenSet[str]) -> None:
        if taint and self._is_live(eqn):
            self.value_fed |= taint

    def walk(self, jaxpr) -> List[FrozenSet[str]]:
        for eqn in jaxpr.eqns:
            ins = [self.val(v) for v in eqn.invars]
            outs = self._eqn_outs(eqn, ins)
            for v, t in zip(eqn.outvars, outs):
                self._set(v, t)
        return [self.val(v) for v in jaxpr.outvars]

    def _eqn_outs(self, eqn, ins):
        prim = eqn.primitive.name
        params = eqn.params
        union = frozenset().union(*ins) if ins else frozenset()

        if prim == "name":
            tag = str(params.get("name", ""))
            if _detector_tag(tag):
                return [frozenset()]
            if tag.startswith(TAG_SPOF):
                # Single-lane call boundary: the callee sees raw lane-0
                # values -- value consumption by definition.
                self._feed(eqn, union)
                return [frozenset()]
            return [ins[0] if ins else frozenset()]

        if prim == "optimization_barrier":
            # n-ary identity fence: words pass through verbatim, per
            # position -- neither consumed nor mixed.
            return list(ins)

        if prim in _STRUCTURAL_PRIMS:
            value_pos = _VALUE_OPERANDS.get(prim, lambda e: ())(eqn)
            data = frozenset()
            for i, t in enumerate(ins):
                if i in value_pos:
                    self._feed(eqn, t)
                else:
                    data |= t
            return [data for _ in eqn.outvars]

        # -- control flow / nested jaxprs --
        if prim == "cond" and "branches" in params:
            self._feed(eqn, ins[0])
            per_branch = []
            for br in params["branches"]:
                self.seed(br.jaxpr.invars, ins[1:])
                per_branch.append(self.walk(br.jaxpr))
            outs = []
            for i in range(len(eqn.outvars)):
                o = frozenset()
                for b in per_branch:
                    o |= b[i]
                outs.append(o)
            return outs
        if prim == "while":
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            cj, bj = params["cond_jaxpr"].jaxpr, params["body_jaxpr"].jaxpr
            carry = list(ins[cn + bn:])
            for _ in range(len(carry) + 2):
                self.seed(cj.invars, ins[:cn] + carry)
                cond_out = self.walk(cj)
                self._feed(eqn, cond_out[0] if cond_out else frozenset())
                self.seed(bj.invars, ins[cn:cn + bn] + carry)
                new_carry = self.walk(bj)
                joined = [c | nc for c, nc in zip(carry, new_carry)]
                if joined == carry:
                    break
                carry = joined
            return carry
        if prim == "scan":
            sub = params["jaxpr"].jaxpr
            nc, ncar = params["num_consts"], params["num_carry"]
            consts, carry = list(ins[:nc]), list(ins[nc:nc + ncar])
            xs = list(ins[nc + ncar:])
            outs = None
            for _ in range(max(ncar, 1) + 2):
                self.seed(sub.invars, consts + carry + xs)
                outs = self.walk(sub)
                joined = [c | nc_ for c, nc_ in zip(carry, outs[:ncar])]
                if joined == carry:
                    break
                carry = joined
            return carry + list(outs[ncar:])
        for key in ("jaxpr", "call_jaxpr"):
            if key in params:
                sub = params[key]
                sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                self.seed(sub.invars, ins)
                return self.walk(sub)

        # Any other primitive transforms values: tainted inputs are
        # value-fed, outputs carry no verbatim words.
        self._feed(eqn, union)
        return [frozenset() for _ in eqn.outvars]


@dataclasses.dataclass(frozen=True)
class SectionSignature:
    """One memory-map section's propagation signature."""

    name: str
    kind: str
    leaf_id: int
    lanes: int
    words: int
    replicated: bool
    written: bool
    consumed: bool
    value_fed: bool
    pre_voted: bool
    step_voted: bool
    mode: int                  # MODE_* merge decision
    fingerprint: str           # sha256 over signature + dataflow cone

    @property
    def mode_name(self) -> str:
        return MODE_NAMES[self.mode]


@dataclasses.dataclass
class EquivPartition:
    """The derived partition: per-section signatures + the site
    classifier the injection stack consumes."""

    benchmark: str
    num_clones: int
    clean_steps: int
    signatures: Dict[str, SectionSignature]
    fingerprint: str           # sha over all section fps + clean_steps
    # Non-None when the pass refused to derive merge modes and degraded
    # every section to exhaustive (TRAIN_FALLBACK for training regions):
    # the typed, documented no-silent-wrong-weights marker.  The dead
    # class (sites past the clean halt step) is still merged -- a flip
    # that provably never fires is sound under any outcome semantics.
    fallback_reason: Optional[str] = None

    def _mode_table(self) -> np.ndarray:
        n = max((s.leaf_id for s in self.signatures.values()),
                default=-1) + 1
        table = np.full(n + 1, MODE_EXH, np.int8)
        for sig in self.signatures.values():
            table[sig.leaf_id] = sig.mode
        return table

    def class_keys(self, sched) -> np.ndarray:
        """int64 [n, 5] class-key rows for a FaultSchedule; equal rows
        are provably outcome-equivalent sites."""
        n = len(sched)
        leaf = np.asarray(sched.leaf_id, np.int64)
        lane = np.asarray(sched.lane, np.int64)
        word = np.asarray(sched.word, np.int64)
        bit = np.asarray(sched.bit, np.int64)
        t = np.asarray(sched.t, np.int64)
        modes = self._mode_table()[np.clip(leaf, 0, None)]
        keys = np.stack([leaf, t, word, bit, lane], axis=1)
        keys[modes == MODE_FREE, 1:] = -2
        keys[modes == MODE_LT, 2:] = -3
        keys[modes == MODE_LTW, 3:] = -4
        # Sites firing at or past the fault-free halt step never fire at
        # all (the run is already halted): one global dead class.
        dead = t >= self.clean_steps
        keys[dead] = -1
        # Cache draws outside the footprint (t < 0, hierarchy overlays)
        # keep their full site identity -- the runner buckets them as
        # cache_invalid, so merging them into a fired class would skew
        # the weighted counts.
        neg = t < 0
        if neg.any():
            keys[neg] = np.stack([leaf, t, word, bit, lane], axis=1)[neg]
        assert keys.shape == (n, 5)
        return keys

    def reduce(self, sched):
        """One seeded representative per realized class: a FaultSchedule
        of the first-drawn site of each class, carrying ``class_weight``
        = how many physical draws that representative stands for.  Rows
        keep schedule order, so batching/journaling/streaming see a
        normal (just shorter) campaign."""
        from coast_tpu.inject.schedule import FaultSchedule
        keys = self.class_keys(sched)
        _, first, inverse, counts = np.unique(
            keys, axis=0, return_index=True, return_inverse=True,
            return_counts=True)
        order = np.argsort(first, kind="stable")
        rep = first[order]
        weights = counts[order].astype(np.int64)
        return FaultSchedule(
            np.ascontiguousarray(sched.leaf_id[rep]),
            np.ascontiguousarray(sched.lane[rep]),
            np.ascontiguousarray(sched.word[rep]),
            np.ascontiguousarray(sched.bit[rep]),
            np.ascontiguousarray(sched.t[rep]),
            np.ascontiguousarray(sched.section_idx[rep]),
            sched.seed, model=sched.model,
            class_weight=weights, equiv_sha=self.fingerprint)

    def summary(self) -> Dict[str, object]:
        return {
            "benchmark": self.benchmark,
            "num_clones": self.num_clones,
            "clean_steps": self.clean_steps,
            "fingerprint": self.fingerprint,
            **({"fallback_reason": self.fallback_reason}
               if self.fallback_reason else {}),
            "sections": {
                name: {"mode": sig.mode_name,
                       "fingerprint": sig.fingerprint}
                for name, sig in sorted(self.signatures.items())},
        }


def _cone_entries(jaxpr, env, live, name: str, out: List[str]) -> None:
    """Program-order ``prim(shape)`` entries of the live equations whose
    output provenance includes ``name`` -- the leaf's dataflow cone, the
    raw material of its fingerprint."""
    for eqn in jaxpr.eqns:
        if live is None or id(eqn) in live:
            for ov in eqn.outvars:
                val = env.get(ov)
                if val is not None and name in val.deps:
                    shape = tuple(getattr(ov.aval, "shape", ()))
                    out.append(f"{eqn.primitive.name}{shape}")
                    break
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                sub = v.jaxpr if hasattr(v.jaxpr, "eqns") else v
                if hasattr(sub, "eqns"):
                    _cone_entries(sub, env, live, name, out)
            elif isinstance(v, (list, tuple)):
                for b in v:
                    if hasattr(b, "jaxpr"):
                        _cone_entries(b.jaxpr, env, live, name, out)


def _check_transparent(region, name: str) -> bool:
    """True when ``check()``'s consumption of shared leaf ``name`` is an
    equality-compare indicator cone: every path is leaf -> eq/ne against
    an untainted operand -> {convert/reduce_sum/reduce_or/add/broadcast/
    reshape} -> E.  Then a completed clean-trajectory run with one
    flipped bit anywhere in the leaf yields E >= 1 (the fault-free check
    passes with E = 0, so exactly the flipped word's compare turns),
    i.e. SDC for every site -- or, if the leaf never reaches E at all,
    SUCCESS for every site.  Anything fancier is reported opaque."""
    import jax.numpy as jnp
    state = jax.eval_shape(region.init)
    try:
        closed = jax.make_jaxpr(region.check)(state)
    except Exception:       # noqa: BLE001 - analysis must not break builds
        return False
    RAW, IND = "raw", "ind"
    env: Dict[object, str] = {}
    from jax.extend.core import Literal

    def val(v):
        if isinstance(v, Literal):
            return None
        return env.get(v)

    jaxpr = closed.jaxpr
    state_names = sorted(state)
    if len(jaxpr.invars) != len(state_names):
        return False
    for leaf_name, var in zip(state_names, jaxpr.invars):
        if leaf_name == name:
            env[var] = RAW

    _IND_OK = {"convert_element_type", "reduce_sum", "reduce_or", "add",
               "broadcast_in_dim", "reshape", "squeeze", "transpose"}

    def walk(jx) -> bool:
        for eqn in jx.eqns:
            ins = [val(v) for v in eqn.invars]
            tainted = [t for t in ins if t is not None]
            prim = eqn.primitive.name
            if not tainted:
                continue
            if prim in ("eq", "ne"):
                if RAW in tainted and len(tainted) == 1:
                    for ov in eqn.outvars:
                        env[ov] = IND
                    continue
                return False
            if RAW in tainted:
                return False
            if prim in _IND_OK:
                for ov in eqn.outvars:
                    env[ov] = IND
                continue
            for key in ("jaxpr", "call_jaxpr"):
                if key in eqn.params:
                    sub = eqn.params[key]
                    sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                    for iv, t in zip(sub.invars, ins):
                        if t is not None:
                            env[iv] = t
                    if not walk(sub):
                        return False
                    for ov, t in zip(eqn.outvars,
                                     [val(v) for v in sub.outvars]):
                        if t is not None:
                            env[ov] = t
                    break
            else:
                return False
        return True

    return walk(jaxpr)


def _clean_steps(prog) -> int:
    """First step index at which the fault-free run is halted: the flip
    window's hard edge (a later flip provably never fires)."""
    rec = jax.jit(lambda: prog.run(None))()
    return int(rec["steps"])


def analyze_equivalence(prog, closed=None) -> EquivPartition:
    """Derive the propagation-equivalence partition of ``prog``'s
    fault-site space.  ``closed`` forwards an already-traced step jaxpr
    (scripts/lint_sweep.py traces once and shares it with the lint)."""
    cfg = prog.cfg
    region = prog.region
    n = cfg.num_clones
    if closed is None:
        closed = trace_step(prog)
    jaxpr = closed.jaxpr

    pstate, flags = jax.eval_shape(prog.init_pstate)
    state_names = sorted(pstate)
    flag_names = sorted(flags)
    assert len(jaxpr.invars) == len(state_names) + len(flag_names) + 1, (
        len(jaxpr.invars), len(state_names), len(flag_names))

    # -- lattice walk (shared machinery with lint_provenance) ------------
    walker = _Walker(n)
    taints: List[FrozenSet[str]] = []
    for name, var in zip(state_names, jaxpr.invars):
        status = "laned" if prog.replicated.get(name) else "shared"
        walker.env[var] = _Val(status, 0, False, False, frozenset({name}))
        taints.append(frozenset({name}))
    out_vals = walker.walk(jaxpr)

    live: Set[int] = set()
    _live_eqns(jaxpr, list(jaxpr.outvars), live)

    # -- value-feeding taint walk ----------------------------------------
    taint = _TaintWalk(live)
    for var, t in zip(jaxpr.invars, taints):
        taint._set(var, t)
    taint.walk(jaxpr)

    # -- per-leaf facts ---------------------------------------------------
    out_names = state_names + flag_names
    consumed: Set[str] = set()
    for out_name, val in zip(out_names, out_vals):
        for dep in val.deps:
            if dep != out_name:
                consumed.add(dep)
    # The write set comes from the REGION's dataflow roles (the same
    # analysis the engine derives its store syncs from): in the
    # protected step's jaxpr every leaf gets fresh outvars (vmap,
    # freeze-select), so var identity cannot tell a semantic write from
    # a passthrough.  Synthetic (CFCSS) leaves are not region leaves;
    # they are EXH below regardless.
    from coast_tpu.passes.verification import analyze
    written = set(analyze(region).written)

    # Live single-lane extractions / unsanctioned collapses implicate
    # their provenance leaves: lane symmetry is not provable there.
    lane_flagged: Set[str] = set()
    for key, cand in walker.candidates.items():
        if key in live:
            lane_flagged |= set(cand["deps"])

    guards = (region.stack_guard is not None
              or region.assert_guard is not None)
    # Training regions (Region.train_probe): the outcome class depends
    # on the flip's numeric VALUE -- classify splits SDC by whether the
    # loss re-converged, and a low bit of a weight heals where the same
    # word's exponent bit diverges -- so the bit/word/lane-dropping
    # merge arguments above are all unsound.  Typed, documented
    # fallback: every section exhaustive (only the dead class merges).
    train_fallback = getattr(region, "train_probe", None) is not None
    cfcss = getattr(prog, "_cfcss_step", None) is not None
    fn_unsafe = n > 1 and any(
        scope not in ("replicated", "replicated_return")
        for scope in getattr(prog, "fn_scope", {}).values())

    clean_steps = _clean_steps(prog)

    # check() cone for fingerprints + shared-leaf transparency.
    check_walker = _Walker(n)
    check_closed = None
    try:
        check_closed = jax.make_jaxpr(region.check)(
            jax.eval_shape(region.init))
        check_names = sorted(jax.eval_shape(region.init))
        for name, var in zip(check_names, check_closed.jaxpr.invars):
            check_walker.env[var] = _Val("shared", 0, False, False,
                                         frozenset({name}))
        check_walker.walk(check_closed.jaxpr)
    except Exception:       # noqa: BLE001 - fingerprint falls back to spec
        check_closed = None

    signatures: Dict[str, SectionSignature] = {}
    for leaf_id, (name, kind, lanes, words) in enumerate(
            prog.injectable_sections()):
        replicated = bool(prog.replicated.get(name, kind == "cfcss"))
        is_written = name in written
        is_consumed = name in consumed
        value_fed = name in taint.value_fed
        pre_voted = bool(getattr(prog, "pre_sync", {}).get(name, False))
        step_voted = bool(getattr(prog, "step_sync", {}).get(name, False))

        if train_fallback:
            mode = MODE_EXH
        elif replicated:
            if (cfcss or guards or fn_unsafe or kind == "cfcss"
                    or name in lane_flagged):
                mode = MODE_EXH
            elif pre_voted:
                # Repaired (TMR) or latched (DWC) before any read.
                mode = MODE_LT
            elif not is_written:
                mode = MODE_FREE if not is_consumed else MODE_LT
            elif not value_fed:
                mode = MODE_LTW
            else:
                mode = MODE_EXH
        else:
            if not is_consumed and not is_written \
                    and _check_transparent(region, name):
                mode = MODE_FREE
            else:
                mode = MODE_EXH

        cone: List[str] = []
        _cone_entries(jaxpr, walker.env, live, name, cone)
        if check_closed is not None:
            cone.append("|check|")
            _cone_entries(check_closed.jaxpr, check_walker.env, None,
                          name, cone)
        h = hashlib.sha256()
        h.update(repr((name, kind, lanes, words, replicated, is_written,
                       is_consumed, value_fed, pre_voted, step_voted,
                       MODE_NAMES[mode], n, clean_steps)).encode())
        h.update("|".join(cone).encode())
        signatures[name] = SectionSignature(
            name=name, kind=kind, leaf_id=leaf_id, lanes=lanes,
            words=words, replicated=replicated, written=is_written,
            consumed=is_consumed, value_fed=value_fed,
            pre_voted=pre_voted, step_voted=step_voted, mode=mode,
            fingerprint=h.hexdigest())

    overall = hashlib.sha256()
    overall.update(str(clean_steps).encode())
    for name in sorted(signatures):
        overall.update(name.encode())
        overall.update(signatures[name].fingerprint.encode())
    return EquivPartition(
        benchmark=region.name,
        num_clones=n,
        clean_steps=clean_steps,
        signatures=signatures,
        fingerprint=overall.hexdigest(),
        fallback_reason=TRAIN_FALLBACK if train_fallback else None)


def section_fingerprints(prog, partition: Optional[EquivPartition] = None
                         ) -> Dict[str, str]:
    """Per-section propagation fingerprints -- the delta-campaign
    identity persisted in the journal header.  A section whose
    fingerprint is unchanged across a rebuild has the identical
    dataflow cone, sync coverage, and merge mode, so its recorded
    outcomes remain valid."""
    if partition is None:
        partition = analyze_equivalence(prog)
    return {name: sig.fingerprint
            for name, sig in partition.signatures.items()}
