"""Propagation-equivalence partition over the fault-site space.

A seeded campaign draws sites ``(leaf, lane, word, bit, t)`` uniformly
over the injectable bits; most draws are redundant -- they land in state
whose downstream dataflow provably carries any single-bit corruption to
the same classification.  This pass walks the protected step's jaxpr
with the lint provenance lattice (:class:`analysis.lint.provenance
._Walker`) and derives, per memory-map section, a *merge mode*: which
site coordinates provably cannot change the outcome class.

The soundness arguments are the engine's own invariants
(passes/dataflow_protection.py); each mode names the coordinates that
remain in the class key:

  * ``FREE`` (class = leaf) -- the flip cannot interact with the step's
    trajectory at all.  Two shapes qualify: an unconsumed shared leaf
    whose only use is an equality-compare cone in ``check()`` (the
    ``golden`` matrix: any flipped bit turns exactly one compare, E
    becomes 1, SDC regardless of lane/word/bit/t); and an unconsumed,
    unwritten replicated leaf (divergence sits untouched until the
    region-boundary sync detects it).
  * ``LT`` (class = leaf x t) -- a replicated leaf that is either
    pre-step voted before any consumption (the ``load_addr`` sync:
    the flip is repaired/latched before the step reads it) or never
    written by the step (the flipped lane survives verbatim in the leaf
    itself, so the region-boundary sync is a guaranteed witness; which
    *other* state the corruption reached on the way does not change the
    class -- TMR corrects, DWC aborts).
  * ``LTW`` (class = leaf x t x word) -- a written replicated leaf whose
    value flows ONLY through structural primitives (selects, slices,
    dynamic-update-slices, reshapes) between its flip and either a
    sanctioned vote input or the leaf commit.  Words travel verbatim, so
    the flip is either overwritten this step (masked -> the clean-run
    outcome) or survives word-for-word to a voter/the boundary
    (detected); which of the two is a deterministic function of
    ``(t, word)`` because the structural routing follows the fault-free
    trajectory.  Bit and lane cannot matter: compares see any bit, and
    the routing is lane-uniform.
  * ``EXH`` (class = the site itself) -- no merge.  Applied to every
    value-fed leaf (its flipped value enters arithmetic that can mask
    bits -- the crc shift-out case), to shared consumed leaves, to any
    leaf implicated in a live single-lane extraction, and to every
    replicated leaf when the region carries per-lane guards, CFCSS, or
    single-lane function scopes (those read raw lane values, so
    detection is value-dependent).

Additionally every site whose ``t`` lies at or past the fault-free halt
step joins one global ``dead`` class: the run is already halted when the
flip would fire, so it provably never fires (SUCCESS).

The partition is *validated differentially* (FuzzyFlow's idiom): the
reduced campaign's weighted classification distribution must equal the
exhaustive one's exactly -- tests/test_equiv.py pins it on seeded TMR
and DWC targets, scripts/equiv_study.py records it.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, FrozenSet, List, Optional, Set

import jax
import numpy as np

# The walk machinery lives in the shared fault-propagation walker
# (analysis/propagation/walker.py) since the static vulnerability map
# joined: one abstract interpretation feeds the partition, the map, and
# the isolation prover.  Re-exported names keep this module the
# historical import point.
from coast_tpu.analysis.propagation.walker import (_DETECTOR_CLASSES,
                                                   _STRUCTURAL_PRIMS,
                                                   _VALUE_OPERANDS,
                                                   _TaintWalk,
                                                   _detector_tag,
                                                   analyze_step)

# Merge modes, coarsest first.  The class key keeps only the coordinates
# the mode names; everything else is proven outcome-irrelevant.
MODE_FREE = 0      # class = (leaf,)
MODE_LT = 1        # class = (leaf, t)
MODE_LTW = 2       # class = (leaf, t, word)
MODE_EXH = 3       # class = (leaf, t, word, bit, lane) -- no merge

MODE_NAMES = ("free", "lt", "ltw", "exhaustive")

#: EquivPartition.fallback_reason value for training regions: the
#: outcome class of a train SDC is a function of the *numeric value* of
#: the flip (a low-mantissa weight flip self-heals where the same
#: word's exponent bit diverges persistently), so every bit/word/lane
#: coordinate is outcome-relevant and no merge mode except the dead
#: class is sound.  The pass degrades to exhaustive -- documented,
#: typed, and pinned by a counterexample test -- rather than deriving
#: weights that would silently misreport wrong-weight outcomes.
TRAIN_FALLBACK = ("train_probe outcome semantics are bit-value-dependent; "
                  "all sections forced exhaustive")


@dataclasses.dataclass(frozen=True)
class SectionSignature:
    """One memory-map section's propagation signature."""

    name: str
    kind: str
    leaf_id: int
    lanes: int
    words: int
    replicated: bool
    written: bool
    consumed: bool
    value_fed: bool
    pre_voted: bool
    step_voted: bool
    mode: int                  # MODE_* merge decision
    fingerprint: str           # sha256 over signature + dataflow cone

    @property
    def mode_name(self) -> str:
        return MODE_NAMES[self.mode]


@dataclasses.dataclass
class EquivPartition:
    """The derived partition: per-section signatures + the site
    classifier the injection stack consumes."""

    benchmark: str
    num_clones: int
    clean_steps: int
    signatures: Dict[str, SectionSignature]
    fingerprint: str           # sha over all section fps + clean_steps
    # Non-None when the pass refused to derive merge modes and degraded
    # every section to exhaustive (TRAIN_FALLBACK for training regions):
    # the typed, documented no-silent-wrong-weights marker.  The dead
    # class (sites past the clean halt step) is still merged -- a flip
    # that provably never fires is sound under any outcome semantics.
    fallback_reason: Optional[str] = None

    def _mode_table(self) -> np.ndarray:
        n = max((s.leaf_id for s in self.signatures.values()),
                default=-1) + 1
        table = np.full(n + 1, MODE_EXH, np.int8)
        for sig in self.signatures.values():
            table[sig.leaf_id] = sig.mode
        return table

    def class_keys(self, sched) -> np.ndarray:
        """int64 [n, 5] class-key rows for a FaultSchedule; equal rows
        are provably outcome-equivalent sites."""
        n = len(sched)
        leaf = np.asarray(sched.leaf_id, np.int64)
        lane = np.asarray(sched.lane, np.int64)
        word = np.asarray(sched.word, np.int64)
        bit = np.asarray(sched.bit, np.int64)
        t = np.asarray(sched.t, np.int64)
        modes = self._mode_table()[np.clip(leaf, 0, None)]
        keys = np.stack([leaf, t, word, bit, lane], axis=1)
        keys[modes == MODE_FREE, 1:] = -2
        keys[modes == MODE_LT, 2:] = -3
        keys[modes == MODE_LTW, 3:] = -4
        # Sites firing at or past the fault-free halt step never fire at
        # all (the run is already halted): one global dead class.
        dead = t >= self.clean_steps
        keys[dead] = -1
        # Cache draws outside the footprint (t < 0, hierarchy overlays)
        # keep their full site identity -- the runner buckets them as
        # cache_invalid, so merging them into a fired class would skew
        # the weighted counts.
        neg = t < 0
        if neg.any():
            keys[neg] = np.stack([leaf, t, word, bit, lane], axis=1)[neg]
        assert keys.shape == (n, 5)
        return keys

    def reduce(self, sched):
        """One seeded representative per realized class: a FaultSchedule
        of the first-drawn site of each class, carrying ``class_weight``
        = how many physical draws that representative stands for.  Rows
        keep schedule order, so batching/journaling/streaming see a
        normal (just shorter) campaign."""
        from coast_tpu.inject.schedule import FaultSchedule
        keys = self.class_keys(sched)
        _, first, inverse, counts = np.unique(
            keys, axis=0, return_index=True, return_inverse=True,
            return_counts=True)
        order = np.argsort(first, kind="stable")
        rep = first[order]
        weights = counts[order].astype(np.int64)
        return FaultSchedule(
            np.ascontiguousarray(sched.leaf_id[rep]),
            np.ascontiguousarray(sched.lane[rep]),
            np.ascontiguousarray(sched.word[rep]),
            np.ascontiguousarray(sched.bit[rep]),
            np.ascontiguousarray(sched.t[rep]),
            np.ascontiguousarray(sched.section_idx[rep]),
            sched.seed, model=sched.model,
            class_weight=weights, equiv_sha=self.fingerprint)

    def summary(self) -> Dict[str, object]:
        return {
            "benchmark": self.benchmark,
            "num_clones": self.num_clones,
            "clean_steps": self.clean_steps,
            "fingerprint": self.fingerprint,
            **({"fallback_reason": self.fallback_reason}
               if self.fallback_reason else {}),
            "sections": {
                name: {"mode": sig.mode_name,
                       "fingerprint": sig.fingerprint}
                for name, sig in sorted(self.signatures.items())},
        }


def _cone_entries(jaxpr, env, live, name: str, out: List[str]) -> None:
    """Program-order ``prim(shape)`` entries of the live equations whose
    output provenance includes ``name`` -- the leaf's dataflow cone, the
    raw material of its fingerprint."""
    for eqn in jaxpr.eqns:
        if live is None or id(eqn) in live:
            for ov in eqn.outvars:
                val = env.get(ov)
                if val is not None and name in val.deps:
                    shape = tuple(getattr(ov.aval, "shape", ()))
                    out.append(f"{eqn.primitive.name}{shape}")
                    break
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                sub = v.jaxpr if hasattr(v.jaxpr, "eqns") else v
                if hasattr(sub, "eqns"):
                    _cone_entries(sub, env, live, name, out)
            elif isinstance(v, (list, tuple)):
                for b in v:
                    if hasattr(b, "jaxpr"):
                        _cone_entries(b.jaxpr, env, live, name, out)


def _check_transparent(region, name: str) -> bool:
    """True when ``check()``'s consumption of shared leaf ``name`` is an
    equality-compare indicator cone: every path is leaf -> eq/ne against
    an untainted operand -> {convert/reduce_sum/reduce_or/add/broadcast/
    reshape} -> E.  Then a completed clean-trajectory run with one
    flipped bit anywhere in the leaf yields E >= 1 (the fault-free check
    passes with E = 0, so exactly the flipped word's compare turns),
    i.e. SDC for every site -- or, if the leaf never reaches E at all,
    SUCCESS for every site.  Anything fancier is reported opaque."""
    import jax.numpy as jnp
    state = jax.eval_shape(region.init)
    try:
        closed = jax.make_jaxpr(region.check)(state)
    except Exception:       # noqa: BLE001 - analysis must not break builds
        return False
    RAW, IND = "raw", "ind"
    env: Dict[object, str] = {}
    from jax.extend.core import Literal

    def val(v):
        if isinstance(v, Literal):
            return None
        return env.get(v)

    jaxpr = closed.jaxpr
    state_names = sorted(state)
    if len(jaxpr.invars) != len(state_names):
        return False
    for leaf_name, var in zip(state_names, jaxpr.invars):
        if leaf_name == name:
            env[var] = RAW

    _IND_OK = {"convert_element_type", "reduce_sum", "reduce_or", "add",
               "broadcast_in_dim", "reshape", "squeeze", "transpose"}

    def walk(jx) -> bool:
        for eqn in jx.eqns:
            ins = [val(v) for v in eqn.invars]
            tainted = [t for t in ins if t is not None]
            prim = eqn.primitive.name
            if not tainted:
                continue
            if prim in ("eq", "ne"):
                if RAW in tainted and len(tainted) == 1:
                    for ov in eqn.outvars:
                        env[ov] = IND
                    continue
                return False
            if RAW in tainted:
                return False
            if prim in _IND_OK:
                for ov in eqn.outvars:
                    env[ov] = IND
                continue
            for key in ("jaxpr", "call_jaxpr"):
                if key in eqn.params:
                    sub = eqn.params[key]
                    sub = sub.jaxpr if hasattr(sub, "jaxpr") else sub
                    for iv, t in zip(sub.invars, ins):
                        if t is not None:
                            env[iv] = t
                    if not walk(sub):
                        return False
                    for ov, t in zip(eqn.outvars,
                                     [val(v) for v in sub.outvars]):
                        if t is not None:
                            env[ov] = t
                    break
            else:
                return False
        return True

    return walk(jaxpr)


def _clean_steps(prog) -> int:
    """First step index at which the fault-free run is halted: the flip
    window's hard edge (a later flip provably never fires)."""
    rec = jax.jit(lambda: prog.run(None))()
    return int(rec["steps"])


def analyze_equivalence(prog, closed=None, facts=None) -> EquivPartition:
    """Derive the propagation-equivalence partition of ``prog``'s
    fault-site space.  ``closed`` forwards an already-traced step jaxpr;
    ``facts`` forwards a full shared-walk result
    (:func:`coast_tpu.analysis.propagation.walker.analyze_step` -- one
    walk feeds this partition, the static vulnerability map, and the
    isolation prover; scripts/lint_sweep.py shares all three)."""
    cfg = prog.cfg
    region = prog.region
    n = cfg.num_clones
    if facts is None:
        # The partition reads only the boolean taint facts; skip the
        # witness-path bookkeeping the vulnerability map would want.
        facts = analyze_step(prog, closed=closed, track_paths=False)
    jaxpr = facts.jaxpr
    walker, live, taint = facts.walker, facts.live, facts.taint
    written, consumed = facts.written, facts.consumed
    lane_flagged = facts.lane_flagged
    guards, cfcss = facts.guards, facts.cfcss
    fn_unsafe = facts.fn_unsafe
    # Training regions (Region.train_probe): the outcome class depends
    # on the flip's numeric VALUE -- classify splits SDC by whether the
    # loss re-converged, and a low bit of a weight heals where the same
    # word's exponent bit diverges -- so the bit/word/lane-dropping
    # merge arguments above are all unsound.  Typed, documented
    # fallback: every section exhaustive (only the dead class merges).
    train_fallback = facts.train_fallback
    check_walker, check_closed = facts.check_walker, facts.check_closed

    clean_steps = _clean_steps(prog)

    signatures: Dict[str, SectionSignature] = {}
    for leaf_id, (name, kind, lanes, words) in enumerate(
            prog.injectable_sections()):
        replicated = bool(prog.replicated.get(name, kind == "cfcss"))
        is_written = name in written
        is_consumed = name in consumed
        value_fed = name in taint.value_fed
        pre_voted = bool(getattr(prog, "pre_sync", {}).get(name, False))
        step_voted = bool(getattr(prog, "step_sync", {}).get(name, False))

        if train_fallback:
            mode = MODE_EXH
        elif replicated:
            if (cfcss or guards or fn_unsafe or kind == "cfcss"
                    or name in lane_flagged):
                mode = MODE_EXH
            elif pre_voted:
                # Repaired (TMR) or latched (DWC) before any read.
                mode = MODE_LT
            elif not is_written:
                mode = MODE_FREE if not is_consumed else MODE_LT
            elif not value_fed:
                mode = MODE_LTW
            else:
                mode = MODE_EXH
        else:
            if not is_consumed and not is_written \
                    and _check_transparent(region, name):
                mode = MODE_FREE
            else:
                mode = MODE_EXH

        cone: List[str] = []
        _cone_entries(jaxpr, walker.env, live, name, cone)
        if check_closed is not None:
            cone.append("|check|")
            _cone_entries(check_closed.jaxpr, check_walker.env, None,
                          name, cone)
        h = hashlib.sha256()
        h.update(repr((name, kind, lanes, words, replicated, is_written,
                       is_consumed, value_fed, pre_voted, step_voted,
                       MODE_NAMES[mode], n, clean_steps)).encode())
        h.update("|".join(cone).encode())
        signatures[name] = SectionSignature(
            name=name, kind=kind, leaf_id=leaf_id, lanes=lanes,
            words=words, replicated=replicated, written=is_written,
            consumed=is_consumed, value_fed=value_fed,
            pre_voted=pre_voted, step_voted=step_voted, mode=mode,
            fingerprint=h.hexdigest())

    overall = hashlib.sha256()
    overall.update(str(clean_steps).encode())
    for name in sorted(signatures):
        overall.update(name.encode())
        overall.update(signatures[name].fingerprint.encode())
    return EquivPartition(
        benchmark=region.name,
        num_clones=n,
        clean_steps=clean_steps,
        signatures=signatures,
        fingerprint=overall.hexdigest(),
        fallback_reason=TRAIN_FALLBACK if train_fallback else None)


def section_fingerprints(prog, partition: Optional[EquivPartition] = None
                         ) -> Dict[str, str]:
    """Per-section propagation fingerprints -- the delta-campaign
    identity persisted in the journal header.  A section whose
    fingerprint is unchanged across a rebuild has the identical
    dataflow cone, sync coverage, and merge mode, so its recorded
    outcomes remain valid."""
    if partition is None:
        partition = analyze_equivalence(prog)
    return {name: sig.fingerprint
            for name, sig in partition.signatures.items()}
