"""Fault-site equivalence analysis: inject one representative per
propagation class.

FastFlip (arXiv:2403.13989) observes that fault-injection cost collapses
when identical fault sites with identical downstream dataflow are proven
equivalent statically and injected once.  This package extends the lint
provenance machinery (analysis/lint/provenance.py) from *finding
protection bugs* to *pruning the campaign space*:

  * :mod:`partition` -- the static pass: walk the protected step's jaxpr
    with the existing ``_Walker`` lattice, derive a per-section
    propagation signature, and partition the fault-site space
    (leaf x lane x word x bit x step) into equivalence classes whose
    members provably classify identically.
  * :mod:`delta` -- delta campaigns: per-section fingerprints persisted
    in the campaign journal header let a later run re-inject only the
    sections whose propagation changed, splicing prior results for the
    rest.

Validation contract (FuzzyFlow, arXiv:2306.16178): the equivalence-
reduced campaign's classification distribution must equal the exhaustive
one's exactly -- pinned by tests/test_equiv.py and recorded in
``artifacts/equiv_study.json``.
"""

from __future__ import annotations

from coast_tpu.analysis.equiv.partition import (TRAIN_FALLBACK,
                                                EquivPartition,
                                                SectionSignature,
                                                analyze_equivalence,
                                                section_fingerprints)
from coast_tpu.analysis.equiv.delta import (DeltaMismatchError, DeltaPlan,
                                            load_delta_base, plan_delta)

__all__ = ["EquivPartition", "SectionSignature", "analyze_equivalence",
           "section_fingerprints", "DeltaMismatchError", "DeltaPlan",
           "load_delta_base", "plan_delta", "TRAIN_FALLBACK"]
