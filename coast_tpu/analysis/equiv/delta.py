"""Delta campaigns: re-inject only the sections whose propagation changed.

FastFlip's compositional observation applied to the journal: a completed
campaign journal already records every site's outcome AND (since the
equivalence pass) a per-section propagation fingerprint.  After a code
change, sections whose fingerprint is unchanged have provably identical
dataflow cones -- their recorded outcomes remain valid, so a delta
campaign re-runs only the sites of changed sections and splices the
rest from the prior journal.

Splicing is by *site identity* (leaf, lane, word, bit, t), never by row
position: an equivalence-reduced schedule may gain/lose representatives
for the changed sections, and site-keyed lookup keeps the unchanged
rows aligned regardless.  A site that cannot be matched (new section,
drifted class weight) is conservatively re-injected.

Incompatible journals refuse with the typed :class:`DeltaMismatchError`
(a :class:`~coast_tpu.inject.journal.JournalMismatchError`): a delta
can only be computed against a *completed* single-seed ``run`` journal
for the same benchmark/strategy/seed/n/fault-model whose header carries
the fingerprint block.  Journals written before the equivalence pass
have no fingerprint block and are refused loudly -- they still open and
resume normally (tests pin that), they just cannot seed a delta.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from coast_tpu.inject.journal import CampaignJournal, JournalMismatchError
from coast_tpu.inject.spec import CampaignSpec

#: Header-level keys that must match between the delta base and the
#: current campaign, beyond the shared spec vocabulary
#: (:meth:`CampaignSpec.delta_identity` -- benchmark / seed / n /
#: start_num / fault_model, with the absent-means-default rules decoded
#: in one place).  The protection-config fingerprint is deliberately in
#: NEITHER: the config (and the program) changing is the whole point of
#: a delta -- the per-section fingerprints decide what that change
#: invalidated.
_HEADER_IDENTITY_KEYS = ("mode", "strategy")


class DeltaMismatchError(JournalMismatchError):
    """The delta base journal cannot seed a delta campaign (wrong mode,
    different campaign identity, missing fingerprint block, or an
    incomplete row record)."""


@dataclasses.dataclass
class DeltaPlan:
    """What a delta campaign will (re-)do, before any dispatch."""

    changed_sections: List[str]
    reused_rows: int
    reinjected_rows: int
    run_mask: np.ndarray            # bool [n_rows] of the current schedule
    spliced: Dict[str, np.ndarray]  # per-run columns for reused rows

    def summary(self) -> Dict[str, object]:
        return {"changed_sections": list(self.changed_sections),
                "reused_rows": int(self.reused_rows),
                "reinjected_rows": int(self.reinjected_rows)}


def _site_keys(leaf_id, lane, word, bit, t) -> np.ndarray:
    return np.stack([np.asarray(c, np.int64)
                     for c in (leaf_id, lane, word, bit, t)], axis=1)


def load_delta_base(path: str):
    """Read a completed run journal: (header, site columns, outcome
    columns).  The site columns come from the journal's own
    ``equiv_schedule`` record when present (equivalence-reduced
    campaigns persist their representatives), else the caller
    reconstructs them from the seed and validates the schedule sha."""
    header, records, _ = CampaignJournal._load(path)
    if header.get("mode") != "run":
        raise DeltaMismatchError(
            f"delta base {path!r} records mode "
            f"{header.get('mode')!r}; only single-seed 'run' journals "
            "carry the row-aligned records a delta can splice")
    if "section_fingerprints" not in header:
        raise DeltaMismatchError(
            f"delta base {path!r} has no section-fingerprint block "
            "(written before the equivalence pass?); rerun the base "
            "campaign once to record fingerprints, then delta against "
            "that journal")
    batches = sorted((r for r in records if r.get("kind") == "batch"),
                     key=lambda r: int(r["lo"]))
    cols = {k: [] for k in ("codes", "errors", "corrected", "steps")}
    expected = 0
    for rec in batches:
        if int(rec["lo"]) != expected:
            raise DeltaMismatchError(
                f"delta base {path!r} is missing rows at {expected} "
                "(interrupted campaign?); finish or rerun the base "
                "campaign before computing a delta from it")
        for k in cols:
            cols[k].extend(rec[k])
        expected += int(rec["n"])
    out = {k: np.asarray(v, np.int32) for k, v in cols.items()}
    sched_rec = next((r for r in records
                      if r.get("kind") == "equiv_schedule"), None)
    sites = None
    if sched_rec is not None:
        sites = {k: np.asarray(sched_rec[k], np.int32)
                 for k in ("leaf_id", "lane", "word", "bit", "t")}
        sites["class_weight"] = np.asarray(
            sched_rec.get("class_weight",
                          np.ones(len(sites["t"]), np.int64)), np.int64)
        if len(sites["t"]) != expected:
            raise DeltaMismatchError(
                f"delta base {path!r}: equiv_schedule records "
                f"{len(sites['t'])} rows but {expected} row outcomes "
                "were journaled")
    return header, sites, out, expected


def plan_delta(base_header: Dict[str, object],
               base_sites: Optional[Dict[str, np.ndarray]],
               base_out: Dict[str, np.ndarray],
               base_rows: int,
               current_header: Dict[str, object],
               current_fps: Dict[str, str],
               sched,
               section_names: Dict[int, str],
               base_path: str = "<journal>") -> DeltaPlan:
    """Decide which rows of the CURRENT schedule must be re-injected.

    ``sched`` is the current campaign's (possibly equivalence-reduced)
    FaultSchedule; ``base_sites`` the base journal's recorded sites
    (None for non-reduced bases, whose sites are the regenerated
    ``sched`` itself, validated upstream by schedule sha)."""
    base_id = {k: base_header.get(k) for k in _HEADER_IDENTITY_KEYS}
    cur_id = {k: current_header.get(k) for k in _HEADER_IDENTITY_KEYS}
    base_id.update(CampaignSpec.from_header(base_header).delta_identity())
    cur_id.update(CampaignSpec.from_header(current_header)
                  .delta_identity())
    for key in base_id:
        a, b = base_id[key], cur_id[key]
        if a != b:
            raise DeltaMismatchError(
                f"delta base {base_path!r} records {key}={a!r} but this "
                f"campaign has {key}={b!r}; a delta splices outcomes "
                "across a CODE change, not a campaign change -- rerun "
                "with the base campaign's parameters or start fresh")
    base_fps = dict(base_header.get("section_fingerprints") or {})
    if set(base_fps) != set(current_fps):
        raise DeltaMismatchError(
            f"delta base {base_path!r} records sections "
            f"{sorted(base_fps)} but the current program has "
            f"{sorted(current_fps)}; the memory map changed, so the "
            "recorded schedule no longer addresses this program")
    changed = sorted(name for name in current_fps
                     if base_fps[name] != current_fps[name])
    changed_set = set(changed)

    n_rows = len(sched)
    leaf_names = np.array([section_names.get(int(l), "?")
                           for l in np.asarray(sched.leaf_id)])
    run_mask = np.isin(leaf_names, list(changed_set)) if changed_set \
        else np.zeros(n_rows, bool)

    cur_keys = _site_keys(sched.leaf_id, sched.lane, sched.word,
                          sched.bit, sched.t)
    cur_w = getattr(sched, "class_weight", None)
    if cur_w is None:
        cur_w = np.ones(n_rows, np.int64)
    if base_sites is not None:
        base_keys = _site_keys(*(base_sites[k] for k in
                                 ("leaf_id", "lane", "word", "bit", "t")))
        base_w = np.asarray(base_sites["class_weight"], np.int64)
        # Vectorized site-identity join (a no-op-rebuild delta against a
        # large journal must stay near-free): sort the base keys as a
        # structured view, binary-search every current key into it.
        void = [("", np.int64)] * cur_keys.shape[1]
        base_v = np.ascontiguousarray(base_keys).view(void).reshape(-1)
        cur_v = np.ascontiguousarray(cur_keys).view(void).reshape(-1)
        order = np.argsort(base_v)
        pos = np.searchsorted(base_v[order], cur_v)
        j = order[np.clip(pos, 0, len(order) - 1)] if len(order) \
            else np.zeros(n_rows, np.int64)
        matched = np.zeros(n_rows, bool) if not len(order) else (
            (pos < len(order)) & (base_v[j] == cur_v))
        # Unmatched site or drifted class weight: the partition moved
        # under this section even though its fingerprint matched --
        # conservatively re-inject.
        reuse = ~run_mask & matched & (base_w[j] == np.asarray(cur_w))
        run_mask |= ~reuse
        spliced = {k: np.zeros(n_rows, np.int32) for k in base_out}
        for k in base_out:
            spliced[k][reuse] = base_out[k][j[reuse]]
    else:
        # Positional splice: only sound when the regenerated schedule IS
        # the journaled one, row for row -- proven by the schedule sha,
        # not just the row count (a partition change can shift rows
        # while coincidentally preserving the total).
        from coast_tpu.inject.journal import schedule_fingerprint
        if base_rows != n_rows:
            raise DeltaMismatchError(
                f"delta base {base_path!r} journaled {base_rows} rows "
                f"but the regenerated schedule has {n_rows}; the "
                "schedules no longer align")
        base_sha = base_header.get("schedule_sha")
        if base_sha != schedule_fingerprint(sched):
            raise DeltaMismatchError(
                f"delta base {base_path!r} has no equiv_schedule record "
                "and its schedule fingerprint does not match the "
                "regenerated schedule; rows cannot be spliced by "
                "position -- rerun the base campaign to record its "
                "representatives")
        spliced = {k: v.copy() for k, v in base_out.items()}
    reused = int(n_rows - run_mask.sum())
    return DeltaPlan(changed_sections=changed, reused_rows=reused,
                     reinjected_rows=int(run_mask.sum()),
                     run_mask=run_mask, spliced=spliced)
