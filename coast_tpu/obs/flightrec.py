"""Blackbox flight recorder: bounded event ring + crash forensics.

Every wedged round since PR 5 has died with a one-line diagnosis
("worker wedged in stage 'spawn'") and zero recorded state -- the
ROADMAP's hardware-measurement item is blocked on exactly that missing
evidence.  This module is the aircraft-style blackbox: a bounded
ring buffer of structured events (dispatch, retry, OOM-halving,
watchdog fire, lease claim/renew/loss, journal open/resume,
compile-cache hits, spawn stages) that any layer can append to for
near-zero cost, plus an atomic forensic *bundle* dump -- last-N
events, ``faulthandler`` all-thread stacks, process/jax/backend
metadata -- written on crash, on :class:`CampaignWedgedError`, on
lease loss, on ``SIGUSR1``, and by the bench parent when a child
exceeds its spawn budget.

Design constraints (ordered, matching :mod:`coast_tpu.obs.spans`):

  * **Overhead**: a disabled ``record()`` costs one attribute test
    (the PR 1 < 2% budget applies); an enabled one costs two clock
    reads and a locked deque append.  Events are infrequent (per
    dispatch / per lifecycle edge), never per injection.
  * **Multi-thread**: unlike the spans stack, the ambient recorder is
    *process-global* -- the watchdog thread, the lease-keeper thread,
    and a signal handler must all land events in the same ring, so
    every append takes the recorder lock and tags the thread name.
  * **Atomic dumps**: a bundle is written tmp + rename (the
    ``atomic_write_json`` discipline) so the parent that SIGKILLs a
    wedged child a moment later never reads a torn file.

Env knobs: ``COAST_FLIGHTREC=0`` disables recording process-wide;
``COAST_FLIGHTREC_DIR`` overrides the bundle directory (the bench
parent points the child at a scratch dir it will harvest);
``COAST_FLIGHTREC_CAP`` overrides the ring capacity.
"""

from __future__ import annotations

import collections
import contextlib
import faulthandler
import json
import os
import signal
import sys
import threading
import time
from typing import Deque, Dict, Iterator, List, Optional

__all__ = ["FlightRecorder", "NULL", "current", "install", "uninstall",
           "record", "activate", "newest_bundle", "BUNDLE_FORMAT"]

BUNDLE_FORMAT = "coast-flightrec"
BUNDLE_VERSION = 1
DEFAULT_CAPACITY = 512


def _env_enabled() -> bool:
    """Default on; COAST_FLIGHTREC=0/off/false disables process-wide."""
    return os.environ.get("COAST_FLIGHTREC", "1").lower() not in (
        "0", "off", "false", "no")


def _default_dir() -> str:
    return os.environ.get("COAST_FLIGHTREC_DIR") or os.path.join(
        "artifacts", "flightrec")


def _jax_meta() -> Dict[str, object]:
    """Best-effort jax/backend identity WITHOUT initializing a backend:
    a dump can fire while the backend is the thing that is wedged, so
    this must never block on device init."""
    meta: Dict[str, object] = {}
    try:
        jax = sys.modules.get("jax")
        if jax is None:
            return meta
        meta["jax_version"] = getattr(jax, "__version__", None)
        # Only read devices if a backend already initialized; calling
        # jax.devices() here could hang exactly like the wedge we are
        # diagnosing.
        try:
            from jax._src import xla_bridge as xb
            if getattr(xb, "_backends", None):
                devs = jax.devices()
                meta["backend"] = devs[0].platform if devs else None
                meta["device_count"] = len(devs)
        except Exception:  # noqa: BLE001 - internals moved: skip devices
            pass
    except Exception:  # noqa: BLE001 - metadata is best-effort
        pass
    return meta


def _all_thread_stacks() -> str:
    """All-thread tracebacks into a string (the in-process analogue of
    the py-spy dump the wedge forensics never had).

    ``sys._current_frames`` + ``threading.enumerate`` rather than
    ``faulthandler.dump_traceback``: faulthandler on this interpreter
    prints only thread ids, and a wedge diagnosis needs the NAMES
    (``coast-collect-watchdog``, lease keeper, ...) to tell the hung
    collect from the scaffolding.  Falls back to faulthandler if frame
    walking fails."""
    try:
        import traceback
        names = {t.ident: t.name for t in threading.enumerate()}
        chunks = []
        for ident, frame in sorted(sys._current_frames().items()):
            name = names.get(ident, "<unknown>")
            chunks.append(f"Thread {ident:#x} [{name}] "
                          "(most recent call last):\n"
                          + "".join(traceback.format_stack(frame)))
        return "\n".join(chunks)
    except Exception:  # noqa: BLE001 - stacks are best-effort
        try:
            import tempfile
            with tempfile.TemporaryFile(mode="w+") as fh:
                faulthandler.dump_traceback(file=fh, all_threads=True)
                fh.seek(0)
                return fh.read()
        except Exception as e:  # noqa: BLE001
            return f"<stack dump failed: {type(e).__name__}: {e}>"


class FlightRecorder:
    """One blackbox: a bounded ring of structured events + dump()."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 dump_dir: Optional[str] = None,
                 source: str = ""):
        cap = capacity
        if cap is None:
            try:
                cap = int(os.environ.get("COAST_FLIGHTREC_CAP",
                                         DEFAULT_CAPACITY))
            except ValueError:
                cap = DEFAULT_CAPACITY
        self.capacity = max(int(cap), 1)
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.dump_dir = dump_dir
        self.source = source
        self.events: Deque[Dict[str, object]] = collections.deque(
            maxlen=self.capacity)
        self.dumps: List[str] = []       # bundle paths written so far
        self._lock = threading.Lock()
        self._seq = 0
        self._epoch = time.time()
        self._origin = time.perf_counter()

    # -- event side ----------------------------------------------------------
    def record(self, event: str, **fields: object) -> None:
        """Append one structured event; thread-safe, bounded, cheap."""
        if not self.enabled:
            return
        t_mono = time.perf_counter()
        row: Dict[str, object] = {
            "event": str(event),
            "t_unix_s": round(self._epoch + (t_mono - self._origin), 6),
            "t_mono_s": round(t_mono, 6),
            "thread": threading.current_thread().name,
        }
        if fields:
            row.update(fields)
        with self._lock:
            row["seq"] = self._seq
            self._seq += 1
            self.events.append(row)

    def tail(self, n: Optional[int] = None) -> List[Dict[str, object]]:
        with self._lock:
            rows = list(self.events)
        return rows if n is None else rows[-int(n):]

    # -- dump side -----------------------------------------------------------
    def dump(self, reason: str, extra: Optional[Dict[str, object]] = None,
             stacks: bool = True) -> Optional[str]:
        """Write one atomic forensic bundle; returns its path (None when
        disabled or the write failed -- a dump must never take the
        process down with it, it IS the crash path)."""
        if not self.enabled:
            return None
        try:
            out_dir = self.dump_dir or _default_dir()
            os.makedirs(out_dir, exist_ok=True)
            with self._lock:
                rows = list(self.events)
                seq = self._seq
            bundle: Dict[str, object] = {
                "format": BUNDLE_FORMAT,
                "version": BUNDLE_VERSION,
                "reason": str(reason),
                "source": self.source,
                "written_unix_s": round(time.time(), 6),
                "process": {
                    "pid": os.getpid(),
                    "argv": list(sys.argv),
                    "python": sys.version.split()[0],
                    "platform": sys.platform,
                    "cwd": os.getcwd(),
                },
                "jax": _jax_meta(),
                "events_recorded_total": seq,
                "events": rows,
                "stacks": _all_thread_stacks() if stacks else "",
            }
            if extra:
                bundle["extra"] = dict(extra)
            name = (f"flightrec_{os.getpid()}_"
                    f"{int(time.time() * 1000)}_"
                    f"{_slug(reason)}.json")
            path = os.path.join(out_dir, name)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(bundle, fh, separators=(",", ":"))
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            with self._lock:
                self.dumps.append(path)
            return path
        except Exception:  # noqa: BLE001 - never crash the crash path
            return None

    # -- signal hook ---------------------------------------------------------
    def install_signal_handler(self,
                               signum: int = signal.SIGUSR1) -> bool:
        """Dump a bundle on ``signum`` (default SIGUSR1): the bench
        parent's "give me your blackbox before I kill you" channel.
        Main thread only (CPython restriction); returns False when the
        hook could not be installed."""
        def _handler(sig, frame):  # noqa: ARG001
            self.record("signal_dump", signum=int(sig))
            self.dump(f"signal:{int(sig)}")
        try:
            signal.signal(signum, _handler)
            return True
        except (ValueError, OSError):   # non-main thread / exotic platform
            return False


def _slug(text: str, limit: int = 48) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "-"
                  for c in str(text))[:limit]
    return out.strip("-") or "dump"


#: Shared no-op recorder: the ambient default, so ``record(...)`` is
#: always safe and costs one attribute test when nothing is installed.
NULL = FlightRecorder(capacity=1, enabled=False)

_active_lock = threading.Lock()
_active: List[FlightRecorder] = []


def current() -> FlightRecorder:
    """The innermost installed recorder of this PROCESS, else ``NULL``
    (process-global, unlike the spans stack: watchdog / lease-keeper
    threads and signal handlers must share the ring)."""
    return _active[-1] if _active else NULL


def install(recorder: Optional[FlightRecorder] = None,
            **kwargs: object) -> FlightRecorder:
    """Install a process-lifetime ambient recorder (fleet worker, bench
    worker, CLI verbs); returns it.  Idempotent layering: the newest
    install wins ``current()`` until :func:`uninstall`."""
    rec = recorder if recorder is not None else FlightRecorder(**kwargs)
    with _active_lock:
        _active.append(rec)
    return rec


def uninstall(recorder: FlightRecorder) -> None:
    with _active_lock:
        try:
            _active.remove(recorder)
        except ValueError:
            pass


@contextlib.contextmanager
def activate(recorder: Optional[FlightRecorder] = None,
             **kwargs: object) -> Iterator[FlightRecorder]:
    """Scoped install for tests and embedded runs."""
    rec = install(recorder, **kwargs)
    try:
        yield rec
    finally:
        uninstall(rec)


def record(event: str, **fields: object) -> None:
    """``current().record(...)`` -- the one-liner for instrumenting
    free functions (one attribute test when nothing is installed)."""
    current().record(event, **fields)


def newest_bundle(dump_dir: Optional[str] = None) -> Optional[str]:
    """Path of the most recently written bundle in ``dump_dir`` (the
    bench parent's harvest after SIGUSR1-ing a wedged child), or None."""
    out_dir = dump_dir or _default_dir()
    try:
        names = [n for n in os.listdir(out_dir)
                 if n.startswith("flightrec_") and n.endswith(".json")]
    except OSError:
        return None
    if not names:
        return None
    paths = [os.path.join(out_dir, n) for n in names]
    try:
        return max(paths, key=os.path.getmtime)
    except OSError:
        return None


def read_bundle(path: str) -> Dict[str, object]:
    """Parse + sanity-check one bundle (the smoke/test oracle)."""
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("format") != BUNDLE_FORMAT:
        raise ValueError(f"not a flight-recorder bundle: {path}")
    return doc
