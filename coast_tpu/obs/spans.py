"""Lightweight campaign telemetry: nested wall-clock spans + counters.

The reference platform logs one injection every few seconds, so "which
stage is slow" is answerable by watching the terminal.  A batched engine
at ~10^5..10^6 injections/sec needs the question answered by *recorded
data*: per-stage wall-clock attribution (schedule generation, host
padding, dispatch, device collect, classification, serialization) on
every campaign, cheap enough to stay on by default.

Design constraints, in order:

  * **Overhead**: one enabled span costs two ``time.perf_counter()``
    calls and one list append; a disabled span costs one attribute test.
    The acceptance bar is < 2% of campaign wall-clock at production
    batch sizes (tests/test_obs.py pins it coarsely on CPU).
  * **No dependencies**: pure stdlib; ``jax.profiler`` is an *optional*
    bracket (``profiler=True``) so device-side traces can be correlated
    with these host-side spans, never a requirement.
  * **Single writer**: a campaign loop is single-threaded; the event
    list is append-only and unlocked.  The ambient-telemetry stack is a
    ``threading.local`` so concurrent runners in different threads do
    not cross-record.

Spans nest (depth is recorded, Perfetto renders containment), counters
are cumulative time series (``ph:"C"`` in the trace), instants mark
point events (heartbeats).  ``Telemetry.stage_totals`` aggregates
top-level span durations by name -- the ``stages`` block of
``CampaignResult.summary()``.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

__all__ = ["Telemetry", "NULL", "current", "span", "count", "instant"]


def _env_enabled() -> bool:
    """Default on; COAST_TELEMETRY=0/off/false disables process-wide."""
    return os.environ.get("COAST_TELEMETRY", "1").lower() not in (
        "0", "off", "false", "no")


class Telemetry:
    """One recorder: an append-only event list plus counter/gauge state.

    Events are plain dicts (kind: "span" | "counter" | "gauge" |
    "instant"); timestamps are ``time.perf_counter()`` floats relative
    to nothing in particular -- ``origin`` anchors them for export, and
    ``epoch`` records the construction wall-clock for humans.
    """

    def __init__(self, enabled: Optional[bool] = None,
                 profiler: bool = False):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.profiler = profiler
        self.events: List[Dict[str, object]] = []
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.origin = time.perf_counter()
        self.epoch = time.time()
        self._depth = 0
        self._trace_annotation = None     # resolved lazily, cached

    # -- spans ---------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **args: object) -> Iterator[None]:
        """Record one nested wall-clock span around the ``with`` body.

        The event is appended at *exit* (events are exit-ordered); the
        recorded ``depth`` is the entry nesting level, so
        ``stage_totals`` can pick top-level stages without a tree walk.
        """
        if not self.enabled:
            yield
            return
        bracket = self._profiler_bracket(name)
        if bracket is not None:
            bracket.__enter__()
        depth = self._depth
        self._depth = depth + 1
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self._depth = depth
            self.events.append({"kind": "span", "name": name, "t0": t0,
                                "t1": t1, "depth": depth,
                                "args": args or None})
            if bracket is not None:
                bracket.__exit__(None, None, None)

    def span_at(self, name: str, t0: float, t1: float,
                depth: int = 0, **args: object) -> None:
        """Append a span with explicit ``perf_counter``-domain times.

        The journal-replay path uses this to re-materialise a crashed
        campaign's recorded batch spans into the resumed recorder, so
        one exported trace covers the whole campaign; ``t0``/``t1`` may
        precede ``origin`` (the export shifts to the earliest event).
        """
        if not self.enabled:
            return
        self.events.append({"kind": "span", "name": name,
                            "t0": float(t0), "t1": float(t1),
                            "depth": depth, "args": args or None})

    def _profiler_bracket(self, name: str):
        """Optional jax.profiler.TraceAnnotation so these host spans show
        up inside a captured device profile; None when off/unavailable."""
        if not self.profiler:
            return None
        if self._trace_annotation is None:
            try:
                from jax.profiler import TraceAnnotation
                self._trace_annotation = TraceAnnotation
            except Exception:          # profiler missing: stay host-only
                self.profiler = False
                return None
        return self._trace_annotation(name)

    # -- counters / gauges / instants ----------------------------------------
    def count(self, name: str, delta: float = 1, **args: object) -> None:
        """Cumulative counter: records the post-increment running total."""
        if not self.enabled:
            return
        value = self.counters.get(name, 0) + delta
        self.counters[name] = value
        self.events.append({"kind": "counter", "name": name,
                            "t": time.perf_counter(), "value": value,
                            "args": args or None})

    def gauge(self, name: str, value: float, **args: object) -> None:
        """Point-in-time level (last-write-wins in ``gauges``)."""
        if not self.enabled:
            return
        self.gauges[name] = value
        self.events.append({"kind": "gauge", "name": name,
                            "t": time.perf_counter(), "value": value,
                            "args": args or None})

    def instant(self, name: str, **args: object) -> None:
        """Zero-duration mark (heartbeats, chunk boundaries)."""
        if not self.enabled:
            return
        self.events.append({"kind": "instant", "name": name,
                            "t": time.perf_counter(), "args": args or None})

    # -- aggregation ---------------------------------------------------------
    def mark(self) -> int:
        """Checkpoint for ``stage_totals(since=...)`` windows."""
        return len(self.events)

    def stage_totals(self, since: int = 0) -> Dict[str, float]:
        """Wall-clock seconds per span name over events[since:].

        Only *top-level* spans in the window count (minimum recorded
        depth), so a nested helper span never double-bills its parent
        stage.  Multiple same-name spans (one per batch) sum.

        Journal-replayed spans (``span_at(..., replayed=True)``) are
        excluded: they exist for trace continuity, but their seconds
        belong to the crashed run -- counting them would make a resumed
        campaign's stage totals exceed its own wall clock.  Device-
        attributed spans (``span_at(..., device=True)``, the campaign
        profiler's per-phase windows) are excluded for the dual reason:
        they re-time work already billed to the host-side
        dispatch/collect stages on another track -- counting them would
        double-bill the device seconds into the host stage table.
        """
        spans = [e for e in self.events[since:] if e["kind"] == "span"
                 and not (e.get("args") or {}).get("replayed")
                 and not (e.get("args") or {}).get("device")]
        if not spans:
            return {}
        top = min(e["depth"] for e in spans)     # type: ignore[type-var]
        totals: Dict[str, float] = {}
        for e in spans:
            if e["depth"] == top:
                name = str(e["name"])
                totals[name] = totals.get(name, 0.0) + (
                    float(e["t1"]) - float(e["t0"]))    # type: ignore[arg-type]
        return totals

    def reset(self) -> None:
        self.events.clear()
        self.counters.clear()
        self.gauges.clear()
        self._depth = 0

    # -- ambient activation --------------------------------------------------
    @contextlib.contextmanager
    def activate(self) -> Iterator["Telemetry"]:
        """Make this recorder the ambient one (``obs.current()``) for the
        ``with`` body, so free functions deep in the pipeline (schedule
        generation, log writers) record here without threading a handle
        through every signature."""
        stack = _ambient.__dict__.setdefault("stack", [])
        stack.append(self)
        try:
            yield self
        finally:
            stack.pop()


#: Shared no-op recorder: the ambient default, so ``current().span(...)``
#: is always safe and costs one attribute test when nothing is active.
NULL = Telemetry(enabled=False)

_ambient = threading.local()


def current() -> Telemetry:
    """The innermost activated Telemetry of this thread, else ``NULL``."""
    stack = getattr(_ambient, "stack", None)
    return stack[-1] if stack else NULL


def span(name: str, **args: object):
    """``current().span(...)`` -- the one-liner for instrumenting free
    functions."""
    return current().span(name, **args)


def count(name: str, delta: float = 1, **args: object) -> None:
    current().count(name, delta, **args)


def instant(name: str, **args: object) -> None:
    current().instant(name, **args)
