"""Chrome/Perfetto ``trace_event`` export of a Telemetry recording.

Emits the JSON Object Format of the Trace Event spec (the format both
``chrome://tracing`` and https://ui.perfetto.dev open directly): a
``traceEvents`` array of

  * ``ph:"X"`` complete events for spans (``ts``/``dur`` in
    microseconds; Perfetto infers nesting from containment on one
    track, matching the recorded span depths),
  * ``ph:"C"`` counter events for counters and gauges (one series per
    name, so pad-waste and heartbeat rates plot as graphs), and
  * ``ph:"i"`` instant events for point marks (heartbeats).

Timestamps are relative to the recorder's ``origin`` so a trace always
starts near t=0; the construction wall-clock is carried in
``otherData.epoch_unix_s`` for correlation with logs.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from coast_tpu.obs.spans import Telemetry

# One synthetic process: the campaign loop is single-threaded and one
# host track renders the nested stage spans the way they ran.  The
# profiler's device-attributed spans (``span_at(..., device=True)``)
# land on their own track so Perfetto shows device-busy windows BESIDE
# the host stages instead of nested inside them.
_PID = 1
_TID = 1
_DEVICE_TID = 2


def _origin(telemetry: Telemetry) -> float:
    """Export time zero: the recorder's origin, or the earliest event if
    one precedes it.  Journal-replayed spans from a crashed campaign are
    re-materialised at their original (earlier) wall-clock offsets
    (``Telemetry.span_at``); shifting to the true minimum keeps every
    exported ``ts`` non-negative and the resumed trace one coherent
    timeline."""
    origin = telemetry.origin
    for e in telemetry.events:
        t = float(e["t0"]) if e["kind"] == "span" else float(e["t"])
        if t < origin:
            origin = t
    return origin


def to_trace_events(telemetry: Telemetry,
                    process_name: str = "coast_tpu campaign"
                    ) -> List[Dict[str, object]]:
    """The recorder's events as trace_event dicts, exit-order preserved."""
    origin = _origin(telemetry)

    def _us(t: float) -> float:
        return round((t - origin) * 1e6, 3)

    events: List[Dict[str, object]] = [{
        "name": "process_name", "ph": "M", "pid": _PID, "tid": _TID,
        "args": {"name": process_name},
    }, {
        "name": "thread_name", "ph": "M", "pid": _PID, "tid": _TID,
        "args": {"name": "host"},
    }, {
        "name": "thread_name", "ph": "M", "pid": _PID,
        "tid": _DEVICE_TID, "args": {"name": "device"},
    }]
    for e in telemetry.events:
        kind = e["kind"]
        args = e.get("args") or {}
        if kind == "span":
            events.append({
                "name": e["name"],
                "cat": ("device" if args.get("device") else
                        "replay" if args.get("replayed") else "stage"),
                "ph": "X",
                "pid": _PID,
                "tid": _DEVICE_TID if args.get("device") else _TID,
                "ts": _us(float(e["t0"])),                  # type: ignore
                "dur": round((float(e["t1"]) - float(e["t0"]))  # type: ignore
                             * 1e6, 3),
                "args": args,
            })
        elif kind in ("counter", "gauge"):
            events.append({
                "name": e["name"], "cat": kind, "ph": "C",
                "pid": _PID, "tid": _TID,
                "ts": _us(float(e["t"])),                   # type: ignore
                "args": {str(e["name"]): e["value"]},
            })
        elif kind == "instant":
            events.append({
                "name": e["name"], "cat": "mark", "ph": "i",
                "pid": _PID, "tid": _TID, "s": "t",
                "ts": _us(float(e["t"])),                   # type: ignore
                "args": args,
            })
    return events


def to_trace_doc(telemetry: Telemetry,
                 metadata: Optional[Dict[str, object]] = None,
                 process_name: str = "coast_tpu campaign"
                 ) -> Dict[str, object]:
    return {
        "traceEvents": to_trace_events(telemetry, process_name),
        "displayTimeUnit": "ms",
        "otherData": {"epoch_unix_s": round(telemetry.epoch, 6),
                      **(metadata or {})},
    }


def write_trace(telemetry: Telemetry, path: str,
                metadata: Optional[Dict[str, object]] = None,
                process_name: str = "coast_tpu campaign") -> str:
    """Write the Perfetto-loadable trace JSON; returns ``path``."""
    with open(path, "w") as f:
        json.dump(to_trace_doc(telemetry, metadata, process_name), f)
    return path
