"""Reliability SLOs: declarative targets, error budgets, burn rates.

ROADMAP #2 wants a protected service that "measures its own SDC rate
under live traffic" with MWTF as a user-facing SLO.  Following
FastFlip's (arXiv:2403.13989) evidence-driven framing, a reliability
target here is a first-class object -- not a number eyeballed out of
``/status`` -- evaluated over exactly the recorded campaign evidence
the convergence tracker already trusts:

  * :class:`SLOSpec` -- one declarative objective.  Four kinds:

      - ``sdc_rate <= c``      SDC-rate ceiling over the weighted class
        histogram (the :data:`classify.SDC_CLASSES` sum, same as the
        live ``sdc_rate`` ring);
      - ``availability >= f``  availability floor, where availability
        is ``1 - rate(DUE classes)`` -- detected-unrecoverable outcomes
        are the "downtime" of a protected region;
      - ``mwtf >= m``          Mean-Work-To-Failure improvement floor
        against a recorded baseline (the ``compare_runs`` definition:
        error improvement over runtime cost);
      - ``p99_dispatch <= s``  a latency-percentile ceiling over the
        PR 15 per-dispatch histograms (``p<q>_dispatch`` reads
        ``dispatch_device_seconds``, ``p<q>_gap`` the host-gap one).

  * **Wilson-backed attainment**: a rate objective is *attained* only
    when its Wilson interval (:func:`obs.convergence.wilson_interval`,
    the same z) lies entirely on the good side of the target, *violated*
    only when the interval lies entirely on the bad side, and
    *inconclusive* (``None``) in between -- small samples cannot buy a
    verdict in either direction.
  * **Error budgets**: a ceiling ``c`` over ``n`` effective injections
    allows ``c*n`` bad events; ``budget.remaining_frac`` is the
    unconsumed fraction (negative = overspent).
  * **Multi-window burn rates**: ``burn = bad_rate / allowed_rate``
    evaluated over the full campaign (long window) AND the recent ring
    tail (short window, when series are available).  The verdict is
    ``page`` when both windows burn at ``page_burn`` or the budget is
    already exhausted, ``warn`` when the long window burns >= 1x (or
    attainment is definitively violated), else ``ok`` -- the
    two-window rule that makes a page mean "burning NOW and not just a
    stale spike".

:class:`SLOSet` parses/round-trips a canonical spec string (the
StopWhen discipline, so a spec can ride in artifacts as identity), and
the evidence extractors accept every surface the repo records: live
:class:`CampaignMetrics` snapshots, ``--status-json`` files, campaign
log summaries, and ``summarize`` artifacts.  ``python -m coast_tpu
slo`` (:mod:`coast_tpu.obs.slo_cli`) is the offline entry; the metrics
hub evaluates the same engine live.
"""

from __future__ import annotations

import dataclasses
import json
import math
import re
from typing import Dict, List, Mapping, Optional, Tuple

from coast_tpu.inject.classify import DUE_CLASSES, SDC_CLASSES
from coast_tpu.obs.convergence import wilson_interval

__all__ = ["SLOSpec", "SLOSet", "SLOError", "evaluate", "worst_verdict",
           "evidence_from_status", "evidence_from_summary",
           "load_evidence", "baseline_from", "summary_block",
           "status_line", "VERDICTS"]

#: Verdict severity order (worst last).
VERDICTS = ("ok", "warn", "page")

#: Short histogram aliases for latency objectives.
_HIST_ALIASES = {"dispatch": "dispatch_device_seconds",
                 "gap": "dispatch_host_gap_seconds"}

_LATENCY_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)_([a-z_]+)$")


class SLOError(ValueError):
    """A malformed SLO specification."""


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative reliability objective.

    ``objective`` is the canonical name (``sdc_rate``, ``availability``,
    ``mwtf``, or ``p<q>_<hist>``); ``op`` is ``<=`` (ceiling) or ``>=``
    (floor); ``target`` the bound.  ``z`` matches the convergence
    tracker's quantile; ``min_n`` floors the effective sample count
    below which no verdict is issued (mirrors StopWhen.min_done);
    ``page_burn`` is the multi-window page threshold.
    """

    objective: str
    op: str
    target: float
    z: float = 1.96
    min_n: float = 0.0
    page_burn: float = 2.0

    def __post_init__(self):
        if self.op not in ("<=", ">="):
            raise SLOError(f"SLO op must be <= or >=, got {self.op!r}")
        kind = self.kind()
        if kind == "rate_ceiling" and self.op != "<=":
            raise SLOError(f"{self.objective} is a ceiling; use <=")
        if kind in ("availability", "mwtf") and self.op != ">=":
            raise SLOError(f"{self.objective} is a floor; use >=")
        if kind == "latency" and self.op != "<=":
            raise SLOError(f"{self.objective} is a ceiling; use <=")
        if kind in ("rate_ceiling", "availability"):
            if not (0.0 < float(self.target) < 1.0):
                raise SLOError(
                    f"{self.objective} target must be in (0, 1), got "
                    f"{self.target!r}")
        elif float(self.target) <= 0.0:
            raise SLOError(
                f"{self.objective} target must be > 0, got "
                f"{self.target!r}")
        if self.z <= 0:
            raise SLOError(f"SLO z must be > 0, got {self.z!r}")
        if self.min_n < 0:
            raise SLOError(f"SLO min_n must be >= 0, got {self.min_n!r}")
        if self.page_burn < 1.0:
            raise SLOError(
                f"SLO page_burn must be >= 1, got {self.page_burn!r}")
        if kind == "latency":
            self.latency_parts()      # reject bad quantiles at parse time

    def kind(self) -> str:
        if self.objective == "sdc_rate":
            return "rate_ceiling"
        if self.objective == "availability":
            return "availability"
        if self.objective == "mwtf":
            return "mwtf"
        if _LATENCY_RE.match(self.objective):
            return "latency"
        raise SLOError(
            f"unknown SLO objective {self.objective!r} (valid: sdc_rate, "
            "availability, mwtf, p<q>_dispatch, p<q>_gap)")

    def latency_parts(self) -> Tuple[float, str]:
        """(quantile, histogram name) for a latency objective."""
        m = _LATENCY_RE.match(self.objective)
        assert m is not None, self.objective
        q = float(m.group(1)) / 100.0
        hist = _HIST_ALIASES.get(m.group(2), m.group(2))
        if not (0.0 < q < 1.0):
            raise SLOError(
                f"latency quantile must be in (0, 100), got {m.group(1)}")
        return q, hist

    def spec(self) -> str:
        return f"{self.objective}{self.op}{self.target:g}"


@dataclasses.dataclass(frozen=True)
class SLOSet:
    """An ordered set of objectives + shared knobs, round-trippable as
    ``"sdc_rate<=0.002,availability>=0.99;z=2.576;min=4096;page=14"``
    (the StopWhen grammar discipline: comma-separated objectives, then
    ``;key=value`` knobs in any order)."""

    objectives: Tuple[SLOSpec, ...]

    def __post_init__(self):
        if not self.objectives:
            raise SLOError("SLO set needs at least one objective")
        seen = set()
        for spec in self.objectives:
            if spec.objective in seen:
                raise SLOError(
                    f"duplicate SLO objective {spec.objective!r}")
            seen.add(spec.objective)

    @classmethod
    def parse(cls, text: str) -> "SLOSet":
        body = (text or "").strip()
        if not body:
            raise SLOError("empty SLO specification")
        parts = body.split(";")
        z, min_n, page_burn = 1.96, 0.0, 2.0
        for knob in parts[1:]:
            knob = knob.strip()
            if not knob:
                continue
            key, sep, value = knob.partition("=")
            try:
                if key == "z" and sep:
                    z = float(value)
                elif key == "min" and sep:
                    min_n = float(value)
                elif key == "page" and sep:
                    page_burn = float(value)
                else:
                    raise SLOError(
                        f"unknown SLO knob {knob!r} (want z=Q, min=N, or "
                        "page=B)")
            except ValueError as e:
                raise SLOError(f"bad SLO knob {knob!r}: {e}") from e
        objectives: List[SLOSpec] = []
        for item in parts[0].split(","):
            item = item.strip()
            if not item:
                continue
            for op in ("<=", ">="):
                name, sep, value = item.partition(op)
                if sep:
                    try:
                        target = float(value)
                    except ValueError as e:
                        raise SLOError(
                            f"bad SLO target in {item!r}: {e}") from e
                    objectives.append(SLOSpec(
                        objective=name.strip(), op=op, target=target,
                        z=z, min_n=min_n, page_burn=page_burn))
                    break
            else:
                raise SLOError(
                    f"bad SLO objective {item!r} (want name<=target or "
                    "name>=target, e.g. sdc_rate<=0.002)")
        return cls(objectives=tuple(objectives))

    def spec(self) -> str:
        """Canonical round-trippable string (knobs only when shared and
        non-default)."""
        body = ",".join(o.spec() for o in self.objectives)
        first = self.objectives[0]
        if all(o.z == first.z for o in self.objectives) and \
                first.z != 1.96:
            body += f";z={first.z:g}"
        if all(o.min_n == first.min_n for o in self.objectives) and \
                first.min_n:
            body += f";min={first.min_n:g}"
        if all(o.page_burn == first.page_burn
               for o in self.objectives) and first.page_burn != 2.0:
            body += f";page={first.page_burn:g}"
        return body


# ---------------------------------------------------------------------------
# Evidence extraction: one neutral shape from every recorded surface
# ---------------------------------------------------------------------------

def evidence_from_status(doc: Mapping[str, object]) -> Dict[str, object]:
    """Evidence from a ``coast-status`` document (a live
    ``CampaignMetrics.snapshot()`` or a ``--status-json`` file):
    cumulative counts, throughput, latency histograms, and the recent
    ``sdc_rate`` ring tail for the short burn window."""
    counts = {str(k): float(v)
              for k, v in (doc.get("counts") or {}).items()}
    prof = doc.get("profile") or {}
    series = doc.get("series") or {}
    sdc_tail = [float(v) for _, v in (series.get("sdc_rate") or [])]
    elapsed = float(doc.get("elapsed_s") or 0.0)
    done = float(doc.get("done_rows") or 0.0)
    return {
        "counts": counts,
        "inj_per_sec": (done / elapsed) if elapsed > 0 else None,
        "histograms": dict(prof.get("histograms") or {}),
        "sdc_rate_recent": sdc_tail,
    }


def evidence_from_summary(doc: Mapping[str, object]) -> Dict[str, object]:
    """Evidence from a ``CampaignResult.summary()`` block (a campaign
    log head or a ``summarize`` artifact row).

    ``summary()`` flattens the class histogram into top-level keys
    (``**self.counts``) and stores the trial count under
    ``injections``; fleet worker done-records instead nest a
    ``counts`` dict.  Accept both shapes."""
    counts = {str(k): float(v)
              for k, v in (doc.get("counts") or {}).items()}
    if not counts:
        from coast_tpu.inject.classify import CLASS_NAMES
        vocab = CLASS_NAMES + ("cache_invalid",)
        counts = {k: float(doc[k]) for k in vocab
                  if isinstance(doc.get(k), (int, float))}
    prof = doc.get("profile") or {}
    n = float(doc.get("n") or doc.get("injections")
              or sum(counts.values()))
    seconds = float(doc.get("seconds") or 0.0)
    hists = dict(prof.get("histograms") or {})
    if "device_seconds_histogram" in prof:
        hists.setdefault("dispatch_device_seconds",
                         prof["device_seconds_histogram"])
    if "host_gap_seconds_histogram" in prof:
        hists.setdefault("dispatch_host_gap_seconds",
                         prof["host_gap_seconds_histogram"])
    return {
        "counts": counts,
        "inj_per_sec": (n / seconds) if seconds > 0 else None,
        "histograms": hists,
        "sdc_rate_recent": [],
    }


def load_evidence(path: str) -> Dict[str, object]:
    """Evidence from a recorded file: a status JSON, a run doc with a
    ``summary`` block, a bare summary JSON, or an NDJSON campaign log
    (head line carries the summary)."""
    with open(path) as fh:
        head = fh.readline()
        doc = json.loads(head)
        if not isinstance(doc, dict):
            raise SLOError(f"not a JSON object: {path}")
        rest = fh.read().strip()
    if rest and not doc.get("format"):
        # Multi-line non-NDJSON JSON document: reparse whole.
        doc = json.loads(head + rest)
    if doc.get("format") == "coast-status":
        return evidence_from_status(doc)
    if isinstance(doc.get("summary"), dict):
        return evidence_from_summary(doc["summary"])
    if "counts" in doc or "injections" in doc:
        return evidence_from_summary(doc)
    raise SLOError(
        f"no SLO evidence in {path}: want a coast-status doc, a run doc "
        "with a summary block, or a summary JSON")


def baseline_from(path: str) -> Dict[str, object]:
    """Reduce recorded evidence (any :func:`load_evidence` surface) to
    the MWTF objective's baseline dict: the unprotected run's SDC rate
    and throughput.  Shared by the offline ``slo`` CLI and the serving
    front end's ``--baseline``, so the two cannot disagree on what an
    ``mwtf>=N`` denominator is."""
    ev = load_evidence(path)
    counts = ev.get("counts") or {}
    n = float(sum(counts.values()))
    bad = sum(float(counts.get(k, 0.0)) for k in SDC_CLASSES)
    return {"sdc_rate": (bad / n) if n > 0 else None,
            "inj_per_sec": ev.get("inj_per_sec")}


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------

def _rate_objective(spec: SLOSpec, bad: float, n: float,
                    allowed: float, recent: List[float]
                    ) -> Dict[str, object]:
    """Shared ceiling math: ``bad`` events over ``n`` effective trials
    against an allowed rate."""
    rate = bad / n if n > 0 else 0.0
    lo, hi = wilson_interval(bad, n, spec.z)
    if n < spec.min_n or n <= 0:
        attained: Optional[bool] = None
    elif hi <= allowed:
        attained = True
    elif lo > allowed:
        attained = False
    else:
        attained = None
    budget_total = allowed * n
    remaining = ((budget_total - bad) / budget_total
                 if budget_total > 0 else 0.0)
    burn_long = rate / allowed if allowed > 0 else math.inf
    burn_short = None
    if recent:
        tail = recent[-min(len(recent), 32):]
        burn_short = (sum(tail) / len(tail)) / allowed
    return {
        "observed": rate,
        "wilson": {"lo": lo, "hi": hi},
        "effective_n": n,
        "bad": bad,
        "attained": attained,
        "budget": {"allowed_rate": allowed,
                   "total": budget_total,
                   "consumed": bad,
                   "remaining_frac": remaining},
        "burn": {"long": burn_long, "short": burn_short},
    }


def _verdict(spec: SLOSpec, row: Dict[str, object]) -> str:
    """page/warn/ok from a row's burn + budget + attainment (the
    two-window rule; a missing short window falls back to the long
    one so offline artifacts still page on gross burns)."""
    if row.get("attained") is None and row.get("effective_n", 0) == 0:
        return "ok"                       # no evidence constrains nothing
    n = float(row.get("effective_n") or 0.0)
    if 0 < n < spec.min_n:
        return "ok"                       # below the sample floor
    burn = row.get("burn") or {}
    long_burn = burn.get("long")
    short_burn = burn.get("short")
    budget = row.get("budget") or {}
    remaining = budget.get("remaining_frac")
    if remaining is not None and remaining <= 0.0 and \
            (long_burn or 0.0) > 1.0:
        # Budget exhausted -- but a page must mean burning NOW, so a
        # quiet short window (the recent ring) downgrades the stale
        # spike to warn; no short window (offline artifacts) pages.
        if short_burn is None or short_burn > 1.0:
            return "page"
        return "warn"
    if long_burn is not None and long_burn >= spec.page_burn:
        if short_burn is None or short_burn >= spec.page_burn:
            return "page"
    if (long_burn is not None and long_burn > 1.0) or \
            row.get("attained") is False:
        return "warn"
    return "ok"


def _quantile_from_hist(hist: Mapping[str, object],
                        q: float) -> Optional[float]:
    """Upper bound of the smallest cumulative ``le`` bucket covering
    quantile ``q`` (Prometheus-style histogram_quantile without
    interpolation below the bound: conservative for a ceiling check).
    None when empty or when ``q`` lands in the +Inf bucket."""
    count = int(hist.get("count") or 0)
    if count <= 0:
        return None
    need = q * count
    for bound, cum in zip(hist.get("le") or (),
                          hist.get("counts") or ()):
        if float(cum) >= need:
            return float(bound)
    return None                           # beyond the last finite bound


def _eval_one(spec: SLOSpec, evidence: Mapping[str, object],
              baseline: Optional[Mapping[str, object]]
              ) -> Dict[str, object]:
    counts = {str(k): float(v)
              for k, v in (evidence.get("counts") or {}).items()}
    n = float(sum(counts.values()))
    kind = spec.kind()
    recent = list(evidence.get("sdc_rate_recent") or [])

    if kind == "rate_ceiling":
        bad = sum(counts.get(k, 0.0) for k in SDC_CLASSES)
        row = _rate_objective(spec, bad, n, float(spec.target), recent)
    elif kind == "availability":
        bad = sum(counts.get(k, 0.0) for k in DUE_CLASSES)
        allowed = 1.0 - float(spec.target)
        row = _rate_objective(spec, bad, n, allowed, [])
        row["observed"] = 1.0 - (bad / n if n > 0 else 0.0)
    elif kind == "mwtf":
        row = _eval_mwtf(spec, counts, n, evidence, baseline)
    else:
        row = _eval_latency(spec, evidence)

    row["objective"] = spec.objective
    row["op"] = spec.op
    row["target"] = float(spec.target)
    row["verdict"] = _verdict(spec, row)
    return row


def _eval_mwtf(spec: SLOSpec, counts, n, evidence, baseline
               ) -> Dict[str, object]:
    """MWTF improvement vs a recorded baseline, the ``compare_runs``
    definition: (baseline sdc rate / ours) / (our seconds-per-injection
    / baseline's).  Without a baseline the objective reports no data
    (None attainment, zero burn) rather than inventing one."""
    base = baseline or {}
    base_rate = base.get("sdc_rate")
    base_ips = base.get("inj_per_sec")
    ips = evidence.get("inj_per_sec")
    empty = {
        "observed": None, "effective_n": n, "attained": None,
        "budget": {"allowed_rate": None, "total": None,
                   "consumed": None, "remaining_frac": None},
        "burn": {"long": None, "short": None},
    }
    if base_rate is None or n <= 0:
        return empty
    bad = sum(counts.get(k, 0.0) for k in SDC_CLASSES)
    # Rare-event honesty: a zero observed rate uses the Wilson upper
    # bound instead, so "no SDC seen yet" never claims infinite MWTF.
    _, hi = wilson_interval(bad, n, spec.z)
    rate = max(bad / n if bad > 0 else hi, 1e-12)
    improvement = float(base_rate) / rate
    runtime_x = 1.0
    if base_ips and ips:
        runtime_x = float(base_ips) / float(ips)  # sec/inj ratio
        runtime_x = max(runtime_x, 1e-12)
    mwtf = improvement / runtime_x
    burn = float(spec.target) / max(mwtf, 1e-12)
    attained: Optional[bool] = None
    if n >= spec.min_n:
        attained = mwtf >= float(spec.target)
    return {
        "observed": mwtf,
        "effective_n": n,
        "attained": attained,
        "budget": {"allowed_rate": None, "total": None, "consumed": None,
                   "remaining_frac": (1.0 - burn)},
        "burn": {"long": burn, "short": None},
    }


def _eval_latency(spec: SLOSpec, evidence) -> Dict[str, object]:
    q, hist_name = spec.latency_parts()
    hist = (evidence.get("histograms") or {}).get(hist_name) or {}
    count = int(hist.get("count") or 0)
    empty = {
        "observed": None, "effective_n": 0, "attained": None,
        "budget": {"allowed_rate": None, "total": None,
                   "consumed": None, "remaining_frac": None},
        "burn": {"long": None, "short": None},
    }
    if count <= 0:
        return empty
    observed = _quantile_from_hist(hist, q)
    # Bad events: observations ABOVE the target bound; allowed:
    # (1-q) of the population -- the latency budget.
    above = count
    for bound, cum in zip(hist.get("le") or (),
                          hist.get("counts") or ()):
        if float(bound) >= float(spec.target):
            above = count - int(cum)
            break
    allowed = (1.0 - q) * count
    burn = above / allowed if allowed > 0 else math.inf
    attained: Optional[bool] = None
    if count >= spec.min_n:
        if observed is not None and observed <= float(spec.target):
            attained = True
        elif burn > 1.0:
            attained = False
    remaining = ((allowed - above) / allowed if allowed > 0 else 0.0)
    return {
        "observed": observed,
        "effective_n": count,
        "bad": above,
        "attained": attained,
        "budget": {"allowed_rate": 1.0 - q, "total": allowed,
                   "consumed": above, "remaining_frac": remaining},
        "burn": {"long": burn, "short": None},
    }


def worst_verdict(verdicts) -> str:
    worst = "ok"
    for v in verdicts:
        if VERDICTS.index(v) > VERDICTS.index(worst):
            worst = v
    return worst


def evaluate(slo_set: SLOSet, evidence: Mapping[str, object],
             baseline: Optional[Mapping[str, object]] = None
             ) -> Dict[str, object]:
    """The one evaluation everybody calls (live hub, CLI, fleet): a
    JSON-able report with per-objective rows and the worst verdict.

    ``baseline`` feeds the MWTF objective: ``{"sdc_rate": r,
    "inj_per_sec": s}`` from an unprotected run's recorded evidence.
    """
    rows = [_eval_one(spec, evidence, baseline)
            for spec in slo_set.objectives]
    burning = [r["objective"] for r in rows if r["verdict"] != "ok"]
    return {
        "spec": slo_set.spec(),
        "objectives": rows,
        "verdict": worst_verdict(r["verdict"] for r in rows),
        "burning": burning,
    }


def summary_block(report: Mapping[str, object]) -> Dict[str, object]:
    """The compact ``Summary.slo`` / ``CampaignResult.slo`` form: per
    objective attainment, budget remaining, burn rate -- the numbers a
    human reads off a run record (rounded; the full report stays in
    artifacts)."""
    out: Dict[str, object] = {
        "spec": report.get("spec"),
        "verdict": report.get("verdict"),
        "burning": list(report.get("burning") or []),
        "objectives": {},
    }
    for row in report.get("objectives") or []:
        budget = row.get("budget") or {}
        burn = row.get("burn") or {}
        out["objectives"][row["objective"]] = {
            "target": row.get("target"),
            "op": row.get("op"),
            "observed": _round6(row.get("observed")),
            "attained": row.get("attained"),
            "budget_remaining_frac": _round6(
                budget.get("remaining_frac")),
            "burn_rate": _round6(burn.get("long")),
            "verdict": row.get("verdict"),
        }
    return out


def status_line(report: Optional[Mapping[str, object]]) -> Optional[str]:
    """One live status fragment for the heartbeat/console: the worst
    verdict, the worst-burning objective and its remaining budget --
    ``slo PAGE sdc_rate burn 3.2x budget 8%`` -- or ``slo ok``.  None
    when there is no report yet."""
    if not report:
        return None
    verdict = str(report.get("verdict") or "ok")
    if verdict == "ok":
        return "slo ok"
    rows = [r for r in (report.get("objectives") or [])
            if r.get("verdict") != "ok"]

    def _severity(row):
        burn = (row.get("burn") or {}).get("long")
        return (VERDICTS.index(row.get("verdict", "warn")),
                burn if burn is not None else 0.0)

    if not rows:
        return f"slo {verdict}"
    worst = max(rows, key=_severity)
    frag = f"slo {verdict.upper()} {worst['objective']}"
    burn = (worst.get("burn") or {}).get("long")
    if burn is not None:
        frag += f" burn {burn:.1f}x"
    remaining = (worst.get("budget") or {}).get("remaining_frac")
    if remaining is not None:
        frag += f" budget {100.0 * remaining:.0f}%"
    return frag


def _round6(value):
    if isinstance(value, float):
        if math.isinf(value):
            return value
        return round(value, 6)
    return value
