"""Live TTY dashboard for a running campaign.

Replaces the bare one-line heartbeat with a repainted panel:

    campaign matrixMultiply/TMR  [##########........]  61.2%
      342016/559104 rows   48213 inj/s (avg 45102)   eta 4s
      success      334112  59.762% +-0.041%  |#########|
      sdc            1893   0.339% +-0.005%  |         |
      ...
      stages: dispatch 61.2%  collect 30.1%  pad 5.4%  (overlap 82%)
      resilience: retry_transient=1

Repainting uses plain ANSI (cursor-up + erase-line) and only when the
output stream is a TTY; redirected to a file (or handed an ``emit``
hook, as tests do) it degrades to one appended snapshot per interval --
the same information, log-friendly.  Rate limiting matches
:class:`coast_tpu.obs.heartbeat.Heartbeat`; ``final`` bypasses it so a
campaign's last state is always painted (the terminal-flush guarantee).

Rates and Wilson CI bars come straight from the counts histogram the
campaign loop already maintains; the optional ``metrics`` hub adds the
stage/resilience/memory rows.  Pure stdlib, injectable clock and emit
for tests.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, Mapping, Optional

from coast_tpu.obs import spans as _spans
from coast_tpu.obs.convergence import StopWhen, wilson_interval

__all__ = ["Console"]

#: Classes in display order (the classifier taxonomy + the invalid-draw
#: bucket); zero-count classes that are not stop targets are elided.
_CLASS_ORDER = ("success", "corrected", "sdc", "train_self_heal",
                "train_sdc", "due_abort", "due_timeout",
                "due_stack_overflow", "due_assert", "invalid",
                "cache_invalid")

_BAR_WIDTH = 18
_CI_BAR_WIDTH = 10


class Console:
    """Rate-limited live dashboard; API-compatible with ``Heartbeat``."""

    def __init__(self, total: int, interval_s: float = 1.0,
                 label: str = "campaign",
                 emit: Optional[Callable[[str], None]] = None,
                 stream=None,
                 metrics=None,
                 stop_when: Optional[StopWhen] = None,
                 z: float = 1.96,
                 clock: Callable[[], float] = time.monotonic):
        self.total = int(total)
        self.interval_s = float(interval_s)
        self.label = label
        self.metrics = metrics
        self.stop_when = stop_when
        self.z = stop_when.z if stop_when is not None else z
        self.emitted = 0
        self._stream = stream if stream is not None else sys.stderr
        self._emit = emit
        self._clock = clock
        self._t0 = clock()
        self._last = self._t0 - self.interval_s   # first update eligible
        self._painted_lines = 0
        from coast_tpu.obs.heartbeat import TransferRateWindow
        self._transfer_window = TransferRateWindow(self._t0)

    # -- painting ------------------------------------------------------------
    def _tty(self) -> bool:
        if self._emit is not None:
            return False
        try:
            return bool(self._stream.isatty())
        except Exception:        # noqa: BLE001 - closed/odd streams
            return False

    def _write(self, text: str) -> None:
        if self._emit is not None:
            self._emit(text)
            return
        n_lines = text.count("\n") + 1
        if self._tty() and self._painted_lines:
            # Cursor up over the previous panel, erasing each line, so
            # the dashboard repaints in place instead of scrolling.
            self._stream.write(
                f"\x1b[{self._painted_lines}F" + "\x1b[J")
        self._stream.write(text + "\n")
        self._stream.flush()
        self._painted_lines = n_lines if self._tty() else 0

    def render(self, done: int, counts: Optional[Mapping[str, int]],
               final: bool = False) -> str:
        counts = dict(counts or {})
        now = self._clock()
        elapsed = max(now - self._t0, 1e-9)
        rate = done / elapsed
        frac = done / self.total if self.total else 0.0
        fill = int(_BAR_WIDTH * min(frac, 1.0))
        bar = "#" * fill + "." * (_BAR_WIDTH - fill)
        state = "done" if final else "live"
        lines = [f"{self.label}  [{bar}]  {100.0 * frac:5.1f}%  ({state})"]
        eta = ""
        if self.total and rate > 0 and done < self.total:
            eta = f"   eta {(self.total - done) / rate:.0f}s"
        lines.append(f"  {done}/{self.total} rows   {rate:.0f} inj/s{eta}")
        total_eff = float(sum(counts.values()))
        peak_hw = max((self._half_width(counts.get(k, 0), total_eff)
                       for k in counts), default=0.0) or 1.0
        for cls_name in _CLASS_ORDER:
            k = counts.get(cls_name, 0)
            is_target = (self.stop_when is not None
                         and cls_name in self.stop_when.targets)
            if not k and not is_target:
                continue
            p = (k / total_eff) if total_eff else 0.0
            hw = self._half_width(k, total_eff)
            # CI bar: wider interval = longer bar, so convergence is the
            # bars visibly draining toward empty.
            ci_fill = int(_CI_BAR_WIDTH * min(hw / peak_hw, 1.0))
            ci_bar = "#" * ci_fill + " " * (_CI_BAR_WIDTH - ci_fill)
            target = ""
            if is_target:
                threshold = self.stop_when.targets[cls_name]
                mark = "v" if hw <= threshold else ">"
                target = f"  {mark} {threshold:g}"
            lines.append(
                f"  {cls_name:<18} {int(k):>9}  {100.0 * p:7.3f}% "
                f"+-{100.0 * hw:6.3f}%  |{ci_bar}|{target}")
        stage_line = self._stage_line()
        if stage_line:
            lines.append(stage_line)
        transfer_line = self._transfer_line(now)
        if transfer_line:
            lines.append(transfer_line)
        res_line = self._resilience_line()
        if res_line:
            lines.append(res_line)
        slo_line = self._slo_line()
        if slo_line:
            lines.append(slo_line)
        return "\n".join(lines)

    def _half_width(self, k: float, n: float) -> float:
        lo, hi = wilson_interval(k, n, self.z)
        return (hi - lo) / 2.0

    def _stage_line(self) -> Optional[str]:
        if self.metrics is None:
            return None
        stages = dict(self.metrics.stages)
        overlap = stages.pop("overlap", None)
        seconds_total = sum(stages.values())
        if not seconds_total:
            return None
        parts = [f"{k} {100.0 * v / seconds_total:.1f}%"
                 for k, v in sorted(stages.items(), key=lambda kv: -kv[1])
                 if v > 0][:4]
        line = "  stages: " + "  ".join(parts)
        if overlap:
            line += f"  (overlap {100.0 * overlap:.0f}%)"
        mem = self.metrics.memory_watermark
        if mem:
            line += f"  mem {mem / 2**20:.0f}MiB"
        return line

    def _transfer_line(self, now: float) -> Optional[str]:
        """Live host<->device link rates from the hub's cumulative
        transfer counters (the PR 12 block, previously summary-only),
        plus the profiler's device-busy fraction when one is armed."""
        if self.metrics is None:
            return None
        profile = dict(getattr(self.metrics, "profile", None) or {})
        from coast_tpu.obs.heartbeat import format_rate
        parts = []
        got = self._transfer_window.rates(
            now, getattr(self.metrics, "transfer", None))
        if got is not None:
            up_rate, down_rate, up, down = got
            parts.append(f"link up {format_rate(up_rate)}"
                         f" / down {format_rate(down_rate)}"
                         f"  (total {up + down} B)")
        busy = profile.get("device_busy_s")
        if busy is not None:
            # Same definition as every recorded surface
            # (device_busy_fraction = busy / wall): busy over the
            # campaign elapsed time, not over busy+gap.
            elapsed = max(now - self._t0, 1e-9)
            parts.append(
                f"device busy {100.0 * float(busy) / elapsed:.0f}%")
        return "  " + "  ".join(parts) if parts else None

    def _resilience_line(self) -> Optional[str]:
        if self.metrics is None:
            return None
        hot = {k: v for k, v in self.metrics.resilience.items() if v}
        if not hot:
            return None
        return "  resilience: " + " ".join(
            f"{k}={v}" for k, v in sorted(hot.items()))

    def _slo_line(self) -> Optional[str]:
        """Live reliability-SLO verdict (worst burning objective plus
        its remaining error budget) when the hub carries an SLO set."""
        status = getattr(self.metrics, "slo_status", None)
        if self.metrics is None or status is None:
            return None
        from coast_tpu.obs.slo import status_line
        frag = status_line(status())
        return f"  {frag}" if frag else None

    # -- the Heartbeat-compatible surface ------------------------------------
    def update(self, done: int, counts: Optional[Mapping[str, int]] = None,
               force: bool = False) -> Optional[str]:
        """Repaint if the interval elapsed (or ``force``); returns the
        painted panel or None when rate-limited."""
        now = self._clock()
        if not force and now - self._last < self.interval_s:
            return None
        self._last = now
        panel = self.render(done, counts)
        self.emitted += 1
        self._write(panel)
        tel = _spans.current()
        tel.instant("console", done=done, total=self.total)
        return panel

    def final(self, done: int,
              counts: Optional[Mapping[str, int]] = None) -> str:
        """Terminal flush: always paints (rate limiter bypassed) and, on
        a TTY, leaves the last panel in the scrollback instead of
        erasing it on the next repaint."""
        panel = self.render(done, counts, final=True)
        self.emitted += 1
        self._write(panel)
        self._painted_lines = 0      # never repaint over the final state
        return panel
