"""Statistical convergence tracking: Wilson intervals + early stop.

A campaign's purpose is an estimate -- the per-class rates of the
classification distribution -- and an estimate has a precision.  The
reference platform sizes campaigns by a crude proxy ("inject until N
errors seen, then round up", supervisor.py:339); FastFlip
(arXiv:2403.13989) makes the sharper observation that injection work
should stop the moment additional samples stop changing the answer.
This module supplies the machinery:

  * :func:`wilson_interval` -- the Wilson score interval for a binomial
    proportion.  Chosen over the normal approximation because campaign
    classes are routinely rare (SDC under TMR is ~0) and Wilson behaves
    at p=0/p=1 and small n where Wald collapses to a zero-width lie.
  * :class:`ConvergenceTracker` -- feeds on the cumulative class
    histogram after every collected batch (weighted counts included:
    equivalence-reduced campaigns converge over *effective*
    injections) and reports per-class rate + CI.
  * :class:`StopWhen` -- the opt-in early-stop condition: named target
    classes each with a CI half-width threshold, plus the z quantile
    and a minimum sample floor.  ``parse``/``spec`` round-trip a
    canonical string so the condition can ride in a journal header as
    campaign identity (resuming under a different stop rule must
    refuse, exactly like a different seed).

The tracker is pure arithmetic over the counts the campaign loop
already maintains -- no extra device work, no extra host passes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Mapping, Optional, Tuple

__all__ = ["wilson_interval", "interval_table", "intervals_overlap",
           "StopWhen", "ConvergenceTracker", "StopWhenError"]

#: Valid stop-condition target classes: the classifier taxonomy plus the
#: cache_invalid bucket the campaign counts alongside it.
_VALID_CLASSES = ("success", "corrected", "sdc", "due_abort",
                  "due_timeout", "invalid", "due_stack_overflow",
                  "due_assert", "train_self_heal", "train_sdc",
                  "cache_invalid")


class StopWhenError(ValueError):
    """A malformed --stop-when specification."""


def wilson_interval(k: float, n: float, z: float = 1.96
                    ) -> Tuple[float, float]:
    """Wilson score interval for ``k`` successes in ``n`` trials.

    Accepts float counts: equivalence-reduced campaigns feed *weighted*
    (effective) counts, and the interval arithmetic is identical.
    ``n <= 0`` returns the vacuous ``(0, 1)`` -- no data constrains
    nothing.
    """
    if n <= 0:
        return (0.0, 1.0)
    p = k / n
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2.0 * n)) / denom
    half = (z / denom) * math.sqrt(p * (1.0 - p) / n
                                   + z2 / (4.0 * n * n))
    return (max(0.0, center - half), min(1.0, center + half))


@dataclasses.dataclass(frozen=True)
class StopWhen:
    """Early-stop condition: every target class's CI half-width must
    drop to (or below) its threshold.

    ``targets`` maps class name -> half-width threshold (absolute rate
    units: 0.001 means the class rate is known to about +-0.1%).
    ``z`` is the normal quantile (1.96 ~ 95%, 2.576 ~ 99%).
    ``min_done`` floors the sample count so a lucky first batch of an
    all-success campaign cannot stop it before the rare classes had any
    chance to appear.
    """

    targets: Mapping[str, float]
    z: float = 1.96
    min_done: int = 0

    def __post_init__(self):
        if not self.targets:
            raise StopWhenError("stop_when needs at least one "
                                "class:half_width target")
        for cls_name, hw in self.targets.items():
            if cls_name not in _VALID_CLASSES:
                raise StopWhenError(
                    f"unknown class {cls_name!r} in stop_when (valid: "
                    f"{', '.join(_VALID_CLASSES)})")
            if not (0.0 < float(hw) < 1.0):
                raise StopWhenError(
                    f"stop_when half-width for {cls_name!r} must be in "
                    f"(0, 1), got {hw!r}")
        if self.z <= 0:
            raise StopWhenError(f"stop_when z must be > 0, got {self.z!r}")
        if self.min_done < 0:
            raise StopWhenError(
                f"stop_when min_done must be >= 0, got {self.min_done!r}")

    @classmethod
    def parse(cls, spec: str) -> "StopWhen":
        """``"sdc:0.002,due_abort:0.01;z=2.576;min=4096"`` -> StopWhen.

        Comma-separated ``class:half_width`` targets, then optional
        ``;z=`` / ``;min=`` knobs in any order.
        """
        text = (spec or "").strip()
        if not text:
            raise StopWhenError("empty stop_when specification")
        parts = text.split(";")
        targets: Dict[str, float] = {}
        for pair in parts[0].split(","):
            pair = pair.strip()
            if not pair:
                continue
            name, sep, value = pair.partition(":")
            if not sep:
                raise StopWhenError(
                    f"bad stop_when target {pair!r} (want "
                    "class:half_width, e.g. sdc:0.002)")
            try:
                targets[name.strip()] = float(value)
            except ValueError as e:
                raise StopWhenError(
                    f"bad stop_when half-width in {pair!r}: {e}") from e
        z, min_done = 1.96, 0
        for knob in parts[1:]:
            knob = knob.strip()
            if not knob:
                continue
            key, sep, value = knob.partition("=")
            try:
                if key == "z" and sep:
                    z = float(value)
                elif key == "min" and sep:
                    min_done = int(value)
                else:
                    raise StopWhenError(
                        f"unknown stop_when knob {knob!r} (want z=Q or "
                        "min=N)")
            except ValueError as e:
                raise StopWhenError(
                    f"bad stop_when knob {knob!r}: {e}") from e
        return cls(targets=targets, z=z, min_done=min_done)

    def spec(self) -> str:
        """Canonical round-trippable string (sorted targets, knobs only
        when non-default) -- the journal-header identity form."""
        body = ",".join(f"{k}:{self.targets[k]:g}"
                        for k in sorted(self.targets))
        if self.z != 1.96:
            body += f";z={self.z:g}"
        if self.min_done:
            body += f";min={self.min_done}"
        return body


def interval_table(counts: Mapping[str, float], z: float = 1.96,
                   ensure: "Optional[tuple]" = None
                   ) -> Dict[str, Dict[str, float]]:
    """{class: {count, rate, lo, hi, half_width}} over a counts
    histogram -- the one shared shape every surface renders (tracker
    reports, /status rates, console rows).  ``ensure`` forces rows for
    named zero-count classes (stop targets: their shrinking upper bound
    IS the convergence story for rare events)."""
    total = float(sum(counts.values()))
    names = {k: float(v) for k, v in counts.items()}
    for k in ensure or ():
        names.setdefault(k, 0.0)
    out: Dict[str, Dict[str, float]] = {}
    for k in sorted(names):
        count = names[k]
        lo, hi = wilson_interval(count, total, z)
        out[k] = {
            "count": count,
            "rate": (count / total) if total else 0.0,
            "lo": lo,
            "hi": hi,
            "half_width": (hi - lo) / 2.0,
        }
    return out


def intervals_overlap(a: Mapping[str, float],
                      b: Mapping[str, float]) -> bool:
    """Whether two ``{lo, hi}`` interval rows (the :func:`interval_table`
    shape) intersect.  Closed-interval semantics: touching endpoints
    count as overlap -- the two estimates are still mutually consistent.
    The one overlap rule shared by the comparison surface
    (``json_parser.compare_runs``) and the protection-regression CI's
    drift verdict."""
    return (float(a["lo"]) <= float(b["hi"])
            and float(b["lo"]) <= float(a["hi"]))


class ConvergenceTracker:
    """Per-class Wilson CIs over a campaign's cumulative counts.

    Feed :meth:`update` the same ``counts_so_far`` histogram the
    campaign loop hands its progress callback after every collected
    batch (weighted counts for reduced campaigns); ``converged`` flips
    True once every target class's CI half-width is at or below its
    threshold.  A tracker without a :class:`StopWhen` still tracks --
    it just never stops anything (the metrics/status surfaces want the
    intervals regardless).
    """

    def __init__(self, stop_when: Optional[StopWhen] = None):
        self.stop_when = stop_when
        self.total = 0.0
        self.counts: Dict[str, float] = {}

    def update(self, counts: Mapping[str, float]) -> None:
        """Replace the tracked histogram with the new cumulative one."""
        self.counts = {k: float(v) for k, v in counts.items()}
        self.total = float(sum(self.counts.values()))

    def interval(self, cls_name: str) -> Tuple[float, float]:
        z = self.stop_when.z if self.stop_when is not None else 1.96
        return wilson_interval(self.counts.get(cls_name, 0.0),
                               self.total, z)

    def intervals(self) -> Dict[str, Dict[str, float]]:
        """Per-class interval table over every class seen so far, plus
        zero-count rows for the stop targets."""
        z = self.stop_when.z if self.stop_when is not None else 1.96
        ensure = (tuple(self.stop_when.targets)
                  if self.stop_when is not None else None)
        return interval_table(self.counts, z, ensure=ensure)

    @property
    def converged(self) -> bool:
        if self.stop_when is None or self.total <= 0:
            return False
        if self.total < self.stop_when.min_done:
            return False
        for cls_name, threshold in self.stop_when.targets.items():
            lo, hi = self.interval(cls_name)
            if (hi - lo) / 2.0 > threshold:
                return False
        return True

    def report(self, stopped: bool, planned_n: int,
               done_n: int) -> Dict[str, object]:
        """The ``CampaignResult.convergence`` block: what the campaign
        knew when it finished (or stopped)."""
        out: Dict[str, object] = {
            "stopped": bool(stopped),
            "planned_n": int(planned_n),
            "done_n": int(done_n),
            "z": (self.stop_when.z if self.stop_when is not None
                  else 1.96),
            "intervals": {
                k: {kk: round(vv, 8) for kk, vv in v.items()}
                for k, v in self.intervals().items()},
        }
        if self.stop_when is not None:
            out["stop_when"] = self.stop_when.spec()
        return out
