"""Live campaign metrics: per-batch time series behind a lock.

The spans/trace layer (:mod:`coast_tpu.obs.spans`) answers "where did
the time go" *after* a campaign ends; this module answers "what is the
campaign doing *now*".  :class:`CampaignMetrics` is a small thread-safe
hub the campaign loop feeds once per collected batch
(``CampaignRunner(metrics=...)``); the HTTP endpoint
(:mod:`coast_tpu.obs.serve`), the status-file export, and the TTY
console (:mod:`coast_tpu.obs.console`) all read coherent snapshots from
it.  The TPU CFD framework (arXiv:2108.11076) is the exemplar: keeping
a long accelerator run efficient is a *host-side monitoring* problem --
slice saturation, throughput, and failure counters have to be visible
while the run is still spending money.

Everything is stdlib + numpy-free; the one accelerator touch (device
memory watermark) imports jax lazily and degrades to ``None`` on
backends without ``memory_stats`` (CPU).

Per batch the hub records into fixed-capacity ring buffers:

  * instantaneous and cumulative injections/sec (physical dispatches);
  * done / total progress (physical rows and weighted effective rows);
  * weighted per-class rates with Wilson confidence intervals
    (:mod:`coast_tpu.obs.convergence`);
  * per-stage wall-clock totals and the streaming overlap fraction;
  * retry / OOM-degrade / watchdog counters
    (:mod:`coast_tpu.inject.resilience`);
  * the device memory watermark (high-water ``bytes_in_use``).

Ring capacity bounds memory for arbitrarily long campaigns: the status
surfaces show the recent window, the scalar aggregates stay exact.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Deque, Dict, List, Mapping, Optional, Tuple

from coast_tpu.inject.classify import SDC_CLASSES as _SDC_CLASSES
from coast_tpu.obs.convergence import interval_table

__all__ = ["Ring", "Histogram", "CampaignMetrics", "device_memory_bytes",
           "atomic_write_json"]


def device_memory_bytes() -> Optional[int]:
    """Live ``bytes_in_use`` of device 0, or None when the backend does
    not report memory stats (CPU) or jax is unavailable."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
    except Exception:            # noqa: BLE001 - any backend gap -> None
        return None
    if not stats:
        return None
    value = stats.get("bytes_in_use")
    return int(value) if value is not None else None


def atomic_write_json(path: str, doc: Dict[str, object]) -> None:
    """Write ``doc`` to ``path`` atomically (tmp + rename): a reader --
    a fleet scraper polling ``--status-json`` -- never sees a torn
    file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, separators=(",", ":"), sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


class Ring:
    """Fixed-capacity (t, value) time series; oldest samples drop."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._buf: Deque[Tuple[float, float]] = collections.deque(
            maxlen=self.capacity)

    def append(self, t: float, value: float) -> None:
        self._buf.append((float(t), float(value)))

    def last(self) -> Optional[float]:
        return self._buf[-1][1] if self._buf else None

    def points(self) -> List[Tuple[float, float]]:
        return list(self._buf)

    def __len__(self) -> int:
        return len(self._buf)


#: The ring series every campaign records, in export order.
_SERIES = ("inj_per_sec", "inj_per_sec_cumulative", "done_rows",
           "effective_done", "sdc_rate", "device_memory_bytes")


class Histogram:
    """Prometheus-style cumulative-bucket histogram (fixed bounds).

    The campaign profiler's per-dispatch device-seconds distribution
    needs more than a gauge: the fused-kernel A/B cares whether the
    dispatch population *shifted*, not just its mean.  This is the one
    histogram implementation behind both the profiler's recorded
    snapshots and the ``/metrics`` exposition -- the first histogram-
    typed exporter in the hub (everything before PR 15 was a
    gauge/counter).

    ``le`` bounds are upper-inclusive seconds; observations above the
    last bound land only in the implicit ``+Inf`` bucket (``count``).
    """

    #: Log-spaced per-dispatch latency bounds: 0.5 ms (a warm tiny-batch
    #: CPU dispatch) through 30 s (a flagship batch behind a tunnel).
    DEFAULT_BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                      0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

    def __init__(self, bounds: Optional[Tuple[float, ...]] = None):
        self.bounds = tuple(float(b) for b in (bounds or
                                               self.DEFAULT_BOUNDS))
        self.bucket_counts = [0] * len(self.bounds)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        for i, bound in enumerate(self.bounds):
            if v <= bound:
                self.bucket_counts[i] += 1

    def snapshot(self) -> Dict[str, object]:
        """JSON-able form: CUMULATIVE per-bucket counts (Prometheus
        ``le`` semantics -- bucket i counts observations <= bounds[i])
        plus the scalar sum/count."""
        return {"le": list(self.bounds),
                "counts": list(self.bucket_counts),
                "count": int(self.count),
                "sum": round(self.sum, 6)}


class CampaignMetrics:
    """Thread-safe live-metrics hub for one campaign at a time.

    The campaign loop (single writer) calls ``campaign_started`` /
    ``record_batch`` / ``campaign_finished``; any number of reader
    threads (HTTP handlers, the console) call ``snapshot`` /
    ``prometheus``.  ``status_path`` additionally mirrors every sample
    to an atomically-replaced JSON file for headless fleets (rate-
    limited by ``status_interval_s``; the terminal states always
    write).
    """

    def __init__(self, ring_capacity: int = 256,
                 status_path: Optional[str] = None,
                 status_interval_s: float = 0.0,
                 z: float = 1.96,
                 slo=None,
                 slo_baseline: Optional[Mapping[str, float]] = None,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.status_path = status_path
        self.status_interval_s = float(status_interval_s)
        self.z = float(z)
        # Reliability SLOs (obs/slo): a spec string or SLOSet; when set,
        # every record_batch re-evaluates the error budgets over the
        # cumulative evidence and snapshot()/prometheus()/the console
        # expose the live verdicts.  ``slo_baseline`` feeds the mwtf
        # objective ({"sdc_rate", "inj_per_sec"} of an unprotected run).
        if isinstance(slo, str):
            from coast_tpu.obs.slo import SLOSet
            slo = SLOSet.parse(slo)
        self.slo_set = slo
        self.slo_baseline = (dict(slo_baseline) if slo_baseline
                             else None)
        self.slo_report: Optional[Dict[str, object]] = None
        self.rings: Dict[str, Ring] = {
            name: Ring(ring_capacity) for name in _SERIES}
        self.state = "idle"
        self.benchmark = ""
        self.strategy = ""
        self.total_rows = 0
        self.total_effective = 0
        self.done_rows = 0
        self.effective_done = 0
        self.counts: Dict[str, float] = {}
        self.stages: Dict[str, float] = {}
        self.resilience: Dict[str, int] = {}
        # Host<->device traffic bytes ({"up", "down"}), cumulative; the
        # sparse-collect campaign loop's headline counter.  Stage
        # attribution: up-bytes accrue in the pad/dispatch stages,
        # down-bytes in collect.
        self.transfer: Dict[str, int] = {}
        # Device-time attribution (CampaignRunner(profile=True)):
        # cumulative device-busy / host-gap seconds plus per-dispatch
        # latency histograms -- the hub's first histogram-typed
        # exporters.  Empty for unprofiled campaigns, so every existing
        # surface is unchanged.
        self.profile: Dict[str, float] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.batches = 0
        self.replayed_batches = 0
        self.memory_watermark: Optional[int] = None
        self.error: Optional[str] = None
        self.convergence: Optional[Dict[str, object]] = None
        self._t_start = 0.0
        self._t_last_batch = 0.0
        self._last_status_write = float("-inf")
        self._updated_unix = time.time()

    # -- writer side (the campaign loop) -------------------------------------
    def campaign_started(self, benchmark: str, strategy: str,
                         total_rows: int, total_effective: int) -> None:
        with self._lock:
            self.state = "running"
            self.benchmark = benchmark
            self.strategy = strategy
            self.total_rows = int(total_rows)
            self.total_effective = int(total_effective)
            self.done_rows = 0
            self.effective_done = 0
            self.counts = {}
            self.stages = {}
            self.resilience = {}
            self.transfer = {}
            self.profile = {}
            self.histograms = {}
            self.batches = 0
            self.replayed_batches = 0
            self.error = None
            self.convergence = None
            now = self._clock()
            self._t_start = now
            self._t_last_batch = now
        self._maybe_write_status(force=True)

    def record_batch(self, done_rows: int, n_rows: int,
                     counts: Mapping[str, float],
                     stages: Mapping[str, float],
                     resilience: Mapping[str, int],
                     replayed: bool = False,
                     transfer: Optional[Mapping[str, int]] = None,
                     profile: Optional[Mapping[str, float]] = None
                     ) -> None:
        """One collected (or journal-replayed) batch: cumulative row
        progress, the cumulative weighted class histogram, stage
        totals, resilience counters, and (when the loop measures it)
        cumulative host<->device transfer bytes so far.  ``profile`` is
        the profiler's per-batch sample ({device_s, gap_s}) -- observed
        into the dispatch-latency histograms and summed into the
        cumulative attribution block."""
        now = self._clock()
        with self._lock:
            if profile is not None:
                self.profile["device_busy_s"] = (
                    self.profile.get("device_busy_s", 0.0)
                    + float(profile.get("device_s", 0.0)))
                self.profile["host_gap_s"] = (
                    self.profile.get("host_gap_s", 0.0)
                    + float(profile.get("gap_s", 0.0)))
                self.profile["dispatches"] = (
                    self.profile.get("dispatches", 0) + 1)
                for key, sample in (("dispatch_device_seconds",
                                     "device_s"),
                                    ("dispatch_host_gap_seconds",
                                     "gap_s")):
                    hist = self.histograms.get(key)
                    if hist is None:
                        hist = self.histograms[key] = Histogram()
                    hist.observe(float(profile.get(sample, 0.0)))
            dt = max(now - self._t_last_batch, 1e-9)
            elapsed = max(now - self._t_start, 1e-9)
            self._t_last_batch = now
            self.done_rows = int(done_rows)
            self.counts = {k: float(v) for k, v in counts.items()}
            self.effective_done = int(sum(self.counts.values()))
            self.stages = {k: float(v) for k, v in stages.items()}
            self.resilience = {k: int(v) for k, v in resilience.items()}
            if transfer is not None:
                self.transfer = {k: int(v) for k, v in transfer.items()}
            self.batches += 1
            if replayed:
                self.replayed_batches += 1
            mem = device_memory_bytes()
            if mem is not None:
                self.memory_watermark = max(self.memory_watermark or 0,
                                            mem)
            inst = n_rows / dt
            cum = self.done_rows / elapsed
            total_eff = float(sum(self.counts.values()))
            # classify.SDC_CLASSES: train regions refine the raw ``sdc``
            # bucket into ``train_sdc`` (persistent) + self-heal, so the
            # live rate must sum the persistent classes, not just "sdc".
            sdc = sum(self.counts.get(k, 0.0) for k in _SDC_CLASSES)
            sdc_rate = sdc / total_eff if total_eff else 0.0
            self.rings["inj_per_sec"].append(now, inst)
            self.rings["inj_per_sec_cumulative"].append(now, cum)
            self.rings["done_rows"].append(now, self.done_rows)
            self.rings["effective_done"].append(now, self.effective_done)
            self.rings["sdc_rate"].append(now, sdc_rate)
            if mem is not None:
                self.rings["device_memory_bytes"].append(now, mem)
            self._refresh_slo_locked()
            self._updated_unix = time.time()
        self._maybe_write_status()

    def campaign_finished(self, summary: Optional[Dict[str, object]] = None,
                          error: Optional[str] = None,
                          convergence: Optional[Dict[str, object]] = None
                          ) -> None:
        with self._lock:
            self.state = "failed" if error else "finished"
            self.error = error
            if convergence is not None:
                self.convergence = dict(convergence)
            if summary:
                stages = summary.get("stages")
                if isinstance(stages, dict):
                    self.stages = {k: float(v) for k, v in stages.items()}
            self._refresh_slo_locked()
            self._updated_unix = time.time()
        self._maybe_write_status(force=True)

    def _refresh_slo_locked(self) -> None:
        """Re-evaluate the attached SLO set over the cumulative evidence
        (caller holds the lock; pure arithmetic, one pass per batch)."""
        if self.slo_set is None:
            return
        from coast_tpu.obs.slo import evaluate
        elapsed = max(self._t_last_batch - self._t_start, 1e-9)
        evidence = {
            "counts": dict(self.counts),
            "inj_per_sec": (self.done_rows / elapsed
                            if self.done_rows else None),
            "histograms": {k: h.snapshot()
                           for k, h in self.histograms.items()},
            "sdc_rate_recent": [v for _, v in
                                self.rings["sdc_rate"].points()],
        }
        self.slo_report = evaluate(self.slo_set, evidence,
                                   baseline=self.slo_baseline)

    def slo_status(self) -> Optional[Dict[str, object]]:
        """The latest live SLO evaluation (None when no SLO set is
        attached or nothing has been recorded yet) -- the console /
        heartbeat feed."""
        with self._lock:
            return self.slo_report

    # -- reader side ---------------------------------------------------------
    def _rates(self) -> Dict[str, Dict[str, float]]:
        """Per-class weighted rate + Wilson CI (caller holds the lock);
        the shared interval-table shape of obs/convergence."""
        return interval_table(self.counts, self.z)

    def snapshot(self) -> Dict[str, object]:
        """One coherent JSON-able status document (the /status body and
        the --status-json file)."""
        with self._lock:
            elapsed = (max(self._t_last_batch - self._t_start, 0.0)
                       if self.state != "idle" else 0.0)
            doc: Dict[str, object] = {
                "format": "coast-status",
                "version": 1,
                "state": self.state,
                "benchmark": self.benchmark,
                "strategy": self.strategy,
                "total_rows": self.total_rows,
                "total_effective": self.total_effective,
                "done_rows": self.done_rows,
                "effective_done": self.effective_done,
                "batches": self.batches,
                "replayed_batches": self.replayed_batches,
                "elapsed_s": round(elapsed, 6),
                "inj_per_sec": self.rings["inj_per_sec"].last() or 0.0,
                "inj_per_sec_cumulative":
                    self.rings["inj_per_sec_cumulative"].last() or 0.0,
                "counts": dict(self.counts),
                "rates": self._rates(),
                "stages": dict(self.stages),
                "resilience": dict(self.resilience),
                "transfer_bytes": dict(self.transfer),
                "device_memory_watermark_bytes": self.memory_watermark,
                "updated_unix_s": round(self._updated_unix, 6),
                "series": {
                    name: [[round(t, 4), v] for t, v in ring.points()]
                    for name, ring in self.rings.items()},
            }
            if self.profile:
                doc["profile"] = {
                    **{k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in self.profile.items()},
                    "histograms": {k: h.snapshot()
                                   for k, h in self.histograms.items()},
                }
            if self.error:
                doc["error"] = self.error
            if self.convergence is not None:
                doc["convergence"] = self.convergence
            if self.slo_report is not None:
                from coast_tpu.obs.slo import summary_block
                doc["slo"] = summary_block(self.slo_report)
            return doc

    def prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the scalar
        aggregates -- what a fleet scraper wants; the ring series stay
        JSON-only."""
        with self._lock:
            labels = (f'benchmark="{_esc(self.benchmark)}",'
                      f'strategy="{_esc(self.strategy)}"')
            lines: List[str] = []

            def metric(name: str, mtype: str, help_text: str,
                       samples: List[Tuple[str, float]]) -> None:
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} {mtype}")
                for label_str, value in samples:
                    # :.17g round-trips any float exactly; :g's 6
                    # significant digits would corrupt counters past
                    # 10^6 (a one-million-row campaign is the NORMAL
                    # case, scripts/campaign_1m.py).
                    text = (f"{int(value)}" if float(value).is_integer()
                            else f"{value:.17g}")
                    lines.append(f"{name}{{{label_str}}} {text}")

            state_samples = [
                (f'{labels},state="{s}"',
                 1.0 if s == self.state else 0.0)
                for s in ("idle", "running", "finished", "failed")]
            metric("coast_campaign_state", "gauge",
                   "Campaign lifecycle state (one-hot).", state_samples)
            metric("coast_campaign_rows_total", "gauge",
                   "Physical schedule rows in this campaign.",
                   [(labels, float(self.total_rows))])
            metric("coast_campaign_rows_done", "gauge",
                   "Physical rows collected so far.",
                   [(labels, float(self.done_rows))])
            metric("coast_campaign_effective_done", "gauge",
                   "Weighted effective injections counted so far.",
                   [(labels, float(self.effective_done))])
            metric("coast_campaign_batches_total", "counter",
                   "Collected batches (journal-replayed included).",
                   [(labels, float(self.batches))])
            metric("coast_campaign_replayed_batches_total", "counter",
                   "Batches replayed from the journal on resume.",
                   [(labels, float(self.replayed_batches))])
            metric("coast_campaign_inj_per_sec", "gauge",
                   "Instantaneous physical injections per second.",
                   [(labels,
                     self.rings["inj_per_sec"].last() or 0.0)])
            metric("coast_campaign_class_total", "gauge",
                   "Weighted cumulative count per classification class.",
                   [(f'{labels},class="{_esc(k)}"', float(v))
                    for k, v in sorted(self.counts.items())]
                   or [(f'{labels},class="success"', 0.0)])
            rates = self._rates()
            if rates:
                metric("coast_campaign_class_rate", "gauge",
                       "Weighted per-class rate.",
                       [(f'{labels},class="{_esc(k)}"', v["rate"])
                        for k, v in rates.items()])
                metric("coast_campaign_class_ci_half_width", "gauge",
                       "Wilson CI half-width of the per-class rate.",
                       [(f'{labels},class="{_esc(k)}"', v["half_width"])
                        for k, v in rates.items()])
            metric("coast_campaign_stage_seconds_total", "counter",
                   "Wall-clock seconds per pipeline stage "
                   "(overlap is a fraction, exported separately).",
                   [(f'{labels},stage="{_esc(k)}"', float(v))
                    for k, v in sorted(self.stages.items())
                    if k != "overlap"]
                   or [(f'{labels},stage="dispatch"', 0.0)])
            metric("coast_campaign_serialize_overlap_ratio", "gauge",
                   "Fraction of serialization hidden under dispatch.",
                   [(labels, float(self.stages.get("overlap", 0.0)))])
            metric("coast_campaign_resilience_total", "counter",
                   "Retry / OOM-degrade / watchdog event counts.",
                   [(f'{labels},kind="{_esc(k)}"', float(v))
                    for k, v in sorted(self.resilience.items())]
                   or [(f'{labels},kind="retry_transient"', 0.0)])
            metric("coast_campaign_transfer_bytes_total", "counter",
                   "Measured host<->device traffic (up: schedule/fault "
                   "upload, billed under pad/dispatch; down: collected "
                   "results, billed under collect).",
                   [(f'{labels},direction="{_esc(k)}"', float(v))
                    for k, v in sorted(self.transfer.items())]
                   or [(f'{labels},direction="up"', 0.0)])
            if self.profile:
                metric("coast_campaign_device_busy_seconds_total",
                       "counter",
                       "Measured device-busy seconds "
                       "(per-dispatch blocking-marker attribution).",
                       [(labels,
                         float(self.profile.get("device_busy_s", 0.0)))])
                metric("coast_campaign_dispatch_gap_seconds_total",
                       "counter",
                       "Measured host-side gap seconds the device sat "
                       "idle between dispatches.",
                       [(labels,
                         float(self.profile.get("host_gap_s", 0.0)))])
            for hname, hist in sorted(self.histograms.items()):
                # The histogram exposition type (new in PR 15): one
                # cumulative le-bucket series + _sum/_count per name.
                full = f"coast_campaign_{hname}"
                lines.append(f"# HELP {full} Per-dispatch latency "
                             "histogram (seconds).")
                lines.append(f"# TYPE {full} histogram")
                for bound, cum in zip(hist.bounds, hist.bucket_counts):
                    lines.append(
                        f'{full}_bucket{{{labels},le="{bound:g}"}} {cum}')
                lines.append(
                    f'{full}_bucket{{{labels},le="+Inf"}} {hist.count}')
                lines.append(f"{full}_sum{{{labels}}} {hist.sum:.17g}")
                lines.append(f"{full}_count{{{labels}}} {hist.count}")
            if self.memory_watermark is not None:
                metric("coast_campaign_device_memory_watermark_bytes",
                       "gauge",
                       "High-water device bytes_in_use seen.",
                       [(labels, float(self.memory_watermark))])
            if self.slo_report is not None:
                rows = self.slo_report.get("objectives") or []
                metric("coast_campaign_slo_burn_rate", "gauge",
                       "Error-budget burn rate per SLO objective "
                       "(1.0 = consuming budget exactly at the allowed "
                       "pace).",
                       [(f'{labels},objective="{_esc(r["objective"])}"',
                         float(r["burn"]["long"]))
                        for r in rows
                        if (r.get("burn") or {}).get("long")
                        is not None])
                metric("coast_campaign_slo_budget_remaining_frac",
                       "gauge",
                       "Unconsumed error-budget fraction per SLO "
                       "objective (negative = overspent).",
                       [(f'{labels},objective="{_esc(r["objective"])}"',
                         float(r["budget"]["remaining_frac"]))
                        for r in rows
                        if (r.get("budget") or {}).get("remaining_frac")
                        is not None])
                metric("coast_campaign_slo_verdict", "gauge",
                       "Per-objective verdict (0=ok, 1=warn, 2=page).",
                       [(f'{labels},objective="{_esc(r["objective"])}"',
                         float(("ok", "warn",
                                "page").index(r["verdict"])))
                        for r in rows])
            return "\n".join(lines) + "\n"

    # -- status file ---------------------------------------------------------
    def _maybe_write_status(self, force: bool = False) -> None:
        if not self.status_path:
            return
        now = self._clock()
        if not force and (now - self._last_status_write
                          < self.status_interval_s):
            return
        self._last_status_write = now
        atomic_write_json(self.status_path, self.snapshot())


def _esc(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))
