"""Periodic progress heartbeat for long campaigns.

A 10^6-injection campaign on a dev box, or a flagship campaign at a few
hundred inj/s, runs minutes with nothing on the terminal between
chunks.  ``Heartbeat`` rate-limits a one-line progress report --

    # heartbeat: 300000/1000000 (30.0%) 45231 inj/s eta 15s sdc=28702 ...

-- emitted at most once per ``interval_s`` no matter how often
``update`` is called (call it per batch or per chunk; it is a no-op
until the interval elapses).  Each emission also drops an ``instant``
mark plus an ``inj_per_sec`` gauge into the ambient telemetry, so the
heartbeat cadence is visible in an exported Perfetto trace.

``clock`` and ``emit`` are injectable for tests (and for routing the
line somewhere other than stderr).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, Optional

from coast_tpu.obs import spans as _spans

# Classes worth a heartbeat column, in print order; zero-count classes
# are elided to keep the line short.
_COUNT_KEYS = ("success", "corrected", "sdc", "train_self_heal",
               "train_sdc", "due_abort", "due_timeout",
               "due_stack_overflow", "due_assert", "invalid",
               "cache_invalid")


def _stderr(line: str) -> None:
    print(line, file=sys.stderr, flush=True)


def format_rate(bytes_per_s: float) -> str:
    """Human bytes/s ('1.2 MB/s'), shared with the console dashboard."""
    v = float(bytes_per_s)
    for unit, scale in (("GB", 1e9), ("MB", 1e6), ("kB", 1e3)):
        if v >= scale:
            return f"{v / scale:.1f} {unit}/s"
    return f"{v:.0f} B/s"


class TransferRateWindow:
    """Cumulative up/down byte counters -> per-window rates.  The one
    windowing implementation behind both the heartbeat's ``up=/down=``
    fields and the console's link line (same ``_prev`` state shape,
    same dt clamp)."""

    def __init__(self, t0: float):
        self._prev = (float(t0), 0, 0)

    def rates(self, now: float, transfer) -> "Optional[tuple]":
        """(up_bytes_per_s, down_bytes_per_s, up_total, down_total) over
        the window since the previous call, or None before the first
        measured byte."""
        transfer = dict(transfer or {})
        if not transfer:
            return None
        up = int(transfer.get("up", 0))
        down = int(transfer.get("down", 0))
        t_prev, up_prev, down_prev = self._prev
        self._prev = (float(now), up, down)
        dt = max(float(now) - t_prev, 1e-9)
        return ((up - up_prev) / dt, (down - down_prev) / dt, up, down)


class Heartbeat:
    """Rate-limited progress reporter for a campaign of ``total`` runs.

    ``metrics`` (a :class:`coast_tpu.obs.metrics.CampaignMetrics` hub
    the same campaign feeds) adds a live host<->device transfer rate to
    each beat -- the PR 12 ``transfer_bytes`` block was summary-only,
    invisible while the campaign it describes is still running."""

    def __init__(self, total: int, interval_s: float = 5.0,
                 label: str = "heartbeat",
                 emit: Optional[Callable[[str], None]] = None,
                 metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        self.total = int(total)
        self.interval_s = float(interval_s)
        self.label = label
        self.metrics = metrics
        self.emitted = 0
        self._emit = emit or _stderr
        self._clock = clock
        self._t0 = clock()
        self._transfer_window = TransferRateWindow(self._t0)
        # First update is eligible immediately: a long first batch should
        # not run silent for interval_s before the first report.
        self._last = self._t0 - self.interval_s

    def _transfer_parts(self, now: float) -> list:
        """Up/down rates over the window since the previous beat, from
        the hub's cumulative transfer counters; empty before the first
        measured byte."""
        if self.metrics is None:
            return []
        got = self._transfer_window.rates(
            now, getattr(self.metrics, "transfer", None))
        if got is None:
            return []
        up_rate, down_rate, _up, _down = got
        return [f"up={format_rate(up_rate)}",
                f"down={format_rate(down_rate)}"]

    def _slo_part(self) -> list:
        """Live SLO status from the hub (worst burning objective plus
        its remaining budget); empty when the hub carries no SLO set or
        nothing was evaluated yet."""
        status = getattr(self.metrics, "slo_status", None)
        if self.metrics is None or status is None:
            return []
        from coast_tpu.obs.slo import status_line
        frag = status_line(status())
        return [frag] if frag else []

    def update(self, done: int, counts: Optional[Dict[str, int]] = None,
               force: bool = False) -> Optional[str]:
        """Report progress if the interval elapsed (or ``force``).

        Returns the emitted line, or None when rate-limited.  ``counts``
        is the cumulative class histogram so far (any subset of keys).
        """
        now = self._clock()
        if not force and now - self._last < self.interval_s:
            return None
        self._last = now
        elapsed = max(now - self._t0, 1e-9)
        rate = done / elapsed
        parts = [f"# {self.label}: {done}/{self.total}"]
        if self.total:
            parts.append(f"({100.0 * done / self.total:.1f}%)")
        parts.append(f"{rate:.0f} inj/s")
        if self.total and rate > 0 and done < self.total:
            parts.append(f"eta {(self.total - done) / rate:.0f}s")
        if counts:
            parts.extend(f"{k}={counts[k]}" for k in _COUNT_KEYS
                         if counts.get(k))
        parts.extend(self._transfer_parts(now))
        parts.extend(self._slo_part())
        line = " ".join(parts)
        self.emitted += 1
        self._emit(line)
        tel = _spans.current()
        tel.instant("heartbeat", done=done, total=self.total)
        tel.gauge("inj_per_sec", round(rate, 2))
        return line

    def final(self, done: int,
              counts: Optional[Dict[str, int]] = None) -> str:
        """Terminal flush: emit unconditionally, bypassing the rate
        limiter.  A campaign's last state -- completion, or the counts
        standing when a ``CampaignWedgedError`` killed it -- must reach
        the terminal even if the previous beat was milliseconds ago."""
        return self.update(done, counts, force=True)
