"""``python -m coast_tpu slo`` -- reliability SLO check/report.

Evaluates a declarative SLO set (:mod:`coast_tpu.obs.slo`) against
RECORDED campaign evidence -- a ``--status-json`` file, a run doc with
a ``summary`` block, a bare summary JSON, or an NDJSON campaign log --
so CI can gate on reliability regressions the same way
``make ci_protection`` gates on distribution drift::

    python -m coast_tpu slo check --spec "sdc_rate<=0.01;min=256" \\
        --input artifacts/status.json
    python -m coast_tpu slo report --spec "availability>=0.95" \\
        --input runs/mm_tmr.ndjson --baseline runs/mm_none.ndjson \\
        --out artifacts/slo.json

``check`` exits 1 unless every objective's verdict is ``ok`` (a
burning error budget is a failed gate); ``report`` always exits 0 and
just prints/records the evaluation.  ``--baseline`` points at an
unprotected run's evidence and feeds the MWTF objective its
improvement denominator; without it, ``mwtf`` objectives report no
data (and cannot gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from coast_tpu.obs import slo as slo_mod

__all__ = ["main"]

#: The default objective set: the ROADMAP #2 service targets at CI
#: scale -- an SDC ceiling and an availability floor over whatever
#: evidence the caller points at.
DEFAULT_SPEC = "sdc_rate<=0.01,availability>=0.9;min=64"


def parse_command_line(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        prog="python -m coast_tpu slo",
        description="Reliability SLO evaluation over recorded campaign "
                    "evidence (error budgets, burn rates, page/warn/ok)")
    parser.add_argument("mode", choices=("check", "report"),
                        help="check: exit 1 on any non-ok objective; "
                        "report: print the evaluation, exit 0")
    parser.add_argument("--spec", default=DEFAULT_SPEC, metavar="SLO",
                        help="objective set, e.g. "
                        "'sdc_rate<=0.002,availability>=0.99;z=2.576;"
                        "min=4096' (default: %(default)s)")
    parser.add_argument("--input", required=True, metavar="PATH",
                        help="recorded evidence: status JSON, run doc "
                        "with summary, summary JSON, or NDJSON log")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="unprotected run's evidence for the mwtf "
                        "objective's improvement denominator")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="write the full JSON evaluation here")
    return parser.parse_args(argv)


#: Kept as the CLI's historical private name; the shared definition
#: lives in obs.slo so the serving front end's --baseline agrees.
_baseline_from = slo_mod.baseline_from


def _fmt(value, digits: int = 4) -> str:
    if value is None:
        return "-"
    if isinstance(value, bool):
        return "yes" if value else "no"
    if isinstance(value, float):
        return f"{value:.{digits}g}"
    return str(value)


def report_lines(report: dict) -> List[str]:
    lines = [f"SLO verdict: {report['verdict']}"
             + (f"  (burning: {', '.join(report['burning'])})"
                if report.get("burning") else "")]
    for row in report["objectives"]:
        budget = row.get("budget") or {}
        burn = row.get("burn") or {}
        wilson = row.get("wilson")
        detail = (f"  {row['objective']} {row['op']} "
                  f"{_fmt(row['target'])}: observed "
                  f"{_fmt(row.get('observed'))}"
                  f"  attained={_fmt(row.get('attained'))}"
                  f"  burn={_fmt(burn.get('long'))}"
                  + (f"/{_fmt(burn.get('short'))}"
                     if burn.get("short") is not None else "")
                  + f"  budget-left={_fmt(budget.get('remaining_frac'))}"
                  + (f"  wilson=[{_fmt(wilson['lo'])}, "
                     f"{_fmt(wilson['hi'])}]" if wilson else "")
                  + f"  -> {row['verdict']}")
        lines.append(detail)
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_command_line(argv)
    try:
        slo_set = slo_mod.SLOSet.parse(args.spec)
    except slo_mod.SLOError as e:
        print(f"Error, bad --spec: {e}", file=sys.stderr)
        return 2
    try:
        evidence = slo_mod.load_evidence(args.input)
    except (OSError, ValueError) as e:
        print(f"Error, cannot load evidence from {args.input}: {e}",
              file=sys.stderr)
        return 2
    baseline = None
    if args.baseline:
        try:
            baseline = _baseline_from(args.baseline)
        except (OSError, ValueError) as e:
            print(f"Error, cannot load baseline from {args.baseline}: "
                  f"{e}", file=sys.stderr)
            return 2

    report = slo_mod.evaluate(slo_set, evidence, baseline=baseline)
    report["input"] = args.input
    if args.baseline:
        report["baseline"] = {"path": args.baseline, **(baseline or {})}
    print("\n".join(report_lines(report)))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            json.dump({"format": "coast-slo", "version": 1, **report},
                      fh, indent=1, sort_keys=True)
        print(f"wrote {args.out}")
    if args.mode == "check" and report["verdict"] != "ok":
        print(f"Error, SLO gate failed: {report['verdict']} on "
              f"{', '.join(report['burning'])}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
