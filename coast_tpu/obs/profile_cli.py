"""``python -m coast_tpu profile`` -- the campaign attribution report.

Runs a short PROFILED campaign per target (warm compile first, so the
measured window is the steady-state loop, not the trace+XLA build),
prints the device-time attribution, and records the machine-readable
artifact the fused-kernel work (ROADMAP #1) A/Bs against::

    python -m coast_tpu profile                       # mm x TMR/DWC
    python -m coast_tpu profile --target crc16\\|-TMR -t 8192
    python -m coast_tpu profile --out artifacts/profile_mm.json \\
        --trace-out profile.trace.json --peak-gflops 197000

Per target the report carries the exact wall-clock identity
``device_busy + host_gap + host_other == wall`` (checked here; a
violation is a profiler bug, exit 1), the per-dispatch device-seconds
histogram, the per-phase split, and the roofline/MFU block
(achieved vs predicted-ceiling MFU, voter-bytes share, generalized
flops overhead).  ``--peak-gflops`` pins the MFU denominator when the
backend has no table entry -- recording a CPU-measured attribution
against the TPU target ceiling is the explicit, labeled convention
(``peak_source: "explicit"``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

__all__ = ["main"]

#: The default target set: the seed benchmark the perf narrative is
#: anchored on, under both protection strategies.
DEFAULT_TARGETS = ("matrixMultiply|-TMR", "matrixMultiply|-DWC")

#: Attribution identity tolerance (absolute seconds + relative): the
#: three buckets are computed from the same perf_counter stream, so any
#: real gap is a profiler bug, not noise.
SUM_TOL_S = 0.005


def parse_command_line(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        prog="python -m coast_tpu profile",
        description="Per-dispatch device-time attribution + roofline/MFU "
                    "report over short profiled campaigns")
    parser.add_argument("--target", action="append", default=None,
                        metavar="SPEC",
                        help="benchmark|opt_passes (repeatable; default "
                        "matrixMultiply x -TMR/-DWC)")
    parser.add_argument("-t", type=int, default=4096, metavar="N",
                        help="injections per target (default 4096)")
    parser.add_argument("--batch-size", type=int, default=512)
    parser.add_argument("--seed", type=int, default=2026)
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="write the JSON attribution artifact here")
    parser.add_argument("--trace-out", default=None, metavar="PATH",
                        help="write the (last target's) Perfetto trace "
                        "with the device track here")
    parser.add_argument("--peak-gflops", type=float, default=None,
                        help="MFU peak denominator in GFLOP/s (default: "
                        "the backend table; unknown backends record "
                        "ops/s with MFU null)")
    parser.add_argument("--hbm-gbps", type=float, default=None,
                        help="roofline HBM bandwidth (default v5e "
                        "819 GB/s)")
    parser.add_argument("--fuse-step", action="store_true",
                        help="run every target TWICE at identical seeds "
                        "-- the unfused baseline and the -fuseStep "
                        "engine -- and record the A/B (achieved_mfu, "
                        "flops_overhead, overhead reduction) in the "
                        "artifact's fused_ab block")
    return parser.parse_args(argv)


def _fmt_pct(x) -> str:
    return f"{100.0 * x:.4g}%" if x is not None else "-"


def _report_lines(tid: str, summ: dict) -> List[str]:
    prof = summ["profile"]
    mfu = summ.get("mfu") or {}
    wall = prof["wall_s"]
    lines = [f"== {tid} =="]
    lines.append(
        f"  wall {wall:.3f}s = device {prof['device_busy_s']:.3f}s "
        f"({_fmt_pct(prof['device_busy_fraction'])}) "
        f"+ host-gap {prof['host_gap_s']:.3f}s "
        f"({_fmt_pct(prof['dispatch_gap_fraction'])}) "
        f"+ other {prof['host_other_s']:.3f}s")
    lines.append(f"  {prof['dispatches']} dispatches over "
                 f"{prof['rows']} rows  "
                 f"({summ['injections_per_sec']} inj/s)")
    phases = prof.get("per_phase_device_s") or {}
    if phases:
        lines.append("  per-phase device: " + "  ".join(
            f"{k} {v:.3f}s" for k, v in phases.items()))
    if mfu:
        lines.append(
            f"  ops/run {mfu['useful_ops_per_run']:.3g} useful / "
            f"{mfu['program_ops_per_run']:.3g} protected "
            f"(overhead {mfu['flops_overhead']}x)")
        lines.append(
            f"  achieved {mfu['achieved_ops_per_s'] / 1e9:.4g} Gops/s "
            f"on device  MFU {_fmt_pct(mfu['achieved_mfu'])} "
            f"(roofline ceiling {_fmt_pct(mfu['roofline_mfu'])}, "
            f"voter-bytes share {_fmt_pct(mfu['voter_bytes_share'])}; "
            f"peak {mfu['peak_gflops']} GFLOP/s, "
            f"{mfu['peak_source']})")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_command_line(argv)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.inject.supervisor import build_program
    from coast_tpu.obs import write_trace
    from coast_tpu.obs.profiler import CampaignProfiler
    from coast_tpu.obs.roofline import DEFAULT_HBM_GBPS

    targets = list(args.target or DEFAULT_TARGETS)
    doc = {"format": "coast-profile", "version": 1,
           "backend": jax.default_backend(),
           "n": int(args.t), "batch_size": int(args.batch_size),
           "seed": int(args.seed), "targets": {}}
    if args.fuse_step:
        doc["fused_ab"] = {}
    last_runner = None
    rc = 0
    for tid in targets:
        bench, _, opt = tid.partition("|")
        # --fuse-step: the baseline arm runs as-is, then the identical
        # campaign (same benchmark, seeds, batch geometry) under the
        # fused engine; the artifact keeps both target entries plus the
        # headline A/B block the perf docs quote.
        arms = ([(tid, opt or "-TMR")] if not args.fuse_step else
                [(tid, opt or "-TMR"),
                 (tid + "+fused", (opt or "-TMR") + " -fuseStep")])
        for arm_tid, arm_opt in arms:
            prog, strategy = build_program(bench, arm_opt)
            profiler = CampaignProfiler(
                prog, peak_gflops=args.peak_gflops,
                hbm_gbps=args.hbm_gbps or DEFAULT_HBM_GBPS)
            runner = CampaignRunner(prog, strategy_name=strategy or "TMR",
                                    profile=profiler)
            warm = min(args.batch_size, args.t)
            runner.run(warm, seed=1, batch_size=args.batch_size)  # compile
            res = runner.run(args.t, seed=args.seed,
                             batch_size=args.batch_size)
            summ = res.summary()
            prof = summ["profile"]
            gap = abs(prof["wall_s"] - prof["device_busy_s"]
                      - prof["host_gap_s"] - prof["host_other_s"])
            if gap > SUM_TOL_S + 0.01 * prof["wall_s"]:
                print(f"Error, {arm_tid}: attribution does not sum to "
                      f"wall clock (off by {gap:.4f}s of "
                      f"{prof['wall_s']:.4f}s)", file=sys.stderr)
                rc = 1
            print("\n".join(_report_lines(arm_tid, summ)))
            doc["targets"][arm_tid] = {
                "benchmark": res.benchmark, "strategy": res.strategy,
                "injections": int(res.n),
                "injections_per_sec": summ["injections_per_sec"],
                "counts": {k: int(v) for k, v in res.counts.items()},
                "profile": summ["profile"],
                "mfu": summ.get("mfu"),
                "stages": summ["stages"],
            }
            last_runner = runner
        if args.fuse_step:
            base = doc["targets"][tid]
            fused = doc["targets"][tid + "+fused"]
            ab = {"counts_identical": base["counts"] == fused["counts"]}
            for arm_name, arm in (("unfused", base), ("fused", fused)):
                m = arm.get("mfu") or {}
                ab[arm_name] = {
                    "flops_overhead": m.get("flops_overhead"),
                    "achieved_mfu": m.get("achieved_mfu"),
                    "program_ops_per_run": m.get("program_ops_per_run"),
                    "injections_per_sec": arm["injections_per_sec"]}
            bo = ab["unfused"]["flops_overhead"]
            fo = ab["fused"]["flops_overhead"]
            if bo and fo:
                ab["overhead_reduction_x"] = round(bo / fo, 3)
            doc["fused_ab"][tid] = ab
            print(f"  fused A/B: overhead {bo}x -> {fo}x "
                  f"({ab.get('overhead_reduction_x', '-')}x reduction), "
                  f"counts identical: {ab['counts_identical']}")
            if not ab["counts_identical"]:
                print(f"Error, {tid}: fused arm changed campaign counts",
                      file=sys.stderr)
                rc = 1
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        print(f"wrote {args.out}")
    if args.trace_out and last_runner is not None:
        write_trace(last_runner.telemetry, args.trace_out,
                    metadata={"profile": True})
        print(f"wrote {args.trace_out}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
