"""coast_tpu.obs: campaign telemetry (spans, counters, trace export).

The observability layer of the injection pipeline: nested wall-clock
spans and counters (:mod:`coast_tpu.obs.spans`), Chrome/Perfetto
``trace_event`` export (:mod:`coast_tpu.obs.trace_export`), and a
rate-limited progress heartbeat (:mod:`coast_tpu.obs.heartbeat`).
See docs/observability.md for the workflow.
"""

from coast_tpu.obs.heartbeat import Heartbeat
from coast_tpu.obs.spans import (NULL, Telemetry, count, current, instant,
                                 span)
from coast_tpu.obs.trace_export import (to_trace_doc, to_trace_events,
                                        write_trace)

__all__ = [
    "Telemetry", "NULL", "current", "span", "count", "instant",
    "to_trace_events", "to_trace_doc", "write_trace",
    "Heartbeat",
]
